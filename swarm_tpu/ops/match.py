"""Device match kernel: response streams → word-slot bits → verdicts.

Pure jnp/XLA (a fused Pallas variant comes later); everything is static
shape, vector ops, gathers from small tables, and a handful of scatters.
Pipeline per batch (design in fingerprints/compile.py docstring):

1. Rolling q-gram hashes of each (stream, case) in use — shifted
   multiply-adds only.
2. Per word-table: Bloom probe every window (2 gathers from a 32 KiB
   bitmap), top-k the surviving windows, binary-search the sorted h1
   groups, then check entry h2 + suffix-gram h1/h2 and position bounds.
   Hits scatter into a word-slot bit vector; all q-gram hits are marked
   *uncertain* (host confirms sparse hits — exactness contract).
3. Tiny slots (1–3 bytes) evaluate by dense shifted compare — exact.
4. Verdict lowering: slot buckets → matcher bits (and/or + negation),
   scalar programs (status/size/len dsl), then op and template
   reductions. Uncertainty propagates alongside values.

The kernel's guarantee: a (row, template) pair whose uncertain bit is
clear has the exact oracle verdict; uncertain pairs carry a superset
signal and only ever need host confirmation when something *fired*.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops import hashing
from swarm_tpu.ops.encoding import STREAMS


#: max live compiled executables per matcher (DeviceDB/ShardedMatcher).
#: Each distinct batch shape compiles a kernel that CAPTURES the corpus
#: tables as constants (tens of MB each); unbounded shape churn grows
#: RSS without limit, while too small a cap thrashes multi-second
#: recompiles against millisecond batches. Coarse width buckets
#: (engine width_multiple=512) and 256-row buckets keep the live
#: working set well under this. Override: SWARM_MAX_COMPILED.
import os as _os

MAX_COMPILED = int(_os.environ.get("SWARM_MAX_COMPILED", "8"))


def lru_fetch(cache: dict, key):
    """Get + refresh (move-to-back) — dict order is the LRU order."""
    val = cache.pop(key, None)
    if val is not None:
        cache[key] = val
    return val


def lru_store(cache: dict, key, val, cap: int = 0) -> None:
    while cap and len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = val


def fused_plane_widths(db: "fpc.CompiledDB") -> list:
    """Byte widths of the six ``full``-mode output planes in fused
    order: t_value, t_unc, op_value, op_unc, m_unc (packed bits), then
    the 1-byte overflow column."""
    # widths mirror eval_verdicts' plane allocations exactly: the
    # template planes are padded to max(NT, 1) there (an all-host-tail
    # corpus still emits one packed byte), the op/matcher planes are not
    nbt = (max(db.num_templates, 1) + 7) >> 3
    nbo = (db.op_src.shape[0] + 7) >> 3
    nbm = (db.m_src.shape[0] + 7) >> 3
    return [nbt, nbt, nbo, nbo, nbm, 1]


def fuse_planes(planes, overflow):
    """Producer half of the fused full-mode output: pack the five bit
    planes and append the overflow byte column — ONE device array, one
    host read. Keep in lockstep with split_fused below (shared by both
    backends so producer and consumer live in this module)."""
    parts = [jnp.packbits(p, axis=1) for p in planes]
    parts.append(overflow[:, None].astype(jnp.uint8))
    return jnp.concatenate(parts, axis=1)


def split_fused(db: "fpc.CompiledDB", buf: np.ndarray):
    """Slice one fused host buffer back into the engine's six outputs.

    The ``full`` planes ship as ONE device array (see DeviceDB.match):
    a single device-to-host read instead of six. Transfer count — not
    bytes — is what the tunneled-accelerator transport charges for
    (BASELINE.md, relay sync mode: ~seconds per read), and even on
    healthy transports one transfer saves five dispatch round-trips.

    The buffer is normalized to C order here: XLA owns the device
    layout and is free to hand back a Fortran-ordered result (observed
    on TPU for corpus-scale plane shapes), and every downstream
    consumer — plane slicing, packbits math, the native sw_ext_resolve
    pass — assumes row-major.
    """
    buf = np.ascontiguousarray(buf)
    outs = []
    off = 0
    for w in fused_plane_widths(db):
        outs.append(buf[:, off : off + w])
        off += w
    if off != buf.shape[1]:
        # producer/consumer drift would otherwise shear every plane
        # after the mismatch silently (op bits read as template bits)
        raise ValueError(
            f"fused buffer is {buf.shape[1]} bytes wide, plane widths "
            f"sum to {off}"
        )
    pt, pu, opv, opu, mu, ovf = outs
    return pt, pu, opv, opu, mu, ovf[:, 0] != 0


class DeviceDB:
    """CompiledDB uploaded to device + the jitted match function.

    The numpy tables become jnp constants captured in the traced
    function; re-tracing happens per distinct batch shape (width
    buckets keep that to a handful of shapes).
    """

    MAX_COMPILED = MAX_COMPILED  # class alias (ShardedMatcher shares it)

    def __init__(self, db: fpc.CompiledDB, candidate_k: int = 128):
        self.db = db
        self.candidate_k = candidate_k
        self._fn_cache: dict = {}

    def match(self, streams: dict, lengths: dict, status, full: bool = False):
        """streams: name → uint8 [B, W]; lengths: name → int32 [B].

        Returns (t_value [B, NT] bool, t_uncertain [B, NT] bool,
        overflow [B] bool); with ``full`` the op/matcher planes are
        included: (t_value, t_unc, op_value, op_unc, m_unc, overflow)
        — the engine's sparse-confirmation inputs, packed, and already
        materialized as HOST numpy views of one fused device read
        (split_fused).
        """
        out = self.dispatch(streams, lengths, status, full=full)
        if full:
            return self.collect(out)
        return out

    def dispatch(self, streams: dict, lengths: dict, status, full: bool = True):
        """Async half of :meth:`match`: launch the jitted kernel and
        return the (device-resident, still-computing) fused output
        WITHOUT a host transfer. JAX dispatch is asynchronous, so the
        kernel crunches while the caller does other host work — the
        continuous-batching scheduler dispatches batch i+1 here before
        walking batch i's verdicts. :meth:`collect` finalizes."""
        shape_key = (
            tuple(sorted((k, v.shape) for k, v in streams.items())),
            full,
        )
        fn = lru_fetch(self._fn_cache, shape_key)
        if fn is None:
            impl = functools.partial(
                _match_impl, self.db, self.candidate_k, full=full
            )
            if full:
                # bit-plane outputs ship packed (MSB-first, np.packbits
                # convention): ~9× less host transfer per batch — and
                # FUSED into one array so the host makes exactly one
                # device read (split_fused slices it back)
                def packed_impl(streams, lengths, status, _impl=impl):
                    *planes, overflow = _impl(streams, lengths, status)
                    return fuse_planes(planes, overflow)

                fn = jax.jit(packed_impl)
            else:
                fn = jax.jit(impl)
            lru_store(self._fn_cache, shape_key, fn, self.MAX_COMPILED)
        return fn(
            {k: jnp.asarray(v) for k, v in streams.items()},
            {k: jnp.asarray(v) for k, v in lengths.items()},
            jnp.asarray(status),
        )

    def collect(self, out):
        """Blocking half of the full-mode split: one host read of the
        fused plane array, sliced into the engine's six outputs."""
        return split_fused(self.db, np.asarray(out))


def _lower_stream(arr):
    is_upper = (arr >= 65) & (arr <= 90)
    return jnp.where(is_upper, arr + 32, arr)


def _shifted(stream, q: int):
    """padded shifted views for window ops."""
    B, W = stream.shape
    padded = jnp.pad(stream, ((0, 0), (0, q)))
    return [padded[:, j : j + W] for j in range(q)]


def table_arrays_of(table: fpc.WordTable) -> dict:
    """The traced-array view of one WordTable (jnp constants by default;
    the sharded path passes per-rank slices instead)."""
    return {
        "group_h1": jnp.asarray(table.group_h1),
        "entry_start": jnp.asarray(table.entry_start),
        "entry_count": jnp.asarray(table.entry_count),
        "entry_h2": jnp.asarray(table.entry_h2),
        "entry_slot": jnp.asarray(table.entry_slot),
        "entry_off": jnp.asarray(table.entry_off),
        "entry_len": jnp.asarray(table.entry_len),
        "entry_suf_delta": jnp.asarray(table.entry_suf_delta),
        "entry_suf_h1": jnp.asarray(table.entry_suf_h1),
        "entry_suf_h2": jnp.asarray(table.entry_suf_h2),
        "bloom": jnp.asarray(table.bloom),
    }


def match_slots(
    db: fpc.CompiledDB,
    candidate_k: int,
    streams,
    lengths,
    table_arrays: Optional[list] = None,
    pos_offset: int = 0,
    back_halo: int = 0,
    fwd_halo: int = 0,
):
    """→ (value_bits [B, NS] bool, uncertain_bits [B, NS] bool, overflow [B]).

    Sequence parallelism support: ``streams`` may be halo-extended
    ([B, back_halo + W_local + fwd_halo]). Candidate windows *start*
    only in the W_local middle region (each global window position is
    owned by exactly one shard) but hash/verify reads may reach into
    both halos — a word whose gram sits in this shard can begin in the
    previous shard's bytes (back halo) and end in the next shard's
    (forward halo). Both halos must be ≥ the longest table entry for
    the superset property to survive sharding. ``pos_offset`` is the
    shard's global byte offset; ``lengths`` are always global.
    """
    ns = db.num_slots
    some = next(iter(streams.values()))
    B = some.shape[0]
    value_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
    uncertain_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
    overflow = jnp.zeros((B,), dtype=bool)

    # --- cached lowered streams and hash arrays ---
    lowered_cache: dict = {}

    def get_stream(name: str, lowered: bool):
        if not lowered:
            return streams[name]
        if name not in lowered_cache:
            lowered_cache[name] = _lower_stream(streams[name])
        return lowered_cache[name]

    hash_cache: dict = {}

    def get_hashes(name: str, lowered: bool, q: int):
        key = (name, lowered, q)
        if key not in hash_cache:
            hash_cache[key] = hashing.window_hashes_jnp(get_stream(name, lowered), q)
        return hash_cache[key]

    def offset_of(name: str):
        # per-stream global byte offset (streams have different widths,
        # so sequence shards start at different global positions per stream)
        if isinstance(pos_offset, dict):
            return pos_offset[name]
        return pos_offset

    # Slot truth bytes for the fused verify — small ([NW, VERIFY_WIDTH],
    # ci slots pre-lowered) and replicated across shards (slot ids are
    # global even when table groups are model-sharded).
    slot_bytes_j = jnp.asarray(db.slot_bytes)
    slot_len_j = jnp.asarray(db.slot_len)

    # --- q-gram tables ---
    for t_idx, table in enumerate(db.tables):
        arrays = (
            table_arrays[t_idx] if table_arrays is not None else table_arrays_of(table)
        )
        h1, h2 = get_hashes(table.stream, table.lowered, table.q)
        We = h1.shape[1]  # extended width (back halo + local + fwd halo)
        W = We - back_halo - fwd_halo  # windows start only in the middle
        slen = lengths[table.stream]  # global length

        flags = hashing.bloom_probe_jnp(
            arrays["bloom"],
            h1[:, back_halo : back_halo + W],
            h2[:, back_halo : back_halo + W],
        )
        # windows starting past slen - q can't begin a real gram
        positions = jnp.arange(W, dtype=jnp.int32)
        gpositions = positions + offset_of(table.stream)
        flags = flags & (gpositions[None, :] <= (slen - table.q)[:, None])

        k = min(candidate_k, W)
        vals = jnp.where(flags, positions[None, :] + 1, 0)
        top_vals, _ = jax.lax.top_k(vals, k)
        pos = top_vals - 1  # -1 = invalid (local window coordinate)
        valid = pos >= 0
        cpos = jnp.maximum(pos, 0) + back_halo  # extended coordinate
        overflow = overflow | (jnp.sum(flags, axis=1) > k)

        h1c = jnp.take_along_axis(h1, cpos, axis=1)
        h2c = jnp.take_along_axis(h2, cpos, axis=1)

        group_h1 = arrays["group_h1"]
        gidx = jnp.searchsorted(group_h1, h1c)
        G = group_h1.shape[0]
        gidx_c = jnp.minimum(gidx, G - 1)
        found = valid & (group_h1[gidx_c] == h1c)

        e_start = arrays["entry_start"][gidx_c]
        e_count = arrays["entry_count"][gidx_c]
        entry_h2 = arrays["entry_h2"]
        entry_slot = arrays["entry_slot"]
        entry_off = arrays["entry_off"]
        entry_len = arrays["entry_len"]
        entry_sufd = arrays["entry_suf_delta"]
        entry_sufh1 = arrays["entry_suf_h1"]
        entry_sufh2 = arrays["entry_suf_h2"]

        b_idx = jnp.arange(B, dtype=jnp.int32)[:, None] * jnp.ones(
            (1, k), dtype=jnp.int32
        )

        stream_v = get_stream(table.stream, table.lowered)
        offs = jnp.arange(fpc.VERIFY_WIDTH, dtype=jnp.int32)

        # EVERY entry hit is byte-verified (the compile.py:16-17
        # contract): gather the slot's true bytes under the window and
        # compare. Equal and len ≤ VERIFY_WIDTH ⇒ the hit is *certain*
        # (no host confirm). Unequal ⇒ a hash collision: provably no
        # match at this window, so no bit is set at all. Equal prefix of
        # a longer slot ⇒ value + uncertain (host checks the tail).
        # Per-entry (not first-hit-per-window) verification matters:
        # words sharing their chosen gram land in one h1 group and can
        # all pass the hash checks at one window — each needs its own
        # byte compare. max_group (≤ compile.MAX_GROUP normally; up to
        # compile.HARD_GROUP when gram shedding degrades) bounds the
        # extra gathers.
        for g in range(table.max_group):
            e = jnp.minimum(e_start + g, entry_h2.shape[0] - 1)
            in_group = found & (g < e_count)
            h2_ok = entry_h2[e] == h2c
            # suffix-gram check from the same rolling-hash arrays; the
            # suffix may live in the halo region (sequence parallelism)
            spos = cpos + entry_sufd[e]
            spos_c = jnp.clip(spos, 0, We - 1)
            suf_ok = (
                (jnp.take_along_axis(h1, spos_c, axis=1) == entry_sufh1[e])
                & (jnp.take_along_axis(h2, spos_c, axis=1) == entry_sufh2[e])
                & (spos >= 0)
                & (spos < We)
            )
            # global bounds: word fully inside the true part bytes
            gstart = (cpos - back_halo) + offset_of(table.stream) - entry_off[e]
            fits = (gstart >= 0) & (gstart + entry_len[e] <= slen[:, None])
            # extended-view bounds: with halos ≥ max entry length these
            # only bite in the unsharded case (buffer edges)
            fits = fits & (cpos - entry_off[e] >= 0) & (
                cpos - entry_off[e] + entry_len[e] <= We
            )
            hit = in_group & h2_ok & suf_ok & fits
            slot = entry_slot[e]
            start = cpos - entry_off[e]  # extended coord of word start
            lv = jnp.minimum(entry_len[e], fpc.VERIFY_WIDTH)
            idx = start[:, :, None] + offs[None, None, :]  # [B, k, V]
            idx_c = jnp.clip(idx, 0, We - 1)
            gathered = jnp.take_along_axis(
                stream_v, idx_c.reshape(B, -1), axis=1
            ).reshape(B, k, fpc.VERIFY_WIDTH)
            expected = slot_bytes_j[slot]  # [B, k, V]
            pos_ok = offs[None, None, :] < lv[:, :, None]
            eq = ((gathered == expected) | ~pos_ok).all(-1)
            long = slot_len_j[slot] > fpc.VERIFY_WIDTH
            fired = hit & eq
            value_bits = value_bits.at[b_idx, slot].max(fired)
            uncertain_bits = uncertain_bits.at[b_idx, slot].max(fired & long)

    # --- tiny slots: dense shifted compare (exact) ---
    tiny_count = int((np.asarray(db.tiny_len) > 0).sum())
    shift_cache: dict = {}
    for i in range(tiny_count):
        length = int(db.tiny_len[i])
        slot_id = int(db.tiny_slot[i])
        stream_name = STREAMS[int(db.tiny_stream[i])]
        lowered = bool(db.tiny_lowered[i])
        skey = (stream_name, lowered)
        if skey not in shift_cache:
            shift_cache[skey] = _shifted(
                get_stream(stream_name, lowered), hashing.TINY_MAX
            )
        shifts = shift_cache[skey]
        We_t = shifts[0].shape[1]
        # global coordinates (halo positions are valid too — the byte
        # compare is exact and the OR across shards dedupes)
        gpositions = (
            jnp.arange(We_t, dtype=jnp.int32) - back_halo + offset_of(stream_name)
        )
        eq = jnp.ones_like(shifts[0], dtype=bool)
        for j in range(length):
            eq = eq & (shifts[j] == int(db.tiny_bytes[i, j]))
        slen = lengths[stream_name]
        eq = eq & (gpositions[None, :] >= 0)
        eq = eq & (gpositions[None, :] <= (slen - length)[:, None])
        # window must lie inside this view's real bytes (an all-zero tiny
        # pattern must not match the zero padding / zero-filled halo edge)
        local = jnp.arange(We_t, dtype=jnp.int32)
        eq = eq & (local[None, :] + length <= We_t)
        hit = eq.any(axis=1)
        value_bits = value_bits.at[:, slot_id].max(hit)

    return value_bits, uncertain_bits, overflow


def eval_verdicts(
    db: fpc.CompiledDB,
    value_bits,
    uncertain_bits,
    lengths,
    status,
    full=False,
    md5_digest=None,
    rx=None,
):
    """Slot bits + scalars → (t_value, t_uncertain) [B, NT] bool.

    With ``full=True`` also returns the intermediate planes
    ``(t_value, t_unc, op_value, op_unc, m_unc)`` so the host can
    resolve an uncertain verdict by re-evaluating only the specific
    uncertain matchers (engine.py) instead of the whole template.
    (No m_value plane: an undecided op's certain matchers are neutral
    by the Kleene argument, so the host never reads their values.)

    Uncertainty is refined with three-valued logic at every reduction:
    a verdict already decided by its *certain* inputs (a certain-true
    input under OR, a certain-false one under AND) is exact no matter
    what the uncertain inputs turn out to be, so its uncertain bit is
    cleared. This is what keeps host confirmation sparse — e.g. a
    status-matcher miss certain-falsifies an AND op and no regex
    sibling ever needs host evaluation.
    """
    B = status.shape[0]
    NM = db.m_kind.shape[0]

    len_body = lengths["body"].astype(jnp.float32)
    len_header = lengths["header"].astype(jnp.float32)
    len_all = lengths["all"].astype(jnp.float32)
    svars = jnp.stack(
        [status.astype(jnp.float32), len_body, len_header, len_all, len_body],
        axis=1,
    )  # [B, SCALAR_VARS]

    # --- slot reductions (vacuously true when a matcher has no slots) ---
    slot_red = jnp.ones((B, NM), dtype=bool)
    m_unc = jnp.zeros((B, NM), dtype=bool)
    cond_and = jnp.asarray(db.m_cond_and)
    for bucket in db.m_slot_buckets:
        gv = value_bits[:, bucket.idx]  # [B, nb, w]
        gu = uncertain_bits[:, bucket.idx]
        rows = jnp.asarray(bucket.rows)
        is_and = cond_and[rows][None, :]
        red = jnp.where(is_and, gv.all(-1), gv.any(-1))
        # Kleene: a certain-hit slot decides OR; a missed slot is always
        # certain (uncertainty only attaches to fired q-gram hits), so
        # any miss decides AND
        decided = jnp.where(
            is_and, (~gv).any(-1), (gv & ~gu).any(-1)
        )
        slot_red = slot_red.at[:, rows].set(red)
        m_unc = m_unc.at[:, rows].set(gu.any(-1) & ~decided)

    # --- negated-contains buckets: NONE of the slots may be present ---
    # (dsl conjuncts like !regex('(?i)x-frame-options', all_headers) —
    # the missing-security-headers shape). Slot absence is always
    # certain; an uncertain *fired* slot leaves presence unknown, so
    # the matcher goes uncertain, and a certain-present slot decides
    # the whole conjunction false.
    neg_present = jnp.zeros((B, NM), dtype=bool)
    neg_decided_false = jnp.zeros((B, NM), dtype=bool)
    for bucket in db.m_negslot_buckets:
        gv = value_bits[:, bucket.idx]
        gu = uncertain_bits[:, bucket.idx]
        rows = jnp.asarray(bucket.rows)
        neg_present = neg_present.at[:, rows].set(gv.any(-1))
        neg_decided_false = neg_decided_false.at[:, rows].set(
            (gv & ~gu).any(-1)
        )
        m_unc = m_unc.at[:, rows].max(gu.any(-1))

    # --- scalar programs ---
    var_id = db.m_scalar[:, :, 0].astype(np.int32)  # [NM, C] static
    op_id = db.m_scalar[:, :, 1].astype(np.int32)
    cmp_val = jnp.asarray(db.m_scalar[:, :, 2])  # [NM, C] f32
    v = svars[:, var_id]  # [B, NM, C]
    checks = [
        v == cmp_val,  # SOP_EQ
        v != cmp_val,
        v < cmp_val,
        v > cmp_val,
        v <= cmp_val,
        v >= cmp_val,
        jnp.ones_like(v, dtype=bool),  # SOP_TRUE
    ]
    conj = jnp.select(
        [op_id[None] == i for i in range(len(checks))], checks, default=False
    )
    scalar_ok = conj.all(-1)  # [B, NM]

    # --- status / size matchers ---
    status_ok = (status[:, None, None] == jnp.asarray(db.m_status)[None]).any(-1)
    len_streams = jnp.stack(
        [lengths[name] for name in STREAMS], axis=1
    )  # [B, len(STREAMS)]
    size_sel = len_streams[:, db.m_size_stream]  # [B, NM]
    size_ok = (size_sel[:, :, None] == jnp.asarray(db.m_size)[None]).any(-1)

    kind = db.m_kind  # static numpy
    is_regex_prefilter = jnp.asarray(kind == fpc.MK_REGEX_PREFILTER)
    is_words = jnp.asarray((kind == fpc.MK_WORDS) | (kind == fpc.MK_REGEX_PREFILTER))
    is_scalar = jnp.asarray(kind == fpc.MK_SCALAR_DSL)
    is_status = jnp.asarray(kind == fpc.MK_STATUS)
    is_size = jnp.asarray(kind == fpc.MK_SIZE)

    # device md5 digest equality (md5(body) == "<hex>" dsl conjuncts).
    # Fail CLOSED without a digest: the matcher keeps its superset value
    # but goes uncertain, so a caller that forgets to supply the digest
    # costs host confirms — never silent false hits.
    has_md5 = bool(db.m_md5_check.any())
    if md5_digest is not None:
        md5_ok = (~jnp.asarray(db.m_md5_check))[None, :] | (
            md5_digest[:, None, :].astype(jnp.uint32)
            == jnp.asarray(db.m_md5)[None]
        ).all(-1)
    else:
        md5_ok = jnp.ones((B, NM), dtype=bool)
        if has_md5:
            m_unc = m_unc | jnp.asarray(db.m_md5_check)[None, :]

    m_value = jnp.zeros((B, NM), dtype=bool)
    m_value = jnp.where(is_words[None, :], slot_red, m_value)
    m_value = jnp.where(
        is_scalar[None, :],
        scalar_ok & slot_red & ~neg_present & md5_ok,
        m_value,
    )
    m_value = jnp.where(is_status[None, :], status_ok, m_value)
    m_value = jnp.where(is_size[None, :], size_ok, m_value)

    # Kleene over the scalar∧slots∧¬neg∧md5 conjunction: a certainly
    # failed exact conjunct decides the matcher false whatever the
    # uncertain slots resolve to
    m_unc = m_unc & ~(
        is_scalar[None, :] & (~scalar_ok | ~md5_ok | neg_decided_false)
    )
    # md5-style residues: a scalar pass still needs host confirmation
    m_unc = m_unc | (jnp.asarray(db.m_residue)[None, :] & m_value)
    # regex prefilters are *semantically* uncertain when fired: the
    # required literal being byte-verified present does not prove the
    # regex matches, so the fired bit always needs host confirmation
    # (absence of the literal stays exact — the regex cannot match).
    m_unc = m_unc | (is_regex_prefilter[None, :] & m_value)
    # ...EXCEPT matchers the device regex verify re-checked exactly
    # (ops/regexdev.py): their value is the true search result and
    # only budget-overflow pairs stay uncertain.
    if rx is not None and len(db.rx_m_ids):
        rx_value, rx_unc = rx
        ids = jnp.asarray(db.rx_m_ids)
        m_value = m_value.at[:, ids].set(rx_value)
        m_unc = m_unc.at[:, ids].set(rx_unc)
    # negation after uncertainty capture
    m_value = m_value ^ jnp.asarray(db.m_negative)[None, :]

    # --- operations ---
    NOP = db.op_cond_and.shape[0]
    op_value = jnp.zeros((B, NOP), dtype=bool)
    op_unc = jnp.zeros((B, NOP), dtype=bool)
    op_cond = jnp.asarray(db.op_cond_and)
    for bucket in db.op_m_buckets:
        gv = m_value[:, bucket.idx]
        gu = m_unc[:, bucket.idx]
        rows = jnp.asarray(bucket.rows)
        is_and = op_cond[rows][None, :]
        red = jnp.where(is_and, gv.all(-1), gv.any(-1))
        # Kleene: certain-true matcher decides OR; certain-false decides
        # AND (matcher certainty = ~gu post-negation)
        decided = jnp.where(
            is_and, (~gv & ~gu).any(-1), (gv & ~gu).any(-1)
        )
        op_value = op_value.at[:, rows].set(red)
        op_unc = op_unc.at[:, rows].set(gu.any(-1) & ~decided)
    # superset-lowered (prefilter) ops: individual matcher bits inside
    # them are weakened (not per-matcher exact), so the Kleene
    # refinement above does not apply — the op is uncertain exactly when
    # it fired, certain-false otherwise, and fired rows are
    # host-confirmed at op granularity.
    is_pref = jnp.asarray(db.op_prefilter)[None, :]
    op_unc = jnp.where(is_pref, op_value, op_unc)

    # --- templates: OR over their operations ---
    NT = max(db.num_templates, 1)
    t_value = jnp.zeros((B, NT), dtype=bool)
    t_unc = jnp.zeros((B, NT), dtype=bool)
    for bucket in db.t_op_buckets:
        gv = op_value[:, bucket.idx]
        gu = op_unc[:, bucket.idx]
        rows = jnp.asarray(bucket.rows)
        t_value = t_value.at[:, rows].set(gv.any(-1))
        # Kleene: any certain-true op decides the template-level OR
        t_unc = t_unc.at[:, rows].set(
            gu.any(-1) & ~(gv & ~gu).any(-1)
        )
    if full:
        return t_value, t_unc, op_value, op_unc, m_unc
    return t_value, t_unc


def ensure_all_stream(streams: dict, lengths: dict):
    """Synthesize the "all" stream (header + CRLF + body) on device.

    The host encode may ship a width-1 placeholder instead of the
    assembled "all" matrix (encode_batch ``build_all=False``) — the
    concatenation is ~half the host encode bytes and half the H2D
    transfer, and on device it is two gathers and a select.
    ``lengths["all_hdr"]`` carries the per-row header-prefix length
    (0 = body-only: banner rows alias the banner, headerless rows the
    body — model.Response.part() semantics). Host-built "all"
    (width > 1, the seq-sharded path) passes through untouched.
    """
    allv = streams.get("all")
    if allv is None or allv.shape[1] > 1 or "all_hdr" not in lengths:
        return streams
    body = streams["body"]
    header = streams["header"]
    B, Wb = body.shape
    Wh = header.shape[1]
    Wa = ((Wb + Wh + 2 + 127) // 128) * 128
    hl = lengths["all_hdr"].astype(jnp.int32)[:, None]  # 0 = body-only
    bl = lengths["body"].astype(jnp.int32)[:, None]
    j = jnp.arange(Wa, dtype=jnp.int32)[None, :]
    off = jnp.where(hl > 0, hl + 2, 0)
    is_hdr = j < hl
    hvals = jnp.take_along_axis(
        header, jnp.broadcast_to(jnp.minimum(j, Wh - 1), (B, Wa)), axis=1
    )
    bpos = j - off
    is_body = (bpos >= 0) & (bpos < bl)
    bvals = jnp.take_along_axis(
        body, jnp.broadcast_to(jnp.clip(bpos, 0, Wb - 1), (B, Wa)), axis=1
    )
    is_crlf = (hl > 0) & (j >= hl) & (j < hl + 2)
    crlf = jnp.where(j == hl, jnp.uint8(13), jnp.uint8(10))
    synth = jnp.where(
        is_hdr,
        hvals,
        jnp.where(is_crlf, crlf, jnp.where(is_body, bvals, jnp.uint8(0))),
    )
    out = dict(streams)
    out["all"] = synth
    return out


def _match_impl(
    db: fpc.CompiledDB, candidate_k: int, streams, lengths, status, full=False
):
    streams = ensure_all_stream(streams, lengths)
    value_bits, uncertain_bits, overflow = match_slots(
        db, candidate_k, streams, lengths
    )
    digest = None
    if bool(db.m_md5_check.any()) and "body" in streams:
        from swarm_tpu.ops.md5 import md5_words

        digest = md5_words(streams["body"], lengths["body"])
    rx = None
    if len(db.rx_m_ids):
        from swarm_tpu.ops.regexdev import regex_verify

        B = next(iter(streams.values())).shape[0]
        rx = regex_verify(
            db, streams, lengths, value_bits, k_pairs=db.rx_k_pairs(B)
        )
    out = eval_verdicts(
        db,
        value_bits,
        uncertain_bits,
        lengths,
        status,
        full=full,
        md5_digest=digest,
        rx=rx,
    )
    return (*out, overflow)
