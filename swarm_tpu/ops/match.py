"""Device match kernel: response streams → word-slot bits → verdicts.

Pure jnp/XLA (a fused Pallas variant comes later); everything is static
shape, vector ops, gathers from small tables, and a handful of scatters.
Pipeline per batch (design in fingerprints/compile.py docstring):

1. Rolling q-gram hashes of each (stream, case) in use — shifted
   multiply-adds only.
2. Per word-table: Bloom probe every window (2 gathers from a 32 KiB
   bitmap), top-k the surviving windows, binary-search the sorted h1
   groups, then check entry h2 + suffix-gram h1/h2 and position bounds.
   Hits scatter into a word-slot bit vector; all q-gram hits are marked
   *uncertain* (host confirms sparse hits — exactness contract).
3. Tiny slots (1–3 bytes) evaluate by dense shifted compare — exact.
4. Verdict lowering: slot buckets → matcher bits (and/or + negation),
   scalar programs (status/size/len dsl), then op and template
   reductions. Uncertainty propagates alongside values.

The kernel's guarantee: a (row, template) pair whose uncertain bit is
clear has the exact oracle verdict; uncertain pairs carry a superset
signal and only ever need host confirmation when something *fired*.
"""

from __future__ import annotations

import functools
import threading
import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# The dispatch path donates the staged per-batch uploads and the
# inter-phase rank plane (DeviceDB._phase_b). The kernel's outputs are
# deliberately tiny packed-bit planes, so XLA usually CANNOT alias a
# donated input into an output and warns about it at compile time —
# that is expected, not a bug: donation here buys early buffer release
# (staged batches free at kernel launch instead of at collect, which
# bounds device footprint with ≥2 batches in flight), not output
# aliasing. Filter exactly that message.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops import hashing
from swarm_tpu.ops.encoding import STREAMS


#: max live compiled step functions on the SHARDED matcher (its pjit
#: cache is still bounded per shape). The single-device DeviceDB's
#: executables take the corpus as arguments (docs/DEVICE_MATCH.md) so
#: a shape entry is small and all buckets of a width class share one;
#: its jit cache is only dropped wholesale past 4x this bound (see
#: DeviceDB.dispatch's shape-churn guard). Coarse width buckets
#: (engine width_multiple=512) and 256-row buckets keep the live shape
#: set tiny either way. Override: SWARM_MAX_COMPILED.
import os as _os

MAX_COMPILED = int(_os.environ.get("SWARM_MAX_COMPILED", "8"))


def lru_fetch(cache: dict, key):
    """Get + refresh (move-to-back) — dict order is the LRU order."""
    val = cache.pop(key, None)
    if val is not None:
        cache[key] = val
    return val


def lru_store(cache: dict, key, val, cap: int = 0) -> None:
    while cap and len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = val


def fused_plane_widths(db: "fpc.CompiledDB") -> list:
    """Byte widths of the ``full``-mode output planes in fused order:
    t_value, t_unc, op_value, op_unc, m_unc (packed bits), then — only
    when the corpus lowered workflow gate tables — wf_cond_v,
    wf_cond_u, wf_emit_v, wf_emit_u, and finally the 1-byte overflow
    column."""
    # widths mirror eval_verdicts' plane allocations exactly: the
    # template planes are padded to max(NT, 1) there (an all-host-tail
    # corpus still emits one packed byte), the op/matcher planes are not
    nbt = (max(db.num_templates, 1) + 7) >> 3
    nbo = (db.op_src.shape[0] + 7) >> 3
    nbm = (db.m_src.shape[0] + 7) >> 3
    widths = [nbt, nbt, nbo, nbo, nbm]
    wf = getattr(db, "wf", None)
    if wf is not None and wf.num_terms:
        nbc = (wf.num_conds + 7) >> 3
        nbe = (wf.num_emits + 7) >> 3
        widths += [nbc, nbc, nbe, nbe]
    widths.append(1)
    return widths


def fuse_planes(planes, overflow):
    """Producer half of the fused full-mode output: pack the five bit
    planes and append the overflow byte column — ONE device array, one
    host read. Keep in lockstep with split_fused below (shared by both
    backends so producer and consumer live in this module)."""
    parts = [jnp.packbits(p, axis=1) for p in planes]
    parts.append(overflow[:, None].astype(jnp.uint8))
    return jnp.concatenate(parts, axis=1)


def split_fused(db: "fpc.CompiledDB", buf: np.ndarray):
    """Slice one fused host buffer back into the engine's six outputs.

    The ``full`` planes ship as ONE device array (see DeviceDB.match):
    a single device-to-host read instead of six. Transfer count — not
    bytes — is what the tunneled-accelerator transport charges for
    (BASELINE.md, relay sync mode: ~seconds per read), and even on
    healthy transports one transfer saves five dispatch round-trips.

    The buffer is normalized to C order here: XLA owns the device
    layout and is free to hand back a Fortran-ordered result (observed
    on TPU for corpus-scale plane shapes), and every downstream
    consumer — plane slicing, packbits math, the native sw_ext_resolve
    pass — assumes row-major.
    """
    buf = np.ascontiguousarray(buf)
    outs = []
    off = 0
    for w in fused_plane_widths(db):
        outs.append(buf[:, off : off + w])
        off += w
    if off != buf.shape[1]:
        # producer/consumer drift would otherwise shear every plane
        # after the mismatch silently (op bits read as template bits)
        raise ValueError(
            f"fused buffer is {buf.shape[1]} bytes wide, plane widths "
            f"sum to {off}"
        )
    if len(outs) == 10:
        pt, pu, opv, opu, mu, cv, cu, ev, eu, ovf = outs
        wf = (cv, cu, ev, eu)
    else:
        pt, pu, opv, opu, mu, ovf = outs
        wf = None
    return pt, pu, opv, opu, mu, ovf[:, 0] != 0, wf


_DEV_METRICS: dict = {}


def _device_metrics() -> dict:
    """Lazy device-kernel metric families (kept out of import time so
    oracle-only users never touch the registry). The staging/compaction
    families live in :mod:`swarm_tpu.telemetry.device_export` (created
    at telemetry import so every process's ``/metrics`` renders them);
    this merges both maps."""
    if not _DEV_METRICS:
        from swarm_tpu.telemetry import REGISTRY, device_export

        _DEV_METRICS["compile_seconds"] = REGISTRY.counter(
            "swarm_device_compile_seconds_total",
            "Seconds spent compiling device match executables",
        )
        _DEV_METRICS["compiles"] = REGISTRY.counter(
            "swarm_device_compile_total",
            "Device match dispatches that compiled a new executable",
        )
        _DEV_METRICS["phase_ms"] = REGISTRY.gauge(
            "swarm_device_phase_ms",
            "Device match per-phase milliseconds from the most recent "
            "instrumented batch (DeviceDB.profile_phases)",
            ("phase",),
        )
        _DEV_METRICS["staged_batches"] = device_export.STAGED_BATCHES
        _DEV_METRICS["staged_bytes"] = device_export.STAGED_BYTES
        _DEV_METRICS["donated"] = device_export.DONATED_DISPATCHES
        _DEV_METRICS["compacted"] = device_export.COMPACTED_DISPATCHES
        _DEV_METRICS["survivor_max"] = device_export.SURVIVOR_MAX
        _DEV_METRICS["verify_k"] = device_export.VERIFY_K
    return _DEV_METRICS


def _env_flag(name: str, default: bool) -> bool:
    raw = _os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "off", "false", "no")


def host_batch_leaves(streams: dict, lengths: dict, status) -> bool:
    """Whether every per-batch input leaf is host numpy — the donation
    precondition shared by :class:`DeviceDB` and the sharded matcher
    (parallel/sharded.py). Caller-owned DEVICE arrays must never be
    donated (the caller may reuse them next call; donation would hand
    it a deleted buffer)."""
    leaves = list(streams.values()) + list(lengths.values()) + [status]
    return all(isinstance(v, np.ndarray) for v in leaves)


class _StagingPool:
    """Per-batch device-upload staging for dispatch.

    Every dispatch uploads fresh ``streams``/``lengths``/``status``
    host arrays; this is the single place that upload happens. With
    ``donate_argnums`` on the consuming kernel (DeviceDB's phase B) the
    staged buffers are handed back to XLA's allocator the moment the
    kernel runs, so the next same-shape upload and the kernel's own
    outputs reuse that memory instead of allocate-upload-free on every
    dispatch; on the non-donated arms the staged set dies with the
    launch, which is the same lifetime the legacy path had. Host
    arrays are always copied on upload (jnp.asarray), so donation can
    never invalidate caller-owned numpy (the engine's recycled encode
    planes keep rotating untouched).

    Accounting only — no aliasing decisions live here: ``uploads`` /
    ``bytes`` back the ``swarm_device_staged_*`` families, updated
    under a lock because dispatch runs on both the scheduler's submit
    thread and the walk-offload worker.
    """

    def __init__(self):
        self.uploads = 0  # guarded-by: _lock
        self.bytes = 0  # guarded-by: _lock
        #: rank planes held by not-yet-launched deferred reductions
        #: (the sharded matcher's double-buffered overlap parks the
        #: per-rank bit planes in a _PendingShard between dispatches —
        #: an extra in-flight plane the pool budget must see)
        self.plane_holds = 0  # guarded-by: _lock
        self.plane_bytes = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def stage(self, streams: dict, lengths: dict, status):
        """Upload one batch; returns (streams, lengths, status) as
        device arrays (pass-through for already-device inputs) plus
        the staged host byte count for this batch."""
        s_j = {k: jnp.asarray(v) for k, v in streams.items()}
        l_j = {k: jnp.asarray(v) for k, v in lengths.items()}
        st_j = jnp.asarray(status)
        n_bytes = int(
            sum(getattr(v, "nbytes", 0) for v in streams.values())
            + sum(getattr(v, "nbytes", 0) for v in lengths.values())
            + int(getattr(status, "nbytes", 0))
        )
        self.account(n_bytes)
        return s_j, l_j, st_j, n_bytes

    def account(self, n_bytes: int) -> None:
        """Record one staged batch whose upload happened elsewhere (the
        sharded matcher's multi-process path builds global jax.Arrays
        itself — the accounting contract stays in this one place)."""
        with self._lock:
            self.uploads += 1
            self.bytes += n_bytes

    def hold_plane(self, n_bytes: int) -> None:
        """One deferred reduction parked its rank planes (device bytes
        that stay live past their dispatch until the launch flushes)."""
        with self._lock:
            self.plane_holds += 1
            self.plane_bytes += int(n_bytes)

    def release_plane(self, n_bytes: int) -> None:
        with self._lock:
            self.plane_holds -= 1
            self.plane_bytes -= int(n_bytes)


class DeviceDB:
    """CompiledDB uploaded to device + the jitted match kernels.

    The corpus arrays are uploaded ONCE (the argument-layout pytree,
    compile.build_device_layout) and passed to the jitted kernels as
    device-resident arguments on every call. The traced programs are
    corpus-size-free: all width buckets of a shape class share one
    executable per batch shape, compile time no longer scales with the
    corpus, and the persistent XLA cache (utils/xlacache.py) hits
    across corpus refreshes. The corpus arrays are never donated —
    every subsequent call reuses them in place.

    Production dispatch is SPLIT-PHASE with survivor compaction
    (docs/DEVICE_MATCH.md): a standing phase-A executable (stacked
    bloom probe → survivor rank plane + per-batch max survivor count)
    runs first; the host reads back ONE scalar (the max), rounds it up
    the power-of-two bucket ladder (compile.survivor_bucket), and
    launches phase B — candidate extraction, gather-verify, tiny,
    regex, verdict lowering — at that compacted width instead of the
    global candidate budget. Per-batch ``streams``/``lengths``/
    ``status`` go through the dispatch staging pool and are DONATED to
    phase B (with the inter-phase rank plane), so XLA reuses the
    staged buffers for kernel outputs across batches. Both knobs are
    runtime-flippable (``compact`` / ``donate`` attributes; env
    ``SWARM_DEVICE_COMPACT`` / ``SWARM_DEVICE_DONATE``); the fused
    non-donated single-kernel arm is kept as the legacy reference twin
    (the bench's dispatch A/B baseline), bit-identical by construction
    since both routes share one verify + verdict lowering.

    ``compile_seconds`` / ``compile_count`` accumulate the wall time
    and count of DISPATCHES that triggered at least one fresh
    executable (measured at the dispatch boundary — launch + phase-A
    wait included, not phase-B compute).

    Cross-thread hand-off (docs/HOST_WALK.md): with the scheduler's
    walk offload, :meth:`dispatch` runs on the submit thread while the
    walk worker calls :meth:`collect` on an earlier batch's output —
    JAX serializes the device work itself, and the WHOLE compile-spy
    read/launch/read/evict sequence runs under ``_counter_lock`` so
    two dispatching threads can neither lose increments nor
    mis-attribute another thread's compile (the read-before/read-after
    pair is atomic now)."""

    MAX_COMPILED = MAX_COMPILED  # legacy alias (sharded path shares it)

    def __init__(
        self,
        db: fpc.CompiledDB,
        candidate_k: int = 128,
        compact: Optional[bool] = None,
        donate: Optional[bool] = None,
    ):
        self.db = db
        self.candidate_k = candidate_k
        self.compact = (
            _env_flag("SWARM_DEVICE_COMPACT", True)
            if compact is None
            else bool(compact)
        )
        self.donate = (
            _env_flag("SWARM_DEVICE_DONATE", True)
            if donate is None
            else bool(donate)
        )
        self.compile_seconds = 0.0  # guarded-by: _counter_lock
        self.compile_count = 0  # guarded-by: _counter_lock
        #: AOT executable-cache twin of the compile spy (docs/AOT.md):
        #: dispatches that LOADED at least one published executable
        #: instead of compiling it, and the wall time those loads took
        #: at the dispatch boundary — counted distinctly so the
        #: compile-count spy stays honest on the fetch path
        self.fetch_seconds = 0.0  # guarded-by: _counter_lock
        self.fetch_count = 0  # guarded-by: _counter_lock
        #: most recent compacted dispatch: survivor_max / verify_k /
        #: budget (the "phase B launches at survivor size" evidence —
        #: bench and tools/profile_device surface it)
        self.last_compact: dict = {}  # guarded-by: _counter_lock
        self.staging = _StagingPool()
        self._counter_lock = threading.Lock()
        self._aot = None  # AotClient (attach_aot) — None = compile-only
        self._meta = None
        self._arrays = None  # device-resident argument pytree
        self._arrays_np = None  # host twin of _arrays (delta refresh)
        # full flag -> fused jit fn (legacy arm); "A" -> phase A;
        # ("B", full, donate_streams) -> phase B. Writes only under the
        # lock; the double-checked fast-path .get() reads are benign
        # (dict get is atomic, a miss just takes the locked slow path)
        self._fn_cache: dict = {}  # guarded-by: _counter_lock

    # ------------------------------------------------------------------
    def _ensure_layout(self):
        if self._arrays is None:
            meta, arrays_np = fpc.build_device_layout(self.db)
            self._meta = meta
            self._arrays_np = arrays_np
            # upload once; jnp.asarray leaves numpy → device committed
            self._arrays = jax.tree_util.tree_map(jnp.asarray, arrays_np)
        return self._meta, self._arrays

    # -- AOT executable cache (docs/AOT.md) ----------------------------
    def attach_aot(self, client) -> None:
        """Attach an :class:`~swarm_tpu.aot.AotClient`: every
        subsequently built kernel wrapper becomes an
        :class:`~swarm_tpu.aot.AotJit` that fetches published
        executables before compiling (and publishes what it compiles).
        Live wrappers are dropped so the attach takes effect at the
        next dispatch; ``None`` detaches."""
        with self._counter_lock:
            self._aot = client
            self._fn_cache.clear()

    def _trace_salt(self, db=None, meta=None) -> str:
        """Everything the traced programs depend on besides argument
        shapes (the aval signature covers those) and the corpus BYTES
        (corpus-free by the PR 3 argument convention): layout metadata
        and the static ints the kernel closures read off ``db``."""
        if db is None:
            db = self.db
        if meta is None:
            meta, _ = self._ensure_layout()
        return repr(
            (
                meta,
                self.candidate_k,
                db.num_slots,
                db.num_templates,
                int(db.op_src.shape[0]),
                int(db.m_src.shape[0]),
                int(db.rx_seq_always.sum()),
            )
        )

    def _layout_signature(self, db, meta, arrays_np) -> tuple:
        """The full trace signature of one (db, layout) pair: the
        trace salt plus every layout leaf's (path, shape, dtype). Two
        equal signatures lower IDENTICAL programs, so the live
        executables can keep serving across a corpus refresh — the
        corpus rides the arguments (docs/DEVICE_MATCH.md), a verdict
        can only depend on the array CONTENT the next dispatch
        passes."""
        leaves = jax.tree_util.tree_flatten_with_path(arrays_np)[0]
        return (
            self._trace_salt(db, meta),
            tuple(
                (
                    jax.tree_util.keystr(p),
                    tuple(leaf.shape),
                    str(leaf.dtype),
                )
                for p, leaf in leaves
            ),
        )

    def update_layout(self, db_new) -> dict:
        """Zero-downtime corpus refresh (docs/AOT.md): swap in a new
        CompiledDB, uploading ONLY the layout leaves the delta build
        actually changed — a leaf adopted by object identity
        (``compile.build_device_layout_delta``) keeps its existing
        DEVICE array, no H2D transfer. When the trace signature is
        unchanged (shapes and statics equal — e.g. a template EDIT
        that keeps every width), the live executables keep serving
        and the refresh costs only the changed uploads; otherwise the
        wrapper cache drops and the next dispatch compiles or AOT-
        fetches against the new shapes.

        Caller contract: quiesce dispatches first (no batch in
        flight) — the engine's :meth:`~swarm_tpu.ops.engine.
        MatchEngine.refresh_corpus` is the supported entry point."""
        meta_old, _ = self._ensure_layout()
        old_np = self._arrays_np
        meta_new, new_np = fpc.build_device_layout(db_new)
        old_host = {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_flatten_with_path(old_np)[0]
        }
        old_dev = {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_flatten_with_path(
                self._arrays
            )[0]
        }
        flat_new, _ = jax.tree_util.tree_flatten_with_path(new_np)
        uploaded = reused = 0
        dev_leaves = []
        for path, leaf in flat_new:
            key = jax.tree_util.keystr(path)
            if old_host.get(key) is leaf and key in old_dev:
                dev_leaves.append(old_dev[key])
                reused += 1
            else:
                dev_leaves.append(jnp.asarray(leaf))
                uploaded += 1
        new_dev = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(new_np), dev_leaves
        )
        keep = self._layout_signature(
            self.db, meta_old, old_np
        ) == self._layout_signature(db_new, meta_new, new_np)
        with self._counter_lock:
            self.db = db_new
            self._meta = meta_new
            self._arrays_np = new_np
            self._arrays = new_dev
            if not keep:
                self._fn_cache.clear()
        return {
            "uploaded_leaves": uploaded,
            "reused_leaves": reused,
            "executables_kept": keep,
        }

    def _wrap_jit(
        self, fun, kernel_id: str, static_argnums=(), donate_argnums=()
    ):
        """``jax.jit`` (no AOT client — today's path, bit-for-bit) or
        the explicitly managed :class:`AotJit` twin."""
        if self._aot is None:
            return jax.jit(
                fun,
                static_argnums=static_argnums,
                donate_argnums=donate_argnums,
            )
        from swarm_tpu.aot.jitcache import AotJit

        return AotJit(
            fun,
            kernel_id=kernel_id,
            salt=self._trace_salt(),
            client=self._aot,
            static_argnums=static_argnums,
            donate_argnums=donate_argnums,
            cap=4 * self.MAX_COMPILED,
        )

    def fetched_executable_count(self, full: bool = True) -> int:
        """Live executables serving this DB that were LOADED from the
        AOT cache instead of compiled — the fetch-path twin of
        :meth:`executable_count` (which counts local compiles only).
        Includes the standing phase-A kernel: a warm-fetch bring-up
        should compile nothing at all."""
        from swarm_tpu.aot.jitcache import fetched_size_of

        n = 0
        for key in (full, ("B", full, True), ("B", full, False), "A"):
            fn = self._fn_cache.get(key)
            if fn is not None:
                n += fetched_size_of(fn)
        return n

    def aot_prewarm(self) -> int:
        """Bring-up fetch (worker/runtime.py): pool every published
        executable for this process's program group so the first
        dispatch of each published shape class loads instead of
        compiling. No-op without an attached client."""
        client = self._aot
        return client.prewarm() if client is not None else 0

    def _budget(self) -> int:
        meta, _ = self._ensure_layout()
        return global_candidate_budget(
            self.candidate_k, len(meta.table_stream)
        )

    def _kernel(self, full: bool):
        """The fused single-kernel arm (legacy reference twin)."""
        # double-checked under _counter_lock: two threads first-touching
        # the same shape class must share ONE jitted wrapper, or each
        # compiles its own twin and the spy double-counts the compile
        fn = self._fn_cache.get(full)
        if fn is not None:
            return fn
        with self._counter_lock:
            fn = self._fn_cache.get(full)
            if fn is None:
                db, k = self.db, self.candidate_k
                meta, _ = self._ensure_layout()

                # jit-captures: db, meta, k, full (host metadata +
                # scalars — trace-static by construction; the corpus
                # rides the `arrays` ARGUMENT, never the closure)
                def kernel(arrays, streams, lengths, status):
                    out = _match_impl_args(
                        db, meta, k, arrays, streams, lengths, status,
                        full=full,
                    )
                    if full:
                        # bit-plane outputs ship packed (MSB-first,
                        # np.packbits convention): ~9× less host
                        # transfer — and FUSED into one array so the
                        # host makes exactly one device read
                        # (split_fused slices it back)
                        *planes, overflow = out
                        return fuse_planes(planes, overflow)
                    return out

                fn = self._wrap_jit(kernel, f"dd.fused.full={full}")
                self._fn_cache[full] = fn
        return fn

    def _phase_a(self):
        """Standing phase-A executable: staged streams → survivor rank
        plane, overflow vector, and the batch's max survivor count
        (the ONE scalar the host reads between phases). Built once;
        jit's shape cache serves every width bucket."""
        fn = self._fn_cache.get("A")
        if fn is not None:
            return fn
        with self._counter_lock:
            fn = self._fn_cache.get("A")
            if fn is None:
                meta, _ = self._ensure_layout()
                budget = self._budget()

                # jit-captures: meta, budget (layout metadata + a
                # python int; both trace-static)
                def kernel_a(arrays, streams, lengths):
                    streams = ensure_all_stream(streams, lengths)
                    ctx = _StreamCtx(streams, lengths)
                    cnt, _cs = prefilter_counts(meta, arrays["tab"], ctx)
                    n_surv = cnt[:, -1]
                    K = max(1, min(budget, cnt.shape[1]))
                    overflow = n_surv > K
                    nmax = jnp.max(jnp.minimum(n_surv, K))
                    return cnt, overflow, nmax

                fn = self._wrap_jit(kernel_a, "dd.A")
                self._fn_cache["A"] = fn
        return fn

    def _phase_b(self, full: bool, donate_streams: bool):
        """Phase-B executable family: survivor extraction at the
        static ladder width ``kc`` + gather-verify + tiny + regex +
        verdict lowering. The staged per-batch uploads and the
        inter-phase rank plane are donated so XLA reuses their buffers
        for outputs (``donate_streams=False`` — caller-owned device
        inputs — still donates the rank plane, which this DB owns)."""
        key = ("B", full, donate_streams)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        with self._counter_lock:
            fn = self._fn_cache.get(key)
            if fn is not None:
                return fn
            db, k = self.db, self.candidate_k
            meta, _ = self._ensure_layout()

            # jit-captures: db, meta, k, full (same contract as the
            # fused kernel: metadata and scalars only)
            def kernel_b(kc, arrays, streams, lengths, status, cnt,
                         overflow):
                streams = ensure_all_stream(streams, lengths)
                ctx = _StreamCtx(streams, lengths)
                budget = max(
                    1,
                    min(
                        global_candidate_budget(k, len(meta.table_stream)),
                        cnt.shape[1],
                    ),
                )
                col = compact_candidates(cnt, kc, budget)
                col_starts = _col_starts_of(meta, streams)
                value_bits, uncertain_bits = verify_candidates(
                    meta,
                    arrays["tab"],
                    arrays["slot_bytes"],
                    arrays["slot_len"],
                    ctx,
                    col,
                    col_starts,
                    db.num_slots,
                )
                value_bits = tiny_slot_bits(
                    meta, arrays["tiny_bytes"], arrays["tiny_slot"], ctx,
                    value_bits,
                )
                out = _finish_match(
                    db, meta, arrays, streams, lengths, status,
                    value_bits, uncertain_bits, overflow, full=full,
                )
                if full:
                    *planes, ovf = out
                    return fuse_planes(planes, ovf)
                return out

            donate = (
                (2, 3, 4, 5, 6) if donate_streams else (5, 6)
            )  # streams, lengths, status, cnt, overflow | cnt, overflow
            fn = self._wrap_jit(
                kernel_b,
                f"dd.B.full={full}",
                static_argnums=(0,),
                donate_argnums=donate,
            )
            self._fn_cache[key] = fn
        return fn

    def executable_count(self, full: bool = True) -> int:
        """Live compiled executables serving the ``full``-mode verify
        (the compile-count spy the width-bucket tests use): the phase-B
        family on the compacted path plus the fused legacy arm."""
        n = 0
        for key in (full, ("B", full, True), ("B", full, False)):
            fn = self._fn_cache.get(key)
            if fn is not None and hasattr(fn, "_cache_size"):
                n += int(fn._cache_size())
        return n

    def lowered_text(
        self, streams: dict, lengths: dict, status, full: bool = True
    ) -> str:
        """StableHLO text of the production kernel(s) for these shapes
        — the corpus-constants regression test inspects this. On the
        compacted path this is phase A and phase B concatenated (both
        must be corpus-free); with ``compact`` off, the fused twin."""
        meta, arrays = self._ensure_layout()
        s_j = {k: jnp.asarray(v) for k, v in streams.items()}
        l_j = {k: jnp.asarray(v) for k, v in lengths.items()}
        st_j = jnp.asarray(status)
        if not (self.compact and len(meta.table_stream)):
            fn = self._kernel(full)
            return fn.lower(arrays, s_j, l_j, st_j).as_text()
        fa = self._phase_a()
        # inspection only — lower phase B against shape avatars of
        # phase A's outputs (corpus-freeness holds at every ladder
        # rung, so the smallest one serves) instead of executing the
        # prefilter on device just to render text
        cnt_s, overflow_s, _ = jax.eval_shape(fa, arrays, s_j, l_j)
        kc = fpc.survivor_bucket(0, self._budget())
        fb = self._phase_b(full, self.donate)
        return (
            fa.lower(arrays, s_j, l_j).as_text()
            + "\n"
            + fb.lower(
                kc, arrays, s_j, l_j, st_j, cnt_s, overflow_s
            ).as_text()
        )

    # ------------------------------------------------------------------
    def match(self, streams: dict, lengths: dict, status, full: bool = False):
        """streams: name → uint8 [B, W]; lengths: name → int32 [B].

        Returns (t_value [B, NT] bool, t_uncertain [B, NT] bool,
        overflow [B] bool); with ``full`` the op/matcher planes are
        included: (t_value, t_unc, op_value, op_unc, m_unc, overflow)
        — the engine's sparse-confirmation inputs, packed, and already
        materialized as HOST numpy views of one fused device read
        (split_fused).
        """
        out = self.dispatch(streams, lengths, status, full=full)
        if full:
            return self.collect(out)
        return out

    @staticmethod
    def _all_host(streams: dict, lengths: dict, status) -> bool:
        """Donation precondition — see :func:`host_batch_leaves` (the
        module helper, shared with the sharded matcher); dispatches
        with caller-owned device inputs take the non-donated phase-B
        variant instead."""
        return host_batch_leaves(streams, lengths, status)

    def _spied_launch(self, fns: list, launch):
        """Run ``launch()`` with the compile spy held atomically: the
        cache-size read-before/read-after pair, the counter updates,
        and the shape-churn eviction all happen under ``_counter_lock``
        so concurrent dispatching threads (scheduler submit + walk
        offload, or two engines) can't interleave and lose or
        double-count a compile. The lock does serialize concurrent
        dispatches on one DeviceDB for the duration of ``launch()``
        (incl. the compacted path's phase-A scalar sync) — accepted:
        production has a single dispatching thread per DB (the walk
        offload thread only collects), so the lock is uncontended
        there, and attribution under the 4x eviction cannot be made
        race-free with snapshot-outside-lock reads."""
        import time as _time

        from swarm_tpu.aot.jitcache import fetched_size_of

        spies = [fn for fn in fns if hasattr(fn, "_cache_size")]
        with self._counter_lock:
            n0 = sum(fn._cache_size() for fn in spies)
            f0 = sum(fetched_size_of(fn) for fn in spies)
            t0 = _time.perf_counter()
            out = launch()
            dt = _time.perf_counter() - t0
            grew = sum(fn._cache_size() for fn in spies) - n0
            grew_f = sum(fetched_size_of(fn) for fn in spies) - f0
            if grew_f > 0:
                # a deserialized AOT load is NOT a compile (docs/AOT.md
                # — the fetch-path honesty contract): it gets its own
                # spy pair; a dispatch that fetched one phase and
                # compiled the other counts on both
                self.fetch_seconds += dt
                self.fetch_count += 1
            if grew > 0:
                self.compile_seconds += dt
                self.compile_count += 1
                m = _device_metrics()
                m["compile_seconds"].inc(dt)
                m["compiles"].inc(1)
                # shape-churn guard: jax.jit never evicts entries, so
                # adversarial width/row variety would grow the caches
                # without bound. Executables are corpus-free (small),
                # hence the generous 4x bound; past it the whole cache
                # drops — a rare recompile beats unbounded RSS.
                for fn in spies:
                    if fn._cache_size() > 4 * self.MAX_COMPILED and hasattr(
                        fn, "clear_cache"
                    ):
                        fn.clear_cache()
        return out

    def dispatch(self, streams: dict, lengths: dict, status, full: bool = True):
        """Async half of :meth:`match`: stage the batch, launch the
        kernel(s), and return the (device-resident, still-computing)
        fused output WITHOUT a full host transfer. JAX dispatch is
        asynchronous, so the kernels crunch while the caller does other
        host work — the continuous-batching scheduler dispatches batch
        i+1 here before walking batch i's verdicts. :meth:`collect`
        finalizes.

        On the compacted path the only blocking point is the phase-A
        max-survivor scalar read (4 bytes) that picks phase B's ladder
        width; phase B itself is launched asynchronously at that
        width."""
        from swarm_tpu.resilience.faults import fault_point
        from swarm_tpu.telemetry import tracing

        # always-on flight-ring record BEFORE the fault point: when a
        # seeded device.dispatch fault fires, the resulting flight dump
        # carries the dispatch that tripped it (docs/OBSERVABILITY.md)
        tracing.flight_event("device.dispatch")
        # device-path chaos lever (docs/RESILIENCE.md): stands in for
        # XLA compile errors / OOM / cache corruption; MatchEngine
        # catches the failure and degrades to the exact CPU oracle
        fault_point("device.dispatch")
        meta, arrays = self._ensure_layout()
        if not (self.compact and len(meta.table_stream)):
            # fused legacy/reference arm (also the no-tables corpus,
            # where there is nothing to compact)
            fn = self._kernel(full)
            s_j, l_j, st_j, staged = self.staging.stage(
                streams, lengths, status
            )
            m = _device_metrics()
            m["staged_batches"].inc(1)
            m["staged_bytes"].inc(staged)
            return self._spied_launch(
                [fn], lambda: fn(arrays, s_j, l_j, st_j)
            )

        donate_streams = self.donate and self._all_host(
            streams, lengths, status
        )
        fa = self._phase_a()
        fb = self._phase_b(full, donate_streams)
        s_j, l_j, st_j, staged = self.staging.stage(
            streams, lengths, status
        )
        budget = self._budget()
        m = _device_metrics()

        # requires-lock: _counter_lock (invoked via _spied_launch)
        def launch():
            t_a = time.perf_counter()
            cnt, overflow, nmax = fa(arrays, s_j, l_j)
            # the ONE host sync between phases: a scalar read that
            # sizes phase B to live work instead of worst-case budget
            # host-sync-ok: the blessed 4-byte phase-A survivor scalar
            n_live = int(nmax)
            # the scalar read blocks on phase A, so the wall up to here
            # IS phase A — MatchEngine pops it into EngineStats
            # phase_a/phase_b attribution (one consumer per dispatch)
            phase_a_s = time.perf_counter() - t_a
            kc = fpc.survivor_bucket(n_live, budget)
            out = fb(kc, arrays, s_j, l_j, st_j, cnt, overflow)
            self.last_compact = {
                "survivor_max": n_live,
                "verify_k": kc,
                "budget": budget,
                "phase_a_s": phase_a_s,
            }
            return out

        out = self._spied_launch([fa, fb], launch)
        m["staged_batches"].inc(1)
        m["staged_bytes"].inc(staged)
        m["compacted"].inc(1)
        if donate_streams:
            m["donated"].inc(1)
        lc = self.last_compact
        m["survivor_max"].set(lc["survivor_max"])
        m["verify_k"].set(lc["verify_k"])
        return out

    def collect(self, out):
        """Blocking half of the full-mode split: one host read of the
        fused plane array, sliced into the engine's six outputs."""
        return split_fused(self.db, np.asarray(out))

    # ------------------------------------------------------------------
    def profile_phases(self, streams: dict, lengths: dict, status) -> dict:
        """Per-phase device milliseconds for ONE batch — the
        attribution surface behind ``tools/profile_device.py`` and the
        ``swarm_device_phase_ms`` gauges.

        Runs each phase as its own jitted call with a blocking sync
        between phases, so the numbers attribute where fresh-batch
        milliseconds go (prefilter / compact / gather / verify / regex
        lanes / verdict / transfer). Phase boundaries mirror the
        production split-phase dispatch: ``prefilter`` is the standing
        phase-A rank-plane kernel, ``compact`` the survivor extraction
        at the batch's measured ladder width, and gather/verify run AT
        THAT WIDTH — ``self.last_compact`` records the
        survivor_max/verify_k/budget evidence. ``verify`` is reported
        as (full phase B) − (hash-screen-only phase B).
        """
        import functools as _functools
        import time as _time

        db, k = self.db, self.candidate_k
        meta, arrays = self._ensure_layout()
        s_j = {k2: jnp.asarray(v) for k2, v in streams.items()}
        l_j = {k2: jnp.asarray(v) for k2, v in lengths.items()}
        st_j = jnp.asarray(status)
        ns = db.num_slots

        def run(fn, *a):
            r = fn(*a)
            jax.block_until_ready(r)
            t0 = _time.perf_counter()
            r = fn(*a)  # timed second call: steady-state, post-compile
            jax.block_until_ready(r)
            return r, (_time.perf_counter() - t0) * 1e3

        budget = global_candidate_budget(k, len(meta.table_stream))

        @jax.jit
        def f_pre(arrays, streams, lengths):  # jit-captures: meta, budget
            streams = ensure_all_stream(streams, lengths)
            ctx = _StreamCtx(streams, lengths)
            cnt, _cs = prefilter_counts(meta, arrays["tab"], ctx)
            n_surv = cnt[:, -1]
            K = max(1, min(budget, cnt.shape[1]))
            return cnt, n_surv > K, jnp.max(jnp.minimum(n_surv, K))

        @_functools.partial(jax.jit, static_argnums=(1,))
        def f_compact(cnt, kc):  # jit-captures: budget
            K = max(1, min(budget, cnt.shape[1]))
            return compact_candidates(cnt, kc, K)

        # col_starts is shape-static: rebuild from the (post-"all"-
        # synthesis) stream widths without tracing anything
        s_full = ensure_all_stream(s_j, l_j)
        col_starts = _col_starts_of(meta, s_full)

        def make_verify(byte_verify):
            # jit-captures: meta, col_starts, ns, byte_verify
            @jax.jit
            def f_ver(arrays, streams, lengths, col):
                streams = ensure_all_stream(streams, lengths)
                ctx = _StreamCtx(streams, lengths)
                return verify_candidates(
                    meta,
                    arrays["tab"],
                    arrays["slot_bytes"],
                    arrays["slot_len"],
                    ctx,
                    col,
                    col_starts,
                    ns,
                    byte_verify=byte_verify,
                )
            return f_ver

        @jax.jit
        def f_tiny(arrays, streams, lengths, vbits):  # jit-captures: meta
            streams = ensure_all_stream(streams, lengths)
            ctx = _StreamCtx(streams, lengths)
            return tiny_slot_bits(
                meta, arrays["tiny_bytes"], arrays["tiny_slot"], ctx, vbits
            )

        @jax.jit
        def f_rx(arrays, streams, lengths, vbits):  # jit-captures: db
            from swarm_tpu.ops.regexdev import regex_verify

            streams = ensure_all_stream(streams, lengths)
            B = next(iter(streams.values())).shape[0]
            return regex_verify(
                db, streams, lengths, vbits,
                k_pairs=db.rx_k_pairs(B), arrays=arrays["rx"],
            )

        # jit-captures: db, meta
        @jax.jit
        def f_verdict(arrays, streams, lengths, status, vbits, ubits, rx):
            streams = ensure_all_stream(streams, lengths)
            digest = None
            if meta.has_md5 and "body" in streams:
                from swarm_tpu.ops.md5 import md5_words

                digest = md5_words(streams["body"], lengths["body"])
            planes = eval_verdicts(
                db, vbits, ubits, lengths, status, full=True,
                md5_digest=digest, rx=rx, arrays=arrays["verdict"],
            )
            return fuse_planes(
                planes, jnp.zeros((planes[0].shape[0],), dtype=bool)
            )

        phases: dict = {}
        T = len(meta.table_stream)
        if T:
            (cnt, _ovf, nmax), phases["prefilter"] = run(
                f_pre, arrays, s_j, l_j
            )
            kc = fpc.survivor_bucket(int(nmax), budget)
            # unguarded-ok: profile_phases is an offline single-threaded
            # attribution path (never races dispatch)
            self.last_compact = {
                "survivor_max": int(nmax), "verify_k": kc, "budget": budget,
            }
            col, phases["compact"] = run(f_compact, cnt, kc)
            _, gather_ms = run(make_verify(False), arrays, s_j, l_j, col)
            (vbits, ubits), full_ms = run(
                make_verify(True), arrays, s_j, l_j, col
            )
            phases["gather"] = gather_ms
            phases["verify"] = max(full_ms - gather_ms, 0.0)
        else:
            B = next(iter(s_j.values())).shape[0]
            vbits = jnp.zeros((B, max(ns, 1)), dtype=bool)
            ubits = jnp.zeros((B, max(ns, 1)), dtype=bool)
            phases["prefilter"] = phases["compact"] = 0.0
            phases["gather"] = phases["verify"] = 0.0
        vbits, phases["tiny"] = run(f_tiny, arrays, s_j, l_j, vbits)
        rx = None
        if meta.n_rx:
            rx, phases["regex"] = run(f_rx, arrays, s_j, l_j, vbits)
        else:
            phases["regex"] = 0.0
        fused, phases["verdict"] = run(
            f_verdict, arrays, s_j, l_j, st_j, vbits, ubits, rx
        )
        t0 = _time.perf_counter()
        np.asarray(fused)
        phases["transfer"] = (_time.perf_counter() - t0) * 1e3
        gauge = _device_metrics()["phase_ms"]
        for name, ms in phases.items():
            gauge.labels(phase=name).set(ms)
        return phases


def _lower_stream(arr):
    is_upper = (arr >= 65) & (arr <= 90)
    return jnp.where(is_upper, arr + 32, arr)


def _shifted(stream, q: int):
    """padded shifted views for window ops."""
    B, W = stream.shape
    padded = jnp.pad(stream, ((0, 0), (0, q)))
    return [padded[:, j : j + W] for j in range(q)]


def table_arrays_of(table: fpc.WordTable) -> dict:
    """The traced-array view of one WordTable (jnp constants by default;
    the sharded path passes per-rank slices instead)."""
    return {
        "group_h1": jnp.asarray(table.group_h1),
        "entry_start": jnp.asarray(table.entry_start),
        "entry_count": jnp.asarray(table.entry_count),
        "entry_h2": jnp.asarray(table.entry_h2),
        "entry_slot": jnp.asarray(table.entry_slot),
        "entry_off": jnp.asarray(table.entry_off),
        "entry_len": jnp.asarray(table.entry_len),
        "entry_suf_delta": jnp.asarray(table.entry_suf_delta),
        "entry_suf_h1": jnp.asarray(table.entry_suf_h1),
        "entry_suf_h2": jnp.asarray(table.entry_suf_h2),
        "bloom": jnp.asarray(table.bloom),
    }


def match_slots(
    db: fpc.CompiledDB,
    candidate_k: int,
    streams,
    lengths,
    table_arrays: Optional[list] = None,
    pos_offset: int = 0,
    back_halo: int = 0,
    fwd_halo: int = 0,
):
    """→ (value_bits [B, NS] bool, uncertain_bits [B, NS] bool, overflow [B]).

    Sequence parallelism support: ``streams`` may be halo-extended
    ([B, back_halo + W_local + fwd_halo]). Candidate windows *start*
    only in the W_local middle region (each global window position is
    owned by exactly one shard) but hash/verify reads may reach into
    both halos — a word whose gram sits in this shard can begin in the
    previous shard's bytes (back halo) and end in the next shard's
    (forward halo). Both halos must be ≥ the longest table entry for
    the superset property to survive sharding. ``pos_offset`` is the
    shard's global byte offset; ``lengths`` are always global.
    """
    ns = db.num_slots
    some = next(iter(streams.values()))
    B = some.shape[0]
    value_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
    uncertain_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
    overflow = jnp.zeros((B,), dtype=bool)

    # --- cached lowered streams and hash arrays ---
    lowered_cache: dict = {}

    def get_stream(name: str, lowered: bool):
        if not lowered:
            return streams[name]
        if name not in lowered_cache:
            lowered_cache[name] = _lower_stream(streams[name])
        return lowered_cache[name]

    hash_cache: dict = {}

    def get_hashes(name: str, lowered: bool, q: int):
        key = (name, lowered, q)
        if key not in hash_cache:
            hash_cache[key] = hashing.window_hashes_jnp(get_stream(name, lowered), q)
        return hash_cache[key]

    def offset_of(name: str):
        # per-stream global byte offset (streams have different widths,
        # so sequence shards start at different global positions per stream)
        if isinstance(pos_offset, dict):
            return pos_offset[name]
        return pos_offset

    # Slot truth bytes for the fused verify — small ([NW, VERIFY_WIDTH],
    # ci slots pre-lowered) and replicated across shards (slot ids are
    # global even when table groups are model-sharded).
    slot_bytes_j = jnp.asarray(db.slot_bytes)
    slot_len_j = jnp.asarray(db.slot_len)

    # --- q-gram tables ---
    for t_idx, table in enumerate(db.tables):
        arrays = (
            table_arrays[t_idx] if table_arrays is not None else table_arrays_of(table)
        )
        h1, h2 = get_hashes(table.stream, table.lowered, table.q)
        We = h1.shape[1]  # extended width (back halo + local + fwd halo)
        W = We - back_halo - fwd_halo  # windows start only in the middle
        slen = lengths[table.stream]  # global length

        flags = hashing.bloom_probe_jnp(
            arrays["bloom"],
            h1[:, back_halo : back_halo + W],
            h2[:, back_halo : back_halo + W],
        )
        # windows starting past slen - q can't begin a real gram
        positions = jnp.arange(W, dtype=jnp.int32)
        gpositions = positions + offset_of(table.stream)
        flags = flags & (gpositions[None, :] <= (slen - table.q)[:, None])

        k = min(candidate_k, W)
        vals = jnp.where(flags, positions[None, :] + 1, 0)
        top_vals, _ = jax.lax.top_k(vals, k)
        pos = top_vals - 1  # -1 = invalid (local window coordinate)
        valid = pos >= 0
        cpos = jnp.maximum(pos, 0) + back_halo  # extended coordinate
        overflow = overflow | (jnp.sum(flags, axis=1) > k)

        h1c = jnp.take_along_axis(h1, cpos, axis=1)
        h2c = jnp.take_along_axis(h2, cpos, axis=1)

        group_h1 = arrays["group_h1"]
        gidx = jnp.searchsorted(group_h1, h1c)
        G = group_h1.shape[0]
        gidx_c = jnp.minimum(gidx, G - 1)
        found = valid & (group_h1[gidx_c] == h1c)

        e_start = arrays["entry_start"][gidx_c]
        e_count = arrays["entry_count"][gidx_c]
        entry_h2 = arrays["entry_h2"]
        entry_slot = arrays["entry_slot"]
        entry_off = arrays["entry_off"]
        entry_len = arrays["entry_len"]
        entry_sufd = arrays["entry_suf_delta"]
        entry_sufh1 = arrays["entry_suf_h1"]
        entry_sufh2 = arrays["entry_suf_h2"]

        b_idx = jnp.arange(B, dtype=jnp.int32)[:, None] * jnp.ones(
            (1, k), dtype=jnp.int32
        )

        stream_v = get_stream(table.stream, table.lowered)
        offs = jnp.arange(fpc.VERIFY_WIDTH, dtype=jnp.int32)

        # EVERY entry hit is byte-verified (the compile.py:16-17
        # contract): gather the slot's true bytes under the window and
        # compare. Equal and len ≤ VERIFY_WIDTH ⇒ the hit is *certain*
        # (no host confirm). Unequal ⇒ a hash collision: provably no
        # match at this window, so no bit is set at all. Equal prefix of
        # a longer slot ⇒ value + uncertain (host checks the tail).
        # Per-entry (not first-hit-per-window) verification matters:
        # words sharing their chosen gram land in one h1 group and can
        # all pass the hash checks at one window — each needs its own
        # byte compare. max_group (≤ compile.MAX_GROUP normally; up to
        # compile.HARD_GROUP when gram shedding degrades) bounds the
        # extra gathers.
        for g in range(table.max_group):
            e = jnp.minimum(e_start + g, entry_h2.shape[0] - 1)
            in_group = found & (g < e_count)
            h2_ok = entry_h2[e] == h2c
            # suffix-gram check from the same rolling-hash arrays; the
            # suffix may live in the halo region (sequence parallelism)
            spos = cpos + entry_sufd[e]
            spos_c = jnp.clip(spos, 0, We - 1)
            suf_ok = (
                (jnp.take_along_axis(h1, spos_c, axis=1) == entry_sufh1[e])
                & (jnp.take_along_axis(h2, spos_c, axis=1) == entry_sufh2[e])
                & (spos >= 0)
                & (spos < We)
            )
            # global bounds: word fully inside the true part bytes
            gstart = (cpos - back_halo) + offset_of(table.stream) - entry_off[e]
            fits = (gstart >= 0) & (gstart + entry_len[e] <= slen[:, None])
            # extended-view bounds: with halos ≥ max entry length these
            # only bite in the unsharded case (buffer edges)
            fits = fits & (cpos - entry_off[e] >= 0) & (
                cpos - entry_off[e] + entry_len[e] <= We
            )
            hit = in_group & h2_ok & suf_ok & fits
            slot = entry_slot[e]
            start = cpos - entry_off[e]  # extended coord of word start
            lv = jnp.minimum(entry_len[e], fpc.VERIFY_WIDTH)
            idx = start[:, :, None] + offs[None, None, :]  # [B, k, V]
            idx_c = jnp.clip(idx, 0, We - 1)
            gathered = jnp.take_along_axis(
                stream_v, idx_c.reshape(B, -1), axis=1
            ).reshape(B, k, fpc.VERIFY_WIDTH)
            expected = slot_bytes_j[slot]  # [B, k, V]
            pos_ok = offs[None, None, :] < lv[:, :, None]
            eq = ((gathered == expected) | ~pos_ok).all(-1)
            long = slot_len_j[slot] > fpc.VERIFY_WIDTH
            fired = hit & eq
            value_bits = value_bits.at[b_idx, slot].max(fired)
            uncertain_bits = uncertain_bits.at[b_idx, slot].max(fired & long)

    # --- tiny slots: dense shifted compare (exact) ---
    tiny_count = int((np.asarray(db.tiny_len) > 0).sum())
    shift_cache: dict = {}
    for i in range(tiny_count):
        length = int(db.tiny_len[i])
        slot_id = int(db.tiny_slot[i])
        stream_name = STREAMS[int(db.tiny_stream[i])]
        lowered = bool(db.tiny_lowered[i])
        skey = (stream_name, lowered)
        if skey not in shift_cache:
            shift_cache[skey] = _shifted(
                get_stream(stream_name, lowered), hashing.TINY_MAX
            )
        shifts = shift_cache[skey]
        We_t = shifts[0].shape[1]
        # global coordinates (halo positions are valid too — the byte
        # compare is exact and the OR across shards dedupes)
        gpositions = (
            jnp.arange(We_t, dtype=jnp.int32) - back_halo + offset_of(stream_name)
        )
        eq = jnp.ones_like(shifts[0], dtype=bool)
        for j in range(length):
            eq = eq & (shifts[j] == int(db.tiny_bytes[i, j]))
        slen = lengths[stream_name]
        eq = eq & (gpositions[None, :] >= 0)
        eq = eq & (gpositions[None, :] <= (slen - length)[:, None])
        # window must lie inside this view's real bytes (an all-zero tiny
        # pattern must not match the zero padding / zero-filled halo edge)
        local = jnp.arange(We_t, dtype=jnp.int32)
        eq = eq & (local[None, :] + length <= We_t)
        hit = eq.any(axis=1)
        value_bits = value_bits.at[:, slot_id].max(hit)

    return value_bits, uncertain_bits, overflow


# ---------------------------------------------------------------------------
# Two-phase fresh-content kernel (corpus as device-resident ARGUMENTS)
# ---------------------------------------------------------------------------
#
# match_slots above is the legacy/reference kernel: a Python loop over
# word tables, each table's arrays inlined as XLA constants and a dense
# per-table top_k over every window. The functions below are the
# production path (docs/DEVICE_MATCH.md):
#
#   phase A  prefilter_candidates — ONE fused bloom/q-gram probe over
#            the whole batch across ALL tables at once (stacked
#            table-major arrays from compile.stack_tables_np), then a
#            single per-row top_k over the concatenated (table, window)
#            candidate axis;
#   phase B  verify_candidates — only the surviving (row, window,
#            table) candidates are gathered: per-candidate binary
#            search into the stacked h1 groups, 128-bit hash screen,
#            and the byte verify — work sized by the SURVIVOR budget,
#            not by tables × windows.
#
# Every corpus array arrives as a traced argument (the layout pytree),
# so the compiled program is corpus-size-free: one executable serves
# every width bucket of a shape class AND every corpus refresh, and the
# persistent XLA cache (utils/xlacache.py) keys stop covering corpus
# bytes. Candidate-overflow contract: a row whose fired windows exceed
# the global budget K sets ``overflow`` and is re-run exactly on the
# host (engine row redo) — a strict superset of the legacy per-table
# condition, so soundness is unchanged.


def global_candidate_budget(candidate_k: int, n_tables: int) -> int:
    """Per-row candidate budget for the global (cross-table) top_k.

    The legacy kernel budgeted ``candidate_k`` PER TABLE (worst case
    ``candidate_k × T``); phase B's cost is proportional to the budget
    on EVERY batch, so the global budget scales sub-linearly with the
    table count instead: ×1 for ≤2 tables up to ×4 for ≥8. A noisy row
    that fires a moderate number of windows in several tables stays on
    device (no overflow host-redo cliff), while the gather-verify
    stays survivor-sized rather than worst-case-sized."""
    return candidate_k * max(1, min(n_tables, 8) // 2)


class _StreamCtx:
    """Per-trace stream/hash caches shared by both kernel phases."""

    def __init__(self, streams: dict, lengths: dict, pos_offset=0):
        self.streams = streams
        self.lengths = lengths
        self.pos_offset = pos_offset
        self._lowered: dict = {}
        self._hashes: dict = {}

    def stream(self, name: str, lowered: bool):
        if not lowered:
            return self.streams[name]
        if name not in self._lowered:
            self._lowered[name] = _lower_stream(self.streams[name])
        return self._lowered[name]

    def hashes(self, name: str, lowered: bool, q: int):
        key = (name, lowered, q)
        if key not in self._hashes:
            self._hashes[key] = hashing.window_hashes_jnp(
                self.stream(name, lowered), q
            )
        return self._hashes[key]

    def offset(self, name: str):
        if isinstance(self.pos_offset, dict):
            return self.pos_offset[name]
        return self.pos_offset


def _combo_groups(meta: "fpc.DeviceLayoutMeta"):
    """Tables grouped by (stream, lowered, q) — the distinct hash
    passes — in first-appearance order. Static."""
    groups: dict = {}
    for t in range(len(meta.table_stream)):
        key = (meta.table_stream[t], meta.table_lowered[t], meta.table_q[t])
        groups.setdefault(key, []).append(t)
    return groups


def _col_starts_of(meta: "fpc.DeviceLayoutMeta", streams: dict) -> np.ndarray:
    """Per-table start offsets on the concatenated candidate axis,
    rebuilt from the (post-``ensure_all_stream``) stream widths —
    shape-static, so safe to call on tracers inside a jit."""
    T = len(meta.table_stream)
    cs = np.zeros(T + 1, dtype=np.int32)
    for t in range(T):
        cs[t + 1] = cs[t] + streams[meta.table_stream[t]].shape[1]
    return cs


def prefilter_counts(
    meta: "fpc.DeviceLayoutMeta",
    tab: dict,
    ctx: _StreamCtx,
    back_halo: int = 0,
    fwd_halo: int = 0,
):
    """Phase A core: fused stacked bloom probe → survivor RANK plane.

    Returns ``(cnt [B, C] int32, col_starts np[T+1])``: ``cnt`` is the
    inclusive running count of fired windows along the concatenated
    table-major (table, window) candidate axis — ``cnt[b, -1]`` is row
    b's total survivor count, and the j-th survivor's column is the
    first index where ``cnt`` reaches j+1 (compact_candidates' binary
    search). The rank plane replaces the former per-row ``top_k`` over
    the full candidate axis: top_k lowers to a whole-axis sort (the
    dominant fresh-batch phase on the CPU backend, ~70% of the fused
    kernel), while the cumulative count is a single linear scan and the
    extraction cost moves to phase B where it is survivor-sized."""
    T = len(meta.table_stream)
    flags_by_table: list = [None] * T
    w_by_table = [0] * T
    for (sname, lowered, q), tids in _combo_groups(meta).items():
        h1, h2 = ctx.hashes(sname, lowered, q)
        We = h1.shape[1]
        W = We - back_halo - fwd_halo
        h1w = h1[:, back_halo : back_halo + W]
        h2w = h2[:, back_halo : back_halo + W]
        # stacked probe: one gather with a leading table axis instead
        # of a bloom_probe per table
        bloom = tab["bloom"][np.asarray(tids, dtype=np.int32)]  # [Tg, BW]
        mask = jnp.uint32(hashing.BLOOM_BITS - 1)
        i1 = (h1w & mask).astype(jnp.int32)
        i2 = (h2w & mask).astype(jnp.int32)
        w1 = bloom[:, i1 >> 5]  # [Tg, B, W]
        w2 = bloom[:, i2 >> 5]
        b1 = (w1 >> (i1 & 31).astype(jnp.uint32)[None]) & 1
        b2 = (w2 >> (i2 & 31).astype(jnp.uint32)[None]) & 1
        fl = (b1 & b2) == 1  # [Tg, B, W]
        # windows starting past slen - q can't begin a real gram
        positions = jnp.arange(W, dtype=jnp.int32)
        gpositions = positions + ctx.offset(sname)
        slen = ctx.lengths[sname]
        fl = fl & (
            gpositions[None, None, :] <= (slen - q)[None, :, None]
        )
        for j, t in enumerate(tids):
            flags_by_table[t] = fl[j]
            w_by_table[t] = W
    col_starts = np.zeros(T + 1, dtype=np.int32)
    for t in range(T):
        col_starts[t + 1] = col_starts[t] + w_by_table[t]
    flags_cat = jnp.concatenate(
        [flags_by_table[t] for t in range(T)], axis=1
    )  # [B, C]
    cnt = jnp.cumsum(flags_cat.astype(jnp.int32), axis=1)
    return cnt, col_starts


def compact_candidates(cnt, kc: int, budget: int):
    """Survivor compaction: extract the first ``kc`` fired columns per
    row from the phase-A rank plane.

    The j-th survivor's column is the first index where the running
    count reaches j+1 — a vectorized binary search over the
    non-decreasing ``cnt`` rows (~log2(C) gathers of [B, kc] elements,
    survivor-sized work instead of candidate-axis-sized). Entries past
    ``min(n_survivors, budget)`` are -1; rows with more than ``budget``
    fired windows keep their first ``budget`` candidates and are
    flagged for the host row-redo by the caller (selection order
    changed from the former top_k's descending-column to ascending —
    candidate order never reaches the slot planes, and overflow rows
    are re-run exactly on the host either way).

    → ``col [B, kc] int32`` indexing the concatenated table-major
    candidate axis, -1 = no candidate.
    """
    B, C = cnt.shape
    target = jnp.arange(1, kc + 1, dtype=jnp.int32)[None, :]  # [1, kc]
    lo = jnp.zeros((B, kc), dtype=jnp.int32)
    hi = jnp.full((B, kc), C, dtype=jnp.int32)
    for _ in range(max(C, 2).bit_length() + 1):
        mid = (lo + hi) >> 1
        v = jnp.take_along_axis(cnt, jnp.minimum(mid, C - 1), axis=1)
        go_right = v < target
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    n_surv = cnt[:, -1:]
    return jnp.where(target <= jnp.minimum(n_surv, budget), lo, -1)


def prefilter_candidates(
    meta: "fpc.DeviceLayoutMeta",
    tab: dict,
    ctx: _StreamCtx,
    candidate_k: int,
    back_halo: int = 0,
    fwd_halo: int = 0,
):
    """Phase A (fused-path form): stacked bloom probe → the first K
    fired windows per row (prefilter_counts + compact_candidates at
    the full budget).

    Returns ``(col [B, K] int32, overflow [B] bool, col_starts
    np[T+1])``: ``col`` indexes the concatenated table-major
    (table, window) candidate axis, -1 = no candidate. ``overflow``
    marks rows with more fired windows than K (host row-redo)."""
    cnt, col_starts = prefilter_counts(meta, tab, ctx, back_halo, fwd_halo)
    c_total = int(col_starts[-1])
    K = max(1, min(candidate_k, c_total))
    col = compact_candidates(cnt, K, K)
    overflow = cnt[:, -1] > K
    return col, overflow, col_starts


def verify_candidates(
    meta: "fpc.DeviceLayoutMeta",
    tab: dict,
    slot_bytes_j,
    slot_len_j,
    ctx: _StreamCtx,
    col,
    col_starts: np.ndarray,
    num_slots: int,
    back_halo: int = 0,
    fwd_halo: int = 0,
    byte_verify: bool = True,
):
    """Phase B: sparse gather-verify over the surviving candidates.

    Per candidate: decode (table, window), fetch the window's rolling
    hashes, binary-search the table's sorted h1 groups (stacked
    [T, Gmax] layout — ~log2(Gmax) scalar gathers instead of a
    searchsorted against a gathered [B, K, Gmax] plane), screen the
    group's entries by the 128 hash bits, byte-verify survivors.

    ``byte_verify=False`` stops after the hash screen (the profiling
    tool's "gather" phase) — the returned planes then over-approximate
    and must not be used for verdicts.

    → (value_bits [B, NS] bool, uncertain_bits [B, NS] bool)
    """
    some = next(iter(ctx.streams.values()))
    B = some.shape[0]
    T = len(meta.table_stream)
    K = col.shape[1]
    value_bits = jnp.zeros((B, max(num_slots, 1)), dtype=bool)
    uncertain_bits = jnp.zeros((B, max(num_slots, 1)), dtype=bool)

    valid = col >= 0
    colc = jnp.maximum(col, 0)
    col_starts_j = jnp.asarray(col_starts)
    tid = (
        jnp.searchsorted(col_starts_j, colc, side="right").astype(jnp.int32)
        - 1
    )
    pos = colc - col_starts_j[tid]  # local window coordinate
    cpos = pos + back_halo  # extended coordinate

    # --- per-candidate static table attributes, via tiny [T] tables ---
    combos = list(_combo_groups(meta))
    t_combo = np.array(
        [
            combos.index(
                (meta.table_stream[t], meta.table_lowered[t], meta.table_q[t])
            )
            for t in range(T)
        ],
        dtype=np.int32,
    )
    vstreams = sorted(
        {(meta.table_stream[t], meta.table_lowered[t]) for t in range(T)}
    )
    t_vs = np.array(
        [
            vstreams.index((meta.table_stream[t], meta.table_lowered[t]))
            for t in range(T)
        ],
        dtype=np.int32,
    )
    t_we = np.array(
        [ctx.streams[meta.table_stream[t]].shape[1] for t in range(T)],
        dtype=np.int32,
    )
    cand_combo = jnp.asarray(t_combo)[tid]
    cand_vs = jnp.asarray(t_vs)[tid]
    cand_we = jnp.asarray(t_we)[tid]
    cand_slen = jnp.take_along_axis(
        jnp.stack(
            [ctx.lengths[meta.table_stream[t]] for t in range(T)], axis=1
        ),
        tid,
        axis=1,
    )
    cand_goff = jnp.stack(
        [
            jnp.asarray(ctx.offset(meta.table_stream[t]), dtype=jnp.int32)
            for t in range(T)
        ]
    )[tid]

    def hash_at(positions):
        """(h1, h2) of each candidate's stream at ``positions`` —
        gather from each combo's hash plane, select by combo id."""
        out1 = jnp.zeros((B, K), dtype=jnp.uint32)
        out2 = jnp.zeros((B, K), dtype=jnp.uint32)
        for ci_, (sname, lowered, q) in enumerate(combos):
            h1, h2 = ctx.hashes(sname, lowered, q)
            p = jnp.clip(positions, 0, h1.shape[1] - 1)
            sel = cand_combo == ci_
            out1 = jnp.where(sel, jnp.take_along_axis(h1, p, axis=1), out1)
            out2 = jnp.where(sel, jnp.take_along_axis(h2, p, axis=1), out2)
        return out1, out2

    h1c, h2c = hash_at(cpos)

    # --- binary search the stacked sorted h1 groups ---
    group_h1 = tab["group_h1"]
    gmax = group_h1.shape[1]
    ng = tab["n_groups"][tid]
    lo = jnp.zeros_like(colc)
    hi = ng
    for _ in range(max(gmax, 1).bit_length() + 1):
        active = lo < hi
        mid = (lo + hi) >> 1
        v = group_h1[tid, jnp.minimum(mid, gmax - 1)]
        right = active & (v < h1c)
        lo = jnp.where(right, mid + 1, lo)
        hi = jnp.where(active & ~right, mid, hi)
    gidx = jnp.minimum(lo, gmax - 1)
    found = valid & (lo < ng) & (group_h1[tid, gidx] == h1c)
    e_start = tab["entry_start"][tid, gidx]
    e_count = tab["entry_count"][tid, gidx]

    emax = tab["entry_h2"].shape[1]
    offs = jnp.arange(fpc.VERIFY_WIDTH, dtype=jnp.int32)
    b_idx = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], (B, K))

    # EVERY entry hit is byte-verified (the compile.py contract) — see
    # match_slots for the per-entry rationale; max_group here is the
    # global bound (per-candidate e_count masks shorter groups).
    for g in range(meta.max_group):
        e = jnp.minimum(e_start + g, emax - 1)
        in_group = found & (g < e_count)
        h2_ok = tab["entry_h2"][tid, e] == h2c
        # suffix-gram check from the same rolling-hash arrays; the
        # suffix may live in the halo region (sequence parallelism)
        spos = cpos + tab["entry_suf_delta"][tid, e]
        s1, s2 = hash_at(spos)
        suf_ok = (
            (s1 == tab["entry_suf_h1"][tid, e])
            & (s2 == tab["entry_suf_h2"][tid, e])
            & (spos >= 0)
            & (spos < cand_we)
        )
        entry_off_e = tab["entry_off"][tid, e]
        entry_len_e = tab["entry_len"][tid, e]
        # global bounds: word fully inside the true part bytes
        gstart = (cpos - back_halo) + cand_goff - entry_off_e
        fits = (gstart >= 0) & (gstart + entry_len_e <= cand_slen)
        # extended-view bounds (buffer edges / halo limits)
        fits = fits & (cpos - entry_off_e >= 0) & (
            cpos - entry_off_e + entry_len_e <= cand_we
        )
        hit = in_group & h2_ok & suf_ok & fits
        slot = tab["entry_slot"][tid, e]
        if byte_verify:
            start = cpos - entry_off_e  # extended coord of word start
            lv = jnp.minimum(entry_len_e, fpc.VERIFY_WIDTH)
            idx = start[:, :, None] + offs[None, None, :]  # [B, K, V]
            expected = slot_bytes_j[slot]  # [B, K, V]
            pos_ok = offs[None, None, :] < lv[:, :, None]
            eq = jnp.zeros((B, K), dtype=bool)
            for vi, (sname, lowered) in enumerate(vstreams):
                sv = ctx.stream(sname, lowered)
                idx_c = jnp.clip(idx, 0, sv.shape[1] - 1)
                gathered = jnp.take_along_axis(
                    sv, idx_c.reshape(B, -1), axis=1
                ).reshape(B, K, fpc.VERIFY_WIDTH)
                eq_v = ((gathered == expected) | ~pos_ok).all(-1)
                eq = jnp.where(cand_vs == vi, eq_v, eq)
            fired = hit & eq
        else:
            fired = hit
        long = slot_len_j[slot] > fpc.VERIFY_WIDTH
        value_bits = value_bits.at[b_idx, slot].max(fired)
        uncertain_bits = uncertain_bits.at[b_idx, slot].max(fired & long)
    return value_bits, uncertain_bits


def tiny_slot_bits(
    meta: "fpc.DeviceLayoutMeta",
    tiny_bytes_j,
    tiny_slot_j,
    ctx: _StreamCtx,
    value_bits,
    back_halo: int = 0,
):
    """Tiny slots (1–3 bytes): dense shifted compare — exact, same
    logic as the legacy path but with the pattern bytes and slot ids
    as traced arguments."""
    shift_cache: dict = {}
    for i, (length, stream_name, lowered) in enumerate(meta.tiny):
        skey = (stream_name, lowered)
        if skey not in shift_cache:
            shift_cache[skey] = _shifted(
                ctx.stream(stream_name, lowered), hashing.TINY_MAX
            )
        shifts = shift_cache[skey]
        We_t = shifts[0].shape[1]
        # global coordinates (halo positions are valid too — the byte
        # compare is exact and the OR across shards dedupes)
        gpositions = (
            jnp.arange(We_t, dtype=jnp.int32)
            - back_halo
            + ctx.offset(stream_name)
        )
        eq = jnp.ones_like(shifts[0], dtype=bool)
        for j in range(length):
            eq = eq & (shifts[j] == tiny_bytes_j[i, j])
        slen = ctx.lengths[stream_name]
        eq = eq & (gpositions[None, :] >= 0)
        eq = eq & (gpositions[None, :] <= (slen - length)[:, None])
        # window must lie inside this view's real bytes (an all-zero
        # tiny pattern must not match the zero padding / halo edge)
        local = jnp.arange(We_t, dtype=jnp.int32)
        eq = eq & (local[None, :] + length <= We_t)
        hit = eq.any(axis=1)
        value_bits = value_bits.at[:, tiny_slot_j[i]].max(hit)
    return value_bits


def match_slots_args(
    db: fpc.CompiledDB,
    meta: "fpc.DeviceLayoutMeta",
    arrays: dict,
    candidate_k: int,
    streams,
    lengths,
    pos_offset=0,
    back_halo: int = 0,
    fwd_halo: int = 0,
):
    """Two-phase twin of :func:`match_slots`: same contract — (value,
    uncertain, overflow) slot planes with the superset/uncertainty
    invariants — with every corpus array a traced argument and the
    candidate budget global per row instead of per table. Sequence
    parallelism (halo-extended streams, global lengths/offsets) works
    exactly as in the legacy kernel."""
    ns = db.num_slots
    some = next(iter(streams.values()))
    B = some.shape[0]
    ctx = _StreamCtx(streams, lengths, pos_offset)
    if len(meta.table_stream):
        budget = global_candidate_budget(
            candidate_k, len(meta.table_stream)
        )
        col, overflow, col_starts = prefilter_candidates(
            meta, arrays["tab"], ctx, budget, back_halo, fwd_halo
        )
        value_bits, uncertain_bits = verify_candidates(
            meta,
            arrays["tab"],
            arrays["slot_bytes"],
            arrays["slot_len"],
            ctx,
            col,
            col_starts,
            ns,
            back_halo,
            fwd_halo,
        )
    else:
        value_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
        uncertain_bits = jnp.zeros((B, max(ns, 1)), dtype=bool)
        overflow = jnp.zeros((B,), dtype=bool)
    value_bits = tiny_slot_bits(
        meta, arrays["tiny_bytes"], arrays["tiny_slot"], ctx,
        value_bits, back_halo,
    )
    return value_bits, uncertain_bits, overflow


def _finish_match(
    db: fpc.CompiledDB,
    meta: "fpc.DeviceLayoutMeta",
    arrays: dict,
    streams,
    lengths,
    status,
    value_bits,
    uncertain_bits,
    overflow,
    full=False,
):
    """Shared tail of every args-kernel route — device md5, device
    regex verify, verdict lowering — factored so the fused twin
    (:func:`_match_impl_args`) and the split survivor-compacted path
    (DeviceDB's phase B) run literally the same lowering and parity
    can't drift. ``streams`` must already be post-``ensure_all_stream``."""
    digest = None
    if meta.has_md5 and "body" in streams:
        from swarm_tpu.ops.md5 import md5_words

        digest = md5_words(streams["body"], lengths["body"])
    rx = None
    if meta.n_rx:
        from swarm_tpu.ops.regexdev import regex_verify

        B = next(iter(streams.values())).shape[0]
        rx = regex_verify(
            db,
            streams,
            lengths,
            value_bits,
            k_pairs=db.rx_k_pairs(B),
            arrays=arrays["rx"],
        )
    out = eval_verdicts(
        db,
        value_bits,
        uncertain_bits,
        lengths,
        status,
        full=full,
        md5_digest=digest,
        rx=rx,
        arrays=arrays["verdict"],
    )
    return (*out, overflow)


def _match_impl_args(
    db: fpc.CompiledDB,
    meta: "fpc.DeviceLayoutMeta",
    candidate_k: int,
    arrays: dict,
    streams,
    lengths,
    status,
    full=False,
):
    """Argument-driven twin of :func:`_match_impl` — the fused jitted
    body (corpus pytree first, so the executable is corpus-free).
    DeviceDB's legacy/reference dispatch arm and ShardedMatcher run
    this; the production single-device path splits the same phases
    around survivor compaction (DeviceDB.dispatch)."""
    streams = ensure_all_stream(streams, lengths)
    value_bits, uncertain_bits, overflow = match_slots_args(
        db, meta, arrays, candidate_k, streams, lengths
    )
    return _finish_match(
        db, meta, arrays, streams, lengths, status,
        value_bits, uncertain_bits, overflow, full=full,
    )


def eval_verdicts(
    db: fpc.CompiledDB,
    value_bits,
    uncertain_bits,
    lengths,
    status,
    full=False,
    md5_digest=None,
    rx=None,
    arrays: Optional[dict] = None,
):
    """Slot bits + scalars → (t_value, t_uncertain) [B, NT] bool.

    With ``full=True`` also returns the intermediate planes
    ``(t_value, t_unc, op_value, op_unc, m_unc)`` so the host can
    resolve an uncertain verdict by re-evaluating only the specific
    uncertain matchers (engine.py) instead of the whole template.
    (No m_value plane: an undecided op's certain matchers are neutral
    by the Kleene argument, so the host never reads their values.)
    When the corpus lowered workflow gate tables (``arrays["wf"]``,
    docs/WORKFLOWS.md), four more planes follow: per-condition value/
    uncertainty and per-emit value/uncertainty from the vectorized
    gate-apply stage.

    Uncertainty is refined with three-valued logic at every reduction:
    a verdict already decided by its *certain* inputs (a certain-true
    input under OR, a certain-false one under AND) is exact no matter
    what the uncertain inputs turn out to be, so its uncertain bit is
    cleared. This is what keeps host confirmation sparse — e.g. a
    status-matcher miss certain-falsifies an AND op and no regex
    sibling ever needs host evaluation.

    ``arrays`` is the verdict half of the argument layout
    (``compile.verdict_arrays_np``): pass the device-resident pytree
    (DeviceDB/ShardedMatcher) and the traced program stays corpus-free;
    omit it and the same arrays are baked in as constants — the legacy
    reference path, byte-identical by construction since both routes
    run this one function.
    """
    if arrays is None:
        arrays = jax.tree_util.tree_map(
            jnp.asarray, fpc.verdict_arrays_np(db)
        )
    B = status.shape[0]
    NM = db.m_kind.shape[0]

    len_body = lengths["body"].astype(jnp.float32)
    len_header = lengths["header"].astype(jnp.float32)
    len_all = lengths["all"].astype(jnp.float32)
    svars = jnp.stack(
        [status.astype(jnp.float32), len_body, len_header, len_all, len_body],
        axis=1,
    )  # [B, SCALAR_VARS]

    # --- slot reductions (vacuously true when a matcher has no slots) ---
    slot_red = jnp.ones((B, NM), dtype=bool)
    m_unc = jnp.zeros((B, NM), dtype=bool)
    cond_and = arrays["m_cond_and"]
    for rows, idx in arrays["m_slot_buckets"]:
        gv = value_bits[:, idx]  # [B, nb, w]
        gu = uncertain_bits[:, idx]
        is_and = cond_and[rows][None, :]
        red = jnp.where(is_and, gv.all(-1), gv.any(-1))
        # Kleene: a certain-hit slot decides OR; a missed slot is always
        # certain (uncertainty only attaches to fired q-gram hits), so
        # any miss decides AND
        decided = jnp.where(
            is_and, (~gv).any(-1), (gv & ~gu).any(-1)
        )
        slot_red = slot_red.at[:, rows].set(red)
        m_unc = m_unc.at[:, rows].set(gu.any(-1) & ~decided)

    # --- negated-contains buckets: NONE of the slots may be present ---
    # (dsl conjuncts like !regex('(?i)x-frame-options', all_headers) —
    # the missing-security-headers shape). Slot absence is always
    # certain; an uncertain *fired* slot leaves presence unknown, so
    # the matcher goes uncertain, and a certain-present slot decides
    # the whole conjunction false.
    neg_present = jnp.zeros((B, NM), dtype=bool)
    neg_decided_false = jnp.zeros((B, NM), dtype=bool)
    for rows, idx in arrays["m_negslot_buckets"]:
        gv = value_bits[:, idx]
        gu = uncertain_bits[:, idx]
        neg_present = neg_present.at[:, rows].set(gv.any(-1))
        neg_decided_false = neg_decided_false.at[:, rows].set(
            (gv & ~gu).any(-1)
        )
        m_unc = m_unc.at[:, rows].max(gu.any(-1))

    # --- scalar programs ---
    var_id = arrays["scalar_var"]  # [NM, C]
    cmp_val = arrays["scalar_cmp"]  # [NM, C] f32
    v = svars[:, var_id]  # [B, NM, C]
    checks = [
        v == cmp_val,  # SOP_EQ
        v != cmp_val,
        v < cmp_val,
        v > cmp_val,
        v <= cmp_val,
        v >= cmp_val,
        jnp.ones_like(v, dtype=bool),  # SOP_TRUE
    ]
    # host-precomputed one-hot op selection (compile.scalar_onehot_np):
    # exactly one check is selected per conjunct, so OR-accumulating
    # the masked checks IS the select — with no [NM, C] id-compare
    # planes left for XLA's constant folder to chew on
    onehot = arrays["scalar_onehot"]  # [NCHECKS, NM, C] bool
    conj = jnp.zeros_like(v, dtype=bool)
    for i, c in enumerate(checks):
        conj = conj | (onehot[i][None] & c)
    scalar_ok = conj.all(-1)  # [B, NM]

    # --- status / size matchers ---
    status_ok = (status[:, None, None] == arrays["m_status"][None]).any(-1)
    len_streams = jnp.stack(
        [lengths[name] for name in STREAMS], axis=1
    )  # [B, len(STREAMS)]
    size_sel = len_streams[:, arrays["m_size_stream"]]  # [B, NM]
    size_ok = (size_sel[:, :, None] == arrays["m_size"][None]).any(-1)

    is_regex_prefilter = arrays["is_rx_prefilter"]
    is_words = arrays["is_words"]
    is_scalar = arrays["is_scalar"]
    is_status = arrays["is_status"]
    is_size = arrays["is_size"]

    # device md5 digest equality (md5(body) == "<hex>" dsl conjuncts).
    # Fail CLOSED without a digest: the matcher keeps its superset value
    # but goes uncertain, so a caller that forgets to supply the digest
    # costs host confirms — never silent false hits.
    has_md5 = bool(db.m_md5_check.any())
    if md5_digest is not None:
        md5_ok = (~arrays["m_md5_check"])[None, :] | (
            md5_digest[:, None, :].astype(jnp.uint32)
            == arrays["m_md5"][None]
        ).all(-1)
    else:
        md5_ok = jnp.ones((B, NM), dtype=bool)
        if has_md5:
            m_unc = m_unc | arrays["m_md5_check"][None, :]

    m_value = jnp.zeros((B, NM), dtype=bool)
    m_value = jnp.where(is_words[None, :], slot_red, m_value)
    m_value = jnp.where(
        is_scalar[None, :],
        scalar_ok & slot_red & ~neg_present & md5_ok,
        m_value,
    )
    m_value = jnp.where(is_status[None, :], status_ok, m_value)
    m_value = jnp.where(is_size[None, :], size_ok, m_value)

    # Kleene over the scalar∧slots∧¬neg∧md5 conjunction: a certainly
    # failed exact conjunct decides the matcher false whatever the
    # uncertain slots resolve to
    m_unc = m_unc & ~(
        is_scalar[None, :] & (~scalar_ok | ~md5_ok | neg_decided_false)
    )
    # md5-style residues: a scalar pass still needs host confirmation
    m_unc = m_unc | (arrays["m_residue"][None, :] & m_value)
    # regex prefilters are *semantically* uncertain when fired: the
    # required literal being byte-verified present does not prove the
    # regex matches, so the fired bit always needs host confirmation
    # (absence of the literal stays exact — the regex cannot match).
    m_unc = m_unc | (is_regex_prefilter[None, :] & m_value)
    # ...EXCEPT matchers the device regex verify re-checked exactly
    # (ops/regexdev.py): their value is the true search result and
    # only budget-overflow pairs stay uncertain.
    if rx is not None and len(db.rx_m_ids):
        rx_value, rx_unc = rx
        ids = arrays["rx_m_ids"]
        m_value = m_value.at[:, ids].set(rx_value)
        m_unc = m_unc.at[:, ids].set(rx_unc)
    # negation after uncertainty capture
    m_value = m_value ^ arrays["m_negative"][None, :]

    # --- operations ---
    NOP = db.op_cond_and.shape[0]
    op_value = jnp.zeros((B, NOP), dtype=bool)
    op_unc = jnp.zeros((B, NOP), dtype=bool)
    op_cond = arrays["op_cond_and"]
    for rows, idx in arrays["op_m_buckets"]:
        gv = m_value[:, idx]
        gu = m_unc[:, idx]
        is_and = op_cond[rows][None, :]
        red = jnp.where(is_and, gv.all(-1), gv.any(-1))
        # Kleene: certain-true matcher decides OR; certain-false decides
        # AND (matcher certainty = ~gu post-negation)
        decided = jnp.where(
            is_and, (~gv & ~gu).any(-1), (gv & ~gu).any(-1)
        )
        op_value = op_value.at[:, rows].set(red)
        op_unc = op_unc.at[:, rows].set(gu.any(-1) & ~decided)
    # superset-lowered (prefilter) ops: individual matcher bits inside
    # them are weakened (not per-matcher exact), so the Kleene
    # refinement above does not apply — the op is uncertain exactly when
    # it fired, certain-false otherwise, and fired rows are
    # host-confirmed at op granularity.
    is_pref = arrays["op_prefilter"][None, :]
    op_unc = jnp.where(is_pref, op_value, op_unc)

    # --- templates: OR over their operations ---
    NT = max(db.num_templates, 1)
    t_value = jnp.zeros((B, NT), dtype=bool)
    t_unc = jnp.zeros((B, NT), dtype=bool)
    for rows, idx in arrays["t_op_buckets"]:
        gv = op_value[:, idx]
        gu = op_unc[:, idx]
        t_value = t_value.at[:, rows].set(gv.any(-1))
        # Kleene: any certain-true op decides the template-level OR
        t_unc = t_unc.at[:, rows].set(
            gu.any(-1) & ~(gv & ~gu).any(-1)
        )
    if full:
        wf = arrays.get("wf")
        if wf is not None:
            cond_v, cond_u, emit_v, emit_u = _apply_workflow_gates(
                wf, t_value, t_unc, op_value, op_unc, m_value, m_unc
            )
            return (
                t_value, t_unc, op_value, op_unc, m_unc,
                cond_v, cond_u, emit_v, emit_u,
            )
        return t_value, t_unc, op_value, op_unc, m_unc
    return t_value, t_unc


def _apply_workflow_gates(
    wf: dict, t_value, t_unc, op_value, op_unc, m_value, m_unc
):
    """Vectorized workflow gate-apply over the whole batch (the
    device stage of docs/WORKFLOWS.md).

    Gathers each DNF condition from the verdict planes just built,
    ANDs them per term under Kleene three-valued logic, and ORs terms
    into the emit plane. Host condition kinds (templates/gates the
    device doesn't own) read as (False, uncertain); the runner resolves
    those — and any other uncertain emit — per row at condition
    granularity, never per workflow. ``m_value`` here is post-negation,
    matching cpu_ref's individual-matcher semantics for named gates.
    """
    B = t_value.shape[0]
    ck = wf["cond_kind"]  # [NC]
    ci = wf["cond_idx"]  # [NC], already >= 0
    host = wf["cond_host"]  # [NC]
    is_t = ck == fpc.WFC_HIT_DEV
    is_op = ck == fpc.WFC_OP
    is_m = ck == fpc.WFC_MATCHER
    # pad each source plane with one certain-False column so host-kind
    # (clipped) indices gather in bounds whatever the plane width
    pad = jnp.zeros((B, 1), dtype=bool)
    tv = jnp.concatenate([t_value, pad], axis=1)
    tu = jnp.concatenate([t_unc, pad], axis=1)
    opv = jnp.concatenate([op_value, pad], axis=1)
    opu = jnp.concatenate([op_unc, pad], axis=1)
    mv = jnp.concatenate([m_value, pad], axis=1)
    mu = jnp.concatenate([m_unc, pad], axis=1)
    ti = jnp.where(is_t, ci, tv.shape[1] - 1)
    oi = jnp.where(is_op, ci, opv.shape[1] - 1)
    mi = jnp.where(is_m, ci, mv.shape[1] - 1)
    cond_v = tv[:, ti] | opv[:, oi] | mv[:, mi]  # host kinds → False
    cond_u = tu[:, ti] | opu[:, oi] | mu[:, mi] | host[None, :]

    tc = wf["term_cond"]  # [NTERM, CMAX], pad -1 = vacuously TRUE
    valid = tc >= 0
    tcc = jnp.maximum(tc, 0)
    g_v = jnp.where(valid[None], cond_v[:, tcc], True)  # [B, NTERM, C]
    g_u = jnp.where(valid[None], cond_u[:, tcc], False)
    # Kleene AND: one certain-false cond kills the term (the dominant
    # no-trigger case — decided entirely on device); certain-true
    # requires every cond certain-true
    term_dead = (~g_v & ~g_u).any(-1)
    term_true = (g_v & ~g_u).all(-1)

    te = wf["term_emit"]  # [NTERM]
    NE = wf["emit_pad"].shape[0]
    zeros = jnp.zeros((B, NE), dtype=bool)
    emit_v = zeros.at[:, te].max(term_true)
    emit_p = zeros.at[:, te].max(~term_dead)
    return cond_v, cond_u, emit_v, emit_p & ~emit_v


def ensure_all_stream(streams: dict, lengths: dict):
    """Synthesize the "all" stream (header + CRLF + body) on device.

    The host encode may ship a width-1 placeholder instead of the
    assembled "all" matrix (encode_batch ``build_all=False``) — the
    concatenation is ~half the host encode bytes and half the H2D
    transfer, and on device it is two gathers and a select.
    ``lengths["all_hdr"]`` carries the per-row header-prefix length
    (0 = body-only: banner rows alias the banner, headerless rows the
    body — model.Response.part() semantics). Host-built "all"
    (width > 1, the seq-sharded path) passes through untouched.
    """
    allv = streams.get("all")
    if allv is None or allv.shape[1] > 1 or "all_hdr" not in lengths:
        return streams
    body = streams["body"]
    header = streams["header"]
    B, Wb = body.shape
    Wh = header.shape[1]
    Wa = ((Wb + Wh + 2 + 127) // 128) * 128
    hl = lengths["all_hdr"].astype(jnp.int32)[:, None]  # 0 = body-only
    bl = lengths["body"].astype(jnp.int32)[:, None]
    j = jnp.arange(Wa, dtype=jnp.int32)[None, :]
    off = jnp.where(hl > 0, hl + 2, 0)
    is_hdr = j < hl
    hvals = jnp.take_along_axis(
        header, jnp.broadcast_to(jnp.minimum(j, Wh - 1), (B, Wa)), axis=1
    )
    bpos = j - off
    is_body = (bpos >= 0) & (bpos < bl)
    bvals = jnp.take_along_axis(
        body, jnp.broadcast_to(jnp.clip(bpos, 0, Wb - 1), (B, Wa)), axis=1
    )
    is_crlf = (hl > 0) & (j >= hl) & (j < hl + 2)
    crlf = jnp.where(j == hl, jnp.uint8(13), jnp.uint8(10))
    synth = jnp.where(
        is_hdr,
        hvals,
        jnp.where(is_crlf, crlf, jnp.where(is_body, bvals, jnp.uint8(0))),
    )
    out = dict(streams)
    out["all"] = synth
    return out


def _match_impl(
    db: fpc.CompiledDB, candidate_k: int, streams, lengths, status, full=False
):
    streams = ensure_all_stream(streams, lengths)
    value_bits, uncertain_bits, overflow = match_slots(
        db, candidate_k, streams, lengths
    )
    digest = None
    if bool(db.m_md5_check.any()) and "body" in streams:
        from swarm_tpu.ops.md5 import md5_words

        digest = md5_words(streams["body"], lengths["body"])
    rx = None
    if len(db.rx_m_ids):
        from swarm_tpu.ops.regexdev import regex_verify

        B = next(iter(streams.values())).shape[0]
        rx = regex_verify(
            db, streams, lengths, value_bits, k_pairs=db.rx_k_pairs(B)
        )
    out = eval_verdicts(
        db,
        value_bits,
        uncertain_bits,
        lengths,
        status,
        full=full,
        md5_digest=digest,
        rx=rx,
    )
    return (*out, overflow)
