"""Workflow execution over the one-pass batched match.

Semantics mirror the reference corpus's workflow templates (SURVEY.md
§2.3): a trigger template (by path or tags) gates subtemplates (by tag
or path), optionally scoped to specific *named matchers* of the trigger;
subtemplates nest recursively. Plus nuclei's automatic-scan mode:
detected technologies (named matchers of tech templates) map through
``wappalyzer-mapping.yml`` to tags whose templates are then selected.

Everything evaluates against ONE device-batched match of the full
corpus — workflows only decide which of those hits get reported, so the
device never waits on conditional host logic.
"""

from __future__ import annotations

from typing import Optional, Sequence

from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.fingerprints.workflows import (
    SubtemplateRef,
    TemplateIndex,
    Workflow,
    parse_workflow,
)
from swarm_tpu.ops import cpu_ref


class WorkflowRunner:
    def __init__(
        self,
        templates: Sequence[Template],
        engine=None,
        wappalyzer: Optional[dict[str, list[str]]] = None,
        **engine_kwargs,
    ):
        self.workflows: list[Workflow] = [
            parse_workflow(t) for t in templates if t.protocol == "workflow"
        ]
        self.matchable = [t for t in templates if t.protocol != "workflow"]
        self.index = TemplateIndex(self.matchable)
        self.by_id = {t.id: t for t in self.matchable}
        self.wappalyzer = {k.lower(): v for k, v in (wappalyzer or {}).items()}
        if engine is None:
            from swarm_tpu.ops.engine import MatchEngine

            engine = MatchEngine(self.matchable, **engine_kwargs)
        self.engine = engine

    # ------------------------------------------------------------------
    def run(self, rows: Sequence[Response]) -> list[dict[str, list[str]]]:
        """→ per row: {workflow_id: [matched template ids]} (workflows
        whose trigger didn't fire are absent)."""
        results = self.engine.match(rows)
        out = []
        for row, rm in zip(rows, results):
            out.append(
                self.evaluate_hits(
                    set(rm.template_ids), lambda _tid, _r=row: [_r]
                )
            )
        return out

    def evaluate_hits(
        self, hit_ids: set, row_of, known_names: Optional[dict] = None
    ) -> dict[str, list[str]]:
        """Workflow gating over an already-matched hit set.

        ``row_of(template_id)`` returns the Response list whose matches
        fired that template — named-matcher gates re-confirm against
        every one (a gate fires if its name fired on ANY of them). This
        is the production entry for the active scanner, where each
        template's hits came from its own requests' responses.
        """
        # pre-seeded fired-name lists (e.g. the ssl scanner records its
        # own named-matcher verdicts) take precedence over re-confirming
        names_cache: dict[str, list[str]] = dict(known_names or {})
        per: dict[str, list[str]] = {}
        for wf in self.workflows:
            matched = self._eval_workflow(wf, row_of, hit_ids, names_cache)
            if matched:
                per[wf.id] = sorted(matched)
        return per

    # ------------------------------------------------------------------
    def _matcher_names(
        self, template: Template, row_of, cache: dict[str, list[str]]
    ) -> list[str]:
        """Named matchers of ``template`` that fired on any of its rows
        — host confirm on demand, once per template."""
        if template.id not in cache:
            names: list[str] = []
            for row in row_of(template.id) or []:
                if row is not None:
                    names.extend(
                        cpu_ref.match_template(template, row).matcher_names
                    )
            cache[template.id] = sorted(set(names))
        return cache[template.id]

    def _eval_workflow(
        self, wf: Workflow, row_of, hit_ids: set, cache: dict
    ) -> set:
        matched: set = set()
        for step in wf.steps:
            triggers: list[Template] = []
            if step.template:
                t = self.index.by_path(step.template)
                if t:
                    triggers.append(t)
            for tag in step.tags:
                triggers.extend(self.index.by_tag.get(tag.lower(), []))
            for trigger in triggers:
                if trigger.id not in hit_ids:
                    continue
                if step.matchers:
                    fired = self._matcher_names(trigger, row_of, cache)
                    for gate in step.matchers:
                        if gate.name in fired:
                            for ref in gate.subtemplates:
                                matched |= self._eval_ref(ref, row_of, hit_ids, cache)
                elif step.subtemplates:
                    for ref in step.subtemplates:
                        matched |= self._eval_ref(ref, row_of, hit_ids, cache)
                else:
                    matched.add(trigger.id)
        return matched

    def _eval_ref(
        self, ref: SubtemplateRef, row_of, hit_ids: set, cache: dict
    ) -> set:
        matched: set = set()
        for t in self.index.resolve(ref):
            if t.id not in hit_ids:
                continue
            if ref.matchers:
                fired = self._matcher_names(t, row_of, cache)
                for gate in ref.matchers:
                    if gate.name in fired:
                        for sub in gate.subtemplates:
                            matched |= self._eval_ref(sub, row_of, hit_ids, cache)
            elif ref.subtemplates:
                for sub in ref.subtemplates:
                    matched |= self._eval_ref(sub, row_of, hit_ids, cache)
            else:
                matched.add(t.id)
        return matched

    # ------------------------------------------------------------------
    # nuclei automatic-scan mode: tech detection → wappalyzer tags
    # ------------------------------------------------------------------
    def auto_scan(self, rows: Sequence[Response]) -> list[dict]:
        """Per row: detected technologies (fired named matchers of
        'tech'-tagged templates), their mapped tags, and the matched
        template ids those tags select."""
        results = self.engine.match(rows)
        tech_templates = self.index.by_tag.get("tech", [])
        out = []
        for row, rm in zip(rows, results):
            hit_ids = set(rm.template_ids)
            cache: dict[str, list[str]] = {}
            techs: set[str] = set()
            for t in tech_templates:
                if t.id in hit_ids:
                    techs.update(
                        n.lower()
                        for n in self._matcher_names(t, lambda _tid, _r=row: [_r], cache)
                    )
            tags: set[str] = set()
            for tech in techs:
                tags.update(tag.lower() for tag in self.wappalyzer.get(tech, []))
                tags.add(tech)  # a tech name is itself a usable tag
            selected = {
                t.id
                for tag in tags
                for t in self.index.by_tag.get(tag, [])
                if t.id in hit_ids
            }
            out.append(
                {
                    "technologies": sorted(techs),
                    "tags": sorted(tags),
                    "template_ids": sorted(selected),
                }
            )
        return out
