"""Workflow execution over the one-pass batched match.

Semantics mirror the reference corpus's workflow templates (SURVEY.md
§2.3): a trigger template (by path or tags) gates subtemplates (by tag
or path), optionally scoped to specific *named matchers* of the trigger;
subtemplates nest recursively. Plus nuclei's automatic-scan mode:
detected technologies (named matchers of tech templates) map through
``wappalyzer-mapping.yml`` to tags whose templates are then selected.

Two execution paths produce bit-identical per-row results
(docs/WORKFLOWS.md):

- **Device gate planes** (default): the compiler lowered each
  workflow's trigger→subtemplate DAG into per-condition / per-emit
  Kleene planes (``fingerprints.compile.lower_workflows``); the verdict
  tail ships them per row and this module decodes them — certain emits
  read straight off the plane, uncertain emits resolve at CONDITION
  granularity on the host (hit conds from the walked hit set, gate
  conds from a memoized named-matcher confirm). Workflows the lowering
  could not express (``plan.host_only_ids``) run through the twin loop.
- **Host-loop reference twin** (``device=False`` or
  ``SWARM_WORKFLOW_DEVICE=0``): the original per-row Python loop,
  retained as the oracle the bench's A/B identity gate compares
  against.

Per-content gating results additionally memoize in a runner L1 and the
shared tier's ``"w"`` family (docs/CACHING.md) when EVERY workflow is
content-pure (no reachable template reads host/port/duration), so a
steady-state rescan of fleet-known trigger content completes without
any device dispatch.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints.compile import (
    WFC_HIT_DEV,
    WFC_HIT_HOST,
    WFC_MATCHER,
    WFC_OP,
)
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.fingerprints.workflows import (
    SubtemplateRef,
    TemplateIndex,
    Workflow,
    parse_workflow,
)
from swarm_tpu.ops import cpu_ref

#: runner-local per-content memo cap (FIFO, oldest half dropped) —
#: small: the shared tier is the real cross-fleet store, this only
#: absorbs same-process rescans between tier round trips
_WF_MEMO_MAX = 4096


def _device_default() -> bool:
    return os.environ.get("SWARM_WORKFLOW_DEVICE", "1").lower() not in (
        "0", "false", "off",
    )


class WorkflowRunner:
    def __init__(
        self,
        templates: Sequence[Template],
        engine=None,
        wappalyzer: Optional[dict[str, list[str]]] = None,
        device: Optional[bool] = None,
        **engine_kwargs,
    ):
        self.workflows: list[Workflow] = [
            parse_workflow(t) for t in templates if t.protocol == "workflow"
        ]
        self.matchable = [t for t in templates if t.protocol != "workflow"]
        self.index = TemplateIndex(self.matchable)
        self.by_id = {t.id: t for t in self.matchable}
        self.wappalyzer = {k.lower(): v for k, v in (wappalyzer or {}).items()}
        if engine is None:
            from swarm_tpu.ops.engine import MatchEngine

            # the FULL list: compile_corpus skips workflow-protocol
            # templates from the match planes but lowers their DAGs
            # into db.wf — building over self.matchable would silently
            # drop the gate planes and pin every row to the twin
            engine = MatchEngine(list(templates), **engine_kwargs)
        self.engine = engine
        plan = getattr(getattr(engine, "db", None), "wf", None)
        if plan is not None and not plan.num_terms:
            plan = None
        self.plan = plan
        want = _device_default() if device is None else bool(device)
        #: device gate-plane decoding active (the host loop is still
        #: the path for plan-less rows and host-only workflows)
        self.device = bool(want and plan is not None)
        self._host_only_wfs = (
            [w for w in self.workflows if w.id in set(plan.host_only_ids)]
            if plan is not None
            else list(self.workflows)
        )
        # emit index → term rows targeting it (uncertain-emit host
        # resolution walks only these)
        self._terms_of_emit: dict[int, list[int]] = {}
        if plan is not None:
            for term, e in enumerate(plan.term_emit.tolist()):
                self._terms_of_emit.setdefault(int(e), []).append(term)
        # content-purity: the per-content memo is sound only when NO
        # workflow can reach a row-dependent template (host/port gates
        # would make content-identical rows disagree)
        from swarm_tpu.ops.engine import _is_row_dependent

        self._memo_complete = all(
            not _is_row_dependent(self.by_id[tid])
            for wf in self.workflows
            for tid in self._wf_template_ids(wf)
            if tid in self.by_id
        )
        self._memo_lock = threading.Lock()  # guards: _wf_memo
        self._wf_memo: dict[str, dict] = {}
        # named-matcher gates the workflows actually query, per
        # template — the active scanner's batched gate re-confirm
        # resolves exactly these (anything else is never looked up)
        self._needed_names: dict[str, set] = self._collect_gate_names()
        self.gate_template_ids: set = set(self._needed_names)
        # (template, gate name) → cond rows in the plan whose value
        # decides it (one per lowered alternative; OR = name fired)
        self._plane_names: dict[str, dict[str, list[int]]] = {}
        if plan is not None:
            for ci in range(plan.num_conds):
                if int(plan.cond_kind[ci]) in (WFC_OP, WFC_MATCHER):
                    self._plane_names.setdefault(
                        plan.cond_template[ci], {}
                    ).setdefault(plan.cond_name[ci], []).append(ci)
        if plan is not None:
            from swarm_tpu.telemetry.workflow_export import (
                WORKFLOW_STEPS_COMPILED,
            )

            WORKFLOW_STEPS_COMPILED.labels().set(
                float(plan.stats.get("steps_compiled", 0))
            )

    # ------------------------------------------------------------------
    def _wf_template_ids(self, wf: Workflow) -> set:
        """Every template id a workflow's evaluation can touch
        (triggers, gate subtemplates, nested refs) — the purity scan's
        domain."""
        ids: set = set()

        def walk_ref(ref: SubtemplateRef) -> None:
            for t in self.index.resolve(ref):
                ids.add(t.id)
            for gate in ref.matchers:
                for sub in gate.subtemplates:
                    walk_ref(sub)
            for sub in ref.subtemplates:
                walk_ref(sub)

        for step in wf.steps:
            if step.template:
                t = self.index.by_path(step.template)
                if t:
                    ids.add(t.id)
            for tag in step.tags:
                ids.update(t.id for t in self.index.by_tag.get(tag.lower(), []))
            for gate in step.matchers:
                for sub in gate.subtemplates:
                    walk_ref(sub)
            for sub in step.subtemplates:
                walk_ref(sub)
        return ids

    def _collect_gate_names(self) -> dict[str, set]:
        """template id → the gate names any workflow queries on it."""
        needed: dict[str, set] = {}

        def note(t: Template, gates) -> None:
            for g in gates:
                needed.setdefault(t.id, set()).add(g.name)

        def walk_ref(ref: SubtemplateRef) -> None:
            if ref.matchers:
                for t in self.index.resolve(ref):
                    note(t, ref.matchers)
                for g in ref.matchers:
                    for sub in g.subtemplates:
                        walk_ref(sub)
            for sub in ref.subtemplates:
                walk_ref(sub)

        for wf in self.workflows:
            for step in wf.steps:
                triggers: list[Template] = []
                if step.template:
                    t = self.index.by_path(step.template)
                    if t:
                        triggers.append(t)
                for tag in step.tags:
                    triggers.extend(self.index.by_tag.get(tag.lower(), []))
                if step.matchers:
                    for t in triggers:
                        note(t, step.matchers)
                    for g in step.matchers:
                        for sub in g.subtemplates:
                            walk_ref(sub)
                for sub in step.subtemplates:
                    walk_ref(sub)
        return needed

    # ------------------------------------------------------------------
    def resolve_gate_names(
        self, needs: Sequence[tuple]
    ) -> list[list[str]]:
        """Batched named-matcher gate re-confirm for the active
        scanner: ``[(template_id, row), ...]`` → the fired gate-name
        list per pair. The distinct rows ride ONE engine batch (the
        scheduler's QoS lanes / in-flight overlap / memo families all
        apply under pipeline mode); pairs whose every queried gate
        lowered to a certain device condition decode straight off the
        gate planes, the rest fall back to the exact per-row cpu_ref
        confirm — the same oracle ``_matcher_names`` uses, so the
        result is bit-identical to the serial path either way."""
        if not needs:
            return []
        rows_u: list = []
        slot: dict[int, int] = {}
        for _tid, row in needs:
            if id(row) not in slot:
                slot[id(row)] = len(rows_u)
                rows_u.append(row)
        results = self.engine.match(rows_u)
        if self.device and any(rm.wf is not None for rm in results):
            from swarm_tpu.telemetry.workflow_export import (
                WORKFLOW_GATE_PLANE_BATCHES,
            )

            WORKFLOW_GATE_PLANE_BATCHES.labels().inc()
        out: list = []
        fb: dict = {}
        for tid, row in needs:
            s = slot[id(row)]
            names = self._names_from_planes(tid, results[s])
            if names is None:
                key = (s, tid)
                if key not in fb:
                    t = self.by_id.get(tid)
                    fb[key] = (
                        sorted(
                            set(
                                cpu_ref.match_template(t, row).matcher_names
                            )
                        )
                        if t is not None and row is not None
                        else []
                    )
                names = fb[key]
            out.append(names)
        return out

    def _names_from_planes(self, tid: str, rm) -> Optional[list]:
        """Fired gate names of ``tid`` for one row, decoded from its
        device cond planes — None when any queried gate is unlowered
        or uncertain (the caller re-confirms the row exactly)."""
        if not self.device or getattr(rm, "wf", None) is None:
            return None
        needed = self._needed_names.get(tid)
        if not needed:
            return []
        lanes = self._plane_names.get(tid, {})
        if not needed <= set(lanes):
            return None
        plan = self.plan
        cv = np.unpackbits(
            np.asarray(rm.wf[0], dtype=np.uint8), count=plan.num_conds
        )
        cu = np.unpackbits(
            np.asarray(rm.wf[1], dtype=np.uint8), count=plan.num_conds
        )
        fired: list = []
        for name in needed:
            cis = lanes[name]
            if any(cu[ci] for ci in cis):
                return None
            if any(cv[ci] for ci in cis):
                fired.append(name)
        return sorted(fired)

    # ------------------------------------------------------------------
    def run(self, rows: Sequence[Response]) -> list[dict[str, list[str]]]:
        """→ per row: {workflow_id: [matched template ids]} (workflows
        whose trigger didn't fire are absent)."""
        from swarm_tpu.telemetry.workflow_export import (
            WORKFLOW_GATE_PLANE_BATCHES,
            WORKFLOW_STEP_MEMO_HITS,
            WORKFLOW_STEP_MEMO_MISSES,
        )

        out: list = [None] * len(rows)
        pending: list[int] = []
        for i, row in enumerate(rows):
            if not getattr(row, "alive", True):
                out[i] = {}  # dead rows match nothing by contract
            else:
                pending.append(i)
        # step-memo front: L1 then the shared "w" family — a served row
        # never reaches the engine at all (the zero-dispatch rescan)
        if pending and self._memo_complete:
            from swarm_tpu.cache.tier import row_digest

            digests = {i: row_digest(rows[i]) for i in pending}
            still: list[int] = []
            with self._memo_lock:
                for i in pending:
                    entry = self._wf_memo.get(digests[i])
                    if entry is None:
                        still.append(i)
                    else:
                        out[i] = {k: list(v) for k, v in entry.items()}
            if len(pending) - len(still):
                WORKFLOW_STEP_MEMO_HITS.labels(tier="l1").inc(
                    len(pending) - len(still)
                )
            client = getattr(self.engine, "_result_cache", None)
            if still and client is not None:
                got = client.lookup_workflows([rows[i] for i in still])
                if got:
                    WORKFLOW_STEP_MEMO_HITS.labels(tier="shared").inc(
                        len(got)
                    )
                served = []
                for pos, entry in got.items():
                    i = still[pos]
                    out[i] = {k: list(v) for k, v in entry.items()}
                    self._memo_put(digests[i], entry)
                    served.append(i)
                still = [i for i in still if i not in set(served)]
            if still:
                WORKFLOW_STEP_MEMO_MISSES.labels().inc(len(still))
            pending = still
        if pending:
            fresh = [rows[i] for i in pending]
            results = self.engine.match(fresh)
            if self.device and any(rm.wf is not None for rm in results):
                WORKFLOW_GATE_PLANE_BATCHES.labels().inc()
            writeback: list = []
            for i, rm in zip(pending, results):
                row = rows[i]
                per = self._gate_row(rm, lambda _tid, _r=row: [_r])
                out[i] = per
                if self._memo_complete:
                    from swarm_tpu.cache.tier import row_digest

                    self._memo_put(row_digest(row), per)
                    writeback.append((row, per))
            client = getattr(self.engine, "_result_cache", None)
            if writeback and client is not None:
                client.writeback_workflows(writeback)
        return out

    def _memo_put(self, digest: str, per: dict) -> None:
        with self._memo_lock:
            memo = self._wf_memo
            if len(memo) >= _WF_MEMO_MAX:
                for k in list(memo)[: _WF_MEMO_MAX // 2]:
                    memo.pop(k, None)
            memo[digest] = {k: list(v) for k, v in per.items()}

    # ------------------------------------------------------------------
    def _gate_row(self, rm, row_of) -> dict[str, list[str]]:
        """One matched row → {workflow_id: [template ids]}, via device
        planes when the row carries them, else the full twin loop."""
        hit_ids = set(rm.template_ids)
        cache: dict[str, list[str]] = {}
        wfp = getattr(rm, "wf", None)
        if self.device and wfp is not None:
            per = self._decode_planes(wfp, hit_ids, row_of, cache)
            for wf in self._host_only_wfs:
                matched = self._eval_workflow(wf, row_of, hit_ids, cache)
                if matched:
                    per[wf.id] = sorted(matched)
            return per
        if self.plan is not None:
            from swarm_tpu.telemetry.workflow_export import (
                WORKFLOW_HOST_TWIN_FALLBACKS,
            )

            WORKFLOW_HOST_TWIN_FALLBACKS.labels().inc()
        per = {}
        for wf in self.workflows:
            matched = self._eval_workflow(wf, row_of, hit_ids, cache)
            if matched:
                per[wf.id] = sorted(matched)
        return per

    def _decode_planes(
        self, wfp: tuple, hit_ids: set, row_of, cache: dict
    ) -> dict[str, list[str]]:
        """Per-row Kleene planes → workflow results. Certain emits read
        off the plane; each uncertain emit re-walks its terms with
        certain conds from the plane and uncertain conds resolved
        exactly on the host."""
        plan = self.plan
        cond_v, cond_u, emit_v, emit_u = wfp
        ev = np.unpackbits(
            np.asarray(emit_v, dtype=np.uint8), count=plan.num_emits
        )
        eu = np.unpackbits(
            np.asarray(emit_u, dtype=np.uint8), count=plan.num_emits
        )
        cv = cu = None
        per: dict[str, set] = {}
        for e in np.flatnonzero(ev).tolist():
            wf_id, tid = plan.emits[e]
            per.setdefault(wf_id, set()).add(tid)
        for e in np.flatnonzero(eu).tolist():
            if cv is None:
                cv = np.unpackbits(
                    np.asarray(cond_v, dtype=np.uint8), count=plan.num_conds
                )
                cu = np.unpackbits(
                    np.asarray(cond_u, dtype=np.uint8), count=plan.num_conds
                )
            for term in self._terms_of_emit.get(e, ()):
                if self._term_true(term, cv, cu, hit_ids, row_of, cache):
                    wf_id, tid = plan.emits[e]
                    per.setdefault(wf_id, set()).add(tid)
                    break
        return {wf_id: sorted(s) for wf_id, s in per.items()}

    def _term_true(
        self, term: int, cv, cu, hit_ids: set, row_of, cache: dict
    ) -> bool:
        plan = self.plan
        for ci in plan.term_cond[term].tolist():
            if ci < 0:  # padding: vacuously true
                continue
            if not cu[ci]:
                if not cv[ci]:
                    return False
                continue
            if not self._cond_host(ci, hit_ids, row_of, cache):
                return False
        return True

    def _cond_host(
        self, ci: int, hit_ids: set, row_of, cache: dict
    ) -> bool:
        """Exact host value of one uncertain condition. Hit conds read
        the engine's walked hit set; gate conds (op/matcher/host) all
        reduce to "did gate NAME fire on TEMPLATE" — sound because the
        lowering duplicates terms per gate alternative, so the
        name-level OR can only re-derive an emit another alternative's
        term already owns."""
        plan = self.plan
        kind = int(plan.cond_kind[ci])
        tid = plan.cond_template[ci]
        if kind in (WFC_HIT_DEV, WFC_HIT_HOST):
            return tid in hit_ids
        t = self.by_id.get(tid)
        if t is None:
            return False
        return plan.cond_name[ci] in self._matcher_names(t, row_of, cache)

    # ------------------------------------------------------------------
    def evaluate_hits(
        self, hit_ids: set, row_of, known_names: Optional[dict] = None
    ) -> dict[str, list[str]]:
        """Workflow gating over an already-matched hit set.

        ``row_of(template_id)`` returns the Response list whose matches
        fired that template — named-matcher gates re-confirm against
        every one (a gate fires if its name fired on ANY of them). This
        is the production entry for the active scanner, where each
        template's hits came from its own requests' responses (no
        single row carries device planes for the joined set, so this
        path is always the host loop)."""
        # pre-seeded fired-name lists (e.g. the ssl scanner records its
        # own named-matcher verdicts) take precedence over re-confirming
        names_cache: dict[str, list[str]] = dict(known_names or {})
        per: dict[str, list[str]] = {}
        for wf in self.workflows:
            matched = self._eval_workflow(wf, row_of, hit_ids, names_cache)
            if matched:
                per[wf.id] = sorted(matched)
        return per

    # ------------------------------------------------------------------
    # host-loop reference twin (bit-identical oracle for the device
    # gate planes; bench --phase workflow gates on the comparison)
    # ------------------------------------------------------------------
    def _matcher_names(
        self, template: Template, row_of, cache: dict[str, list[str]]
    ) -> list[str]:
        """Named matchers of ``template`` that fired on any of its rows
        — host confirm on demand, once per template."""
        if template.id not in cache:
            names: list[str] = []
            for row in row_of(template.id) or []:
                if row is not None:
                    names.extend(
                        cpu_ref.match_template(template, row).matcher_names
                    )
            cache[template.id] = sorted(set(names))
        return cache[template.id]

    def _eval_workflow(
        self, wf: Workflow, row_of, hit_ids: set, cache: dict
    ) -> set:
        matched: set = set()
        for step in wf.steps:
            triggers: list[Template] = []
            if step.template:
                t = self.index.by_path(step.template)
                if t:
                    triggers.append(t)
            for tag in step.tags:
                triggers.extend(self.index.by_tag.get(tag.lower(), []))
            for trigger in triggers:
                if trigger.id not in hit_ids:
                    continue
                if step.matchers:
                    fired = self._matcher_names(trigger, row_of, cache)
                    for gate in step.matchers:
                        if gate.name in fired:
                            for ref in gate.subtemplates:
                                matched |= self._eval_ref(ref, row_of, hit_ids, cache)
                elif step.subtemplates:
                    for ref in step.subtemplates:
                        matched |= self._eval_ref(ref, row_of, hit_ids, cache)
                else:
                    matched.add(trigger.id)
        return matched

    def _eval_ref(
        self, ref: SubtemplateRef, row_of, hit_ids: set, cache: dict
    ) -> set:
        matched: set = set()
        for t in self.index.resolve(ref):
            if t.id not in hit_ids:
                continue
            if ref.matchers:
                fired = self._matcher_names(t, row_of, cache)
                for gate in ref.matchers:
                    if gate.name in fired:
                        for sub in gate.subtemplates:
                            matched |= self._eval_ref(sub, row_of, hit_ids, cache)
            elif ref.subtemplates:
                for sub in ref.subtemplates:
                    matched |= self._eval_ref(sub, row_of, hit_ids, cache)
            else:
                matched.add(t.id)
        return matched

    # ------------------------------------------------------------------
    # nuclei automatic-scan mode: tech detection → wappalyzer tags
    # ------------------------------------------------------------------
    def auto_scan(self, rows: Sequence[Response]) -> list[dict]:
        """Per row: detected technologies (fired named matchers of
        'tech'-tagged templates), their mapped tags, and the matched
        template ids those tags select."""
        results = self.engine.match(rows)
        tech_templates = self.index.by_tag.get("tech", [])
        out = []
        for row, rm in zip(rows, results):
            hit_ids = set(rm.template_ids)
            cache: dict[str, list[str]] = {}
            techs: set[str] = set()
            for t in tech_templates:
                if t.id in hit_ids:
                    techs.update(
                        n.lower()
                        for n in self._matcher_names(t, lambda _tid, _r=row: [_r], cache)
                    )
            tags: set[str] = set()
            for tech in techs:
                tags.update(tag.lower() for tag in self.wappalyzer.get(tech, []))
                tags.add(tech)  # a tech name is itself a usable tag
            selected = {
                t.id
                for tag in tags
                for t in self.index.by_tag.get(tag, [])
                if t.id in hit_ids
            }
            out.append(
                {
                    "technologies": sorted(techs),
                    "tags": sorted(tags),
                    "template_ids": sorted(selected),
                }
            )
        return out
