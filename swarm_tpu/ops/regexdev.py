"""Device regex verify: exact shift-and over fired rows.

Regex matchers whose patterns compiled to linear programs
(fingerprints/regexlin.py) are re-checked ON DEVICE when their literal
prefilter fires: the fired (row, sequence) pairs are compacted with a
fixed budget, each pair's stream bytes are gathered once, and a
``lax.scan`` runs the bit-parallel shift-and recurrence — up to
``regexlin.MAX_POSITIONS`` (96) NFA positions in uint32 lanes
(lane-count generic; 96 states = 3 lanes) — over the bytes, with
byte-class masks from one [NSEQ, 256, L] lookup per byte. The result
replaces the prefilter's
uncertain-on-fire semantics with an exact device verdict; only pairs
beyond the compaction budget stay uncertain (host confirms them).

This is the "regex on TPU" piece of SURVEY.md §7's hard-part #1: no
general regex engine exists in XLA, but the corpus's matcher regexes
are linear-program shaped, and search semantics (does it match
anywhere) need no captures or backtracking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops.encoding import STREAMS

UNROLL = 8  # bytes per scan step


def regex_verify(
    db: fpc.CompiledDB,
    streams: dict,
    lengths: dict,
    value_bits,
    k_pairs: int,
    arrays: dict | None = None,
):
    """→ (rx_value [B, NRXM] bool, rx_unc [B, NRXM] bool).

    ``value_bits`` are the post-combine slot bits (the literal
    prefilters gate which pairs run). ``streams`` must be the FULL
    per-row byte streams (sequence-sharded callers gather first).

    ``arrays`` is the rx half of the argument layout
    (``compile.rx_arrays_np``) — pass the device-resident pytree and
    the program/bytemap tables stay out of the compiled executable;
    omit it for the legacy constants behavior.
    """
    NRXM = len(db.rx_m_ids)
    some = next(iter(streams.values()))
    B = some.shape[0]
    if NRXM == 0:
        z = jnp.zeros((B, 1), dtype=bool)
        return z, z
    if arrays is None:
        import jax as _jax

        arrays = _jax.tree_util.tree_map(
            jnp.asarray, fpc.rx_arrays_np(db)
        )

    # --- fired gate, per sequence: OR over the owning pattern's
    # literal slots; literal-less sequences scan every row (rationed
    # by the compiler's rx_always_budget) ---
    seq_matcher = arrays["seq_matcher"]
    NSEQ = db.rx_seq_matcher.shape[0]
    fired_seq = jnp.broadcast_to(arrays["seq_always"][None, :], (B, NSEQ))
    for rows, idx_b in arrays["slot_buckets"]:
        gv = value_bits[:, idx_b]
        fired_seq = fired_seq.at[:, rows].max(gv.any(-1))

    # --- compact fired pairs under a fixed budget ---
    flat = fired_seq.reshape(-1)
    K = int(k_pairs)
    (idx,) = jnp.nonzero(flat, size=K, fill_value=-1)
    valid = idx >= 0
    safe = jnp.maximum(idx, 0)
    pair_b = safe // NSEQ
    pair_s = safe % NSEQ

    # --- stacked stream variants (static SET from the compiled db;
    # the per-seq variant ids ride the argument pytree) ---
    variants = fpc.rx_variants(db)
    var_of_seq = arrays["var_of_seq"]
    w_max = max(streams[STREAMS[s]].shape[1] for s, _ in variants)
    bufs = []
    lens = []
    for s, ci in variants:
        name = STREAMS[s]
        arr = jnp.asarray(streams[name])
        if ci:
            up = (arr >= 65) & (arr <= 90)
            arr = jnp.where(up, arr + 32, arr)
        if arr.shape[1] < w_max:
            arr = jnp.pad(arr, ((0, 0), (0, w_max - arr.shape[1])))
        bufs.append(arr)
        lens.append(jnp.asarray(lengths[name]))
    stacked = jnp.stack(bufs, axis=1)  # [B, V, w_max]
    len_stack = jnp.stack(lens, axis=1)  # [B, V]

    pair_var = var_of_seq[pair_s]
    pair_bytes = stacked[pair_b, pair_var]  # [K, w_max]
    pair_len = len_stack[pair_b, pair_var]  # [K]

    # --- per-pair program masks ([K, L] state lanes) ---
    bytemap = arrays["bytemap"]  # [NSEQ, 256, L]
    L = db.rx_bytemap.shape[2]
    seed = arrays["seed"][pair_s]  # [K, L]
    skip = arrays["skip"][pair_s]
    accept = arrays["accept"][pair_s]
    sloop = arrays["self"][pair_s]
    anchored = arrays["anchored"][pair_s][:, None]  # [K, 1]
    end_mode = arrays["end_mode"][pair_s]  # [K]
    start_wb = arrays["start_wb"][pair_s]
    end_wb = arrays["end_wb"][pair_s]
    r_closure = int(db.rx_max_skip_run)

    from swarm_tpu.fingerprints.regexlin import (
        END_DOLLAR,
        END_NONE,
        END_Z,
        _WORD_BYTES,
    )

    word_tab = jnp.asarray(_WORD_BYTES)
    # $ needs "just before a final newline" — precompute per pair
    last_byte = jnp.take_along_axis(
        pair_bytes, jnp.maximum(pair_len - 1, 0)[:, None], axis=1
    )[:, 0]
    trail_nl = (last_byte == 0x0A) & (pair_len > 0)

    def lane_shift(d):
        """64/96-bit left shift by 1 across uint32 lanes [K, L]."""
        carry = jnp.concatenate(
            [jnp.zeros((K, 1), dtype=jnp.uint32), d[:, :-1] >> 31],
            axis=1,
        )
        return (d << 1) | carry

    pad = (-w_max) % UNROLL
    if pad:
        pair_bytes_p = jnp.pad(pair_bytes, ((0, 0), (0, pad)))
    else:
        pair_bytes_p = pair_bytes
    n_steps = (w_max + pad) // UNROLL
    xs = jnp.moveaxis(
        pair_bytes_p.reshape(K, n_steps, UNROLL), 1, 0
    )  # [n_steps, K, UNROLL]

    map_flat = bytemap.reshape(-1, L)
    pair_s32 = pair_s.astype(jnp.int32)
    zeros_k = jnp.zeros((K,), dtype=jnp.uint32)

    def step(carry, inp):
        d, matched, t0, prev_word, pending, pend_word = carry
        block = inp  # [K, UNROLL]
        for u in range(UNROLL):
            c = block[:, u].astype(jnp.int32)
            pos = t0 + u
            live = pos < pair_len
            w_c = word_tab[c] & live
            # trailing-\b accepts from the previous byte resolve now:
            # boundary iff wordness changes (or EOS, handled after)
            matched = matched | (pending & live & (pend_word ^ w_c))
            pending = pending & ~live  # EOS case resolves after scan
            bc = map_flat[pair_s32 * 256 + c]  # [K, L]
            bc = jnp.where(live[:, None], bc, 0)
            # seed guards: anchors fix the start, \b needs a boundary
            s_ok = (~anchored[:, 0] | (pos == 0)) & (
                ~start_wb | (w_c ^ prev_word)
            )
            s = jnp.where(s_ok[:, None], seed, 0)
            d = ((lane_shift(d) | s) & bc) | (d & sloop & bc)
            for _ in range(r_closure):
                d = d | (lane_shift(d) & skip)
            acc = ((d & accept) != 0).any(axis=1)
            end_ok = (
                (end_mode == END_NONE)
                | ((end_mode == END_Z) & (pos == pair_len - 1))
                | (
                    (end_mode == END_DOLLAR)
                    & (
                        (pos == pair_len - 1)
                        | (trail_nl & (pos == pair_len - 2))
                    )
                )
            )
            matched = matched | (acc & end_ok & ~end_wb)
            pending = pending | (acc & end_wb)
            pend_word = jnp.where(acc & end_wb, w_c, pend_word)
            prev_word = w_c
        return (d, matched, t0 + UNROLL, prev_word, pending, pend_word), None

    init = (
        jnp.zeros((K, L), dtype=jnp.uint32),
        jnp.zeros((K,), dtype=bool),
        jnp.int32(0),
        jnp.zeros((K,), dtype=bool),
        jnp.zeros((K,), dtype=bool),
        jnp.zeros((K,), dtype=bool),
    )
    (_, matched, _, _, pending, pend_word), _ = jax.lax.scan(
        step, init, xs
    )
    # end of stream is a boundary exactly after a word char
    matched = matched | (pending & pend_word)
    matched = matched & valid

    # --- scatter back to matcher granularity ---
    rx_value = jnp.zeros((B, NRXM), dtype=bool)
    rx_value = rx_value.at[pair_b, seq_matcher[pair_s]].max(matched)
    # pairs that didn't fit the budget leave their matcher uncertain
    included = jnp.zeros((B * NSEQ,), dtype=bool).at[safe].max(valid)
    missing_seq = fired_seq & ~included.reshape(B, NSEQ)
    rx_unc = jnp.zeros((B, NRXM), dtype=bool)
    rx_unc = rx_unc.at[:, seq_matcher].max(missing_seq)
    return rx_value, rx_unc
