"""Fleet-wide content-addressed result cache (docs/CACHING.md)."""

from swarm_tpu.cache.tier import (  # noqa: F401
    ResultCacheClient,
    SharedResultTier,
    build_result_cache,
    confirm_digest,
    corpus_digest,
    decode_entry,
    encode_entry,
    row_digest,
)
