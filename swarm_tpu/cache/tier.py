"""Fleet-wide content-addressed result cache (docs/CACHING.md).

Internet-scale scans are dedup-heavy: thousands of hosts serve
byte-identical pages, banners and certs — yet the engine's caches (the
native verdict memo, the scheduler's encode-first speculation, the
batched walk's confirm cache) are per-process and die with the worker.
This module lifts them into a SHARED tier so a row any worker has ever
fully resolved short-circuits before device dispatch fleet-wide:

- **Keys are content hashes**: sha256 over the normalized row bytes
  (exactly the fields ``engine._content_key`` reads — banner, body,
  header, status, oob planes — length-prefixed so concatenation is
  unambiguous), scoped by a **corpus epoch** that combines the corpus
  content digest with an operator-bumpable generation counter. A
  corpus refresh changes the digest, so every stale entry becomes
  unreachable with no deletion pass — that IS the invalidation story;
  ``bump_epoch`` handles the "poisoned tier, same corpus" operator
  case the same way.
- **Two value families** ride the same tier: packed verdict planes
  plus their extraction/deferral extras (the native memo's entry
  shape, family ``v``), and the batched walk's part-keyed confirm
  verdicts (family ``c``).
- **Fencing-token discipline** (the PR-4 output-spool contract): every
  writer acquires a monotonic token keyed by its writer identity;
  re-acquiring the same identity (worker restart / slot re-lease)
  SUPERSEDES the old instance, whose writes the tier then rejects —
  checked before the write and re-checked after it (a write that raced
  its own supersession is unwound), so a stale worker can never poison
  the tier.
- **Storage is the Redis/S3 role pair** behind ``swarm_tpu/stores``:
  the state store holds the hash-addressed entries (one ``hmget`` per
  batched lookup), oversized values spill to the blob store with a
  pointer in the hash — the embedded defaults make the tier runnable
  with zero side-cars, the Redis/S3 adapters make it fleet-wide.

The per-engine native memo stays in front as the L1; the engine
consults L1 → shared tier → device (``ops/engine.py``), and the
scheduler batch-pipelines the remote lookups inside its prefetch stage
so a shared miss costs no added latency on the dispatch path
(``sched/scheduler.py``). All tier traffic goes through
:class:`ResultCacheClient`, which wraps every store op in a circuit
breaker — a dead Redis degrades the scan to L1-only, it never blocks
it (docs/RESILIENCE.md; fault points ``cache.get`` / ``cache.put``).
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import time
import weakref
from typing import Optional, Sequence

from swarm_tpu.telemetry.memo_export import (
    MEMO_EPOCH,
    MEMO_EVICTIONS,
    MEMO_HIT_RATIO,
    MEMO_LOOKUP_SECONDS,
    MEMO_WRITEBACKS,
    SHARED_HITS,
    SHARED_MISSES,
)

#: serialization format version — salts every digest so a wire-format
#: change can never deserialize stale entries
_FORMAT = b"swarm-cache-v1"


# ---------------------------------------------------------------------------
# Content addressing
# ---------------------------------------------------------------------------


def _lp(out: bytearray, b: bytes) -> None:
    out += len(b).to_bytes(8, "little")
    out += b


def _lp_seq(out: bytearray, seq) -> None:
    """Length-prefix a string sequence element-wise (count, then each
    element) — joining with a separator would make element boundaries
    ambiguous, exactly what the prefix discipline exists to prevent."""
    _lp(out, str(len(seq)).encode())
    for item in seq:
        _lp(out, item.encode("utf-8", "surrogateescape"))


def row_digest(row) -> str:
    """Content address of one response row: sha256 over the normalized
    row bytes — exactly the fields the device and the content-side host
    walk read (``engine._content_key``), length-prefixed. host/port/
    duration are deliberately NOT hashed: row-dependent templates are
    stored as deferrals and re-decided per member row on replay, so
    content-identical rows from different hosts share one entry."""
    out = bytearray(_FORMAT)
    _lp(out, b"\x01" + row.banner if row.banner is not None else b"\x00")
    _lp(out, row.body)
    _lp(out, row.header)
    _lp(out, str(int(row.status)).encode())
    _lp_seq(out, row.oob_protocols)
    _lp(out, row.oob_requests)
    _lp_seq(out, row.oob_ips)
    return hashlib.sha256(bytes(out)).hexdigest()


def confirm_digest(key: tuple) -> str:
    """Content address of one confirm-cache entry ``(tag, m_id,
    part_bytes)`` (the engine's ``_confirm_cache`` key shape for the
    shareable ``"m"``/``"pe"`` namespaces). ``m_id`` is a compiled-db
    matcher index — stable only per corpus, which is why every lookup
    is epoch-scoped and the epoch digest covers the compiler source."""
    tag, m_id, part = key
    out = bytearray(_FORMAT)
    _lp(out, tag.encode())
    _lp(out, str(int(m_id)).encode())
    _lp(out, part)
    return hashlib.sha256(bytes(out)).hexdigest()


def corpus_digest(templates: Sequence) -> str:
    """Content digest of a template corpus — the epoch's identity half.

    Hashes every template's dataclass repr (deterministic across
    processes: field order is declaration order, values are
    bytes/str/int reprs) PLUS the compiler-source salt from
    ``fingerprints/dbcache`` — matcher/op/template INDICES are baked
    into both value families, and a lowering change can renumber them
    even when the YAML is unchanged."""
    from swarm_tpu.fingerprints.dbcache import _code_salt

    h = hashlib.sha256(_FORMAT)
    h.update(_code_salt())
    for t in templates:
        h.update(repr(t).encode("utf-8", "surrogateescape"))
        h.update(b"\x00")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Entry wire format (family "v"): the native memo's (bits, ment, mdef)
# ---------------------------------------------------------------------------


def encode_entry(bits_bytes: bytes, ment: tuple, mdef: tuple) -> Optional[str]:
    """One verdict entry → compact JSON string (None when the extras
    hold something JSON can't carry — e.g. a lone-surrogate host
    remnant; the entry is simply not shared, never mangled)."""
    try:
        return json.dumps(
            {
                "b": base64.b64encode(bits_bytes).decode("ascii"),
                "e": [[tid, list(vals)] for tid, vals in ment],
                "d": list(mdef),
            },
            separators=(",", ":"),
        )
    except (TypeError, ValueError):
        return None


def decode_entry(raw: str) -> Optional[tuple]:
    """JSON string → ``(bits_bytes, ment, mdef)`` in exactly the
    deep-frozen shape the verdict memos store; None on anything
    malformed (a corrupt entry is a MISS, never an exception on the
    match path)."""
    try:
        doc = json.loads(raw)
        bits = base64.b64decode(doc["b"], validate=True)
        ment = tuple(
            (str(tid), tuple(str(v) for v in vals)) for tid, vals in doc["e"]
        )
        mdef = tuple(int(t) for t in doc["d"])
        return bits, ment, mdef
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Entry wire format (family "w"): per-content workflow gating results
# ---------------------------------------------------------------------------


def encode_workflow_entry(per: dict) -> Optional[str]:
    """One workflow gating result ``{workflow_id: [template ids]}`` →
    compact JSON string (None when an id holds something JSON can't
    carry — the entry is simply not shared, never mangled)."""
    try:
        return json.dumps(
            {str(k): sorted(str(t) for t in v) for k, v in per.items()},
            separators=(",", ":"),
            sort_keys=True,
        )
    except (TypeError, ValueError):
        return None


def decode_workflow_entry(raw: str) -> Optional[dict]:
    """JSON string → ``{workflow_id: [template ids]}``; None on
    anything malformed (a corrupt entry is a MISS, never an exception
    on the gating path)."""
    try:
        doc = json.loads(raw)
        return {str(k): [str(t) for t in v] for k, v in doc.items()}
    except Exception:
        return None


# ---------------------------------------------------------------------------
# The shared tier proper
# ---------------------------------------------------------------------------


class SharedResultTier:
    """Backend-agnostic shared tier over a :class:`~swarm_tpu.stores.
    StateStore` (hash-addressed entries, fencing registry, epoch
    generation) plus an optional :class:`~swarm_tpu.stores.BlobStore`
    spill for oversized values.

    Wire layout (Redis-compatible, namespaced under ``prefix``):

    - ``{prefix}:meta`` hash — ``epoch_gen`` (int), ``fence_next``
      (monotonic token counter)
    - ``{prefix}:writers`` hash — writer identity → current token
    - ``{prefix}:{family}:{epoch}`` hash — content digest → JSON entry,
      or the ``@blob`` pointer sentinel
    - blob key ``cache/{family}/{epoch}/{digest}`` — spilled values

    All methods are thread-safe to the extent the underlying stores
    are (both embedded defaults and both real adapters are)."""

    _BLOB_SENTINEL = "@blob"

    def __init__(self, state, blobs=None, prefix: str = "swarm:cache",
                 spill_bytes: int = 8192, ttl_s: float = 0.0,
                 max_entries: int = 0):
        self._state = state
        self._blobs = blobs
        self._prefix = prefix
        self._spill = int(spill_bytes)
        self.configure_policy(ttl_s, max_entries)

    # -- TTL/size policy (docs/CACHING.md) -----------------------------
    def configure_policy(self, ttl_s: float = 0.0, max_entries: int = 0) -> None:
        """Optional retention policy, per value-family namespace.
        Both default OFF (0) — today's behavior (backend eviction +
        epoch bumps) unless the operator configures it. ``ttl_s``
        expires entries lazily at lookup; ``max_entries`` bounds EACH
        ``{family}:{epoch}`` hash at write time, oldest-first. Only
        entries written while a policy is active carry a write stamp
        and participate; concurrent evictions are idempotent hdels."""
        self._ttl_s = float(ttl_s)
        self._max_entries = int(max_entries)

    def _policy_active(self) -> bool:
        return self._ttl_s > 0 or self._max_entries > 0

    def _ts_name(self, family: str, epoch: str) -> str:
        """Side hash of write timestamps (digest → unix seconds): the
        entry wire format stays untouched, so flipping the policy on
        or off can never strand or corrupt existing values."""
        return f"{self._prefix}:ts:{family}:{epoch}"

    def _evict(self, family: str, epoch: str, digests, reason: str) -> int:
        name = self._hash_name(family, epoch)
        ts_name = self._ts_name(family, epoch)
        n = 0
        for digest in digests:
            # a spilled value's blob becomes unreachable garbage, the
            # same reclamation story as a stale epoch's namespace
            self._state.hdel(name, digest)
            self._state.hdel(ts_name, digest)
            n += 1
        if n:
            MEMO_EVICTIONS.labels(reason=reason).inc(n)
        return n

    def entry_count(self, family: str, epoch: str) -> int:
        """Policy-tracked entries in one family namespace (test/ops
        surface; entries written with the policy off aren't counted)."""
        return len(self._state.hkeys(self._ts_name(family, epoch)))

    # -- epoch ---------------------------------------------------------
    def epoch_generation(self) -> int:
        raw = self._state.hget(f"{self._prefix}:meta", "epoch_gen")
        return int(raw) if raw else 0

    def bump_epoch(self) -> int:
        """Invalidate EVERY live entry by moving all readers/writers to
        a fresh key namespace (the operator lever for "poisoned tier,
        unchanged corpus"; stale-epoch entries are unreachable garbage,
        reclaimed by backend TTL/eviction policy, not by a scan)."""
        return self._state.hincr(f"{self._prefix}:meta", "epoch_gen", 1)

    # -- fencing -------------------------------------------------------
    def acquire_writer(self, writer_id: str) -> int:
        """Mint a fencing token for ``writer_id`` and make it the
        identity's CURRENT token — any prior holder of the same
        identity (the restarted/re-leased predecessor) is superseded
        from this moment and its writes are rejected."""
        token = self._state.hincr(f"{self._prefix}:meta", "fence_next", 1)
        self._state.hset(f"{self._prefix}:writers", writer_id, str(token))
        return token

    def writer_token(self, writer_id: str) -> Optional[int]:
        raw = self._state.hget(f"{self._prefix}:writers", writer_id)
        return int(raw) if raw else None

    def fence_writer(self, writer_id: str) -> None:
        """Revoke an identity outright (no successor yet): its token is
        dropped, so every in-flight write from it is rejected."""
        self._state.hdel(f"{self._prefix}:writers", writer_id)

    # -- data plane ----------------------------------------------------
    def _hash_name(self, family: str, epoch: str) -> str:
        return f"{self._prefix}:{family}:{epoch}"

    def _blob_key(self, family: str, epoch: str, digest: str) -> str:
        return f"cache/{family}/{epoch}/{digest}"

    def get_many(self, family: str, epoch: str, digests: list) -> dict:
        """digest → raw value for every present entry, ONE state-store
        round trip (``hmget``) plus a blob fetch per spilled value. A
        missing/vanished blob behind a live pointer is a miss."""
        if not digests:
            return {}
        name = self._hash_name(family, epoch)
        expired: set = set()
        if self._ttl_s > 0:
            # lazy TTL expiry: stamps ride a side hash, one extra
            # hmget per batched lookup only while the policy is on; an
            # expired entry is dropped and served as a miss
            now = time.time()
            ts_name = self._ts_name(family, epoch)
            stamps = self._state.hmget(ts_name, digests)
            stale: dict = {}
            for digest, raw_ts in zip(digests, stamps):
                if raw_ts is None:
                    continue  # pre-policy entry: no stamp, no expiry
                try:
                    if now - float(raw_ts) > self._ttl_s:
                        stale[digest] = raw_ts
                except ValueError:
                    stale[digest] = raw_ts
            if stale:
                # re-read just before deleting: a concurrent writer may
                # have refreshed the entry between the two reads, and
                # deleting THAT would destroy a fresh value. The
                # residual window after this check is benign (an
                # entry loss = one recompute, never a wrong verdict).
                recheck = self._state.hmget(ts_name, list(stale))
                expired = {
                    d for d, ts in zip(stale, recheck) if ts == stale[d]
                }
                if expired:
                    self._evict(family, epoch, expired, "ttl")
        out: dict = {}
        for digest, raw in zip(digests, self._state.hmget(name, digests)):
            if raw is None or digest in expired:
                continue
            if raw == self._BLOB_SENTINEL:
                if self._blobs is None:
                    continue
                try:
                    raw = self._blobs.get(
                        self._blob_key(family, epoch, digest)
                    ).decode("utf-8")
                except Exception:
                    continue
            out[digest] = raw
        return out

    # pairs: writer_token / _state.hset_many; pairs: writer_token / _blobs.put (fence re-check, docs/CACHING.md)
    def put_many(
        self, family: str, epoch: str, items: list, writer_id: str,
        token: int,
    ) -> tuple[str, int]:
        """Store ``[(digest, value), ...]`` under the writer's fencing
        token. Returns ``(outcome, stored_count)`` with outcome
        ``"stored"`` or ``"fenced"``. The token is checked BEFORE the
        write (the stale-writer reject) and AGAIN after it, so a
        writer superseded mid-write learns it was fenced instead of
        claiming success. The mid-write entries themselves are
        deliberately NOT unwound: within an epoch every entry is a
        pure function of its content digest (the epoch namespace pins
        corpus AND lowering code), so a superseded same-epoch writer's
        bytes are value-identical to what the live successor would
        store — deleting them could only ever destroy the successor's
        valid concurrent write for the same digest, never remove
        poison. Cross-epoch stale writers cannot reach this namespace
        at all (the actual poison vector the discipline closes)."""
        if self.writer_token(writer_id) != token:
            return "fenced", 0
        name = self._hash_name(family, epoch)
        mapping: dict = {}
        for digest, value in items:
            if self._blobs is not None and len(value) > self._spill:
                self._blobs.put(
                    self._blob_key(family, epoch, digest),
                    value.encode("utf-8"),
                )
                value = self._BLOB_SENTINEL
            mapping[digest] = value
        # ONE state-store round trip for the whole batch (hset_many) —
        # a walked plane's writeback must not cost one RTT per row
        self._state.hset_many(name, mapping)
        if self._policy_active():
            now = str(time.time())
            self._state.hset_many(
                self._ts_name(family, epoch), {d: now for d in mapping}
            )
            if self._max_entries > 0:
                stamps = self._state.hgetall(self._ts_name(family, epoch))
                excess = len(stamps) - self._max_entries
                if excess > 0:
                    oldest = sorted(
                        stamps, key=lambda d: (float(stamps[d]), d)
                    )[:excess]
                    self._evict(family, epoch, oldest, "size")
        if self.writer_token(writer_id) != token:
            return "fenced", 0
        return "stored", len(mapping)


# ---------------------------------------------------------------------------
# Breaker-wrapped per-engine client
# ---------------------------------------------------------------------------

# process-wide shared hit/miss totals behind the hit-ratio gauge (every
# client in the process reports into one ratio)
_G_LOCK = threading.Lock()
# [shared hits, shared misses] across every client in the process
_G_TOTALS = [0, 0]  # guarded-by: _G_LOCK

# ONE fencing token per writer identity PER PROCESS: two clients in the
# same process that derive the same identity (same worker id + same
# corpus digest — e.g. two modules over identical templates) are the
# SAME live writer and must share a token; re-acquiring would
# supersede the first client and silently fence its writebacks. A
# restart is a new process with an empty registry, so it re-acquires
# and supersedes the dead predecessor — exactly the discipline's
# intent. Keyed per tier object (WeakKey: the registry never extends a
# tier's lifetime).
_TOKEN_LOCK = threading.Lock()
_PROC_TOKENS = weakref.WeakKeyDictionary()  # guarded-by: _TOKEN_LOCK (reads)


def _process_token(tier: SharedResultTier, writer: str) -> int:
    """The process's token for (tier, writer) — acquired once, shared
    by every same-identity client. Store I/O runs under the lock;
    binding is rare (once per engine per process)."""
    with _TOKEN_LOCK:
        per_tier = _PROC_TOKENS.get(tier)
        if per_tier is None:
            per_tier = _PROC_TOKENS[tier] = {}
        token = per_tier.get(writer)
        if token is None:
            token = per_tier[writer] = tier.acquire_writer(writer)  # blocking-ok: one-time token mint per (tier, writer) — serialized registration IS the discipline (docs/CACHING.md)
        return token


class ResultCacheClient:
    """The engine's view of the shared tier: epoch-bound, breaker-
    wrapped, telemetry-counted. Every tier access runs behind a
    circuit breaker (``cache.tier.<worker>``): a dead/slow backend
    trips it and the engine silently degrades to L1-only — lookups
    return misses, writebacks drop — until the cooldown's half-open
    probe heals it. Chaos levers ``cache.get`` / ``cache.put``
    (docs/RESILIENCE.md) inject exactly that failure mode.

    Thread contract: the scheduler calls ``lookup_rows`` from its
    prefetch thread while the walk worker calls ``writeback_rows`` /
    ``writeback_confirms`` — all mutable client state sits under
    ``_lock``."""

    #: recent-miss suppression cap: a digest this client just missed is
    #: not re-queried (the engine will compute and write it back
    #: itself); bounded FIFO, oldest half dropped at the cap
    _RECENT_MAX = 8192
    #: how long a bound epoch is trusted before the generation counter
    #: is re-read — the propagation ceiling for an operator
    #: ``bump_epoch`` on a LIVE fleet (no restart needed; the re-read
    #: is one breaker-guarded hget per client per interval)
    _EPOCH_TTL_S = 60.0

    def __init__(
        self,
        tier: SharedResultTier,
        worker_id: str = "worker",
        confirm: bool = True,
        writeback: bool = True,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
    ):
        from swarm_tpu.resilience.breaker import CircuitBreaker

        self._tier = tier
        self._worker_id = worker_id
        self.confirm = bool(confirm)
        self.writeback = bool(writeback)
        self._breaker = CircuitBreaker(
            f"cache.tier.{worker_id}",
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
        )
        self._lock = threading.Lock()  # guards: _recent_miss (reads), _hits, _misses, _fam, _epoch, _writer, _token, _digest, _warned, _fence_warned
        # serializes the bind SEQUENCE (epoch read + token acquisition,
        # store I/O included): two threads racing a lazy re-bind must
        # not each mint a token for the same identity — the loser's
        # token would disagree with the registry and every later
        # writeback would be silently fenced
        # lock-order: _bind_lock -> _lock
        self._bind_lock = threading.Lock()
        self._recent_miss: dict = {}
        self._hits = 0
        self._misses = 0
        # per-family [hits, misses]: the bench's gated hit ratio reads
        # verdict-family outcomes only (confirm digests would dilute it)
        self._fam: dict = {"v": [0, 0], "c": [0, 0], "w": [0, 0]}
        self._digest: Optional[str] = None
        self._epoch: Optional[str] = None
        self._epoch_read_at = 0.0
        self._writer: Optional[str] = None
        self._token: Optional[int] = None
        self._warned = False
        self._fence_warned = False

    # -- binding -------------------------------------------------------
    def bind_corpus(self, digest: str) -> None:
        """Bind this client to a corpus content digest (the engine
        calls this at attach time). Tier registration — reading the
        epoch generation and acquiring the fencing token — happens
        through the breaker and retries lazily on the next op if the
        backend is down at bind time."""
        with self._lock:
            self._digest = digest
            self._epoch = None
            self._writer = f"{self._worker_id}:{digest[:8]}"
            self._token = None
        self._ensure_bound()

    def refresh_epoch(self) -> None:
        """Re-read the tier's epoch generation (after an operator
        ``bump_epoch``; new entries land in — and lookups read — the
        fresh namespace)."""
        with self._lock:
            self._epoch = None
        self._ensure_bound()

    def _ensure_bound(self) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            if (
                self._epoch is not None
                and now - self._epoch_read_at < self._EPOCH_TTL_S
            ):
                return self._epoch
        with self._bind_lock:
            # re-check under the bind lock: the thread that lost the
            # race adopts the winner's bind instead of re-acquiring
            with self._lock:
                if (
                    self._epoch is not None
                    and now - self._epoch_read_at < self._EPOCH_TTL_S
                ):
                    return self._epoch
                stale_epoch = self._epoch
                digest = self._digest
                writer = self._writer
                token = self._token
            if digest is None:
                return None

            def bind():
                gen = self._tier.epoch_generation()
                tok = token
                if tok is None:
                    tok = _process_token(self._tier, writer)
                return f"{digest[:24]}.g{gen}", tok

            out = self._guarded("cache.get", "bind", bind)  # blocking-ok: the bind sequence (epoch read + token mint) is serialized by design — one guarded RTT per epoch TTL
            if out is None:
                # re-read failed (breaker open / backend down): keep
                # serving on the stale-by-≤TTL epoch if we have one —
                # a flaky meta read must not un-bind a working client
                return stale_epoch
            epoch, tok = out
            with self._lock:
                self._epoch = epoch
                self._epoch_read_at = time.monotonic()
                self._token = tok
        MEMO_EPOCH.labels().set(float(epoch.rsplit(".g", 1)[-1]))
        return epoch

    # -- breaker plumbing ---------------------------------------------
    # may-block: wraps one tier store op behind the breaker
    def _guarded(self, point: str, detail: str, fn):
        """Run one tier op behind the breaker; None = degraded (the
        caller treats it as a miss / dropped write)."""
        from swarm_tpu.resilience.faults import fault_point

        br = self._breaker
        if not br.allow():
            return None
        try:
            from swarm_tpu.telemetry import tracing

            fault_point(point, detail=detail)
            # child span under the worker's ambient attempt context
            # (no-op object when tracing is off / no context bound)
            with tracing.span(point, detail=detail):
                out = fn()
        except Exception as e:
            br.record_failure()
            with self._lock:
                warn = not self._warned
                self._warned = True
            if warn:
                print(
                    f"result cache degraded to L1-only "
                    f"({type(e).__name__}: {e}) "
                    f"[breaker {br.name}: {br.state}]"
                )
            return None
        br.record_success()
        with self._lock:
            self._warned = False
        return out

    # -- verdict family ------------------------------------------------
    def lookup_rows(self, rows: Sequence) -> dict:
        """Batched shared lookup: row position → decoded verdict entry
        ``(bits_bytes, ment, mdef)`` for every row whose content the
        tier holds. Dead rows never consult the tier (they resolve to
        zero verdicts by contract); duplicate contents are queried
        once and fan out to every member position; digests this client
        recently missed are suppressed entirely (the engine is about
        to compute them anyway)."""
        if not rows:
            return {}
        epoch = self._ensure_bound()
        if epoch is None:
            return {}
        members: dict = {}
        for i, row in enumerate(rows):
            if not getattr(row, "alive", True):
                continue
            members.setdefault(row_digest(row), []).append(i)
        with self._lock:
            digests = [d for d in members if d not in self._recent_miss]
        if not digests:
            return {}
        t0 = time.perf_counter()
        got = self._guarded(
            "cache.get", "verdict",
            lambda: self._tier.get_many("v", epoch, digests),
        )
        if got is None:
            # breaker-open / failed op: no real lookup happened — an
            # observation here would fill the low buckets with zeros
            # exactly while the tier is down
            return {}
        MEMO_LOOKUP_SECONDS.labels().observe(time.perf_counter() - t0)
        out: dict = {}
        hits = misses = 0
        missed: list = []
        for digest in digests:
            raw = got.get(digest)
            entry = decode_entry(raw) if raw is not None else None
            if entry is None:
                misses += 1
                missed.append(digest)
                continue
            hits += 1
            for i in members[digest]:
                out[i] = entry
        self._count(hits, misses, missed, "v")
        return out

    def writeback_rows(self, entries: list) -> int:
        """Batch-write freshly walked results: ``[(row, bits_bytes,
        (ment, mdef) | None), ...]`` → the verdict family. Returns the
        stored count (0 when fenced/degraded/disabled)."""
        if not self.writeback or not entries:
            return 0
        items: list = []
        for row, bits_bytes, extras in entries:
            if not getattr(row, "alive", True):
                continue
            ment, mdef = extras if extras is not None else ((), ())
            value = encode_entry(bits_bytes, ment, mdef)
            if value is not None:
                items.append((row_digest(row), value))
        return self._put("v", "verdict", items)

    # -- confirm family ------------------------------------------------
    def lookup_confirms(self, keys: list) -> dict:
        """Batched confirm-family lookup: engine ``_confirm_cache`` key
        → bool for every present entry (keys are the shareable
        ``("m"|"pe", m_id, part)`` namespaces)."""
        if not keys or not self.confirm:
            return {}
        epoch = self._ensure_bound()
        if epoch is None:
            return {}
        by_digest = {confirm_digest(k): k for k in keys}
        with self._lock:
            digests = [
                d for d in by_digest if d not in self._recent_miss
            ]
        if not digests:
            return {}
        t0 = time.perf_counter()
        got = self._guarded(
            "cache.get", "confirm",
            lambda: self._tier.get_many("c", epoch, digests),
        )
        if got is None:
            return {}  # degraded: no real lookup to time
        MEMO_LOOKUP_SECONDS.labels().observe(time.perf_counter() - t0)
        out: dict = {}
        hits = misses = 0
        missed: list = []
        for digest in digests:
            raw = got.get(digest)
            if raw == "1" or raw == "0":
                hits += 1
                out[by_digest[digest]] = raw == "1"
            else:
                misses += 1
                missed.append(digest)
        self._count(hits, misses, missed, "c")
        return out

    def writeback_confirms(self, items: list) -> int:
        """Batch-write confirm verdicts: ``[(key, bool), ...]`` from
        the batched walk's merge phase; non-shareable key namespaces
        (``"op"``-tagged per-object keys) are skipped here by the
        caller."""
        if not self.writeback or not self.confirm or not items:
            return 0
        return self._put(
            "c", "confirm",
            [(confirm_digest(k), "1" if v else "0") for k, v in items],
        )

    # -- workflow step family ------------------------------------------
    def lookup_workflows(self, rows: Sequence) -> dict:
        """Batched workflow-family lookup: row position → decoded
        gating result ``{workflow_id: [template ids]}`` for every row
        whose content the tier holds. Same content addressing as the
        verdict family (``row_digest``) under the separate ``"w"``
        namespace — entries cover content-pure workflows only, so a
        fleet-known trigger's gating costs this lookup, not a device
        dispatch. Recent-miss suppression is tracked under a
        ``"w:"``-prefixed key so a workflow miss never suppresses the
        verdict family's lookup of the same content (or vice versa)."""
        if not rows:
            return {}
        epoch = self._ensure_bound()
        if epoch is None:
            return {}
        members: dict = {}
        for i, row in enumerate(rows):
            if not getattr(row, "alive", True):
                continue
            members.setdefault(row_digest(row), []).append(i)
        with self._lock:
            digests = [
                d for d in members if ("w:" + d) not in self._recent_miss
            ]
        if not digests:
            return {}
        t0 = time.perf_counter()
        got = self._guarded(
            "cache.get", "workflow",
            lambda: self._tier.get_many("w", epoch, digests),
        )
        if got is None:
            return {}  # degraded: no real lookup to time
        MEMO_LOOKUP_SECONDS.labels().observe(time.perf_counter() - t0)
        out: dict = {}
        hits = misses = 0
        missed: list = []
        for digest in digests:
            raw = got.get(digest)
            entry = decode_workflow_entry(raw) if raw is not None else None
            if entry is None:
                misses += 1
                missed.append("w:" + digest)
                continue
            hits += 1
            for i in members[digest]:
                out[i] = entry
        self._count(hits, misses, missed, "w")
        return out

    def writeback_workflows(self, entries: list) -> int:
        """Batch-write freshly gated results: ``[(row, per_dict), ...]``
        → the workflow family (``per_dict`` restricted to content-pure
        workflows by the caller). Returns the stored count (0 when
        fenced/degraded/disabled)."""
        if not self.writeback or not entries:
            return 0
        items: list = []
        for row, per in entries:
            if not getattr(row, "alive", True):
                continue
            value = encode_workflow_entry(per)
            if value is not None:
                items.append((row_digest(row), value))
        return self._put("w", "workflow", items)

    # -- shared plumbing -----------------------------------------------
    def _put(self, family: str, label: str, items: list) -> int:
        if not items:
            return 0
        epoch = self._ensure_bound()
        if epoch is None:
            MEMO_WRITEBACKS.labels(family=label, outcome="error").inc(
                len(items)
            )
            return 0
        with self._lock:
            writer, token = self._writer, self._token
        out = self._guarded(
            "cache.put", label,
            lambda: self._tier.put_many(family, epoch, items, writer, token),
        )
        if out is None:
            MEMO_WRITEBACKS.labels(family=label, outcome="error").inc(
                len(items)
            )
            return 0
        outcome, stored = out
        MEMO_WRITEBACKS.labels(
            family=label, outcome=outcome
        ).inc(len(items) if outcome == "fenced" else stored)
        if outcome == "fenced":
            # being superseded is a normal fleet event, but a client
            # that keeps writing fenced is usually a DUPLICATE worker
            # id (two live processes sharing one identity) — say so
            # once instead of silently dropping every writeback
            with self._lock:
                warn = not self._fence_warned
                self._fence_warned = True
            if warn:
                print(
                    f"result cache writebacks fenced (writer "
                    f"{writer!r} superseded — restarted elsewhere or "
                    f"duplicate worker id); this engine is now a "
                    f"read-only tier consumer"
                )
        elif stored:
            # this content is provably in the tier now — stop
            # suppressing its digest, or recurring content evicted
            # from the L1 would be re-walked while the tier holds it
            with self._lock:
                for digest, _value in items:
                    self._recent_miss.pop(digest, None)
        return stored

    def _count(
        self, hits: int, misses: int, missed: list, family: str
    ) -> None:
        with self._lock:
            self._hits += hits
            self._misses += misses
            self._fam[family][0] += hits
            self._fam[family][1] += misses
            if len(self._recent_miss) + len(missed) > self._RECENT_MAX:
                for k in list(self._recent_miss)[: self._RECENT_MAX // 2]:
                    self._recent_miss.pop(k, None)
            for d in missed:
                self._recent_miss[d] = None
        if hits:
            SHARED_HITS.inc(hits)
        if misses:
            SHARED_MISSES.inc(misses)
        with _G_LOCK:
            _G_TOTALS[0] += hits
            _G_TOTALS[1] += misses
            total = _G_TOTALS[0] + _G_TOTALS[1]
            ratio = _G_TOTALS[0] / total if total else 0.0
        MEMO_HIT_RATIO.labels().set(ratio)

    def counters(self) -> dict:
        """This client's lifetime lookup outcomes (bench/test surface).
        ``shared_*`` are both families pooled; the ``verdict_*`` /
        ``confirm_*`` splits exist so row-granular gates (the dedup
        bench's hit ratio) aren't diluted by confirm-part digests."""
        with self._lock:
            return {
                "shared_hits": self._hits,
                "shared_misses": self._misses,
                "verdict_hits": self._fam["v"][0],
                "verdict_misses": self._fam["v"][1],
                "confirm_hits": self._fam["c"][0],
                "confirm_misses": self._fam["c"][1],
                "epoch": self._epoch,
                "breaker": self._breaker.state,
            }


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------

_MEMORY_LOCK = threading.Lock()
_MEMORY_TIER: Optional[SharedResultTier] = None  # guarded-by: _MEMORY_LOCK (reads)
# one tier object per (url, spill dir) in this process: the fencing
# registry (_PROC_TOKENS) is keyed per tier OBJECT, so two clients
# over the same backend must see the same instance or same-identity
# clients would mint competing tokens and fence each other
_REDIS_TIERS: dict = {}  # guarded-by: _MEMORY_LOCK (reads)


def _memory_tier() -> SharedResultTier:
    """Process-wide embedded tier (the no-side-car default): every
    engine in the process shares one instance, so multi-module workers
    still get cross-engine reuse."""
    global _MEMORY_TIER
    from swarm_tpu.stores import MemoryBlobStore, MemoryStateStore

    with _MEMORY_LOCK:
        if _MEMORY_TIER is None:
            _MEMORY_TIER = SharedResultTier(
                MemoryStateStore(), MemoryBlobStore()
            )
        return _MEMORY_TIER


def _redis_tier(url: str, spill_dir: str) -> SharedResultTier:
    from swarm_tpu.stores import LocalBlobStore, RedisStateStore

    with _MEMORY_LOCK:
        tier = _REDIS_TIERS.get((url, spill_dir))
        if tier is None:
            blobs = LocalBlobStore(spill_dir) if spill_dir else None
            tier = _REDIS_TIERS[(url, spill_dir)] = SharedResultTier(
                RedisStateStore(url), blobs
            )
        return tier


def build_tier(cfg) -> Optional[SharedResultTier]:
    """The shared tier for a Config's ``SWARM_CACHE_*`` knobs — the
    ONE backend-dispatch + retention-policy site, shared by the
    engine-side :func:`build_result_cache` and the gateway-side scan
    cache (``gateway/qoscache.py``) so the two can never drift. None
    when the tier is off."""
    backend = (getattr(cfg, "cache_backend", "off") or "off").lower()
    if backend in ("off", "", "0", "none", "false"):
        return None
    if backend == "memory":
        tier = _memory_tier()
    elif backend == "redis":
        tier = _redis_tier(
            cfg.cache_url or cfg.redis_url, cfg.cache_spill_dir
        )
    else:
        raise ValueError(f"unknown cache_backend {backend!r}")
    # TTL/size policy (docs/CACHING.md): the tier objects are process
    # singletons per backend, so the most recent configuration wins —
    # defaults (0/0) keep today's behavior untouched
    tier.configure_policy(
        getattr(cfg, "cache_ttl_s", 0.0),
        getattr(cfg, "cache_max_entries", 0),
    )
    return tier


def build_result_cache(cfg) -> Optional[ResultCacheClient]:
    """Construct the tier client from a :class:`swarm_tpu.config.
    Config` (``SWARM_CACHE_*`` knobs); None when the tier is off."""
    tier = build_tier(cfg)
    if tier is None:
        return None
    return ResultCacheClient(
        tier,
        worker_id=cfg.worker_id,
        confirm=cfg.cache_confirm,
        writeback=cfg.cache_writeback,
        breaker_threshold=cfg.cache_breaker_threshold,
        breaker_cooldown_s=cfg.cache_breaker_cooldown_s,
    )
