"""swarm_tpu — a TPU-native distributed scanning framework.

A ground-up re-design of the capabilities of Jec00/swarm (reference:
``/root/reference``) for TPU hardware:

- The reference's shelled-out scan engines (nmap -sV service detection,
  nuclei template matching, httpx/httprobe probing — see
  ``worker/modules/*.json`` in the reference) are replaced by a
  **fingerprint-match engine**: template corpora compile to flat tensor
  databases and banner/response batches are matched on-device with
  jit/vmap XLA kernels (``swarm_tpu.ops``), sharded across chips with
  ``jax.sharding`` meshes (``swarm_tpu.parallel``).
- The control plane (server REST API, job queue, chunk blob storage,
  scan summaries — reference ``server/server.py``) is wire-compatible
  but rebuilt on embedded stores with lease-based dispatch
  (``swarm_tpu.server``, ``swarm_tpu.stores``).
- The worker (reference ``worker/worker.py``) keeps the poll loop and
  module registry but adds a ``tpu`` backend that batches chunk rows
  onto the device (``swarm_tpu.worker``).
- Host-side network I/O (the one thing XLA cannot do) lives in a native
  C++ front-end (``native/``), bound via ctypes.
"""

__version__ = "0.1.0"
