"""QoS (latency) classes for latency-tiered serving (docs/GATEWAY.md).

Two classes, threaded end to end — client flag → ``X-Swarm-QoS``
header → ``Job.qos`` wire field → the queue's express dispatch lane →
the scheduler's deadline-flush path:

- **bulk** (the default, and what every reference submission is): rows
  coalesce into full device batches; throughput-optimal, latency
  unbounded by design.
- **interactive**: single-target lookups that want an answer in tens
  of milliseconds. Jobs ride a per-tenant express lane that ``next_job``
  serves ahead of bulk (bounded by ``qos_express_burst`` so bulk can
  never starve), and rows force an early partial-bucket flush once
  older than ``qos_deadline_ms`` in the scheduler's planner.

Absent/None always means bulk — the wire contract the reference client
speaks is untouched.
"""

from __future__ import annotations

from typing import Optional

#: header carrying the class, next to X-Swarm-Tenant
QOS_HEADER = "X-Swarm-QoS"

QOS_BULK = "bulk"
QOS_INTERACTIVE = "interactive"

#: every accepted wire value (anything else is a 400 at the gateway)
QOS_CLASSES = (QOS_BULK, QOS_INTERACTIVE)


def parse_qos(value: Optional[str]) -> Optional[str]:
    """Normalize a header/flag value to a stored class: ``None`` for
    absent/empty/bulk (the record then round-trips byte-identical to a
    pre-QoS submission), ``"interactive"`` for the express class.
    Raises ValueError on anything else — an unknown class must 400 at
    the gateway, not silently ride the bulk lane."""
    if value is None:
        return None
    v = value.strip().lower()
    if v in ("", QOS_BULK):
        return None
    if v == QOS_INTERACTIVE:
        return QOS_INTERACTIVE
    raise ValueError(f"Invalid QoS class {value!r}")


def qos_class(qos: Optional[str]) -> str:
    """The metric-label class of a stored ``Job.qos`` value (None and
    anything unrecognized count as bulk — label space stays bounded
    even against a hand-crafted job record)."""
    return QOS_INTERACTIVE if qos == QOS_INTERACTIVE else QOS_BULK
