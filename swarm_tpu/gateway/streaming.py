"""Server-push result streaming: ``GET /stream/<scan_id>`` (docs/GATEWAY.md).

The reference client's only way to watch a running scan is polling
``cat`` (merged ``/raw``) — O(scan size) per poll and no ordering
story. The gateway serves incremental results instead: one NDJSON
record per output chunk, pushed over a chunked HTTP/1.1 response as
chunks land in the (idempotent) chunk store, IN INDEX ORDER so the
client's resume cursor is simply "last delivered chunk + 1".

Wire format, one JSON object per line:

- ``{"chunk": i, "data": "<chunk text>"}`` — chunk ``i``'s output
- ``{"chunk": i, "event": "skipped", "status": "..."}`` — chunk ``i``
  reached a terminal failure (dead letter) and will never produce
  output; the cursor advances past it
- ``{"event": "end", "next_chunk": n}`` — every chunk up to the scan's
  known extent has been delivered or skipped; the stream is complete
- ``{"event": "timeout", "next_chunk": n}`` — nothing new for the idle
  window; the server closes the stream (bounded handler lifetime) and
  the client reconnects with ``?from=n``

Resume across a server RESTART rides the idempotent chunk store:
output chunks are durable blobs, so a fresh server (empty in-memory job
table) still serves ``?from=n`` for every stored chunk and ends the
stream when the store holds nothing at or past the cursor.
"""

from __future__ import annotations

import json
import time
from typing import Iterator

from swarm_tpu.datamodel import JobStatus
from swarm_tpu.telemetry.gateway_export import GATEWAY_STREAM_BYTES


def _record(payload: dict) -> bytes:
    return json.dumps(payload, separators=(",", ":")).encode() + b"\n"


def stream_scan(
    queue,
    scan_id: str,
    from_chunk: int = 0,
    poll_s: float = 0.05,
    idle_timeout_s: float = 300.0,
    clock=time.monotonic,
    sleep=time.sleep,
) -> Iterator[bytes]:
    """Yield NDJSON records for ``scan_id`` starting at ``from_chunk``.

    Ordering contract: records for chunk ``i`` are only emitted after
    every chunk ``< i`` was delivered or skipped, so a consumer's ack
    cursor is a single integer. The generator polls the queue service
    (never holds its locks) and bounds its own lifetime with the idle
    timeout."""
    next_index = int(from_chunk)
    last_progress = clock()
    #: consecutive polls the cursor's index had NO job record while
    #: later records existed — a gap is only skipped once it persists
    #: (an in-flight multi-chunk submission writes records in order,
    #: so a transient snapshot race must not drop a chunk forever)
    gap_polls = 0
    while True:
        content = queue.output_chunk(scan_id, next_index)
        if content is not None:
            line = _record({"chunk": next_index, "data": content})
            GATEWAY_STREAM_BYTES.inc(len(line))
            yield line
            next_index += 1
            last_progress = clock()
            gap_polls = 0
            continue

        # hot path: ONE hget for the chunk the cursor is waiting on —
        # a live record that isn't terminal-failed just means "not
        # ready yet", no reason to render the whole job table
        status = queue.chunk_status(scan_id, next_index)
        if status is not None and status not in JobStatus.FAILED:
            if clock() - last_progress >= idle_timeout_s:
                yield _record({"event": "timeout", "next_chunk": next_index})
                return
            sleep(poll_s)
            continue

        states = queue.scan_chunk_states(scan_id)
        if not states:
            # no live job records (e.g. a restarted server streaming a
            # historical scan from the durable chunk store): serve
            # what the store holds, end when nothing remains at or
            # past the cursor
            stored = queue.stored_output_chunks(scan_id)
            ahead = sorted(i for i in stored if i >= next_index)
            if not ahead:
                yield _record({"event": "end", "next_chunk": next_index})
                return
            if ahead[0] > next_index:
                # a gap with no job record will never fill — skip it
                yield _record(
                    {"chunk": next_index, "event": "skipped", "status": "missing"}
                )
                next_index += 1
                last_progress = clock()
                continue
            # ahead[0] == next_index: the blob landed between the two
            # reads — loop back and serve it
            continue

        total = max(states) + 1
        status = states.get(next_index)
        if status is None and next_index < total:
            # a gap inside the known chunk-index space (explicit
            # chunk_index submissions can be sparse or out of order):
            # give it a few polls to appear — a submission racing this
            # snapshot writes records in index order — then skip it,
            # or the stream would idle to timeout forever with
            # delivered chunks waiting past the gap. An index skipped
            # here and submitted LATER is served by /raw, not the
            # stream (the in-order contract is what makes the resume
            # cursor a single integer).
            gap_polls += 1
            if gap_polls < 4:
                sleep(poll_s)
                continue
            yield _record(
                {"chunk": next_index, "event": "skipped", "status": "missing"}
            )
            next_index += 1
            last_progress = clock()
            gap_polls = 0
            continue
        if status in JobStatus.FAILED:
            # terminal failure (dead letter / cmd failed): no output is
            # coming for this chunk — advance the cursor past it
            yield _record(
                {"chunk": next_index, "event": "skipped", "status": status}
            )
            next_index += 1
            last_progress = clock()
            continue
        if next_index >= total and all(
            s in JobStatus.TERMINAL for s in states.values()
        ):
            yield _record({"event": "end", "next_chunk": next_index})
            return

        if clock() - last_progress >= idle_timeout_s:
            yield _record({"event": "timeout", "next_chunk": next_index})
            return
        sleep(poll_s)
