"""Gateway-tier scan-result cache: the interactive short-circuit
(docs/GATEWAY.md §QoS).

The fleet result tier (docs/CACHING.md) already means a worker never
re-walks content any worker has resolved — but an interactive lookup
still pays admission, dispatch, a worker poll and a device round trip
to learn what the fleet already knows. This module closes that last
gap at the FRONT door: completed small chunks are written back keyed
by ``(module, chunk target lines)``, and an interactive submission
whose every chunk is fleet-known is answered AT THE GATEWAY — outputs
persisted, job records created COMPLETE, zero worker dispatch
(``JobQueueService.complete_scan_from_cache``).

Rides the same :class:`~swarm_tpu.cache.tier.SharedResultTier` as the
verdict/confirm families (family ``"g"``, own epoch namespace
``gw.g<generation>``), so:

- the fencing-token discipline applies to gateway writers exactly as
  to workers (a superseded server instance cannot poison the tier);
- the operator ``bump_epoch`` lever invalidates gateway entries along
  with every other family — the documented move after a corpus change
  (the gateway holds no corpus, so content-digest scoping cannot apply
  here; the generation counter is the whole invalidation story);
- a dead backend degrades to pass-through (every lookup a miss, every
  writeback dropped) — the cache is an accelerator, never a
  dependency.

Bulk submissions never consult this cache; with ``cache_backend=off``
(the default) it is never built at all, preserving the pre-QoS wire
behavior byte-for-byte.
"""

from __future__ import annotations

import base64
import hashlib
import threading
from typing import Optional, Sequence

from swarm_tpu.cache.tier import (
    SharedResultTier,
    _FORMAT,
    _lp,
    _lp_seq,
    _process_token,
)

#: tier value family for gateway scan entries ("v" = verdict planes,
#: "c" = confirm verdicts — docs/CACHING.md)
FAMILY = "g"


def scan_chunk_digest(module: str, chunk_lines: Sequence[str]) -> str:
    """Content address of one submission chunk: sha256 over the module
    name and the chunk's target lines, length-prefixed (the same
    discipline as ``cache.tier.row_digest`` — concatenation stays
    unambiguous). Since the per-target key landed this is the
    MIGRATION-PATH key: still consulted on lookup so entries written by
    a pre-migration server keep hitting for one epoch, no longer
    written. Remove with the next ``bump_epoch``-worthy change."""
    out = bytearray(_FORMAT)
    _lp(out, b"gwscan")
    _lp(out, module.encode("utf-8", "surrogateescape"))
    _lp_seq(out, chunk_lines)
    return hashlib.sha256(bytes(out)).hexdigest()


def scan_target_digest(module: str, target: str) -> str:
    """Content address of ONE target line's output segment — the
    primary gateway-family key. Keying by (module, target) instead of
    (module, chunk) means any re-chunking of the same assets dedups: a
    monitor epoch at batch 5 hits entries written by a one-shot scan at
    batch 16, because both decompose to the same target keys."""
    out = bytearray(_FORMAT)
    _lp(out, b"gwtarget")
    _lp(out, module.encode("utf-8", "surrogateescape"))
    _lp(out, target.encode("utf-8", "surrogateescape"))
    return hashlib.sha256(bytes(out)).hexdigest()


def split_output_segments(output: bytes, n: int) -> Optional[list[bytes]]:
    """Split a chunk output into per-target segments, one per input
    line, such that ``b"".join(segments) == output`` exactly. None when
    the output does not carry one line per target (multi-line verdict
    modules) — those chunks stay chunk-granular. The invariant is what
    makes per-target reassembly byte-identical to the output a worker
    would have uploaded for the same chunk."""
    if n <= 0:
        return None
    if n == 1:
        return [output]
    parts = output.split(b"\n")
    if parts and parts[-1] == b"":
        core = parts[:-1]
        if len(core) != n:
            return None
        return [p + b"\n" for p in core]
    if len(parts) != n:
        return None
    return [p + b"\n" for p in parts[:-1]] + [parts[-1]]


class GatewayScanCache:
    """The server's view of the gateway family: epoch-bound, fenced,
    fail-open. One instance per server process; thread contract —
    request threads call ``lookup_chunks``/``writeback`` concurrently,
    all mutable state sits under ``_lock``."""

    #: how long a read epoch generation is trusted before re-reading —
    #: the propagation ceiling for an operator ``bump_epoch`` against a
    #: live gateway (same constant as the worker-side cache client)
    _EPOCH_TTL_S = 60.0

    def __init__(self, tier: SharedResultTier, writer_id: str = "gateway"):
        self._tier = tier
        self._writer = f"gw:{writer_id}"
        self._lock = threading.Lock()  # guards: _epoch, _epoch_read_at, _token, _hits, _misses, _stored, _warned
        self._epoch: Optional[str] = None
        self._epoch_read_at = 0.0
        self._token: Optional[int] = None
        self._hits = 0
        self._misses = 0
        self._stored = 0
        self._warned = False

    # ------------------------------------------------------------------
    def _degraded(self, e: Exception) -> None:
        with self._lock:
            warn = not self._warned
            self._warned = True
        if warn:
            print(
                f"gateway scan cache degraded to pass-through "
                f"({type(e).__name__}: {e})"
            )

    def _ensure_bound(self) -> Optional[tuple[str, int]]:
        """(epoch, fencing token), read through the store — None while
        the backend is unreachable (the caller treats the cache as a
        miss / dropped write)."""
        import time

        now = time.monotonic()
        with self._lock:
            if (
                self._epoch is not None
                and self._token is not None
                and now - self._epoch_read_at < self._EPOCH_TTL_S
            ):
                return self._epoch, self._token
        try:
            gen = self._tier.epoch_generation()
            token = _process_token(self._tier, self._writer)
        except Exception as e:
            self._degraded(e)
            return None
        epoch = f"gw.g{gen}"
        with self._lock:
            self._epoch = epoch
            self._epoch_read_at = now
            self._token = token
            self._warned = False
        return epoch, token

    # ------------------------------------------------------------------
    @staticmethod
    def _b64(raw) -> Optional[bytes]:
        try:
            return base64.b64decode(raw, validate=True)
        except (ValueError, TypeError):
            # a corrupt entry is a MISS, never an exception on the
            # submit path
            return None

    def lookup_chunks_partial(
        self, module: str, chunks: Sequence[Sequence[str]]
    ) -> Optional[list]:
        """Per-chunk outputs with None holes for unknown chunks — or
        None outright when the backend is unreachable. One batched tier
        read covers every target digest of every chunk PLUS the legacy
        chunk digests (the migration-path read). A chunk resolves
        per-target first (so re-chunked assets dedup), falling back to
        its whole-chunk entry."""
        if not chunks:
            return None
        bound = self._ensure_bound()
        if bound is None:
            return None
        epoch, _token = bound
        want: list[str] = []
        per_chunk: list[tuple[list[str], str]] = []
        for c in chunks:
            tdigests = [scan_target_digest(module, t) for t in c]
            cdigest = scan_chunk_digest(module, c)
            per_chunk.append((tdigests, cdigest))
            want.extend(tdigests)
            want.append(cdigest)
        try:
            got = self._tier.get_many(FAMILY, epoch, want)
        except Exception as e:
            self._degraded(e)
            return None
        outputs: list = []
        for tdigests, cdigest in per_chunk:
            segments = [self._b64(got[d]) for d in tdigests if d in got]
            if len(segments) == len(tdigests) and all(
                s is not None for s in segments
            ):
                outputs.append(b"".join(segments))
                with self._lock:
                    self._hits += 1
                continue
            whole = self._b64(got[cdigest]) if cdigest in got else None
            outputs.append(whole)
            with self._lock:
                if whole is not None:
                    self._hits += 1
                else:
                    self._misses += 1
        return outputs

    def lookup_chunks(
        self, module: str, chunks: Sequence[Sequence[str]]
    ) -> Optional[list[bytes]]:
        """Outputs for EVERY chunk of a submission, or None when any
        chunk is unknown (all-or-nothing: a partial hit falls through
        to normal admission so lease/retry semantics stay untouched —
        the interactive short-circuit contract). The monitor epoch path
        uses :meth:`lookup_chunks_partial` instead, where partial
        completion is the point."""
        outputs = self.lookup_chunks_partial(module, chunks)
        if outputs is None or any(o is None for o in outputs):
            return None
        return outputs

    def writeback(
        self, module: str, chunk_lines: Sequence[str], output: bytes
    ) -> bool:
        """Store one completed chunk's output under its content keys —
        fenced, best-effort (a dropped write costs one future device
        round trip, never correctness). Splittable outputs (one line
        per target — the normal module contract) store PER-TARGET
        segments; unsplittable ones keep the whole-chunk key, so
        multi-line-verdict modules stay exactly as cacheable as before
        the migration."""
        bound = self._ensure_bound()
        if bound is None:
            return False
        epoch, token = bound
        segments = split_output_segments(output, len(chunk_lines))
        if segments is not None:
            pairs = [
                (
                    scan_target_digest(module, target),
                    base64.b64encode(seg).decode("ascii"),
                )
                for target, seg in zip(chunk_lines, segments)
            ]
        else:
            pairs = [
                (
                    scan_chunk_digest(module, chunk_lines),
                    base64.b64encode(output).decode("ascii"),
                )
            ]
        try:
            outcome, stored = self._tier.put_many(
                FAMILY, epoch, pairs, self._writer, token,
            )
        except Exception as e:
            self._degraded(e)
            return False
        if outcome == "stored" and stored:
            with self._lock:
                self._stored += stored
            return True
        return False

    def counters(self) -> dict:
        """Lifetime outcomes (test/bench surface)."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "stored": self._stored,
            }


def build_gateway_cache(cfg) -> Optional[GatewayScanCache]:
    """Construct the gateway cache from a Config: None when the shared
    tier is off (``cache_backend=off``, the default) or the gateway
    short-circuit is disabled (``qos_gateway_cache=false``) — either
    way the submit path is byte-identical to pre-QoS behavior. Backend
    dispatch AND the TTL/size retention policy ride
    :func:`cache.tier.build_tier`, so a server-only process honors
    ``cache_ttl_s``/``cache_max_entries`` exactly like a worker."""
    if not getattr(cfg, "qos_gateway_cache", True):
        return None
    from swarm_tpu.cache.tier import build_tier

    tier = build_tier(cfg)
    if tier is None:
        return None
    return GatewayScanCache(tier, writer_id=getattr(cfg, "worker_id", "gw"))
