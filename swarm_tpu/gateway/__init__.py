"""Multi-tenant admission-controlled gateway (docs/GATEWAY.md).

The serving tier's front door: every ``/queue`` submission carries a
tenant id (``X-Swarm-Tenant`` header; absent = the ``default`` tenant,
preserving the reference wire contract), and admission is decided by
:class:`~swarm_tpu.gateway.admission.AdmissionController` — per-tenant
token buckets, bounded per-tenant queues, and a composite backpressure
signal (queue depth, worker-reported in-flight saturation, breaker
states) that sheds with ``429 + Retry-After`` instead of letting
overload turn into silent queue growth. Results stream back over
``GET /stream/<scan_id>`` as NDJSON push
(:mod:`swarm_tpu.gateway.streaming`), and the queue-depth-driven
autoscale advisor lives in :class:`swarm_tpu.server.fleet.
AutoscaleAdvisor`.
"""

from swarm_tpu.gateway.admission import (  # noqa: F401
    AdmissionController,
    Decision,
    PressureSnapshot,
    TokenBucket,
)
from swarm_tpu.gateway.streaming import stream_scan  # noqa: F401
