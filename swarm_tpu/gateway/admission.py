"""Tenant model + admission control (docs/GATEWAY.md).

One global FIFO in front of the fleet means one abusive tenant starves
every other scan and overload turns into unbounded queue growth. The
gateway replaces that front door with three deterministic mechanisms:

- **Per-tenant token buckets** — ``gateway_tenant_rate`` submissions/s
  with ``gateway_tenant_burst`` burst capacity (0 rate = unlimited, the
  default, so single-operator deployments are unchanged).
- **Bounded per-tenant queues** — a tenant whose waiting-job depth
  reaches ``gateway_tenant_queue_max`` is shed, not buffered (0 =
  unbounded default).
- **Composite pressure load shed** — admission consults one
  :class:`PressureSnapshot` (queue depth by state, worker-reported
  scheduler in-flight saturation, open breaker count) folded into a
  single scalar; at/over ``gateway_shed_pressure`` every non-empty
  submission sheds with ``429 + Retry-After``. Shed, never block: the
  client owns the retry schedule. Per-class overrides
  (``gateway_shed_pressure_bulk`` / ``_interactive``, 0 = use the
  global knob) let bulk shed first so interactive latency survives a
  pressure ramp.

Every decision is a PURE function of ``(tenant state, snapshot, now)``
— :meth:`AdmissionController.decide` takes both explicitly so tests
can replay any overload scenario byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Optional

from swarm_tpu.telemetry.gateway_export import (
    GATEWAY_ADMITTED,
    GATEWAY_PRESSURE,
    GATEWAY_SHED,
)

DEFAULT_TENANT = "default"


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/s refill up to
    ``burst`` capacity; ``take(now)`` consumes one token or reports the
    seconds until one is available. Time is an explicit argument — the
    bucket holds no clock, so decisions replay exactly."""

    def __init__(self, rate: float, burst: int):
        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._stamp: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._stamp is not None and now > self._stamp:
            self._tokens = min(
                float(self.burst), self._tokens + (now - self._stamp) * self.rate
            )
        self._stamp = now if self._stamp is None else max(self._stamp, now)

    def take(self, now: float) -> tuple[bool, float]:
        """``(True, 0.0)`` and one token consumed, or ``(False,
        retry_after_s)`` — the exact wait until the next whole token."""
        if self.rate <= 0:
            return True, 0.0  # unlimited
        self._refill(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        return self._tokens


@dataclasses.dataclass(frozen=True)
class PressureSnapshot:
    """One observation of the serving tier's load, the sole input of
    the shed decision (beyond the tenant's own bucket/queue state)."""

    #: jobs waiting in dispatch queues, all tenants (queued state)
    queue_depth: int = 0
    #: jobs currently leased out (any ACTIVE status)
    active_jobs: int = 0
    #: worker-reported scheduler in-flight saturation, 0..1 (the
    #: fraction of wall time the submit thread stalled on a full
    #: in-flight window — see worker heartbeat/perf plumbing)
    saturation: float = 0.0
    #: process-wide circuit breakers not in the closed state
    open_breakers: int = 0


@dataclasses.dataclass(frozen=True)
class Decision:
    admitted: bool
    reason: str = "ok"  # "ok" | "rate" | "queue_full" | "pressure"
    retry_after_s: float = 0.0
    pressure: float = 0.0


class AdmissionController:
    """Per-tenant admission state + the deterministic decision rule.

    Thread contract: ``decide``/``note_saturation``/``snapshot`` are
    called from server request threads; all mutable state sits under
    ``_lock``."""

    def __init__(
        self,
        tenant_rate: float = 0.0,
        tenant_burst: int = 64,
        tenant_queue_max: int = 0,
        queue_high: int = 0,
        shed_pressure: float = 1.0,
        retry_after_s: float = 1.0,
        breaker_pressure: float = 0.5,
        max_tenants: int = 1024,
        saturation_ttl_s: float = 60.0,
        tenant_ttl_s: float = 3600.0,
        shed_pressure_bulk: float = 0.0,
        shed_pressure_interactive: float = 0.0,
    ):
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = int(tenant_burst)
        self.tenant_queue_max = int(tenant_queue_max)
        self.queue_high = int(queue_high)
        self.shed_pressure = float(shed_pressure)
        # per-class shed thresholds (docs/GATEWAY.md §QoS): bulk sheds
        # at a LOWER pressure than interactive so background work makes
        # room before foreground work feels anything. 0 = fall back to
        # the single shed_pressure knob — the pre-QoS wire behavior.
        self.shed_pressure_bulk = float(shed_pressure_bulk)
        self.shed_pressure_interactive = float(shed_pressure_interactive)
        self.retry_after_s = float(retry_after_s)
        self.breaker_pressure = float(breaker_pressure)
        # tenant-id cardinality bound: tenant names are CLIENT data, so
        # without a cap a flooder rotating fresh ids would mint a fresh
        # full token bucket per request (defeating the rate limit) and
        # grow per-tenant state without bound. A NEW tenant past the
        # cap sheds with reason "tenant_limit".
        self.max_tenants = max(1, int(max_tenants))
        # a worker's saturation report decays after this long: a dead
        # or idle worker's last report must not pin fleet pressure
        # (heartbeats only tick while a chunk runs, so nothing would
        # ever overwrite it)
        self.saturation_ttl_s = float(saturation_ttl_s)
        # registry slots free again after this much tenant INACTIVITY:
        # without expiry, one rotation flood would fill the cap and
        # lock out every genuinely new tenant until restart; with it,
        # a flooder's dead ids age out while the lockout worst case
        # for a new tenant is bounded by one TTL. A rotation attack
        # regains fresh buckets only at slots/TTL — a bounded trickle.
        self.tenant_ttl_s = float(tenant_ttl_s)
        self._lock = threading.Lock()  # guards: _buckets, _counts (reads), _saturation, _last_seen
        self._buckets: dict[str, TokenBucket] = {}
        # tenant -> {"admitted": n, "shed": n, "shed_rate": n, ...}
        self._counts: dict[str, dict[str, int]] = {}
        # tenant -> last decide() stamp (the idle-expiry clock)
        self._last_seen: dict[str, float] = {}
        # worker id -> (last reported in-flight saturation 0..1,
        # monotonic stamp); the snapshot folds live entries with max()
        # so one saturated worker is visible even in a mixed fleet
        self._saturation: dict[str, tuple[float, float]] = {}

    @classmethod
    def from_config(cls, cfg) -> "AdmissionController":
        return cls(
            tenant_rate=getattr(cfg, "gateway_tenant_rate", 0.0),
            tenant_burst=getattr(cfg, "gateway_tenant_burst", 64),
            tenant_queue_max=getattr(cfg, "gateway_tenant_queue_max", 0),
            queue_high=getattr(cfg, "gateway_queue_high", 0),
            shed_pressure=getattr(cfg, "gateway_shed_pressure", 1.0),
            retry_after_s=getattr(cfg, "gateway_retry_after_s", 1.0),
            max_tenants=getattr(cfg, "gateway_max_tenants", 1024),
            saturation_ttl_s=getattr(cfg, "gateway_saturation_ttl_s", 60.0),
            tenant_ttl_s=getattr(cfg, "gateway_tenant_ttl_s", 3600.0),
            shed_pressure_bulk=getattr(cfg, "gateway_shed_pressure_bulk", 0.0),
            shed_pressure_interactive=getattr(
                cfg, "gateway_shed_pressure_interactive", 0.0
            ),
        )

    def shed_threshold(self, qos: Optional[str]) -> float:
        """The pressure at/over which this QoS class sheds. The
        per-class knobs default to 0 = "use the global threshold", so
        deployments that never set them keep the single-knob rule."""
        if qos == "bulk" and self.shed_pressure_bulk > 0:
            return self.shed_pressure_bulk
        if qos == "interactive" and self.shed_pressure_interactive > 0:
            return self.shed_pressure_interactive
        return self.shed_pressure

    # ------------------------------------------------------------------
    def pressure(self, snap: PressureSnapshot) -> float:
        """Fold one snapshot into the composite scalar. max() of the
        component signals, each normalized so 1.0 means "shed" under
        the default threshold: queue depth against ``queue_high`` (0
        disables the component), reported in-flight saturation as-is,
        and any open breaker contributing a fixed ``breaker_pressure``
        floor (degraded, not yet shedding on its own)."""
        parts = [0.0]
        if self.queue_high > 0:
            parts.append(snap.queue_depth / float(self.queue_high))
        parts.append(min(1.0, max(0.0, float(snap.saturation))))
        if snap.open_breakers > 0:
            parts.append(self.breaker_pressure)
        return max(parts)

    def note_saturation(self, worker_id: str, value, now=None) -> None:
        """Record one worker's reported in-flight saturation (from the
        lease-heartbeat body or a completed job's perf fields)."""
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if not math.isfinite(v):
            return
        import time

        stamp = time.monotonic() if now is None else float(now)
        with self._lock:
            self._saturation[worker_id] = (min(1.0, max(0.0, v)), stamp)

    def drop_saturation(self, worker_id: str) -> None:
        """Forget a worker's saturation report NOW. A deregistered or
        preempted worker is gone — waiting out ``saturation_ttl_s``
        would let its final (often maximal: it was draining under
        load) report pin fleet pressure for up to a minute after the
        node died."""
        with self._lock:
            self._saturation.pop(worker_id, None)

    def fleet_saturation(self, now=None) -> float:
        """max() over reports younger than ``saturation_ttl_s`` —
        stale ones are dropped (a dead worker's last word must not
        shed traffic on an idle fleet forever)."""
        import time

        cutoff = (time.monotonic() if now is None else float(now))
        cutoff -= self.saturation_ttl_s
        with self._lock:
            for worker_id in [
                w for w, (_v, ts) in self._saturation.items() if ts < cutoff
            ]:
                del self._saturation[worker_id]
            return max(
                (v for v, _ts in self._saturation.values()), default=0.0
            )

    # ------------------------------------------------------------------
    def decide(
        self,
        tenant: str,
        snap: PressureSnapshot,
        now: float,
        tenant_depth: int = 0,
        qos: Optional[str] = None,
    ) -> Decision:
        """Admit or shed one submission for ``tenant``. Deterministic
        given ``(snapshot, now, tenant_depth, qos)`` and the tenant's
        bucket fill; counters and gauges update as a side effect."""
        pressure = self.pressure(snap)
        shed_at = self.shed_threshold(qos)
        GATEWAY_PRESSURE.labels().set(pressure)
        with self._lock:
            if tenant not in self._counts and tenant != DEFAULT_TENANT:
                # the default tenant is the reference wire contract —
                # it can NEVER be locked out by the cardinality cap
                if len(self._counts) >= self.max_tenants:
                    # slots free again after tenant_ttl_s of
                    # inactivity, so a past rotation flood doesn't
                    # deny new tenants forever
                    cutoff = now - self.tenant_ttl_s
                    for stale in [
                        t for t, seen in self._last_seen.items()
                        if seen < cutoff and t != DEFAULT_TENANT
                    ]:
                        self._counts.pop(stale, None)
                        self._buckets.pop(stale, None)
                        self._last_seen.pop(stale, None)
                if len(self._counts) >= self.max_tenants:
                    # tenant-rotation defense: a flooder minting fresh
                    # ids must not get a fresh token bucket per
                    # request, and per-tenant state must stay bounded.
                    # Counted against the shared "default" row so the
                    # metric label space stays bounded too.
                    GATEWAY_SHED.labels(
                        tenant=DEFAULT_TENANT, reason="tenant_limit"
                    ).inc()
                    return Decision(
                        False, "tenant_limit", self.retry_after_s, pressure
                    )
            self._last_seen[tenant] = now
            counts = self._counts.setdefault(
                tenant, {"admitted": 0, "shed": 0}
            )
            if pressure >= shed_at:
                decision = Decision(
                    False, "pressure", self.retry_after_s, pressure
                )
            elif (
                self.tenant_queue_max > 0
                and tenant_depth >= self.tenant_queue_max
            ):
                decision = Decision(
                    False, "queue_full", self.retry_after_s, pressure
                )
            else:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = TokenBucket(
                        self.tenant_rate, self.tenant_burst
                    )
                ok, wait = bucket.take(now)
                if ok:
                    decision = Decision(True, "ok", 0.0, pressure)
                else:
                    decision = Decision(False, "rate", wait, pressure)
            if decision.admitted:
                counts["admitted"] += 1
            else:
                counts["shed"] += 1
                counts[f"shed_{decision.reason}"] = (
                    counts.get(f"shed_{decision.reason}", 0) + 1
                )
        if decision.admitted:
            GATEWAY_ADMITTED.labels(tenant=tenant).inc()
        else:
            GATEWAY_SHED.labels(tenant=tenant, reason=decision.reason).inc()
        return decision

    def snapshot(self) -> dict:
        """Per-tenant admitted/shed counters (the /tenants surface)."""
        with self._lock:
            return {t: dict(c) for t, c in self._counts.items()}
