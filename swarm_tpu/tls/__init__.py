"""TLS fingerprinting stack: wire codec, JARM/JA3S, used by the jarm module.

See swarm_tpu/tls/jarm.py for the fingerprint construction and
swarm_tpu/ops/cluster.py for the device-side clustering of the results.
"""

from swarm_tpu.tls.jarm import (  # noqa: F401
    TlsFingerprint,
    fingerprint_from_banners,
    ja3s,
    jarm_hash,
    probe_set,
)
from swarm_tpu.tls.wire import (  # noqa: F401
    HelloSpec,
    ServerHello,
    build_client_hello,
    parse_server_flight,
)
