"""Minimal TLS wire codec: ClientHello construction + ServerHello parse.

Just enough TLS (no crypto) to drive active TLS fingerprinting: build
ClientHello probes with controlled version/cipher-order/extension
shapes, and parse whatever the server sends back — ServerHello fields
(version, chosen cipher, extension types in order, ALPN selection) or
an alert. The handshake is never completed; fingerprinting only needs
the server's first flight.

New capability relative to the reference (Jec00/swarm drives external
Go/C tools and has no TLS stack of its own — SURVEY.md §2.2); built for
BASELINE.json config #5 (JA3/JARM fingerprint + clustering).
"""

from __future__ import annotations

import dataclasses
import os
import struct
from typing import Optional

HANDSHAKE = 0x16
ALERT = 0x15
CCS = 0x14
APPDATA = 0x17

HELLO_CLIENT = 0x01
HELLO_SERVER = 0x02

TLS10 = 0x0301
TLS11 = 0x0302
TLS12 = 0x0303
TLS13 = 0x0304

EXT_SNI = 0x0000
EXT_GROUPS = 0x000A
EXT_EC_FORMATS = 0x000B
EXT_SIGALGS = 0x000D
EXT_ALPN = 0x0010
EXT_EMS = 0x0017
EXT_SESSION_TICKET = 0x0023
EXT_SUPPORTED_VERSIONS = 0x002B
EXT_PSK_MODES = 0x002D
EXT_KEY_SHARE = 0x0033
EXT_RENEG = 0xFF01

GREASE = 0x0A0A  # one fixed GREASE value keeps probes deterministic

X25519 = 0x001D
SECP256R1 = 0x0017
SECP384R1 = 0x0018


def _u8(v: int) -> bytes:
    return struct.pack("!B", v)


def _u16(v: int) -> bytes:
    return struct.pack("!H", v)


def _u24(v: int) -> bytes:
    return struct.pack("!I", v)[1:]


def _vec8(b: bytes) -> bytes:
    return _u8(len(b)) + b


def _vec16(b: bytes) -> bytes:
    return _u16(len(b)) + b


def ext(ext_type: int, body: bytes) -> bytes:
    return _u16(ext_type) + _vec16(body)


def sni_ext(hostname: str) -> bytes:
    name = hostname.encode("idna") if hostname else b""
    entry = _u8(0) + _vec16(name)
    return ext(EXT_SNI, _vec16(entry))


def alpn_ext(protocols: list[bytes]) -> bytes:
    blob = b"".join(_vec8(p) for p in protocols)
    return ext(EXT_ALPN, _vec16(blob))


def groups_ext(groups: list[int]) -> bytes:
    return ext(EXT_GROUPS, _vec16(b"".join(_u16(g) for g in groups)))


def sigalgs_ext() -> bytes:
    algs = [0x0403, 0x0804, 0x0401, 0x0503, 0x0805, 0x0501, 0x0806, 0x0601, 0x0201]
    return ext(EXT_SIGALGS, _vec16(b"".join(_u16(a) for a in algs)))


def supported_versions_ext(versions: list[int]) -> bytes:
    return ext(EXT_SUPPORTED_VERSIONS, _vec8(b"".join(_u16(v) for v in versions)))


def key_share_ext(group: int = X25519, pub: Optional[bytes] = None) -> bytes:
    # Any 32 bytes form a valid x25519 public key; the handshake is
    # abandoned after the server's first flight so the key never matters.
    pub = pub if pub is not None else bytes(range(1, 33))
    entry = _u16(group) + _vec16(pub)
    return ext(EXT_KEY_SHARE, _vec16(entry))


@dataclasses.dataclass
class HelloSpec:
    """One ClientHello probe shape (what varies across JARM probes)."""

    record_version: int = TLS12
    hello_version: int = TLS12
    ciphers: tuple[int, ...] = ()
    hostname: str = ""
    alpn: tuple[bytes, ...] = (b"h2", b"http/1.1")
    offer_tls13: bool = False
    grease: bool = False
    extension_order_reversed: bool = False
    minimal: bool = False  # SNI + groups only (rare-extension shape)


def build_client_hello(spec: HelloSpec, random: Optional[bytes] = None) -> bytes:
    """HelloSpec → full TLS record bytes ready to write to the socket."""
    rnd = random if random is not None else os.urandom(32)
    assert len(rnd) == 32
    session_id = os.urandom(32) if spec.offer_tls13 else b""

    ciphers = list(spec.ciphers)
    if spec.grease:
        ciphers = [GREASE] + ciphers
    cipher_blob = b"".join(_u16(c) for c in ciphers)

    exts: list[bytes] = []
    if spec.hostname:
        exts.append(sni_ext(spec.hostname))
    exts.append(groups_ext(([GREASE] if spec.grease else []) + [X25519, SECP256R1, SECP384R1]))
    if not spec.minimal:
        exts.append(ext(EXT_EC_FORMATS, _vec8(b"\x00")))
        exts.append(sigalgs_ext())
        if spec.alpn:
            exts.append(alpn_ext(list(spec.alpn)))
        exts.append(ext(EXT_EMS, b""))
        exts.append(ext(EXT_SESSION_TICKET, b""))
        exts.append(ext(EXT_RENEG, b"\x00"))
    if spec.offer_tls13:
        versions = ([GREASE] if spec.grease else []) + [TLS13, TLS12]
        exts.append(supported_versions_ext(versions))
        exts.append(ext(EXT_PSK_MODES, _vec8(b"\x01")))
        exts.append(key_share_ext())
    if spec.extension_order_reversed:
        exts = exts[::-1]
    ext_blob = b"".join(exts)

    body = (
        _u16(spec.hello_version)
        + rnd
        + _vec8(session_id)
        + _vec16(cipher_blob)
        + _vec8(b"\x00")  # null compression
        + _vec16(ext_blob)
    )
    handshake = _u8(HELLO_CLIENT) + _u24(len(body)) + body
    return _u8(HANDSHAKE) + _u16(spec.record_version) + _vec16(handshake)


# ---------------------------------------------------------------------------
# Server-side parse


@dataclasses.dataclass
class ServerHello:
    version: int  # negotiated (supported_versions-aware)
    legacy_version: int
    cipher: int
    extensions: tuple[int, ...]  # extension types, wire order
    alpn: bytes = b""
    alert: Optional[int] = None  # alert description when no hello came back

    @property
    def ok(self) -> bool:
        return self.cipher != -1


NO_HELLO = ServerHello(
    version=-1, legacy_version=-1, cipher=-1, extensions=(), alert=None
)


def parse_server_flight(raw: bytes) -> ServerHello:
    """Bytes off the wire → first ServerHello (or alert) found.

    Walks TLS records, reassembles handshake fragments, stops at the
    first ServerHello. Tolerates trailing garbage and truncation —
    internet scans see every malformed variant imaginable.
    """
    pos = 0
    handshake = b""
    alert_desc: Optional[int] = None
    while pos + 5 <= len(raw):
        rtype = raw[pos]
        rlen = struct.unpack("!H", raw[pos + 3 : pos + 5])[0]
        frag = raw[pos + 5 : pos + 5 + rlen]
        pos += 5 + rlen
        if rtype == ALERT and len(frag) >= 2 and alert_desc is None:
            alert_desc = frag[1]
        elif rtype == HANDSHAKE:
            handshake += frag
            hello = _parse_handshake(handshake)
            if hello is not None:
                return hello
        elif rtype not in (CCS, APPDATA):
            break  # not TLS at all
    if alert_desc is not None:
        return dataclasses.replace(NO_HELLO, alert=alert_desc)
    return NO_HELLO


def _parse_handshake(blob: bytes) -> Optional[ServerHello]:
    pos = 0
    while pos + 4 <= len(blob):
        mtype = blob[pos]
        mlen = struct.unpack("!I", b"\x00" + blob[pos + 1 : pos + 4])[0]
        if pos + 4 + mlen > len(blob):
            return None  # fragment incomplete; caller feeds more records
        if mtype == HELLO_SERVER:
            return _parse_server_hello(blob[pos + 4 : pos + 4 + mlen])
        pos += 4 + mlen
    return None


def _parse_server_hello(body: bytes) -> Optional[ServerHello]:
    try:
        pos = 0
        legacy = struct.unpack("!H", body[pos : pos + 2])[0]
        pos += 2 + 32  # random
        sid_len = body[pos]
        pos += 1 + sid_len
        cipher = struct.unpack("!H", body[pos : pos + 2])[0]
        pos += 2 + 1  # compression
        exts: list[int] = []
        version = legacy
        alpn = b""
        if pos + 2 <= len(body):
            ext_total = struct.unpack("!H", body[pos : pos + 2])[0]
            pos += 2
            end = min(pos + ext_total, len(body))
            while pos + 4 <= end:
                etype, elen = struct.unpack("!HH", body[pos : pos + 4])
                data = body[pos + 4 : pos + 4 + elen]
                pos += 4 + elen
                exts.append(etype)
                if etype == EXT_SUPPORTED_VERSIONS and len(data) >= 2:
                    version = struct.unpack("!H", data[:2])[0]
                elif etype == EXT_ALPN and len(data) >= 3:
                    # ALPN: u16 list len, u8 name len, name
                    nlen = data[2]
                    alpn = data[3 : 3 + nlen]
        return ServerHello(
            version=version,
            legacy_version=legacy,
            cipher=cipher,
            extensions=tuple(exts),
            alpn=alpn,
        )
    except (IndexError, struct.error):
        return None
