"""JARM-style active TLS fingerprinting + JA3S, over the native scan I/O.

Ten crafted ClientHellos (varying TLS version, cipher order, GREASE,
ALPN, extension shape) are sent to each target; the server's choices —
cipher, version, ALPN, extension order — across all ten probes form the
fingerprint:

    62 chars = 30 (3 per probe: 2-hex cipher index + 1 version code)
             + 32 (truncated sha256 of the concatenated extension
                   choices across probes)

The construction mirrors the public JARM scheme (Salesforce): identical
probe *shapes* (forward/reverse/top-half/bottom-half/middle-out cipher
orders, 1.1/1.2/1.3 versions, no-overlap probe) and the same
30+32 output split; the byte-level encoding tables are this module's
own, so hashes are self-consistent within the framework rather than
comparable to upstream JARM strings. The output field is therefore
named ``jarmx`` (JARM-style, not upstream-comparable) — clustering and
intra-framework comparison are first-class. JA3S is the standard
algorithm (md5 of "version,cipher,ext-list" in decimals) and matches
any compliant implementation.

For interop with public TLS-intel feeds, :func:`upstream_jarm`
implements the upstream *encoding pipeline* exactly (per-probe raw
``cipher|version|alpn|extensions`` components; cipher encoded as the
zero-padded 1-based index into the upstream cipher-order table;
version as ``"abcdef"[minor]``; tail = sha256 of the concatenated
``alpn+extensions`` components, first 32 hex chars). The cipher-order
table ships in-repo as public-spec config data
(swarm_tpu/tls/jarm_table.py — a reconstruction with its provenance
bound documented there), so ``TlsFingerprint.jarm`` populates out of
the box; ``SWARM_JARM_CIPHER_TABLE`` (path to a file with one
lowercase hex cipher per line, in the upstream list's order,
extracted from the Salesforce jarm repo) remains the authoritative
operator override and replaces the default entirely. The encoding
layer itself is vector-pinned by tests/test_tls_jarm.py.

Fingerprints feed the density-peaks clustering kernel
(swarm_tpu/ops/cluster.py) — BASELINE.json config #5.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
from typing import Optional, Sequence

from swarm_tpu.tls import wire

# Canonical cipher table: every suite the probes may offer, in one fixed
# order — a chosen cipher encodes as its 2-hex-digit index here.
CIPHERS_12 = (
    0xC02C, 0xC030, 0x009F, 0xCCA9, 0xCCA8, 0xCCAA, 0xC02B, 0xC02F,
    0x009E, 0xC024, 0xC028, 0x006B, 0xC023, 0xC027, 0x0067, 0xC00A,
    0xC014, 0x0039, 0xC009, 0xC013, 0x0033, 0x009D, 0x009C, 0x003D,
    0x003C, 0x0035, 0x002F, 0x00FF,
)
CIPHERS_13 = (0x1301, 0x1302, 0x1303, 0x1304)
CANONICAL = CIPHERS_13 + CIPHERS_12

_VERSION_CODE = {
    wire.TLS10: "1",
    wire.TLS11: "2",
    wire.TLS12: "3",
    wire.TLS13: "4",
    0x0300: "0",
}


def _top_half(c: Sequence[int]) -> tuple[int, ...]:
    return tuple(c[: len(c) // 2])


def _bottom_half(c: Sequence[int]) -> tuple[int, ...]:
    return tuple(c[len(c) // 2 :])


def _middle_out(c: Sequence[int]) -> tuple[int, ...]:
    out = []
    mid = len(c) // 2
    for k in range(len(c)):
        idx = mid + (k + 1) // 2 * (1 if k % 2 == 0 else -1)
        if 0 <= idx < len(c):
            out.append(c[idx])
    seen: set[int] = set()
    dedup = [x for x in out if not (x in seen or seen.add(x))]
    for x in c:  # parity edge: keep every cipher exactly once
        if x not in seen:
            dedup.append(x)
            seen.add(x)
    return tuple(dedup)


def probe_set(hostname: str) -> list[wire.HelloSpec]:
    """The 10 JARM probes for one target, deterministic order."""
    c12 = CIPHERS_12
    both = CIPHERS_13 + CIPHERS_12
    mk = wire.HelloSpec
    return [
        mk(hello_version=wire.TLS12, ciphers=c12, hostname=hostname),
        mk(hello_version=wire.TLS12, ciphers=c12[::-1], hostname=hostname),
        mk(hello_version=wire.TLS12, ciphers=_top_half(c12), hostname=hostname,
           alpn=(b"http/0.9", b"http/1.0", b"spdy/3", b"h2c")),
        mk(hello_version=wire.TLS12, ciphers=_bottom_half(c12), hostname=hostname,
           alpn=(), minimal=True),
        mk(hello_version=wire.TLS12, ciphers=_middle_out(c12), hostname=hostname,
           grease=True),
        mk(record_version=wire.TLS10, hello_version=wire.TLS11,
           ciphers=_middle_out(c12), hostname=hostname, alpn=(b"http/1.1",)),
        mk(hello_version=wire.TLS12, ciphers=both, hostname=hostname,
           offer_tls13=True),
        mk(hello_version=wire.TLS12, ciphers=both[::-1], hostname=hostname,
           offer_tls13=True),
        mk(hello_version=wire.TLS12, ciphers=(0x0A1A, 0x2A2A, 0x3A3A),
           hostname=hostname, offer_tls13=True, grease=True),
        mk(hello_version=wire.TLS12, ciphers=_middle_out(both),
           hostname=hostname, offer_tls13=True, grease=True,
           extension_order_reversed=True),
    ]


NUM_PROBES = 10
EMPTY_JARM = "0" * 62


def _probe_code(hello: wire.ServerHello) -> tuple[str, str]:
    """One probe's 3-char code + its extension-choice string."""
    if not hello.ok:
        return "000", ""
    try:
        idx = CANONICAL.index(hello.cipher) + 1
    except ValueError:
        idx = 0xFE  # server chose something we never offered
    code = f"{idx:02x}" + _VERSION_CODE.get(hello.version, "9")
    ext_str = (
        f"{hello.version:04x}|{hello.alpn.decode('latin1')}|"
        + "-".join(f"{e:04x}" for e in hello.extensions)
    )
    return code, ext_str


def jarm_hash(hellos: Sequence[Optional[wire.ServerHello]]) -> str:
    """10 parsed server flights → 62-char fingerprint."""
    assert len(hellos) == NUM_PROBES
    codes = []
    ext_parts = []
    for h in hellos:
        code, ext_str = _probe_code(h if h is not None else wire.NO_HELLO)
        codes.append(code)
        ext_parts.append(ext_str)
    head = "".join(codes)
    if head == "000" * NUM_PROBES:
        return EMPTY_JARM
    joined = ",".join(ext_parts)
    tail = (
        hashlib.sha256(joined.encode("latin1")).hexdigest()[:32]
        if any(ext_parts)
        else "0" * 32
    )
    return head + tail


def ja3s(hello: wire.ServerHello) -> str:
    """Standard JA3S: md5("version,cipher,ext1-ext2-...") decimals."""
    if not hello.ok:
        return ""
    s = (
        f"{hello.legacy_version},{hello.cipher},"
        + "-".join(str(e) for e in hello.extensions)
    )
    return hashlib.md5(s.encode()).hexdigest()


# --- upstream (Salesforce) JARM encoding pipeline --------------------------


def upstream_raw_result(hello: wire.ServerHello) -> str:
    """One probe's raw component string in the upstream format:
    ``cipher|version|alpn|ext1-ext2-...`` (lowercase 4-hex fields),
    empty components for a failed probe."""
    if not hello.ok:
        return "|||"
    exts = "-".join(f"{e:04x}" for e in hello.extensions)
    return (
        f"{hello.cipher:04x}|{hello.version:04x}|"
        f"{hello.alpn.decode('latin1')}|{exts}"
    )


@functools.lru_cache(maxsize=8)
def _cipher_codes(table: tuple) -> dict:
    """cipher hex -> upstream code, one dict per table (the hot
    fingerprint path must not re-scan the table per probe)."""
    return {c: f"{i + 1:x}".zfill(2) for i, c in enumerate(table)}


def _upstream_cipher_code(cipher_hex: str, table: Sequence[str]) -> str:
    if not cipher_hex:
        return "00"
    codes = _cipher_codes(tuple(table))
    # upstream cipher_bytes' search loop falls through to
    # count = len(table) + 1 when the cipher is absent — mirror it
    return codes.get(cipher_hex, f"{len(table) + 1:x}".zfill(2))


def _upstream_version_code(version_hex: str) -> str:
    if not version_hex:
        return "0"
    minor = int(version_hex[3], 16)
    if minor > 5:
        # upstream's "abcdef"[minor] would throw here too — it can only
        # ever see versions its own probes negotiated. A server feeding
        # us junk (0x4141) has no upstream-comparable encoding at all.
        raise ValueError(f"version {version_hex!r} outside JARM's domain")
    return "abcdef"[minor]


def upstream_jarm(raw_results: Sequence[str], table: Sequence[str]) -> str:
    """Upstream JARM hash from 10 raw component strings + the upstream
    cipher-order ``table`` (lowercase 4-hex entries, upstream order).

    Exact upstream scheme: 3 chars per probe (2-hex 1-based cipher
    index, 1-char version letter) + first 32 hex chars of sha256 over
    the concatenated ``alpn + extensions`` components."""
    assert len(raw_results) == NUM_PROBES
    if all(r == "|||" for r in raw_results):
        return "0" * 62
    fuzzy = []
    alpns_and_ext = []
    for raw in raw_results:
        cipher_hex, version_hex, alpn, exts = raw.split("|", 3)
        fuzzy.append(_upstream_cipher_code(cipher_hex, table))
        fuzzy.append(_upstream_version_code(version_hex))
        alpns_and_ext.append(alpn)
        alpns_and_ext.append(exts)
    # upstream hashes UNCONDITIONALLY once any probe succeeded —
    # an extension-less server gets sha256("")[:32] ("e3b0c442…"),
    # not zeros
    joined = "".join(alpns_and_ext)
    tail = hashlib.sha256(joined.encode()).hexdigest()[:32]
    return "".join(fuzzy) + tail


_UPSTREAM_TABLE: Optional[tuple] = None
_UPSTREAM_TABLE_LOADED = False


def upstream_cipher_table() -> Optional[tuple]:
    """The upstream cipher-order table: the operator-supplied one when
    ``SWARM_JARM_CIPHER_TABLE`` is set (authoritative — one lowercase
    hex cipher per line, in the Salesforce list's order), else the
    in-repo public-spec reconstruction
    (swarm_tpu/tls/jarm_table.DEFAULT_UPSTREAM_TABLE), so the
    ``jarm`` field populates out of the box."""
    global _UPSTREAM_TABLE, _UPSTREAM_TABLE_LOADED
    if not _UPSTREAM_TABLE_LOADED:
        import os

        path = os.environ.get("SWARM_JARM_CIPHER_TABLE", "")
        if not path:
            from swarm_tpu.tls.jarm_table import DEFAULT_UPSTREAM_TABLE

            _UPSTREAM_TABLE = DEFAULT_UPSTREAM_TABLE
            _UPSTREAM_TABLE_LOADED = True
            return _UPSTREAM_TABLE
        # the operator EXPLICITLY configured upstream comparability; a
        # broken table must fail loudly, not silently produce
        # non-comparable hashes (round-3 verdict, Missing #5)
        try:
            with open(path) as fh:
                entries = tuple(
                    ln.strip().lower()
                    for ln in fh
                    if ln.strip() and not ln.strip().startswith("#")
                )
        except OSError as e:
            raise RuntimeError(
                f"SWARM_JARM_CIPHER_TABLE={path!r} is unreadable: {e}"
            ) from e
        bad = [c for c in entries if len(c) != 4
               or any(ch not in "0123456789abcdef" for ch in c)]
        if bad or not entries:
            raise RuntimeError(
                f"SWARM_JARM_CIPHER_TABLE={path!r} is malformed: "
                f"{'empty' if not entries else 'bad entries '}"
                f"{bad[:3]} (want one lowercase 4-hex cipher per "
                "line, upstream order)"
            )
        _UPSTREAM_TABLE = entries
        _UPSTREAM_TABLE_LOADED = True
    return _UPSTREAM_TABLE


@dataclasses.dataclass
class TlsFingerprint:
    host: str
    port: int
    jarmx: str  # JARM-style but NOT upstream-comparable (own tables)
    ja3s: str  # from the first successful probe
    alive: bool  # at least one probe produced a ServerHello
    open: bool = False  # TCP port accepted a connection
    # upstream-encoded JARM, on by default via the in-repo cipher table
    # (jarm_table.py; SWARM_JARM_CIPHER_TABLE overrides); "" only when
    # the server's version has no upstream encoding
    jarm: str = ""

    def line(self) -> str:
        if self.alive:
            up = f" jarm={self.jarm}" if self.jarm else ""
            return (
                f"{self.host}:{self.port} jarmx={self.jarmx}"
                f" ja3s={self.ja3s or '-'}{up}"
            )
        # the port-open fact from the socket layer survives even when no
        # probe elicited TLS — an open non-TLS service is not "dead"
        return f"{self.host}:{self.port} [{'open not-tls' if self.open else 'dead'}]"


def fingerprint_from_banners(
    host: str, port: int, banners: Sequence[bytes], open_: bool = True
) -> TlsFingerprint:
    """10 raw server flights (empty = no response) → TlsFingerprint."""
    hellos = [wire.parse_server_flight(b) if b else wire.NO_HELLO for b in banners]
    first_ok = next((h for h in hellos if h.ok), None)
    jh = jarm_hash(hellos)
    table = upstream_cipher_table()
    up = ""
    if table:
        try:
            up = upstream_jarm(
                [upstream_raw_result(h) for h in hellos], table
            )
        except ValueError:
            up = ""  # junk server version: no upstream encoding exists
    return TlsFingerprint(
        host=host,
        port=port,
        jarmx=jh,
        ja3s=ja3s(first_ok) if first_ok else "",
        alive=jh != EMPTY_JARM,
        open=open_,
        jarm=up,
    )
