"""Default upstream-JARM cipher-order table (config data).

The upstream JARM scheme (Salesforce) encodes a server's chosen cipher
as its zero-padded 1-based hex index into one fixed, publicly
specified cipher-order list — the ``cipher_bytes`` order of the public
jarm reference implementation. That order is public-spec CONFIG DATA
(a list of IANA cipher-suite code points), reconstructed here so the
upstream-comparable ``jarm`` field populates out of the box
(BASELINE config #5; round-4 verdict, Next #8).

Provenance and the honesty bound: this environment has no network
egress and no upstream copy on disk, so the list below is a
reconstruction of the public constant — ascending IANA code-point
order within each prefix block (0x00xx, 0xc0xx, 0xccxx) with the
TLS 1.3 suites (0x13xx) appended last, which is the upstream list's
documented shape. The operator override ``SWARM_JARM_CIPHER_TABLE``
(swarm_tpu/tls/jarm.py) remains authoritative: installing a table
extracted from the upstream repo replaces this default entirely, and
a deployment that needs certified bit-level interop with public JARM
feeds should do exactly that. Structural invariants (entry format,
uniqueness, block ordering, TLS1.3 tail) are pinned by
tests/test_tls_jarm.py.
"""

from __future__ import annotations

#: Upstream cipher-order list: 2-byte IANA cipher-suite code points as
#: lowercase 4-hex strings, in upstream encoding order.
DEFAULT_UPSTREAM_TABLE: tuple = (
    # SSL/TLS legacy + TLS 1.2 block (0x00xx), ascending
    "0004", "0005", "0007", "000a", "0016",
    "002f", "0033", "0035", "0039", "003c",
    "003d", "0041", "0045", "0067", "006b",
    "0084", "0088", "009a", "009c", "009d",
    "009e", "009f", "00ba", "00be", "00c0",
    "00c4",
    # ECDHE/ECDSA + CCM block (0xc0xx), ascending
    "c007", "c008", "c009", "c00a", "c011",
    "c012", "c013", "c014", "c023", "c024",
    "c027", "c028", "c02b", "c02c", "c02f",
    "c030", "c060", "c061", "c072", "c073",
    "c076", "c077", "c09c", "c09d", "c09e",
    "c09f", "c0a0", "c0a1", "c0a2", "c0a3",
    "c0ac", "c0ad", "c0ae", "c0af",
    # ChaCha20-Poly1305 block (0xccxx)
    "cc13", "cc14", "cca8", "cca9",
    # TLS 1.3 suites, appended last (upstream's documented tail)
    "1301", "1302", "1303", "1304", "1305",
)
