"""One typed config layer for server, worker and client.

Replaces the reference's three ad-hoc config mechanisms — server-side
module constants (``server/server.py:18-45``), worker argparse-from-env
(``worker/worker.py:131-140``), and the client's ``~/.axiom.json``
(``client/swarm:84-92``) — with a single dataclass resolved from, in
increasing precedence: defaults → config file → environment → explicit
overrides.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Optional

DEFAULT_CONFIG_FILE = "~/.swarm_tpu.json"
# Also honored for client compatibility with the reference CLI's config.
LEGACY_CONFIG_FILE = "~/.axiom.json"

_ENV_PREFIX = "SWARM_"
# Reference worker env names (worker/Dockerfile:20-21) honored as aliases.
_ENV_ALIASES = {
    "server_url": ["SERVER_URL"],
    "api_key": ["API_KEY"],
    "worker_id": ["WORKER_ID"],
}


@dataclasses.dataclass
class Config:
    # --- control plane ---
    server_url: str = "http://127.0.0.1:5001"
    # True when server_url was derived from the actually-bound port by a
    # SwarmServer (server/app.py _advertise_url) rather than set by the
    # operator — a later server instance reusing this Config re-derives
    # instead of advertising the prior (possibly dead) ephemeral port.
    # A regular init field so dict-copied Configs keep their derived-ness.
    server_url_derived: bool = False
    api_key: str = "CHANGE_THIS"
    host: str = "0.0.0.0"
    port: int = 5001

    # --- stores (embedded by default; URLs switch to real backends) ---
    state_backend: str = "memory"  # "memory" | "redis"
    redis_url: str = "redis://127.0.0.1:6379/0"
    blob_backend: str = "local"  # "local" | "s3"
    blob_root: str = "uploads"  # local blob directory (doubles as S3 layout)
    s3_bucket: str = "bucket_name"
    doc_backend: str = "local"  # "local" | "mongo"
    doc_root: str = "docdb"
    mongo_url: str = "mongodb://localhost:27017"
    mongo_db: str = "asm"

    # --- worker ---
    worker_id: str = "worker-0"
    poll_interval_idle_s: float = 10.0
    poll_interval_busy_s: float = 0.8
    modules_dir: str = "modules"
    max_jobs: int = 0  # 0 = unlimited (the reference accepted but ignored this)
    # comma-separated module names whose engines are built before the
    # poll loop starts (with the persistent XLA cache, a prewarmed
    # worker serves its first job at steady-state latency)
    prewarm_modules: str = ""

    # --- dispatch leases (new vs reference: requeue-on-expiry) ---
    lease_seconds: float = 600.0
    max_attempts: int = 3

    # --- resilience (docs/RESILIENCE.md) ---
    # seeded fault-injection plan (resilience/faults grammar); empty =
    # fault points are no-ops. Env: SWARM_FAULT_PLAN.
    fault_plan: str = ""
    # worker-reported failed terminal states requeue (bounded by
    # max_attempts) instead of going terminal on the first attempt;
    # exhausted jobs land in dead-letter quarantine either way
    retry_failed: bool = True
    # retrying transport (jittered exponential backoff + per-operation
    # circuit breakers around the worker's ServerClient)
    transport_retries: int = 3
    transport_backoff_s: float = 0.2
    transport_backoff_max_s: float = 5.0
    transport_breaker_threshold: int = 5
    transport_breaker_cooldown_s: float = 10.0
    # lease heartbeat: renewal cadence while a chunk executes
    # (0 = lease_seconds / 3)
    heartbeat_interval_s: float = 0.0
    # disk spool for completed output chunks when the server is
    # unreachable ("" = <worker work_dir>/spool)
    spool_dir: str = ""

    # --- durable queue journal (docs/DURABILITY.md) ---
    # write-ahead journal of queue mutations in the blob store: every
    # mutation is journaled BEFORE the state store (and before the
    # client's 200), and a restarting server replays it — the embedded
    # MemoryStateStore deployment becomes crash-consistent. Off keeps
    # the pre-journal behavior (state dies with the process).
    journal_enabled: bool = True
    # WAL segments accumulated before an opportunistic checkpoint
    # folds them into a snapshot
    journal_compact_segments: int = 512
    # re-lease grace: recovered leases are EXPIRED down to this window
    # (0 = lease_seconds / 2) — long enough for a live worker's next
    # heartbeat to re-lease its job through the normal fencing path,
    # short enough that a dead worker's job requeues quickly
    journal_recovery_grace_s: float = 0.0

    # --- fleet result cache (docs/CACHING.md) ---
    # shared content-addressed result tier behind the per-engine memo:
    # "off" (default) leaves every path unchanged; "memory" shares one
    # embedded tier across this process's engines; "redis" goes
    # fleet-wide over the state-store adapter. Env: SWARM_CACHE_BACKEND.
    cache_backend: str = "off"
    # tier Redis URL ("" = reuse redis_url)
    cache_url: str = ""
    # blob-spill directory for oversized values on the redis backend
    # ("" = state store only; the memory backend spills to an embedded
    # blob store regardless)
    cache_spill_dir: str = ""
    # promote the batched walk's confirm cache as the tier's second
    # value family
    cache_confirm: bool = True
    # write freshly walked results back to the tier (off = read-only
    # consumer)
    cache_writeback: bool = True
    # breaker around every tier op: a dead backend degrades the scan
    # to L1-only, it never blocks it
    cache_breaker_threshold: int = 3
    cache_breaker_cooldown_s: float = 30.0
    # TTL/size policy for the shared namespaces (docs/CACHING.md): 0 =
    # today's behavior (backend eviction + epoch bumps only). TTL is
    # per entry; the max-entry bound applies to EACH value-family
    # namespace independently, oldest entries evicted first.
    cache_ttl_s: float = 0.0
    cache_max_entries: int = 0

    # --- AOT executable cache (docs/AOT.md) ---
    # ship serialized XLA executables through the shared stores so a
    # joining worker fetches instead of compiling: "off" (default)
    # keeps today's per-process compile path; "memory" shares one
    # embedded store across this process's engines (tests); "local"
    # is file-backed under aot_dir (cross-process on one host, zero
    # side-cars); "redis" goes fleet-wide (state via aot_url/
    # redis_url, payload blobs via the S3 role when s3_bucket is set,
    # else a shared directory). Env: SWARM_AOT_BACKEND.
    aot_backend: str = "off"
    # store Redis URL ("" = reuse redis_url)
    aot_url: str = ""
    # artifact directory for the local backend / redis blob side
    aot_dir: str = ""
    # publish locally compiled executables back to the store (off =
    # read-only consumer)
    aot_publish: bool = True
    # fetch-and-load every published same-group executable at engine
    # bring-up (the cold-start win; off = lazy per-dispatch fetch)
    aot_prewarm: bool = True
    # breaker around every store op: a dead backend degrades to
    # compile-only, it never blocks a dispatch
    aot_breaker_threshold: int = 3
    aot_breaker_cooldown_s: float = 30.0

    # --- multi-tenant gateway (docs/GATEWAY.md) ---
    # per-tenant token bucket: submissions/second refill (0 = unlimited,
    # the single-operator default) and burst capacity
    gateway_tenant_rate: float = 0.0
    gateway_tenant_burst: int = 64
    # max jobs waiting in ONE tenant's dispatch queue before its
    # submissions shed (0 = unbounded)
    gateway_tenant_queue_max: int = 0
    # queue depth that maps to composite pressure 1.0 (0 disables the
    # depth component)
    gateway_queue_high: int = 0
    # composite pressure at/over which every submission sheds
    gateway_shed_pressure: float = 1.0
    # Retry-After seconds advertised on pressure/queue-full sheds
    # (rate sheds compute the exact token wait instead)
    gateway_retry_after_s: float = 1.0
    # tenant-id cardinality cap: a NEW tenant past this sheds with
    # reason "tenant_limit" (tenant ids are client data — without a
    # bound, rotating fresh ids would mint a fresh token bucket per
    # request and grow per-tenant state without limit)
    gateway_max_tenants: int = 1024
    # a worker's reported in-flight saturation decays after this many
    # seconds (a dead worker's last report must not pin pressure)
    gateway_saturation_ttl_s: float = 60.0
    # registry slots free after this much tenant inactivity, so a past
    # id-rotation flood can't deny new tenants until restart
    gateway_tenant_ttl_s: float = 3600.0
    # /stream/<scan_id>: poll cadence for new chunks and the idle
    # window after which the server closes the stream (client resumes
    # with ?from=<cursor>)
    gateway_stream_poll_s: float = 0.05
    gateway_stream_idle_timeout_s: float = 300.0
    # --- latency-tiered serving (docs/GATEWAY.md §QoS) ---
    # bulk-starvation bound for the express dispatch lane: at most this
    # many consecutive interactive serves while bulk work is waiting,
    # then one bulk job is served unconditionally. With no interactive
    # submissions the express lists stay empty and dispatch order is
    # byte-identical to the pre-QoS queue.
    qos_express_burst: int = 4
    # an interactive row older than this forces an early partial-bucket
    # flush in the scheduler's planner (the deadline that bounds
    # express-lane tail latency; only rows of the interactive class
    # consult it, so bulk-only feeds are untouched)
    qos_deadline_ms: float = 50.0
    # max-age flush for EVERY bucket class (the bulk trickle-tail fix):
    # 0 = off, today's behavior — a partial bucket waits for end of
    # stream; >0 bounds how long any planned row can sit unflushed
    sched_max_age_ms: float = 0.0
    # answer fleet-known interactive submissions at the gateway tier
    # (content-key lookup against the shared result cache BEFORE
    # admission — zero worker dispatch on a hit). Requires
    # cache_backend != off; bulk submissions never consult it.
    qos_gateway_cache: bool = True
    # completed chunks at or under this many target lines are written
    # back to the gateway cache (the short-circuit's feed; 0 disables
    # writeback). Small by design: interactive probes are single-target
    # and the gateway tier must not mirror whole bulk scans.
    qos_cache_max_rows: int = 16
    # queue-depth-driven autoscale advisor (server/fleet.py): target
    # waiting-jobs-per-node ratio, node bounds, and whether POST
    # /autoscale may actually apply the recommendation (default:
    # dry-run — recommend only)
    gateway_autoscale_jobs_per_node: int = 4
    gateway_autoscale_min_nodes: int = 0
    gateway_autoscale_max_nodes: int = 8
    gateway_autoscale_apply: bool = False
    # --- closed-loop elastic fleet (docs/RESILIENCE.md §Preemption) ---
    # EWMA smoothing factor for the inflow forecaster over the
    # admission history (higher = reacts faster to a spike shoulder)
    fleet_forecast_alpha: float = 0.3
    # forecast horizon: how many seconds of forecasted inflow are added
    # to the current depth when the advisor sizes the fleet (scale
    # AHEAD of the spike; 0 = depth-reactive, the PR 10 behavior)
    fleet_forecast_horizon_s: float = 30.0
    # scale-down hysteresis: the advisor must see a below-target fleet
    # for this many consecutive recommendations before it shrinks
    # (scale-up is always immediate)
    fleet_scaledown_hysteresis: int = 3
    # park an idle tenant fleet entirely (target 0) after this many
    # seconds with zero depth and zero forecasted inflow; 0 disables
    # scale-to-zero and min_nodes is the floor
    fleet_scale_to_zero_after_s: float = 0.0
    # simulated provider (tests/bench): RNG seed for preemption draws
    # and the preemption notice → forced-kill grace window
    fleet_sim_seed: int = 0
    fleet_sim_preempt_grace_s: float = 5.0
    # simulated cold-start latency per node, drawn from the measured
    # AOT bring-up numbers (docs/AOT.md: 4.2 s cold compile vs 0.23 s
    # AOT-warm fetch) — aot_warm picks which one a booting sim node pays
    fleet_sim_coldstart_cold_s: float = 4.2
    fleet_sim_coldstart_warm_s: float = 0.23
    fleet_sim_aot_warm: bool = True
    # cold-start SLO the bench autoscale phase gates on: a parked
    # (scale-to-zero) tenant's first node must be servable within this
    # wall-clock budget when the store is AOT-warm
    fleet_coldstart_slo_s: float = 2.0
    # graceful drain: how long a draining worker keeps polling for the
    # drain signal to settle before exiting, and how long the server
    # waits for a draining worker's lease before force-requeueing
    worker_drain_timeout_s: float = 30.0
    # --- per-class shed (docs/GATEWAY.md §QoS, the PR 15 follow-up) ---
    # composite pressure at/over which BULK submissions shed; 0 = use
    # gateway_shed_pressure for both classes (pre-PR behavior)
    gateway_shed_pressure_bulk: float = 0.0
    # composite pressure at/over which INTERACTIVE submissions shed;
    # 0 = use gateway_shed_pressure. Set bulk < interactive to shed
    # bulk first under rising pressure.
    gateway_shed_pressure_interactive: float = 0.0
    # --- continuous monitoring (docs/MONITORING.md) ---
    # standing rescan subsystem: registered monitor specs fire epochs
    # on a cadence through the admission path, diff verdicts against
    # the prior epoch and push changes over /monitor-feed. Off = the
    # routes 404-equivalent (registration rejected) and no ticker
    # thread starts.
    monitor_enabled: bool = True
    # scheduler ticker cadence: how often due specs are checked. The
    # cadence floor for spec intervals too — an interval below one
    # tick can never fire more often than the ticker runs.
    monitor_tick_s: float = 0.25
    # registry bound: a POST /monitor past this many standing specs is
    # rejected (specs are journaled state — an unbounded registry
    # would grow every snapshot)
    monitor_max_specs: int = 256
    # /monitor-feed/<id>: poll cadence for new diff records and the
    # idle window after which the server closes the stream (client
    # resumes with ?from=<cursor>)
    monitor_feed_poll_s: float = 0.1
    monitor_feed_idle_timeout_s: float = 300.0
    # end-to-end span tracing (docs/OBSERVABILITY.md §Tracing): off by
    # default — disabled tracing keeps wire payloads byte-identical to
    # the untraced build. Env: SWARM_TRACE_ENABLED (SWARM_TRACE also
    # arms the tracing module directly, process-wide).
    trace_enabled: bool = False

    # --- fleet orchestration ---
    fleet_provider: str = "null"  # "null"|"digitalocean"|"process"|"sim"
    fleet_api_token: str = ""
    fleet_rate_limit_per_min: int = 250
    fleet_region: str = "nyc3"
    fleet_size: str = "s-1vcpu-1gb"
    fleet_image: str = ""
    idle_polls_before_teardown: int = 15

    # --- TPU engine ---
    templates_dir: str = ""
    engine_batch_rows: int = 2048
    engine_row_width: int = 1024
    mesh_data_axis: int = 0  # 0 = all available devices on the data axis
    # continuous-batching scheduler (swarm_tpu/sched, docs/PIPELINE.md):
    # "on" routes device-batch execution through prefetch + padding
    # buckets + bounded in-flight submission; "off" keeps the direct
    # path. Env: SWARM_PIPELINE. Results are bit-identical either way.
    pipeline: str = "off"

    def resolve_url(self) -> str:
        return self.server_url.rstrip("/")

    @classmethod
    def load(
        cls,
        path: Optional[str] = None,
        env: Optional[dict[str, str]] = None,
        **overrides: Any,
    ) -> "Config":
        env = os.environ if env is None else env
        values: dict[str, Any] = {}

        if path:
            # An explicitly supplied config must load — a typo'd path or
            # malformed JSON silently falling back to defaults would start
            # the server with the placeholder API key.
            values.update(json.loads(Path(path).expanduser().read_text()))
        else:
            for candidate in (DEFAULT_CONFIG_FILE, LEGACY_CONFIG_FILE):
                p = Path(candidate).expanduser()
                if p.is_file():
                    try:
                        values.update(json.loads(p.read_text()))
                    except (json.JSONDecodeError, OSError):
                        pass
                    break

        fields = {f.name: f for f in dataclasses.fields(cls)}
        for name, field in fields.items():
            env_keys = [_ENV_PREFIX + name.upper()] + _ENV_ALIASES.get(name, [])
            for key in env_keys:
                if key in env:
                    values[name] = env[key]
                    break

        values.update({k: v for k, v in overrides.items() if v is not None})

        coerced: dict[str, Any] = {}
        for name, value in values.items():
            field = fields.get(name)
            if field is None:
                continue
            if field.type in ("int", int) and not isinstance(value, int):
                value = int(value)
            elif field.type in ("float", float) and not isinstance(value, float):
                value = float(value)
            elif field.type in ("bool", bool) and not isinstance(value, bool):
                value = str(value).strip().lower() in ("1", "true", "yes", "on")
            coerced[name] = value
        return cls(**coerced)

    def save(self, path: Optional[str] = None) -> Path:
        p = Path(path or DEFAULT_CONFIG_FILE).expanduser()
        p.write_text(json.dumps(dataclasses.asdict(self), indent=4))
        return p
