"""Active template-request scanning — the nuclei execution mode.

The reference's nuclei engine issues each template's *own* HTTP requests
(custom paths, methods, headers, bodies) and matches responses
per-request (`worker/modules/nuclei.json` runs it over the full corpus).
This module is the TPU-shaped equivalent:

1. **Plan** (host, once per corpus): every http operation's requests are
   compiled and deduplicated into a flat request table — measured on the
   corpus: 2,816 simple-GET templates collapse onto ~3.2k distinct
   paths, 559 of them sharing bare ``{{BaseURL}}`` (SURVEY.md §2.3).
   Standard methods, payload fan-outs, and fully-resolvable ``raw``
   requests plan as batch work; extractor-chain and req-condition
   templates route to stateful per-target sessions
   (worker/sessions.py); the remaining skip classes are counted
   honestly (oob-interactsh / requires-var / external-target).
2. **Probe** (native I/O): the (target × request) fan-out runs in waves
   through the epoll front-end — the same massive concurrency nuclei
   gets from its internal scheduler, but as flat batches.
3. **Match** (device): every response row goes through the one compiled
   corpus DB in big vmap batches — no per-template dispatch.
4. **Attribute** (host): a row's hits only count for templates that own
   the row's request — nuclei's "matchers see their own request's
   response" semantics; a template fires on a target if any of its
   requests' rows fired.
"""

from __future__ import annotations

import dataclasses
import re
import secrets
from typing import Optional, Sequence

import numpy as np

from swarm_tpu.fingerprints import dslc
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.native import scanio
from swarm_tpu.worker.executor import (
    ProbeExecutor,
    is_ip,
    parse_http_response,
    use_tls,
)

_PLACEHOLDER_RE = re.compile(r"\{\{([^{}]+)\}\}")

# one deterministic-per-process random token: nuclei uses {{randstr}} to
# provoke 404s that are distinguishable from real content
_RANDSTR = "swarm" + secrets.token_hex(8)


@dataclasses.dataclass(frozen=True)
class PlannedRequest:
    method: str
    path: str  # begins with '/', placeholders already substituted
    headers: tuple[tuple[str, str], ...] = ()
    body: bytes = b""

    @property
    def uses_oob(self) -> bool:
        """Whether wiring this request needs a minted interaction URL."""
        return (
            "\x00OOB\x00" in self.path
            or b"\x00OOB\x00" in self.body
            or any("\x00OOB\x00" in v for _k, v in self.headers)
        )

    def wire(
        self, host: str, port: int, tls: bool = False,
        oob_url: Optional[str] = None,
    ) -> bytes:
        host_hdr = _host_hdr(host, port, tls)
        body = _finalize(
            self.body.decode("latin-1"), host, port, tls, oob_url
        ).encode("latin-1")
        lines = [
            f"{self.method} {_finalize(self.path, host, port, tls, oob_url)} HTTP/1.1",
            f"Host: {host_hdr}",
        ]
        has = {k.lower() for k, _ in self.headers}
        for k, v in self.headers:
            if k.lower() not in ("host", "connection", "content-length"):
                lines.append(f"{k}: {_finalize(v, host, port, tls, oob_url)}")
        if "user-agent" not in has:
            lines.append("User-Agent: swarm-tpu/1.0")
        if body:
            lines.append(f"Content-Length: {len(body)}")
        lines.append("Connection: close")
        raw = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1", "replace")
        return raw + body


@dataclasses.dataclass(frozen=True)
class NetRequest:
    """One network-protocol probe: raw bytes to a template-declared port.

    ``port`` 0 = the target's own port (a bare ``{{Hostname}}`` host
    entry); ``tls`` = the ``tls://`` host-entry prefix."""

    port: int
    payload: bytes
    tls: bool = False


@dataclasses.dataclass
class RequestPlan:
    requests: list[PlannedRequest]
    owners: list[set[int]]  # request idx -> template indices
    skipped: dict[str, list[str]]  # reason -> template ids
    planned_templates: set[int]  # template indices with ≥1 request
    # templates that RAN but with a truncated payload set (cap hit with
    # values actually dropped) — distinct from skipped: these produced
    # requests, just not the whole wordlist/product
    payload_truncated: list[str] = dataclasses.field(default_factory=list)
    net_requests: list[NetRequest] = dataclasses.field(default_factory=list)
    net_owners: list[set[int]] = dataclasses.field(default_factory=list)
    # dns protocol: record types to query, each owned by its templates
    dns_qtypes: list[str] = dataclasses.field(default_factory=list)
    dns_owners: list[set[int]] = dataclasses.field(default_factory=list)


def _substitute(
    text: str,
    payload_vars: Optional[dict] = None,
    oob: bool = False,
) -> Optional[str]:
    """Resolve standard nuclei placeholders to plan-time markers; None
    if any unknown placeholder remains. Markers are resolved per target
    in ``_finalize`` — the plan itself stays target-free.

    With ``payload_vars`` set (payload-attack expansion), bare variable
    placeholders take the combo's value and expression placeholders
    ({{base64('user:' + token)}}) are evaluated through the dsl
    engine with the combo as the environment.

    With ``oob`` (an interaction listener is running — worker/oob.py),
    ``{{interactsh-url}}`` resolves to a marker that the wire layer
    replaces with a freshly minted per-probe correlation URL; without
    it the placeholder stays unresolved and the template keeps its
    honest oob-interactsh skip class."""

    def repl(m: re.Match) -> str:
        name = m.group(1).strip()
        low = name.lower()
        if payload_vars is not None:
            if name in payload_vars:
                return str(payload_vars[name])
            ast = dslc.try_parse(name)
            if ast is not None and ast[0] != "var":
                try:
                    v = dslc.evaluate(ast, dict(payload_vars))
                    return v.decode("latin-1") if isinstance(v, bytes) else str(v)
                except Exception:
                    pass  # unknown fn/var → fall through to builtins
        if low in ("baseurl", "rooturl"):
            return "\x00BASE\x00"  # stripped later; plan paths are host-free
        if low == "hostname":
            return "\x00HOSTPORT\x00"
        if low == "host":
            return "\x00HOST\x00"
        if low == "port":
            return "\x00PORT\x00"
        if low == "path":
            return "/"
        if low == "scheme":
            return "\x00SCHEME\x00"
        if low.startswith("randstr") or low.startswith("rand_"):
            return _RANDSTR
        if oob and low == "interactsh-url":
            return "\x00OOB\x00"
        return m.group(0)  # unknown → leave; caller rejects

    out = _PLACEHOLDER_RE.sub(repl, text)
    if _PLACEHOLDER_RE.search(out):
        return None
    return out


def _host_hdr(host: str, port: int, tls: bool) -> str:
    """host[:port], omitting the port only when it is the scheme default."""
    default = 443 if tls else 80
    return host if port == default else f"{host}:{port}"


def _finalize(
    text: str, host: str, port: int, tls: bool,
    oob_url: Optional[str] = None,
) -> str:
    """Per-target resolution of the plan-time markers with the probe's
    actual scheme/port (not defaults). An *interior* BaseURL/RootURL
    (query params, bodies, headers) becomes the absolute URL; a path's
    leading BaseURL was already stripped at plan time. ``oob_url`` is
    this probe's minted correlation URL (worker/oob.py)."""
    scheme = "https" if tls else "http"
    hdr = _host_hdr(host, port, tls)
    out = (
        text.replace("\x00BASE\x00", f"{scheme}://{hdr}")
        .replace("\x00HOSTPORT\x00", hdr)
        .replace("\x00HOST\x00", host)
        .replace("\x00PORT\x00", str(port))
        .replace("\x00SCHEME\x00", scheme)
    )
    if oob_url is not None:
        out = out.replace("\x00OOB\x00", oob_url)
    return out


# payload fan-out bounds. nuclei walks wordlists in full (the corpus
# drives the 89,810-line helpers/wordlists/wordpress-plugins.txt —
# SURVEY §2.3), so the defaults now cover that scale; the env knobs
# let an operator bound per-job work instead. Hitting either bound is
# surfaced in plan stats (payload_truncated) — never a silent cap.
import os as _os

MAX_PAYLOAD_VALUES = int(
    _os.environ.get("SWARM_MAX_PAYLOAD_VALUES", "100000")
)
MAX_PAYLOAD_COMBOS = int(
    _os.environ.get("SWARM_MAX_PAYLOAD_COMBOS", "100000")
)


def _payload_values(
    spec, template_path: Optional[str]
) -> "tuple[Optional[list[str]], bool]":
    """One payload variable's (value list, truncated); file refs resolve
    against the template's ancestors (the corpus root holds
    helpers/wordlists). ``truncated`` is True only when values were
    actually dropped at the MAX_PAYLOAD_VALUES cap."""
    if isinstance(spec, list):
        vals = [str(v) for v in spec[:MAX_PAYLOAD_VALUES]]
        return vals, len(spec) > len(vals)
    if not isinstance(spec, str):
        return None, False
    import pathlib

    cand: list[pathlib.Path] = []
    if template_path:
        for parent in pathlib.Path(template_path).parents:
            cand.append(parent / spec)
    for path in cand:
        try:
            if path.is_file():
                out = []
                truncated = False
                with open(path, "r", encoding="utf-8", errors="replace") as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if len(out) >= MAX_PAYLOAD_VALUES:
                            if line:
                                truncated = True  # a value WAS dropped
                                break
                            continue
                        if line:
                            out.append(line)
                return out, truncated
        except OSError:
            continue
    return None, False


def _payload_combos(
    op, template_path: Optional[str]
) -> tuple[Optional[list[dict]], bool]:
    """Attack-mode expansion → (bounded list of var→value dicts,
    truncated) — truncated is True only when combos were actually
    dropped, so an exactly-cap-sized product isn't misreported.

    batteringram: one shared value stream; pitchfork: zip the lists;
    clusterbomb: cartesian product (capped)."""
    lists: dict[str, list[str]] = {}
    values_truncated = False
    for var, spec in op.payloads.items():
        vals, v_trunc = _payload_values(spec, template_path)
        if vals is None or not vals:
            return None, False
        values_truncated = values_truncated or v_trunc
        lists[str(var)] = vals
    if not lists:
        return [], False
    mode = (op.attack or "batteringram").lower()
    names = list(lists)
    combos: list[dict] = []
    if mode == "clusterbomb" and len(names) > 1:
        import itertools

        total = 1
        for n in names:
            total *= len(lists[n])
        for values in itertools.product(*(lists[n] for n in names)):
            combos.append(dict(zip(names, values)))
            if len(combos) >= MAX_PAYLOAD_COMBOS:
                break
    elif mode == "pitchfork" and len(names) > 1:
        total = min(len(lists[n]) for n in names)
        for values in zip(*(lists[n] for n in names)):
            combos.append(dict(zip(names, values)))
            if len(combos) >= MAX_PAYLOAD_COMBOS:
                break
    else:
        # batteringram (or single-var): one value stream, every var
        # takes the same value (nuclei's batteringram semantics)
        total = len(lists[names[0]])
        for v in lists[names[0]]:
            combos.append({n: v for n in names})
            if len(combos) >= MAX_PAYLOAD_COMBOS:
                break
    # either bound counts: values dropped at the per-variable cap are
    # as truncated as combos dropped at the product cap
    return combos, values_truncated or total > len(combos)


_INDEXED_VAR_RE = re.compile(
    r"\b(?:body|header|all_headers|status_code|response|raw|duration)_\d+\b"
)


def _uses_indexed_vars(t: Template) -> bool:
    """True when any matcher/extractor references per-step history vars
    (the req-condition idiom) — cross-request evaluation state. Indexed
    references appear both in dsl expressions and as matcher/extractor
    ``part`` names (e.g. ``part: body_2``,
    misconfiguration/google/insecure-firebase-database.yaml)."""
    for op in t.operations:
        for m in op.matchers:
            if _INDEXED_VAR_RE.search(m.part or ""):
                return True
            for expr in m.dsl:
                if _INDEXED_VAR_RE.search(expr):
                    return True
        for ex in op.extractors:
            if _INDEXED_VAR_RE.search(ex.part or ""):
                return True
    return False


def _parse_raw(raw: str) -> Optional[PlannedRequest]:
    """One raw HTTP request text → PlannedRequest (None = unsupported)."""
    raw = raw.replace("\r\n", "\n").strip("\n")
    if "\n\n" in raw:
        head, _, body = raw.partition("\n\n")
    else:
        head, body = raw, ""
    lines = head.split("\n")
    first = lines[0].split()
    if len(first) < 2:
        return None
    method, path = first[0].upper(), first[1]
    if not path.startswith("/"):
        if path.startswith("\x00BASE\x00"):
            path = path[len("\x00BASE\x00"):] or "/"
        elif path.startswith(("http://", "https://")):
            # absolute-URL raws target other hosts — out of scope
            return None
        # else: verbatim request-target (nuclei sends raws as written —
        # e.g. CVE-2018-16133's backslash path-traversal probe)
    headers = []
    for line in lines[1:]:
        if ":" not in line:
            return None
        k, _, v = line.partition(":")
        if k.strip().lower() == "host":
            continue  # rebuilt per target
        headers.append((k.strip(), v.strip()))
    return PlannedRequest(
        method=method,
        path=path,
        headers=tuple(headers),
        body=body.encode("latin-1", "replace"),
    )


def _ast_var_names(ast) -> set:
    """Variable names referenced anywhere in a parsed dsl expression."""
    names: set = set()

    def walk(node):
        if not isinstance(node, tuple):
            return
        if node[0] == "var":
            names.add(node[1])
        for part in node:
            if isinstance(part, tuple):
                walk(part)
            elif isinstance(part, list):
                for sub in part:
                    walk(sub)

    walk(ast)
    return names


def _unresolved_names(t: Template) -> set:
    """Placeholder names in the template's request text that the plain
    substitution layer cannot resolve."""
    out: set = set()
    for op in t.operations:
        texts = list(op.paths) + list(op.raw) + [op.body or ""]
        texts += [v for _k, v in op.headers]
        for text in texts:
            for m in _PLACEHOLDER_RE.finditer(text):
                name = m.group(1).strip()
                if _substitute("{{" + name + "}}") is None:
                    out.add(name)
    return out


def _classify_dynamic(t: Template, user_vars: Optional[dict] = None) -> str:
    """Honest skip bucket for a template with unresolved placeholders:

    - ``oob-interactsh`` — needs an out-of-band interaction server
      (already surfaced per-template in scan output);
    - ``extractor-chain`` — every unresolved value comes from the
      template's own (internal) extractors/payloads: a per-target
      session could execute it;
    - ``requires-var`` — needs operator-supplied values (nuclei's
      ``-var``; the token-spray class). Supply via the active module's
      ``"vars"`` object.
    """
    if _uses_oob(t):
        return "oob-interactsh"
    sources: set = set(user_vars or ())
    for op in t.operations:
        sources |= {ex.name for ex in op.extractors if ex.name}
        sources |= set(op.payloads.keys())
    unresolved = _unresolved_names(t)

    def covered(name: str) -> bool:
        if name in sources:
            return True
        ast = dslc.try_parse(name)
        if ast is None:
            return False
        refs = _ast_var_names(ast)
        return bool(refs) and refs <= sources

    if unresolved and all(covered(n) for n in unresolved):
        return "extractor-chain"
    return "requires-var"


def build_plan(
    templates: Sequence[Template],
    user_vars: Optional[dict] = None,
    oob: bool = False,
) -> RequestPlan:
    """Corpus → deduplicated request table + ownership map.

    ``user_vars`` are operator-supplied template variables (nuclei's
    ``-var token=…``), substituted wherever payload values would be —
    they unlock the requires-var class (API token-spray templates
    etc.) when the operator provides credentials."""
    dedup: dict[PlannedRequest, int] = {}
    owners: list[set[int]] = []
    skipped: dict[str, list[str]] = {}
    planned: set[int] = set()
    payload_truncated: list[str] = []

    current_added: list[list[int]] = [[]]  # per-template http indices

    def add(req: PlannedRequest, t_idx: int) -> None:
        idx = dedup.get(req)
        if idx is None:
            idx = dedup[req] = len(owners)
            owners.append(set())
        owners[idx].add(t_idx)
        current_added[0].append(idx)
        planned.add(t_idx)

    def skip(reason: str, t: Template) -> None:
        skipped.setdefault(reason, []).append(t.id)

    net_dedup: dict[NetRequest, int] = {}
    dns_qtype_idx: dict[str, int] = {}
    dns_qtypes_list: list[str] = []
    dns_owners_list: list[set[int]] = []

    def add_net(req: NetRequest, t_idx: int) -> None:
        idx = net_dedup.get(req)
        if idx is None:
            idx = net_dedup[req] = len(net_owners_list)
            net_owners_list.append(set())
        net_owners_list[idx].add(t_idx)
        planned.add(t_idx)

    net_owners_list: list[set[int]] = []

    for t_idx, t in enumerate(templates):
        if t.protocol == "network":
            # hosts entries declare the port ("{{Host}}:873", optionally
            # "tls://" prefixed); a bare "{{Hostname}}" rides the
            # target's own port (planned as port 0, expanded per target
            # at probe time). Each operation carries its own
            # (ports, payload) pair (SURVEY.md §2.3: network templates
            # send inputs.data and match banners).
            any_entry = False
            for op in t.operations:
                entries: set[tuple[int, bool]] = set()  # (port, tls)
                for h in op.hosts:
                    tls = False
                    if "://" in h:
                        scheme, _, h = h.partition("://")
                        tls = scheme.lower() in ("tls", "ssl")
                    _, sep, port_s = h.rpartition(":")
                    if sep and port_s.isdigit():
                        entries.add((int(port_s), tls))
                    else:
                        entries.add((0, tls))  # target's own port
                if not entries:
                    continue
                any_entry = True
                payload = b"".join(op.inputs)
                for port, tls in sorted(entries):
                    add_net(
                        NetRequest(port=port, payload=payload, tls=tls), t_idx
                    )
            if not any_entry:
                skip("network-no-port", t)
            continue
        if t.protocol == "dns":
            # dns templates query one record type for the target name;
            # several templates share a query (4 CNAME templates → one
            # CNAME query per host)
            from swarm_tpu.worker.dnsquery import QTYPES

            any_q = False
            for op in t.operations:
                qtype = op.dns_type or "A"
                if qtype not in QTYPES:
                    continue
                any_q = True
                if qtype in dns_qtype_idx:
                    dns_owners_list[dns_qtype_idx[qtype]].add(t_idx)
                else:
                    dns_qtype_idx[qtype] = len(dns_qtypes_list)
                    dns_qtypes_list.append(qtype)
                    dns_owners_list.append({t_idx})
                planned.add(t_idx)
            if not any_q:
                skip("dns-qtype", t)
            continue
        if t.protocol != "http":
            # file and ssl templates run under their dedicated modules
            # (worker/filescan.py, worker/sslscan.py — modules/file.json,
            # modules/ssl.json), not the batch planner; headless
            # templates in the browserless JS-free subset execute via
            # worker/headless.py (ActiveScanner removes them from this
            # skip list), the js-required rest keep the honest marker
            skip(f"protocol-{t.protocol}", t)
            continue
        ok = False
        unsupported: Optional[str] = None
        current_added[0] = []
        planned_matchers = False  # did any PLANNED op carry matchers?
        for op in t.operations:
            # payload attacks (default-logins, fuzzing, token-spray):
            # expand the bounded combo set and plan one request per
            # combo — every combo's response batch-matches on device
            # and any hit attributes to the template
            if op.payloads:
                combos, truncated = _payload_combos(op, t.source_path)
                if combos is None:
                    unsupported = "payload-values"
                    continue
                if truncated and t.id not in payload_truncated:
                    # cap hit with values dropped: surfaced, never
                    # silent — but the template still RUNS, so this is
                    # its own stats channel, not a skip
                    payload_truncated.append(t.id)
            else:
                combos = [None]
            if user_vars:
                # operator vars are the base layer; payload combos
                # override on collision (nuclei -var semantics)
                combos = [{**user_vars, **(c or {})} for c in combos]
            for payload_vars in combos:
                if op.raw:
                    # multi-request raws: nuclei evaluates matchers per
                    # response (OR across steps) unless they reference
                    # indexed history vars (body_2, status_code_1 … /
                    # req-condition) — those need cross-request state
                    # this engine doesn't model, so they stay skipped
                    # rather than silently never-matching.
                    if len(op.raw) > 1 and _uses_indexed_vars(t):
                        unsupported = "multi-step-condition"
                        continue
                    # all-or-nothing: a step a matcher depends on must
                    # not silently drop while its siblings plan
                    step_reqs = []
                    step_fail = None
                    for step in op.raw:
                        sub = _substitute(step, payload_vars, oob=oob)
                        if sub is None:
                            step_fail = "dynamic-values"
                            break
                        req = _parse_raw(sub)
                        if req is None:
                            # @Host:-annotated and absolute-URL raws
                            # address third-party hosts, not the target
                            step_fail = (
                                "external-target"
                                if "@Host:" in step
                                or sub.lstrip().split(None, 2)[1:2]
                                and sub.lstrip().split(None, 2)[1].startswith(
                                    ("http://", "https://")
                                )
                                else "raw-unparseable"
                            )
                            break
                        step_reqs.append(req)
                    if step_fail:
                        unsupported = step_fail
                        continue
                    for req in step_reqs:
                        add(req, t_idx)
                    ok = True
                    planned_matchers = planned_matchers or bool(op.matchers)
                    continue
                method = (op.method or "GET").upper()
                if method not in (
                    "GET", "POST", "PUT", "HEAD", "OPTIONS",
                    "DELETE", "PATCH", "PURGE", "TRACE",
                ):
                    unsupported = f"method-{method}"
                    continue
                body_t = _substitute(op.body or "", payload_vars, oob=oob)
                if body_t is None:
                    unsupported = "dynamic-values"
                    continue
                body = body_t.encode("latin-1", "replace")
                for path_t in op.paths:
                    sub = _substitute(path_t, payload_vars, oob=oob)
                    if sub is None:
                        unsupported = "dynamic-values"
                        continue
                    # strip only the *leading* BaseURL; interior
                    # occurrences resolve to absolute URLs at wire time
                    if sub.startswith("\x00BASE\x00"):
                        sub = sub[len("\x00BASE\x00"):]
                    elif sub.startswith(("http://", "https://")):
                        # token-spray-style templates request third-party
                        # API hosts, not the scanned target — out of
                        # scope here
                        unsupported = "external-target"
                        continue
                    path = sub or "/"
                    if not path.startswith("/"):
                        path = "/" + path
                    headers = []
                    header_ok = True
                    for k, v in op.headers:
                        hv = _substitute(v, payload_vars, oob=oob)
                        if hv is None:
                            header_ok = False  # e.g. "Bearer {{token}}"
                            break
                        headers.append((k, hv))
                    if not header_ok:
                        unsupported = "dynamic-values"
                        continue
                    add(
                        PlannedRequest(
                            method=method,
                            path=path,
                            headers=tuple(headers),
                            body=body,
                        ),
                        t_idx,
                    )
                    ok = True
                    planned_matchers = planned_matchers or bool(op.matchers)
        if ok and unsupported and not planned_matchers:
            # the planned subset carries no matchers while sibling
            # ops/steps failed — nothing planned can ever fire, so a
            # silent partial plan would hide the gap; retract and skip
            for idx in current_added[0]:
                owners[idx].discard(t_idx)
            planned.discard(t_idx)
            ok = False
        if not ok and unsupported:
            if unsupported == "dynamic-values":
                unsupported = _classify_dynamic(t, user_vars)
            skip(unsupported, t)

    # drop orphaned requests (a retracted partial template may leave a
    # dedup entry whose owner set emptied — probing it would be wasted
    # I/O with no possible attribution)
    requests_list = list(dedup)
    keep = [i for i, o in enumerate(owners) if o]
    return RequestPlan(
        requests=[requests_list[i] for i in keep],
        owners=[owners[i] for i in keep],
        skipped=skipped,
        planned_templates=planned,
        payload_truncated=payload_truncated,
        net_requests=list(net_dedup),
        net_owners=net_owners_list,
        dns_qtypes=dns_qtypes_list,
        dns_owners=dns_owners_list,
    )


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ActiveHit:
    host: str
    port: int
    template_id: str
    path: str
    extractions: list[str]
    tls: bool = False  # how the hit's request was actually probed
    # the response that fired the hit (internal: workflow named-matcher
    # gates re-confirm against it; never rendered into output)
    row: Optional[Response] = None
    # fired named matchers, when the producing engine knows them (ssl
    # hits have no Response row to re-confirm against)
    matcher_names: list[str] = dataclasses.field(default_factory=list)


def _uses_oob(t: Template) -> bool:
    """True when the template references out-of-band interaction
    (interactsh) anywhere — matcher parts, dsl expressions, or request
    text embedding ``{{interactsh-url}}``. Such templates cannot fully
    evaluate without an interaction callback server (scope-excluded;
    SURVEY §2.3: 144 interactsh matchers), and scan output must say so
    rather than silently not matching."""
    for op in t.operations:
        for m in op.matchers:
            if (m.part or "").startswith("interactsh"):
                return True
            if any("interactsh" in e for e in m.dsl):
                return True
        texts = list(op.paths) + list(op.raw) + [op.body or ""]
        texts += [v for _k, v in op.headers]
        texts += [str(v) for v in op.payloads.values()]
        for text in texts:
            if "interactsh" in text:
                return True
    return False


class ActiveScanner:
    """(targets × planned requests) → device-matched, request-attributed
    template hits. ``engine`` is a MatchEngine over the same corpus the
    plan was built from."""

    def __init__(
        self,
        engine,
        probe_spec: Optional[dict] = None,
        user_vars: Optional[dict] = None,
    ):
        self.engine = engine
        # OOB interaction listener (worker/oob.py): opt-in via the
        # module's probe spec — "oob": true (defaults) or a config
        # object {"advertise_host", "http_port", "dns_port", "domain",
        # "answer_ip", "poll_s"}. With it running, {{interactsh-url}}
        # templates plan and execute; without it they keep the honest
        # oob-skipped marker.
        spec0 = probe_spec or {}
        oob_spec = spec0.get("oob")
        self.oob_listener = None
        self.oob_poll_s = 3.0
        if oob_spec:
            from swarm_tpu.worker.oob import shared_listener

            kw = dict(oob_spec) if isinstance(oob_spec, dict) else {}
            kw.pop("enabled", None)
            self.oob_poll_s = float(kw.pop("poll_s", 3.0))
            # process-shared: the runtime caches scanners for process
            # lifetime, so per-scanner listeners would leak sockets and
            # EADDRINUSE on fixed ports (worker/oob.py shared_listener)
            self.oob_listener = shared_listener(**kw)
        self.plan = build_plan(
            engine.templates,
            user_vars=user_vars,
            oob=self.oob_listener is not None,
        )
        # honest scope marker: these ids are emitted as oob-skipped in
        # scan output (runtime._execute_active) so "didn't match" and
        # "can't match without OOB" stay distinguishable in /raw. With
        # a listener running, only oob templates that STILL could not
        # plan (e.g. ones also needing session state) keep the marker.
        planned_ids = {
            engine.templates[i].id for i in self.plan.planned_templates
        }
        self.oob_limited = sorted(
            t.id
            for t in engine.templates
            if _uses_oob(t)
            and (self.oob_listener is None or t.id not in planned_ids)
        )
        # request indices that need a minted correlation URL at wire time
        self._oob_reqs = {
            i for i, r in enumerate(self.plan.requests) if r.uses_oob
        }
        # deferred rows awaiting the interaction poll window:
        # (row, meta, token) triples collected across waves
        self._pending_oob: list = []
        # session-class templates (extractor chains, indexed-history
        # raw flows) execute statefully per target instead of batching
        session_ids = set(
            self.plan.skipped.get("extractor-chain", [])
        ) | set(self.plan.skipped.get("multi-step-condition", []))
        self.session_scanner = None
        if session_ids:
            from swarm_tpu.worker.sessions import SessionScanner

            self.session_scanner = SessionScanner(
                [t for t in engine.templates if t.id in session_ids],
                probe_spec=probe_spec,
                user_vars=user_vars,
            )
        # ssl-protocol templates run alongside the http corpus (nuclei
        # host-scan parity); their hits join the workflow hit set
        self.ssl_scanner = None
        self._ssl_ports: list[int] = []
        ssl_templates = [t for t in engine.templates if t.protocol == "ssl"]
        if ssl_templates:
            from swarm_tpu.worker import sslscan

            spec0 = probe_spec or {}
            self.ssl_scanner = sslscan.SslScanner(
                ssl_templates,
                concurrency=int(spec0.get("concurrency", 32)),
                timeout=float(spec0.get("connect_timeout_ms", 4000)) / 1000.0,
            )
            # portless targets follow the module's port fan-out minus
            # known-plaintext ports; explicit ssl_ports wins verbatim;
            # nothing TLS-plausible configured → nuclei's default 443
            if "ssl_ports" in spec0:
                self._ssl_ports = [
                    int(p) for p in spec0["ssl_ports"]
                ] or [443]
            else:
                self._ssl_ports = [
                    int(p)
                    for p in spec0.get("ports", [443])
                    if int(p) not in sslscan.PLAINTEXT_PORTS
                ] or [443]
        # headless-protocol templates: the browserless JS-free subset
        # (worker/headless.py) executes per live target — form flows
        # and DOM attribute-collection scripts; js-required ones stay
        # in the skip list with the honest [headless-skipped] marker
        self.headless_scanner = None
        headless_templates = [
            t for t in engine.templates if t.protocol == "headless"
        ]
        if headless_templates:
            from swarm_tpu.worker import headless as headlesslite

            runnable = [
                t for t in headless_templates
                if headlesslite.classify(t) is None
            ]
            if runnable:
                self.headless_scanner = headlesslite.HeadlessScanner(
                    runnable, probe_spec=probe_spec
                )
                runnable_ids = {t.id for t in runnable}
                kept = [
                    i
                    for i in self.plan.skipped.get("protocol-headless", [])
                    if i not in runnable_ids
                ]
                if kept:
                    self.plan.skipped["protocol-headless"] = kept
                else:
                    self.plan.skipped.pop("protocol-headless", None)
        # workflow templates gate which hits report (ops/workflows.py);
        # evaluation reuses this scanner's engine — no extra compile
        self.workflow_runner = None
        if any(t.protocol == "workflow" for t in engine.templates):
            from swarm_tpu.ops.workflows import WorkflowRunner

            self.workflow_runner = WorkflowRunner(
                engine.templates, engine=engine
            )
        self.executor = ProbeExecutor(probe_spec)
        spec = self.executor.spec
        self.wave_rows = int(spec.get("wave_rows", 16384))
        # template index -> id, and per-request owner id sets, once
        self._tid = [t.id for t in engine.templates]
        self._owner_ids = [
            {self._tid[i] for i in owner} for owner in self.plan.owners
        ]
        self._net_owner_ids = [
            {self._tid[i] for i in owner} for owner in self.plan.net_owners
        ]
        self._dns_owner_ids = [
            {self._tid[i] for i in owner} for owner in self.plan.dns_owners
        ]

    def run(self, target_lines: Sequence[str]) -> tuple[list[ActiveHit], dict]:
        parsed, malformed = self.executor._parse_lines(target_lines)
        addr_of = self.executor._resolve_names(parsed)
        spec_ports = [
            int(p) for p in self.executor.spec["ports"] if 0 < int(p) < 65536
        ]
        targets: list[tuple[str, str, int, bool]] = []  # (host, ip, port, tls)
        dead = 0
        for host, explicit_port, _path, scheme in parsed:
            ip = host if is_ip(host) else next(iter(addr_of.get(host) or []), None)
            ports = [explicit_port] if explicit_port else spec_ports
            for port in ports:
                if ip is None:
                    dead += 1
                else:
                    targets.append((host, ip, port, use_tls(scheme, port)))

        hits: list[ActiveHit] = []
        stats = {
            "targets": len(targets),
            "dead_targets": dead,
            "malformed": len(malformed),
            "requests_planned": len(self.plan.requests),
            "rows_probed": 0,
            # session-handled classes aren't skips: the session pass
            # below executes them
            "skipped_templates": {
                k: len(v)
                for k, v in self.plan.skipped.items()
                if self.session_scanner is None
                or k not in ("extractor-chain", "multi-step-condition")
            },
            "oob_limited": len(self.oob_limited),
            "payload_truncated": len(self.plan.payload_truncated),
        }
        plan_has_work = (
            self.plan.requests
            or self.plan.net_requests
            or self.plan.dns_qtypes
            or self.session_scanner is not None
            or self.ssl_scanner is not None
            or self.headless_scanner is not None
        )
        if not targets or not plan_has_work:
            return hits, stats

        # liveness pre-pass: one connect per target; only live targets
        # fan out over the full request table (and over sessions)
        need_live = (
            bool(self.plan.requests)
            or self.session_scanner is not None
            or self.headless_scanner is not None
        )
        live = self._liveness(targets) if need_live else []
        stats["live_targets"] = len(live)

        # headless offload: launch the emulation round NOW so its
        # network I/O overlaps the device waves below (shared pool,
        # worker/headless.py); joined where its hits are consumed
        headless_fut = None
        if self.headless_scanner is not None and live:
            headless_fut = self.headless_scanner.run_async(live)

        # index-sliced waves: never materialize the full (target × request)
        # cross product — 10k live targets × 3.2k requests is 32M tuples
        nreq = len(self.plan.requests)
        total = len(live) * nreq
        for w0 in range(0, total, self.wave_rows):
            wave = [
                (*live[i // nreq], i % nreq)
                for i in range(w0, min(w0 + self.wave_rows, total))
            ]
            stats["rows_probed"] += len(wave)
            hits.extend(self._run_wave(wave))

        # network-protocol pass: template-declared ports on each host
        # (port-0 requests ride the target's own port)
        if self.plan.net_requests:
            net_hits, net_rows = self._run_network(targets)
            hits.extend(net_hits)
            stats["rows_probed"] += net_rows

        # dns-protocol pass: typed queries per distinct hostname
        if self.plan.dns_qtypes:
            dns_hits, dns_rows = self._run_dns(parsed, addr_of)
            hits.extend(dns_hits)
            stats["rows_probed"] += dns_rows

        # session pass: extractor-chain / multi-step-condition templates
        # run stateful per-target flows (worker/sessions.py) — against
        # the liveness-gated set only (dead hosts would each burn a
        # connect timeout per session template)
        if self.session_scanner is not None and live:
            session_hits = self.session_scanner.run(live)
            stats["session_templates"] = len(self.session_scanner.templates)
            stats["session_hits"] = len(session_hits)
            hits.extend(
                ActiveHit(
                    host=h.host, port=h.port, template_id=h.template_id,
                    path="", extractions=h.extractions, tls=h.tls,
                    # the final step's response stands in for workflow
                    # named-matcher gates on session templates
                    row=h.row if self.workflow_runner is not None else None,
                )
                for h in session_hits
            )

        # ssl-protocol pass: version-pinned handshakes + session/cert
        # document matchers (worker/sslscan.py); hits participate in
        # workflow gating below like any other protocol's
        if self.ssl_scanner is not None:
            ssl_findings, ssl_stats = self.ssl_scanner.scan(
                target_lines, default_ports=self._ssl_ports
            )
            stats["ssl_targets"] = ssl_stats["targets"]
            hits.extend(
                ActiveHit(
                    host=f.host, port=f.port, template_id=f.template_id,
                    path="", extractions=f.extractions, tls=True,
                    matcher_names=f.matcher_names,
                )
                for f in ssl_findings
            )

        # headless join: the round launched after liveness ran
        # overlapped with every device wave above
        if headless_fut is not None:
            h_hits = headless_fut.result()
            stats["headless_templates"] = len(
                self.headless_scanner.templates
            )
            stats["headless_hits"] = len(h_hits)
            hits.extend(
                ActiveHit(
                    host=h.host, port=h.port, template_id=h.template_id,
                    path="", extractions=h.extractions, tls=h.tls,
                    matcher_names=h.matcher_names,
                )
                for h in h_hits
            )

        # OOB drain: wait out the interaction window (a vulnerable
        # target's callback races our response read), attach each
        # token's interactions to its probe row, then device-match the
        # deferred rows in one batch like any other wave
        if self._pending_oob:
            import time as _time

            if self.oob_poll_s > 0:
                _time.sleep(self.oob_poll_s)
            rows, meta = [], []
            n_inter = 0
            for row, m, tok in self._pending_oob:
                inter = self.oob_listener.poll(tok)
                self.oob_listener.release(tok)
                if inter:
                    n_inter += len(inter)
                    row.oob_protocols = tuple(
                        sorted({i.protocol for i in inter})
                    )
                    row.oob_requests = b"\n\n".join(
                        i.raw_request for i in inter
                    )
                    row.oob_ips = tuple(
                        dict.fromkeys(i.remote_addr for i in inter)
                    )
                rows.append(row)
                meta.append(m)
            self._pending_oob = []
            stats["oob_probes"] = len(rows)
            stats["oob_interactions"] = n_inter
            hits.extend(self._attribute(rows, meta, self._owner_ids))

        # one line per finding: a template observed via several requests
        # on the same endpoint (e.g. {{Hostname}} + {{Host}}:<port> both
        # landing on one service) reports once, as nuclei does
        seen: set = set()
        unique: list[ActiveHit] = []
        for h in hits:
            key = (h.host, h.port, h.template_id, h.path)
            if key not in seen:
                seen.add(key)
                unique.append(h)

        # workflow pass: per-(host, port) gating — nuclei's workflow
        # unit is one input target, so trigger and subtemplates must
        # have matched the same service. Port-less protocol hits (dns:
        # port 0) describe the host, not a service, and join every
        # service group of their host.
        if self.workflow_runner is not None:
            stats["workflow_hits"] = 0
            groups: dict[tuple, dict] = {}
            hostwide: dict[str, list] = {}
            for h in unique:
                if h.port == 0:
                    hostwide.setdefault(h.host, []).append(h)
                else:
                    groups.setdefault((h.host, h.port), {}).setdefault(
                        h.template_id, []
                    ).append(h)
            for host, hs in hostwide.items():
                host_groups = [
                    g for (gh, _p), g in groups.items() if gh == host
                ] or [groups.setdefault((host, 0), {})]
                for g in host_groups:
                    for h in hs:
                        g.setdefault(h.template_id, []).append(h)
            wf_hits: list[ActiveHit] = []
            seen_wf: set = set()
            # batched gate re-confirm: every row-carrying hit of a
            # gate-queried template rides ONE engine batch through the
            # scheduler (QoS lanes, in-flight overlap, memo families)
            # instead of a serial per-row host confirm inside
            # evaluate_hits; recorded names (ssl) keep precedence
            gate_tids = self.workflow_runner.gate_template_ids
            needs: list = []
            where: list = []
            for gkey, hitmap in groups.items():
                for tid, hhs in hitmap.items():
                    if tid not in gate_tids or any(
                        hh.matcher_names for hh in hhs
                    ):
                        continue
                    for hh in hhs:
                        if hh.row is not None:
                            needs.append((tid, hh.row))
                            where.append((gkey, tid))
            resolved: dict[tuple, set] = {}
            if needs:
                for loc, names in zip(
                    where, self.workflow_runner.resolve_gate_names(needs)
                ):
                    resolved.setdefault(loc, set()).update(names)
            for (host, port), hitmap in groups.items():
                # ssl hits carry no Response row; their fired matcher
                # names were recorded by the ssl scanner itself
                known = {
                    tid: sorted(
                        {n for hh in hhs for n in hh.matcher_names}
                    )
                    for tid, hhs in hitmap.items()
                    if any(hh.matcher_names for hh in hhs)
                }
                for (gkey, tid), names in resolved.items():
                    if gkey == (host, port):
                        known[tid] = sorted(names)
                per = self.workflow_runner.evaluate_hits(
                    set(hitmap),
                    lambda tid, _m=hitmap: [
                        hh.row for hh in _m.get(tid, [])
                    ],
                    known_names=known,
                )
                for wid, sub_ids in sorted(per.items()):
                    # report at the matched subtemplate's service
                    anchor = next(
                        (hitmap[s][0] for s in sub_ids if s in hitmap),
                        next(iter(hitmap.values()))[0],
                    )
                    key = (host, anchor.port, wid, tuple(sorted(sub_ids)))
                    if key in seen_wf:
                        # a hostwide (port-0) trigger+sub pair joined
                        # several service groups — report it once
                        continue
                    seen_wf.add(key)
                    wf_hits.append(
                        ActiveHit(
                            host=host, port=anchor.port, template_id=wid,
                            path="", extractions=sorted(sub_ids),
                            tls=anchor.tls,
                        )
                    )
            stats["workflow_hits"] = len(wf_hits)
            unique.extend(wf_hits)
        # the rows only existed for workflow re-confirmation — don't
        # keep every matched response body alive in the hit list
        for h in unique:
            h.row = None
        # wave-loop batching mode: with pipeline="on" every _attribute
        # device pass above rode the continuous-batching scheduler
        # (memo short-circuit + padding buckets + bounded in-flight) —
        # surface its feed-health counters next to the probe stats
        stats["pipeline"] = getattr(self.engine, "pipeline", "off")
        sched = getattr(self.engine, "_sched", None)
        if sched is not None:
            stats["sched"] = sched.stats.snapshot()
        return unique, stats

    def close(self) -> None:
        """Nothing to release: the OOB listener is process-shared
        (other scanners may be using it); its daemon threads die with
        the process. Kept so callers can treat scanners uniformly."""

    # ------------------------------------------------------------------
    def _liveness(self, targets):
        result = scanio.tcp_scan(
            [ip for _h, ip, _p, _t in targets],
            np.asarray([p for _h, _ip, p, _t in targets], dtype=np.uint16),
            None,
            max_concurrency=int(self.executor.spec["concurrency"]),
            connect_timeout_ms=int(self.executor.spec["connect_timeout_ms"]),
            read_timeout_ms=1,  # connect-only
            banner_cap=1,
        )
        # SW_OPEN = connect succeeded (banner may be empty at 1 ms read)
        return [
            t for t, s in zip(targets, result.status) if int(s) == scanio.STATUS_OPEN
        ]

    def _attribute(self, rows, meta, owner_table) -> list[ActiveHit]:
        """Device-match ``rows`` and keep each row's hits only for the
        templates owning its request (shared by http and network passes).
        ``meta``: (host, port, tls, r_idx, path) aligned with rows."""
        out: list[ActiveHit] = []
        if not rows:
            return out
        matched = self.engine.match(rows)
        keep_rows = self.workflow_runner is not None  # rows feed gates
        for row, (host, port, tls, r_idx, path), rm in zip(rows, meta, matched):
            owner_ids = owner_table[r_idx]
            for tid in rm.template_ids:
                if tid in owner_ids:
                    out.append(
                        ActiveHit(
                            host=host,
                            port=port,
                            template_id=tid,
                            path=path,
                            extractions=rm.extractions.get(tid, []),
                            tls=tls,
                            row=row if keep_rows else None,
                        )
                    )
        return out

    def _run_dns(self, parsed, addr_of) -> tuple[list[ActiveHit], int]:
        """Typed DNS queries per distinct target name → attributed hits.

        Matchers run over the dig-style rendering (dnsquery.render), so
        rcode words (SERVFAIL/REFUSED) and answer rdata both match."""
        from swarm_tpu.worker import dnsquery
        from swarm_tpu.worker.executor import _system_resolvers

        hosts = sorted({t[0] for t in parsed})
        if not hosts:
            return [], 0
        resolvers = list(self.executor.spec["resolvers"]) or _system_resolvers()
        if not resolvers:
            return [], 0
        queries: list[tuple[str, str]] = []
        meta_q: list[tuple[str, int]] = []  # (host, qtype idx)
        for host in hosts:
            for q_idx, qtype in enumerate(self.plan.dns_qtypes):
                qname = (
                    dnsquery.reverse_name(host)
                    if qtype == "PTR" and is_ip(host)
                    else host
                )
                queries.append((qname, qtype))
                meta_q.append((host, q_idx))
        replies = dnsquery.query_batch(
            queries,
            resolvers,
            timeout_ms=int(self.executor.spec["read_timeout_ms"]),
        )
        rows: list[Response] = []
        meta: list[tuple[str, int, bool, int, str]] = []
        for (host, q_idx), reply in zip(meta_q, replies):
            if reply is None:
                continue
            rows.append(Response(host=host, port=53, banner=reply.render()))
            meta.append((host, 53, False, q_idx, ""))
        return self._attribute(rows, meta, self._dns_owner_ids), len(queries)

    def _run_network(self, targets) -> tuple[list[ActiveHit], int]:
        """(host × net request) banner probes → attributed hits.

        Port-0 requests expand to each target's own port; explicit-port
        requests probe once per distinct host."""
        work: set[tuple[str, str, int, int]] = set()  # (host, ip, port, r_idx)
        for r_idx, req in enumerate(self.plan.net_requests):
            if req.port:
                for host, ip in {(h, ip) for h, ip, _p, _t in targets}:
                    work.add((host, ip, req.port, r_idx))
            else:
                for host, ip, port, _t in targets:
                    work.add((host, ip, port, r_idx))
        work_list = sorted(work)  # deterministic probe/hit ordering
        out: list[ActiveHit] = []
        for w0 in range(0, len(work_list), self.wave_rows):
            wave = work_list[w0 : w0 + self.wave_rows]
            reqs = [self.plan.net_requests[r] for _h, _ip, _p, r in wave]
            result = scanio.tcp_scan(
                [ip for _h, ip, _p, _r in wave],
                np.asarray([p for _h, _ip, p, _r in wave], dtype=np.uint16),
                [r.payload or None for r in reqs],
                tls=[r.tls for r in reqs],
                sni=[
                    h if not is_ip(h) else None for h, _ip, _p, _r in wave
                ],
                max_concurrency=int(self.executor.spec["concurrency"]),
                connect_timeout_ms=int(self.executor.spec["connect_timeout_ms"]),
                read_timeout_ms=int(self.executor.spec["read_timeout_ms"]),
                banner_cap=int(self.executor.spec["banner_cap"]),
            )
            rows: list[Response] = []
            meta: list[tuple[str, int, bool, int, str]] = []
            for i, (host, _ip, port, r_idx) in enumerate(wave):
                if int(result.status[i]) != scanio.STATUS_OPEN or not result.banner(i):
                    continue
                rows.append(Response(host=host, port=port, banner=result.banner(i)))
                meta.append((host, port, reqs[i].tls, r_idx, ""))
            out.extend(self._attribute(rows, meta, self._net_owner_ids))
        return out, len(work_list)

    def _run_wave(self, wave) -> list[ActiveHit]:
        # mint one correlation token per OOB probe: an interaction can
        # then be attributed to exactly one (target, request) pair
        tokens: dict[int, str] = {}
        if self._oob_reqs and self.oob_listener is not None:
            for i, (_h, _ip, _p, _t, r_idx) in enumerate(wave):
                if r_idx in self._oob_reqs:
                    tokens[i] = self.oob_listener.new_token()
        payloads = [
            self.plan.requests[r_idx].wire(
                host, port, tls,
                oob_url=(
                    self.oob_listener.url_for(tokens[i])
                    if i in tokens
                    else None
                ),
            )
            for i, (host, _ip, port, tls, r_idx) in enumerate(wave)
        ]
        result = scanio.tcp_scan(
            [ip for _h, ip, _p, _t, _r in wave],
            np.asarray([p for _h, _ip, p, _t, _r in wave], dtype=np.uint16),
            payloads,
            tls=[t for _h, _ip, _p, t, _r in wave],
            sni=[h if not is_ip(h) else None for h, _ip, _p, _t, _r in wave],
            max_concurrency=int(self.executor.spec["concurrency"]),
            connect_timeout_ms=int(self.executor.spec["connect_timeout_ms"]),
            read_timeout_ms=int(self.executor.spec["read_timeout_ms"]),
            banner_cap=int(self.executor.spec["banner_cap"]),
        )
        rows: list[Response] = []
        meta: list[tuple[str, int, bool, int, str]] = []
        for i, (host, _ip, port, t, r_idx) in enumerate(wave):
            if int(result.status[i]) != scanio.STATUS_OPEN:
                continue
            code, header, body = parse_http_response(result.banner(i))
            row = Response(
                host=host, port=port, status=code,
                header=header, body=body, tls=t,
            )
            # reported-path form: plan-time markers render as their
            # target-resolved values; the per-probe OOB token renders
            # as the canonical placeholder (one line per finding, not
            # one per minted token)
            m = (
                host, port, t, r_idx,
                _finalize(
                    self.plan.requests[r_idx].path, host, port, t,
                    "{{interactsh-url}}",
                ),
            )
            if i in tokens:
                # OOB probes defer: their matchers need the interaction
                # poll window to close first (run() drains _pending_oob)
                self._pending_oob.append((row, m, tokens.pop(i)))
            else:
                rows.append(row)
                meta.append(m)
        # probes that never produced a row can't be called back in any
        # attributable way later — release their tokens now
        if self.oob_listener is not None:
            for tok in tokens.values():
                self.oob_listener.release(tok)
        return self._attribute(rows, meta, self._owner_ids)
