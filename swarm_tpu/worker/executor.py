"""Probe executor: bare targets → native I/O front-end → Response rows.

The reference's ``web`` module was a unix pipeline ``dnsx | httpx``
(``worker/modules/web.json``) and its nmap module grabbed banners via
``-sV``. Here those become one batch pipeline over the native engine
(swarm_tpu/native): resolve hostnames (bulk UDP DNS), fan out
(host × ports) TCP connects with optional HTTP payloads, and parse the
raw responses into the fixed-shape rows the device matcher consumes.

Module spec (``modules/<name>.json``)::

    {"backend": "tpu", "templates": "...", "input_format": "targets",
     "probe": {"type": "http",          # or "banner"
               "ports": [80, 8080],
               "path": "/",             # http only
               "resolvers": ["1.1.1.1", "8.8.8.8"],
               "concurrency": 512, "connect_timeout_ms": 1500,
               "read_timeout_ms": 2000, "banner_cap": 4096}}

Target lines accept ``host``, ``host:port``, ``ip``, ``ip:port`` and
``http://host[:port][/path]`` forms; an explicit port overrides the
spec's port fan-out.
"""

from __future__ import annotations

import ipaddress
import re
from pathlib import Path
from typing import Optional, Sequence
from urllib.parse import urlsplit

import numpy as np

from swarm_tpu.fingerprints.model import Response
from swarm_tpu.native import scanio


_DEFAULTS = {
    "type": "http",
    "ports": [80],
    "path": "/",
    "resolvers": [],
    "concurrency": 512,
    "connect_timeout_ms": 1500,
    "read_timeout_ms": 2000,
    "banner_cap": 4096,
}


def parse_target(
    line: str,
) -> Optional[tuple[str, Optional[int], str, str]]:
    """→ (host, explicit_port | None, path, scheme) — scheme "" unless
    the line stated one; None for blank/comment lines.

    Malformed lines (bad URL, out-of-range port) raise ValueError — the
    caller turns those into dead rows so one bad line never sinks the
    chunk."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    path = "/"
    if "://" in line:
        parts = urlsplit(line)
        host = parts.hostname or ""
        port = parts.port  # raises ValueError when out of range
        if parts.path:
            path = parts.path
        if port is None and parts.scheme == "https":
            port = 443
        if not host:
            raise ValueError(f"no host in target {line!r}")
        return (host, port, path, parts.scheme.lower())
    host, sep, port_s = line.rpartition(":")
    if sep and port_s.isdigit():
        port = int(port_s)
        if not 0 < port < 65536:
            raise ValueError(f"port out of range in target {line!r}")
        return (host, port, path, "")
    return (line, None, path, "")


def tls_port(port: int) -> bool:
    """Default TLS heuristic when the target stated no scheme
    (url_of convention)."""
    return port in (443, 8443)


def use_tls(scheme: str, port: int) -> bool:
    """A user-stated scheme always wins over the port heuristic."""
    if scheme == "https":
        return True
    if scheme == "http":
        return False
    return tls_port(port)


def is_ip(host: str) -> bool:
    try:
        ipaddress.IPv4Address(host)
        return True
    except ValueError:
        return False


def parse_http_response(raw: bytes) -> tuple[int, bytes, bytes]:
    """raw bytes → (status_code, header, body); 0 when not HTTP."""
    head, sep, body = raw.partition(b"\r\n\r\n")
    if not sep:
        head, sep, body = raw.partition(b"\n\n")
    status = 0
    m = re.match(rb"HTTP/\d\.\d (\d{3})", head)
    if m:
        status = int(m.group(1))
    return status, head, body


_TOP_PORTS_FILE = (
    Path(__file__).resolve().parent.parent / "data" / "top-ports.txt"
)
_top_ports_cache: Optional[list[int]] = None


def top_ports(limit: Optional[int] = None) -> list[int]:
    """Default port fan-out for service scans (data/top-ports.txt)."""
    global _top_ports_cache
    if _top_ports_cache is None:
        ports: list[int] = []
        for line in _TOP_PORTS_FILE.read_text().splitlines():
            if line.startswith("#"):
                continue
            ports.extend(int(tok) for tok in line.split())
        _top_ports_cache = ports
    return _top_ports_cache[:limit] if limit else list(_top_ports_cache)


_resolv_cache: Optional[list[str]] = None


def _system_resolvers() -> list[str]:
    """IPv4 nameservers from /etc/resolv.conf (dnsx's default source)."""
    global _resolv_cache
    if _resolv_cache is None:
        out: list[str] = []
        try:
            with open("/etc/resolv.conf") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0] == "nameserver" and is_ip(parts[1]):
                        out.append(parts[1])
        except OSError:
            pass
        _resolv_cache = out
    return _resolv_cache


class ProbeExecutor:
    def __init__(self, spec: Optional[dict] = None):
        self.explicit = set(spec or {})  # keys the caller actually set
        self.spec = {**_DEFAULTS, **(spec or {})}

    # ------------------------------------------------------------------
    def _parse_lines(
        self, target_lines: Sequence[str]
    ) -> tuple[list[tuple[str, Optional[int], str, str]], list[str]]:
        """→ (parsed targets, malformed lines). Malformed lines become
        dead rows downstream so every input line stays accounted for."""
        parsed: list[tuple[str, Optional[int], str, str]] = []
        malformed: list[str] = []
        for line in target_lines:
            try:
                t = parse_target(line)
            except ValueError:
                malformed.append(line.strip())
                continue
            if t is not None:
                parsed.append(t)
        return parsed, malformed

    def _resolve_names(
        self,
        parsed: Sequence[tuple[str, Optional[int], str, str]],
        all_addrs: bool = False,
    ) -> dict[str, list[str]]:
        """Bulk-resolve the non-IP hostnames in ``parsed`` → name→addrs
        (empty list when unresolvable)."""
        names = sorted({t[0] for t in parsed if not is_ip(t[0])})
        addr_of: dict[str, list[str]] = {n: [] for n in names}
        resolvers = list(self.spec["resolvers"]) or _system_resolvers()
        if names and resolvers:
            res = scanio.dns_resolve(
                names, resolvers, timeout_ms=int(self.spec["read_timeout_ms"])
            )
            for i, name in enumerate(names):
                addrs = res.addresses(i)
                addr_of[name] = addrs if all_addrs else addrs[:1]
        return addr_of

    # ------------------------------------------------------------------
    def resolve(self, target_lines: Sequence[str]) -> list[tuple[str, list[str]]]:
        """Resolve-only mode (the dnsx module): → [(name, [A records])].

        IP literals pass through as (ip, [ip]); unresolvable names keep
        an empty address list so callers see every input accounted for.
        """
        parsed, _malformed = self._parse_lines(target_lines)
        addr_of = self._resolve_names(parsed, all_addrs=True)
        seen: set[str] = set()
        out: list[tuple[str, list[str]]] = []
        for name, *_ in parsed:
            if name in seen:
                continue
            seen.add(name)
            out.append((name, [name] if is_ip(name) else addr_of.get(name, [])))
        return out

    # ------------------------------------------------------------------
    def run(self, target_lines: Sequence[str]) -> list[Response]:
        """Probe every target; one Response per (target, port) probe.

        Unresolvable or unreachable targets still yield a row (status 0,
        empty streams) so output row counts track input targets — the
        chunk contract the reference's tools also kept (every input line
        is accounted for in the output file).
        """
        parsed, malformed = self._parse_lines(target_lines)
        addr_of = self._resolve_names(parsed)

        # --- fan out (target × ports) ---
        # probes: (host, ip, port, path, tls)
        probes: list[tuple[str, str, int, str, bool]] = []
        dead: list[tuple[str, int]] = []  # unresolved rows
        spec_ports = [p for p in self.spec["ports"] if 0 < int(p) < 65536]
        for host, explicit_port, path, scheme in parsed:
            ip = host if is_ip(host) else next(iter(addr_of.get(host) or []), None)
            ports = [explicit_port] if explicit_port else spec_ports
            for port in ports:
                if ip is None:
                    dead.append((host, port))
                else:
                    probes.append((host, ip, port, path, use_tls(scheme, port)))

        rows: list[Response] = []
        if probes:
            http = self.spec["type"] == "http"
            payloads = None
            if http:
                payloads = [
                    (
                        f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                        "User-Agent: swarm-tpu/1.0\r\nAccept: */*\r\n"
                        "Connection: close\r\n\r\n"
                    ).encode()
                    for host, _ip, _port, path, _tls in probes
                ]
            result = scanio.tcp_scan(
                [ip for _h, ip, _p, _pa, _t in probes],
                np.asarray([p for _h, _ip, p, _pa, _t in probes], dtype=np.uint16),
                payloads,
                tls=[http and t for _h, _ip, _p, _pa, t in probes],
                sni=[
                    host if not is_ip(host) else None
                    for host, _ip, _p, _pa, _t in probes
                ],
                max_concurrency=int(self.spec["concurrency"]),
                connect_timeout_ms=int(self.spec["connect_timeout_ms"]),
                read_timeout_ms=int(self.spec["read_timeout_ms"]),
                banner_cap=int(self.spec["banner_cap"]),
            )
            for i, (host, _ip, port, _path, tls_used) in enumerate(probes):
                raw = result.banner(i)
                if int(result.status[i]) != scanio.STATUS_OPEN:
                    rows.append(Response(host=host, port=port, alive=False))
                    continue
                if http:
                    code, header, body = parse_http_response(raw)
                    rows.append(
                        Response(
                            host=host, port=port, status=code,
                            header=header, body=body, tls=tls_used,
                        )
                    )
                else:
                    rows.append(Response(host=host, port=port, banner=raw))
        rows.extend(Response(host=h, port=p, alive=False) for h, p in dead)
        rows.extend(Response(host=m, port=0, alive=False) for m in malformed)
        return rows

    # ------------------------------------------------------------------
    def run_service(
        self, target_lines: Sequence[str], classifier
    ) -> tuple[list[Response], list[Optional[str]]]:
        """Service-scan probing (the nmap -sV front half): per-port probe
        payload selection from the probes DB, raw banner capture.

        → (rows, sent_probe_names) aligned for
        ``ServiceClassifier.classify``. Targets without an explicit port
        fan out over the spec's ports (default: the bundled top-ports
        list).
        """
        parsed, malformed = self._parse_lines(target_lines)
        addr_of = self._resolve_names(parsed)

        # explicit ports only when the caller set them; service scans
        # default to the top-ports fan-out, not the HTTP default [80]
        spec_ports = (
            [int(p) for p in self.spec["ports"] if 0 < int(p) < 65536]
            if "ports" in self.explicit
            else []
        ) or top_ports()
        probes: list[tuple[str, str, int, str, bytes]] = []
        rows: list[Response] = []
        sent: list[Optional[str]] = []
        for line in malformed:
            rows.append(Response(host=line, port=0, alive=False))
            sent.append(None)
        for host, explicit_port, _path, _scheme in parsed:
            ip = host if is_ip(host) else next(iter(addr_of.get(host) or []), None)
            for port in [explicit_port] if explicit_port else spec_ports:
                if ip is None:
                    rows.append(Response(host=host, port=port, alive=False))
                    sent.append(None)
                    continue
                probe = classifier.probe_for_port(port)
                probes.append((host, ip, port, probe.name, probe.payload))

        if probes:
            result = scanio.tcp_scan(
                [ip for _h, ip, _p, _n, _pl in probes],
                np.asarray([p for _h, _ip, p, _n, _pl in probes], dtype=np.uint16),
                [pl if pl else None for _h, _ip, _p, _n, pl in probes],
                max_concurrency=int(self.spec["concurrency"]),
                connect_timeout_ms=int(self.spec["connect_timeout_ms"]),
                read_timeout_ms=int(self.spec["read_timeout_ms"]),
                banner_cap=int(self.spec["banner_cap"]),
            )
            for i, (host, _ip, port, probe_name, _pl) in enumerate(probes):
                alive = int(result.status[i]) == scanio.STATUS_OPEN
                rows.append(
                    Response(
                        host=host,
                        port=port,
                        banner=result.banner(i) if alive else b"",
                        alive=alive,
                    )
                )
                sent.append(probe_name)

            # second round: open ports that stayed silent under the NULL
            # listen get the lowest-rarity payload probe (nmap escalates
            # through payload probes when nothing announces itself)
            second = classifier.default_payload_probe()
            base = len(rows) - len(probes)
            retry = [
                (base + i, probes[i])
                for i in range(len(probes))
                if rows[base + i].alive
                and not rows[base + i].banner
                and not probes[i][4]  # no payload was sent the first time
            ]
            if second is not None and retry:
                result2 = scanio.tcp_scan(
                    [p[1] for _ri, p in retry],
                    np.asarray([p[2] for _ri, p in retry], dtype=np.uint16),
                    [second.payload] * len(retry),
                    max_concurrency=int(self.spec["concurrency"]),
                    connect_timeout_ms=int(self.spec["connect_timeout_ms"]),
                    read_timeout_ms=int(self.spec["read_timeout_ms"]),
                    banner_cap=int(self.spec["banner_cap"]),
                )
                for j, (ri, p) in enumerate(retry):
                    if (
                        int(result2.status[j]) == scanio.STATUS_OPEN
                        and result2.banner(j)
                    ):
                        rows[ri] = Response(
                            host=p[0], port=p[2], banner=result2.banner(j)
                        )
                        sent[ri] = second.name
        return rows, sent

    # ------------------------------------------------------------------
    def run_jarm(self, target_lines: Sequence[str]):
        """Active TLS fingerprinting: 10 JARM ClientHellos per target.

        → list[TlsFingerprint], one per (target, port), default port
        443. Every input line is accounted for (dead/malformed targets
        yield alive=False rows), matching the chunk contract of the
        other probe paths.
        """
        from swarm_tpu.tls import jarm as jarm_mod
        from swarm_tpu.tls import wire as tls_wire
        from swarm_tpu.tls.jarm import EMPTY_JARM, TlsFingerprint

        parsed, malformed = self._parse_lines(target_lines)
        addr_of = self._resolve_names(parsed)
        targets: list[tuple[str, str, int]] = []
        dead: list[tuple[str, int]] = []
        for host, explicit_port, _path, _scheme in parsed:
            ip = host if is_ip(host) else next(iter(addr_of.get(host) or []), None)
            port = explicit_port or 443
            if ip is None:
                dead.append((host, port))
            else:
                targets.append((host, ip, port))

        fps: list = []
        if targets:
            ips, ports, payloads = [], [], []
            for host, ip, port in targets:
                sni = "" if is_ip(host) else host
                for spec in jarm_mod.probe_set(sni):
                    ips.append(ip)
                    ports.append(port)
                    payloads.append(tls_wire.build_client_hello(spec))
            result = scanio.tcp_scan(
                ips,
                np.asarray(ports, dtype=np.uint16),
                payloads,
                max_concurrency=int(self.spec["concurrency"]),
                connect_timeout_ms=int(self.spec["connect_timeout_ms"]),
                read_timeout_ms=int(self.spec["read_timeout_ms"]),
                banner_cap=max(8192, int(self.spec["banner_cap"])),
            )
            np_count = jarm_mod.NUM_PROBES
            for t, (host, _ip, port) in enumerate(targets):
                statuses = [
                    int(result.status[t * np_count + k]) for k in range(np_count)
                ]
                banners = [
                    result.banner(t * np_count + k)
                    if statuses[k] == scanio.STATUS_OPEN
                    else b""
                    for k in range(np_count)
                ]
                fps.append(
                    jarm_mod.fingerprint_from_banners(
                        host, port, banners,
                        open_=scanio.STATUS_OPEN in statuses,
                    )
                )
        fps.extend(
            TlsFingerprint(host=h, port=p, jarmx=EMPTY_JARM, ja3s="", alive=False)
            for h, p in dead
        )
        fps.extend(
            TlsFingerprint(host=m, port=0, jarmx=EMPTY_JARM, ja3s="", alive=False)
            for m in malformed
        )
        return fps
