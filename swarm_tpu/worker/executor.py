"""Probe executor: bare targets → native I/O front-end → Response rows.

The reference's ``web`` module was a unix pipeline ``dnsx | httpx``
(``worker/modules/web.json``) and its nmap module grabbed banners via
``-sV``. Here those become one batch pipeline over the native engine
(swarm_tpu/native): resolve hostnames (bulk UDP DNS), fan out
(host × ports) TCP connects with optional HTTP payloads, and parse the
raw responses into the fixed-shape rows the device matcher consumes.

Module spec (``modules/<name>.json``)::

    {"backend": "tpu", "templates": "...", "input_format": "targets",
     "probe": {"type": "http",          # or "banner"
               "ports": [80, 8080],
               "path": "/",             # http only
               "resolvers": ["1.1.1.1", "8.8.8.8"],
               "concurrency": 512, "connect_timeout_ms": 1500,
               "read_timeout_ms": 2000, "banner_cap": 4096}}

Target lines accept ``host``, ``host:port``, ``ip``, ``ip:port`` and
``http://host[:port][/path]`` forms; an explicit port overrides the
spec's port fan-out.
"""

from __future__ import annotations

import ipaddress
import re
from typing import Optional, Sequence
from urllib.parse import urlsplit

import numpy as np

from swarm_tpu.fingerprints.model import Response
from swarm_tpu.native import scanio


_DEFAULTS = {
    "type": "http",
    "ports": [80],
    "path": "/",
    "resolvers": [],
    "concurrency": 512,
    "connect_timeout_ms": 1500,
    "read_timeout_ms": 2000,
    "banner_cap": 4096,
}


def parse_target(line: str) -> Optional[tuple[str, Optional[int], str]]:
    """→ (host, explicit_port | None, path); None for blank/comment lines.

    Malformed lines (bad URL, out-of-range port) raise ValueError — the
    caller turns those into dead rows so one bad line never sinks the
    chunk."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    path = "/"
    if "://" in line:
        parts = urlsplit(line)
        host = parts.hostname or ""
        port = parts.port  # raises ValueError when out of range
        if parts.path:
            path = parts.path
        if port is None and parts.scheme == "https":
            port = 443
        if not host:
            raise ValueError(f"no host in target {line!r}")
        return (host, port, path)
    host, sep, port_s = line.rpartition(":")
    if sep and port_s.isdigit():
        port = int(port_s)
        if not 0 < port < 65536:
            raise ValueError(f"port out of range in target {line!r}")
        return (host, port, path)
    return (line, None, path)


def is_ip(host: str) -> bool:
    try:
        ipaddress.IPv4Address(host)
        return True
    except ValueError:
        return False


def parse_http_response(raw: bytes) -> tuple[int, bytes, bytes]:
    """raw bytes → (status_code, header, body); 0 when not HTTP."""
    head, sep, body = raw.partition(b"\r\n\r\n")
    if not sep:
        head, sep, body = raw.partition(b"\n\n")
    status = 0
    m = re.match(rb"HTTP/\d\.\d (\d{3})", head)
    if m:
        status = int(m.group(1))
    return status, head, body


_resolv_cache: Optional[list[str]] = None


def _system_resolvers() -> list[str]:
    """IPv4 nameservers from /etc/resolv.conf (dnsx's default source)."""
    global _resolv_cache
    if _resolv_cache is None:
        out: list[str] = []
        try:
            with open("/etc/resolv.conf") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0] == "nameserver" and is_ip(parts[1]):
                        out.append(parts[1])
        except OSError:
            pass
        _resolv_cache = out
    return _resolv_cache


class ProbeExecutor:
    def __init__(self, spec: Optional[dict] = None):
        self.spec = {**_DEFAULTS, **(spec or {})}

    # ------------------------------------------------------------------
    def resolve(self, target_lines: Sequence[str]) -> list[tuple[str, list[str]]]:
        """Resolve-only mode (the dnsx module): → [(name, [A records])].

        IP literals pass through as (ip, [ip]); unresolvable names keep
        an empty address list so callers see every input accounted for.
        """
        names: list[str] = []
        for line in target_lines:
            try:
                t = parse_target(line)
            except ValueError:
                continue
            if t is not None:
                names.append(t[0])
        to_resolve = sorted({n for n in names if not is_ip(n)})
        resolvers = list(self.spec["resolvers"]) or _system_resolvers()
        addr_of: dict[str, list[str]] = {n: [] for n in to_resolve}
        if to_resolve and resolvers:
            res = scanio.dns_resolve(
                to_resolve, resolvers, timeout_ms=int(self.spec["read_timeout_ms"])
            )
            for i, name in enumerate(to_resolve):
                addr_of[name] = res.addresses(i)
        seen: set[str] = set()
        out: list[tuple[str, list[str]]] = []
        for name in names:
            if name in seen:
                continue
            seen.add(name)
            out.append((name, [name] if is_ip(name) else addr_of.get(name, [])))
        return out

    # ------------------------------------------------------------------
    def run(self, target_lines: Sequence[str]) -> list[Response]:
        """Probe every target; one Response per (target, port) probe.

        Unresolvable or unreachable targets still yield a row (status 0,
        empty streams) so output row counts track input targets — the
        chunk contract the reference's tools also kept (every input line
        is accounted for in the output file).
        """
        parsed = []
        malformed: list[str] = []
        for line in target_lines:
            try:
                t = parse_target(line)
            except ValueError:
                malformed.append(line.strip())
                continue
            if t is not None:
                parsed.append(t)

        # --- resolve hostnames in bulk ---
        names = sorted({h for h, _, _ in parsed if not is_ip(h)})
        addr_of: dict[str, Optional[str]] = {}
        resolvers = list(self.spec["resolvers"]) or _system_resolvers()
        if names and resolvers:
            res = scanio.dns_resolve(
                names,
                resolvers,
                timeout_ms=int(self.spec["read_timeout_ms"]),
            )
            for i, name in enumerate(names):
                addrs = res.addresses(i)
                addr_of[name] = addrs[0] if addrs else None
        else:
            for name in names:
                addr_of[name] = None

        # --- fan out (target × ports) ---
        probes: list[tuple[str, str, int, str]] = []  # (host, ip, port, path)
        dead: list[tuple[str, int]] = []  # unresolved rows
        spec_ports = [p for p in self.spec["ports"] if 0 < int(p) < 65536]
        for host, explicit_port, path in parsed:
            ip = host if is_ip(host) else addr_of.get(host)
            ports = [explicit_port] if explicit_port else spec_ports
            for port in ports:
                if ip is None:
                    dead.append((host, port))
                else:
                    probes.append((host, ip, port, path))

        rows: list[Response] = []
        if probes:
            http = self.spec["type"] == "http"
            payloads = None
            if http:
                payloads = [
                    (
                        f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                        "User-Agent: swarm-tpu/1.0\r\nAccept: */*\r\n"
                        "Connection: close\r\n\r\n"
                    ).encode()
                    for host, _ip, _port, path in probes
                ]
            result = scanio.tcp_scan(
                [ip for _h, ip, _p, _pa in probes],
                np.asarray([p for _h, _ip, p, _pa in probes], dtype=np.uint16),
                payloads,
                max_concurrency=int(self.spec["concurrency"]),
                connect_timeout_ms=int(self.spec["connect_timeout_ms"]),
                read_timeout_ms=int(self.spec["read_timeout_ms"]),
                banner_cap=int(self.spec["banner_cap"]),
            )
            for i, (host, _ip, port, _path) in enumerate(probes):
                raw = result.banner(i)
                if int(result.status[i]) != scanio.STATUS_OPEN:
                    rows.append(Response(host=host, port=port, alive=False))
                    continue
                if http:
                    code, header, body = parse_http_response(raw)
                    rows.append(
                        Response(
                            host=host, port=port, status=code,
                            header=header, body=body,
                        )
                    )
                else:
                    rows.append(Response(host=host, port=port, banner=raw))
        rows.extend(Response(host=h, port=p, alive=False) for h, p in dead)
        rows.extend(Response(host=m, port=0, alive=False) for m in malformed)
        return rows
