"""Stateful per-target template sessions (nuclei's dynamic-value flows).

Two template classes need *sequential* per-target execution that the
batch planner (worker/active.py) cannot express:

- **extractor-chain** — a later request embeds a value an earlier
  step's *internal* extractor produced (CSRF tokens, auth cookies:
  ``{{csrf}}`` in step 2 from ``extractors: [name: csrf, internal]`` in
  step 1). 38 reference-corpus templates.
- **multi-step-condition** — matchers reference indexed history
  variables (``body_2``, ``status_code_1``, req-condition raw chains).
  79 reference-corpus templates.

A session executes one (target, template) pair: requests run in order
over plain sockets (TLS per the probe's scheme), each step's internal
extractors feed the variable environment for later steps, and matchers
evaluate host-side — per-step for plain matchers, against the full
response history for indexed ones. Sessions are the cold path (~100
templates × targets, each a handful of requests); the hot corpus still
runs as device batches. Matcher semantics stay oracle-exact: plain
parts reuse ops/cpu_ref on the step response; indexed parts/dsl build
the history environment the same way nuclei's req-condition does.
"""

from __future__ import annotations

import dataclasses
import re
import socket
import ssl as pyssl
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

from swarm_tpu.fingerprints import dslc
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref
from swarm_tpu.worker import active as planner
from swarm_tpu.worker.executor import parse_http_response

_INDEXED_RE = re.compile(
    r"\b(body|header|all_headers|status_code|response|raw|duration)_(\d+)\b"
)


@dataclasses.dataclass
class SessionHit:
    host: str
    port: int
    template_id: str
    extractions: list[str]
    tls: bool = False
    # final step's response (workflow named-matcher gates re-confirm
    # against it; None outside workflow contexts)
    row: Optional[Response] = None


def _request_once(
    host: str,
    port: int,
    tls: bool,
    payload: bytes,
    timeout: float,
    connect_timeout: Optional[float] = None,
) -> Optional[bytes]:
    """One HTTP exchange over a fresh connection; None on any failure."""
    try:
        with socket.create_connection(
            (host, port), timeout=connect_timeout or timeout
        ) as sock:
            sock.settimeout(timeout)
            if tls:
                ctx = pyssl.SSLContext(pyssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = pyssl.CERT_NONE
                sock = ctx.wrap_socket(sock, server_hostname=host)
            sock.sendall(payload)
            chunks = []
            total = 0
            while total < 1 << 20:  # 1 MiB response cap
                try:
                    chunk = sock.recv(65536)
                except TimeoutError:
                    # server ignored Connection: close / keeps streaming
                    # — whatever arrived is still a usable response
                    break
                if not chunk:
                    break
                chunks.append(chunk)
                total += len(chunk)
            if tls:
                sock.close()
            return b"".join(chunks) if chunks else None
    except (OSError, pyssl.SSLError, ValueError):
        return None


def _history_env(responses: Sequence[Response]) -> dict:
    """dsl environment over the response history: unindexed names bind
    to the LAST response (nuclei's default), ``name_N`` to step N."""
    env = dslc.build_env(responses[-1])
    for i, r in enumerate(responses, 1):
        step = dslc.build_env(r)
        for key in (
            "body", "header", "all_headers", "raw", "status_code",
            "duration",
        ):
            env[f"{key}_{i}"] = step[key]
        env[f"response_{i}"] = step["raw"]
    return env


_MISSING = object()  # indexed step that was never fetched


def _indexed_part(responses: Sequence[Response], part: str):
    """bytes for an indexed part, None when the part isn't indexed, or
    ``_MISSING`` when the referenced step was never fetched (truncated
    session) — a missing step must evaluate False, never empty-match."""
    m = _INDEXED_RE.fullmatch(part or "")
    if not m:
        return None
    name, idx = m.group(1), int(m.group(2))
    if not 1 <= idx <= len(responses):
        return _MISSING
    base = {"response": "raw", "status_code": "status_code"}.get(name, name)
    if base == "status_code":
        return str(responses[idx - 1].status).encode()
    return responses[idx - 1].part(base)


def _eval_matcher(m, responses: Sequence[Response]) -> bool:
    """One matcher over the history: indexed parts/dsl see every step,
    plain matchers see the step they belong to (the last response)."""
    if m.type == "dsl":
        env = _history_env(responses)
        vs = []
        for expr in m.dsl:
            ast = dslc.try_parse(expr)
            if ast is None:
                vs.append(False)
                continue
            try:
                vs.append(bool(dslc.evaluate(ast, env)))
            except Exception:
                vs.append(False)
        v = all(vs) if m.condition == "and" else any(vs)
        return (not v) if m.negative else v
    data = _indexed_part(responses, m.part)
    if data is _MISSING:
        return False  # phantom step: no matcher may fire on it
    if data is not None:
        # evaluate against a synthetic response whose body is the
        # indexed slice, with the part rewritten to plain "body"
        row = Response(body=data, status=responses[-1].status)
        m = dataclasses.replace(m, part="body")
        return bool(cpu_ref.match_matcher(m, row))
    return bool(cpu_ref.match_matcher(m, responses[-1]))


class SessionScanner:
    """Execute session-class templates per target."""

    def __init__(
        self,
        templates: Sequence[Template],
        probe_spec: Optional[dict] = None,
        user_vars: Optional[dict] = None,
    ):
        spec = probe_spec or {}
        self.templates = list(templates)
        self.user_vars = dict(user_vars or {})
        self.timeout = float(spec.get("read_timeout_ms", 2500)) / 1000.0
        self.connect_timeout = (
            float(spec.get("connect_timeout_ms", 1500)) / 1000.0
        )
        self.concurrency = int(spec.get("session_concurrency", 32))
        self.max_steps = int(spec.get("max_session_steps", 8))

    # ------------------------------------------------------------------
    def _steps_of(self, t: Template):
        """Flatten a template into (op, PlannedRequest-template-text)
        steps; raw ops contribute one step per raw block."""
        steps = []
        for op in t.operations:
            if op.raw:
                for raw in op.raw:
                    steps.append((op, ("raw", raw)))
            else:
                method = (op.method or "GET").upper()
                for path in op.paths:
                    steps.append((op, ("req", method, path)))
        return steps[: self.max_steps]

    def _render(self, text: str, vars_: dict) -> Optional[str]:
        return planner._substitute(text, vars_ or None)

    def _run_one(
        self, t: Template, host: str, ip: str, port: int, tls: bool
    ) -> Optional[SessionHit]:
        """One (target, template): payload-bearing templates fan out
        over their (bounded) combo set, first hit wins — nuclei's
        payload semantics for stateful flows (default-logins with
        req-condition etc.)."""
        combos: list = [None]
        for op in t.operations:
            if op.payloads:
                combos, _trunc = planner._payload_combos(op, t.source_path)
                combos = combos or [None]
                break
        for combo in combos:
            hit = self._run_combo(
                t, host, ip, port, tls,
                {**self.user_vars, **(combo or {})},
            )
            if hit is not None:
                return hit
        return None

    def _run_combo(
        self, t: Template, host: str, ip: str, port: int, tls: bool,
        base_vars: dict,
    ) -> Optional[SessionHit]:
        vars_: dict = dict(base_vars)
        responses: list[Response] = []
        op_results: dict[int, list[bool]] = {}
        extractions: list[str] = []
        # req-condition semantics: templates referencing indexed history
        # vars evaluate their matchers ONCE after every step completed
        # (nuclei's cond mode) — per-step evaluation would see future
        # steps as empty, letting negative matchers false-positive
        indexed_mode = planner._uses_indexed_vars(t)
        deferred: list = []  # (op, history_len_at_op_end) for indexed mode
        for op, step in self._steps_of(t):
            if step[0] == "raw":
                rendered = self._render(step[1], vars_)
                if rendered is None:
                    return None  # a needed value never materialized
                req = planner._parse_raw(rendered)
                if req is None:
                    return None
            else:
                _, method, path_t = step
                path = self._render(path_t, vars_)
                body = self._render(op.body or "", vars_)
                if path is None or body is None:
                    return None
                if path.startswith("\x00BASE\x00"):
                    path = path[len("\x00BASE\x00"):] or "/"
                if not path.startswith("/"):
                    path = "/" + path
                headers = []
                for k, v in op.headers:
                    hv = self._render(v, vars_)
                    if hv is None:
                        return None
                    headers.append((k, hv))
                req = planner.PlannedRequest(
                    method=method,
                    path=path,
                    headers=tuple(headers),
                    body=body.encode("latin-1", "replace"),
                )
            raw = _request_once(
                ip, port, tls, req.wire(host, port, tls), self.timeout,
                connect_timeout=self.connect_timeout,
            )
            if raw is None:
                return None  # target gone mid-session
            status, header, body_b = parse_http_response(raw)
            row = Response(
                host=host, port=port, status=status,
                header=header, body=body_b, tls=tls,
            )
            responses.append(row)
            # internal extractors feed the variable environment;
            # non-internal ones contribute to output
            for ex in op.extractors:
                values = cpu_ref._extract(
                    dataclasses.replace(op, extractors=[ex]), row
                )
                if ex.internal and ex.name:
                    if values:
                        vars_.setdefault(ex.name, values[0])
                elif values:
                    extractions.extend(values)
            op_idx = id(op)
            if op.matchers:
                if indexed_mode:
                    deferred.append(op)
                else:
                    vs = [_eval_matcher(m, responses) for m in op.matchers]
                    verdict = (
                        all(vs)
                        if op.matchers_condition == "and"
                        else any(vs)
                    )
                    op_results.setdefault(op_idx, []).append(verdict)
        if indexed_mode:
            for op in {id(o): o for o in deferred}.values():
                vs = [_eval_matcher(m, responses) for m in op.matchers]
                verdict = (
                    all(vs) if op.matchers_condition == "and" else any(vs)
                )
                op_results.setdefault(id(op), []).append(verdict)
        # a template fires if any op matched on any of its steps (OR —
        # the same per-response semantics the batch path uses)
        if any(any(v) for v in op_results.values()):
            return SessionHit(
                host=host, port=port, template_id=t.id,
                extractions=extractions, tls=tls,
                row=responses[-1] if responses else None,
            )
        return None

    # ------------------------------------------------------------------
    def run(
        self, targets: Sequence[tuple[str, str, int, bool]]
    ) -> list[SessionHit]:
        """``targets``: (host, resolved_ip, port, tls) tuples — the
        connection dials the ip, the Host header carries the name."""
        jobs = [
            (t, host, ip, port, tls)
            for host, ip, port, tls in targets
            for t in self.templates
        ]
        hits: list[SessionHit] = []
        if not jobs:
            return hits
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            for hit in pool.map(lambda j: self._run_one(*j), jobs):
                if hit is not None:
                    hits.append(hit)
        return hits
