"""Browserless execution of the JS-free headless-template subset.

nuclei's headless protocol drives a real Chrome over CDP; this
framework has no browser engine, so the corpus class was previously
classified out with an honest ``[headless-skipped]`` marker. A
principled subset needs no JS runtime and executes here:

- **navigation**: ``navigate`` (HTTP fetch with redirects + cookie
  jar), ``waitload``/``sleep`` (no-ops without a renderer),
  ``setheader part=request``.
- **form interaction**: ``click``/``text`` steps addressed by xpath.
  ``text`` fills the addressed input; ``click`` on a submit control
  submits its enclosing form (method/action resolution, urlencoded
  fields), on an anchor navigates its href, on anything else is a
  focus no-op. This executes the reference corpus's
  ``headless/dvwa-headless-automatic-login.yaml`` end to end.
- **DOM attribute-collection scripts** (the
  ``headless/extract-urls.yaml`` idiom):
  ``document.querySelectorAll('[src], [href], …')`` mapped over
  property accessors — emulated exactly over the static DOM, with
  URL-valued properties (src/href/action) resolved against the page
  base the way the browser's property getters would.

- **API-instrumentation hooks** (the postmessage-tracker /
  postmessage-outgoing-tracker / window-name-domxss /
  location-domxss idiom): the hook
  script installs a wrapper that logs when the PAGE's own code calls
  the instrumented API at load time (``addEventListener('message')``,
  ``postMessage(.., '*')``, a ``window.name`` or ``location.hash`` /
  ``location.search`` flow into
  eval/document.write/innerHTML). Without a JS runtime the same
  load-time facts are read statically from the page's actual script
  content — inline ``<script>`` bodies, ``on*`` handler attributes,
  and same-origin external scripts (fetched) — and the synthesized
  ``window.alerts`` entries are serialized the way nuclei's Go side
  prints the evaluated value (``map[k:v]``/space-joined arrays), so
  the corpus matchers/extractors run unmodified. Documented bound:
  registrations created only by DYNAMIC code paths (script-built
  script tags, eval'd registrations) are invisible, exactly as DOM
  nodes built by JS are below.

- **prototype-pollution probing** (the PPScan hook in
  prototype-pollution-check.yaml): the hook's location-driven loop is
  replayed for real — the polluted-query URL and the bare-path page
  for the fragment probe are both fetched through the session — and
  the ``Object.prototype`` observation is a static property model
  over the probe page's load-time scripts (does any parse a
  location-derived string into object keys with a prototype-unguarded
  merge: deparam/parseQuery, split('&') + bracket assignment, deep
  extend). See the property-model section below for the bound.

- **library version-check scripts** (CVE-2022-0776's RevealJS
  probe): ``return (X.VERSION <op> "lit" || ...)`` evaluates against
  the VERSION value in the page's actual library source — only
  scripts that DEFINE the global are consulted, with one identifier
  hop for minified dists — under JS string-comparison semantics.
  Documented bound: library sources are fetched same-origin only
  (like every script surface here), so a CDN-hosted library yields no
  verdict (silent, never a guess); a page without the library yields
  no output, matching the browser's ReferenceError.

- **screenshot as a no-op**: the capture itself needs a renderer, but
  a template whose matchers/extractors only inspect response-derivable
  state (status/header/body, emulated script outputs) never CONSUMES
  the image — for those the ``screenshot`` step is an honest no-op and
  the rest of the flow executes. A template that reads the capture
  (a matcher/extractor part named after the screenshot step) keeps the
  skip with its ``js-required-screenshot`` reason: a real render is
  semantically required.

Anything else needing a JS runtime is classified ``js-required`` by
:func:`classify` and keeps the honest skip marker. The documented
bound of the emulation: nodes inserted by page JavaScript are
invisible (the DOM here is the served HTML, not a rendered tree).

Execution scales through one process-wide bounded pool of emulation
contexts (``SWARM_HEADLESS_THREADS`` / :func:`configure_headless`) —
the browser-pool analogue of the engine's walk pool — and
:meth:`HeadlessScanner.run_async` lets the active scanner overlap a
whole headless round with its device batches. The pooled round is
bit-identical to the serial reference path
(``SWARM_HEADLESS_THREADS=0``): every job owns its session, and
results assemble in job order.

Matchers evaluate on the final page via the exact CPU oracle with
nuclei's headless part names mapped (``resp``/``page``/``data`` → the
full response); matchers/extractors over a named script's output read
the emulated script result.

Reference: /root/reference/worker/artifacts/templates/headless/*.yaml
plus cves/2022/CVE-2022-0776.yaml (8 headless templates: 8 execute —
2 browserless + 4 hook-emulated + 1 version-check + the screenshot
template, whose capture is a no-op because its matchers read only
response-derivable state).
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional, Sequence
from urllib.parse import urljoin, urlencode, urlsplit, urlunsplit

from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref
from swarm_tpu.worker.executor import parse_http_response
from swarm_tpu.worker.sessions import _request_once


# ---------------------------------------------------------------------------
# classification

def _origin(url: str) -> str:
    """Normalized origin key: lowercased hostname plus the port, with
    an explicit scheme-default port (:80 on http, :443 on https)
    collapsed to the implicit form — so a redirect that merely adds the
    default port is still same-origin, as in real browsers. Scheme
    stays OUT of the key (the session socket is pinned to one endpoint;
    an implicit-port http→https redirect rides the same policy the old
    netloc comparison applied: 'h' == 'h' follows)."""
    sp = urlsplit(url)
    if not sp.netloc:
        return ""
    host = (sp.hostname or "").lower()
    try:
        port = sp.port
    except ValueError:
        return sp.netloc.lower()  # unparsable port: compare verbatim
    default = 443 if sp.scheme.lower() == "https" else 80
    if port is None or port == default:
        return host
    return f"{host}:{port}"


def _same_origin(target: str, base: str) -> bool:
    """True when ``target`` stays on ``base``'s origin (relative URLs
    always do)."""
    t = _origin(target)
    return t == "" or t == _origin(base)


_QSA_RE = re.compile(r"querySelectorAll\(\s*['\"]([^'\"]+)['\"]\s*\)")
_ACCESSOR_RE = re.compile(
    r"\.map\(\s*(\w+)\s*=>\s*((?:\1\.\w+\s*\|\|\s*)*\1\.\w+)\s*\)"
)


def _attr_collect_spec(code: str) -> Optional[dict]:
    """Parse the attribute-collection script idiom, or None.

    Recognizes ``[...new Set(Array.from(document.querySelectorAll(
    '[a], [b]')).map(i => i.a || i.b))].join('SEP')`` (optionally
    wrapped in literal prefix/suffix concatenation) and returns
    ``{"attrs": [...], "sep": str, "dedupe": bool, "prefix": str,
    "suffix": str}``.
    """
    qsa = _QSA_RE.search(code)
    acc = _ACCESSOR_RE.search(code)
    if not qsa or not acc:
        return None
    sel_attrs = re.findall(r"\[\s*(\w+)\s*\]", qsa.group(1))
    if not sel_attrs:
        return None
    var = acc.group(1)
    attrs = re.findall(re.escape(var) + r"\.(\w+)", acc.group(2))
    join = re.search(r"\.join\(\s*'((?:\\.|[^'])*)'\s*\)", code)
    sep = join.group(1).encode().decode("unicode_escape") if join else "\n"

    def literal(pat: str) -> str:
        m = re.search(pat, code)
        return (
            m.group(1).encode().decode("unicode_escape") if m else ""
        )

    return {
        "select": sel_attrs,
        "attrs": attrs or sel_attrs,
        "sep": sep,
        "dedupe": "new Set" in code,
        "prefix": literal(r"return\s+'((?:\\.|[^'])*)'\s*\+"),
        "suffix": literal(r"\+\s*'((?:\\.|[^'])*)'\s*\n?\s*}?\s*$"),
    }


#: window.alerts read-back idiom closing every hook template
_ALERTS_READ_RE = re.compile(r"^\s*window\.alerts\s*;?\s*$")

#: library version-check idiom (CVE-2022-0776's RevealJS probe):
#: `return (X.VERSION <op> "lit" || X.VERSION <op> "lit" ...)` — a
#: boolean over a library global's VERSION string, which is a LOAD-TIME
#: fact readable from the page's actual script source (the same
#: honesty class as the hook emulation)
_VERSION_TERM_RE = re.compile(
    r"(\w+)\.VERSION\s*(<=|>=|<|>|===|==)\s*['\"]([^'\"]+)['\"]"
)


def _strip_outer_parens(expr: str) -> str:
    """Remove outer parens only when THE opening paren closes at the
    very end — ``(A) || (B)`` must not lose its per-term parens."""
    while expr.startswith("(") and expr.endswith(")"):
        depth = 0
        wraps = True
        for i, ch in enumerate(expr):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0 and i != len(expr) - 1:
                    wraps = False
                    break
                if depth < 0:
                    wraps = False
                    break
        if not wraps or depth != 0:
            break
        expr = expr[1:-1].strip()
    return expr


def _version_check_spec(code: str) -> Optional[dict]:
    """Parse the version-comparison script shape, or None.

    Accepts a single `return (...)` expression whose every term is
    `GLOBAL.VERSION <op> "literal"` over ONE global, joined by || / &&
    (JS precedence: && binds tighter); parens may wrap the whole
    expression and/or individual terms. Anything else stays
    js-required."""
    m = re.search(r"return\s*(.+?)\s*;?\s*}?\s*$", code, re.S)
    if not m:
        return None
    expr = _strip_outer_parens(m.group(1).strip())
    or_groups = []
    globals_seen = set()
    for part in expr.split("||"):
        and_terms = []
        part = _strip_outer_parens(part.strip())
        for term in part.split("&&"):
            term = _strip_outer_parens(term.strip())
            tm = _VERSION_TERM_RE.fullmatch(term)
            if tm is None:
                return None
            globals_seen.add(tm.group(1))
            and_terms.append((tm.group(2), tm.group(3)))
        or_groups.append(and_terms)
    if len(globals_seen) != 1 or not or_groups:
        return None
    return {"global": globals_seen.pop(), "or_groups": or_groups}


_VERSION_LITERAL_RE = re.compile(
    r"\bVERSION\s*[:=]\s*['\"]([0-9][\w.\-]*)['\"]"
)
#: minified dists hoist the value: ``VERSION:t`` with ``t="4.2.1"``
#: elsewhere — resolved with a single identifier hop
_VERSION_IDENT_RE = re.compile(r"\bVERSION\s*[:=]\s*([A-Za-z_$][\w$]*)\b")


_QUALIFIER_RE = re.compile(r"([A-Za-z_$][\w$]*)\s*\.\s*$")


def _qualifier_before(text: str, pos: int) -> Optional[str]:
    """Identifier qualifying a match at ``pos`` (``ident .`` directly
    before it), from a bounded lookbehind window — the qualifier is a
    few tokens, and an unbounded ``$``-anchored search re-scans the
    whole prefix per candidate (O(n·k) on minified bundles). The
    256-byte window covers the long qualified chains real minified
    bundles produce (the old 64-byte window clipped them); a match
    that begins EXACTLY at a clipped window's start may be the tail of
    a longer identifier the window cut in half — discard it rather
    than attribute the VERSION to a truncated name."""
    lo = max(0, pos - 256)
    qm = _QUALIFIER_RE.search(text, lo, pos)
    if qm is None:
        return None
    if lo > 0 and qm.start(1) == lo:
        return None  # possibly truncated identifier
    return qm.group(1)


def _matched_brace_pairs(text: str) -> tuple:
    """``(starts_sorted, ends_sorted, pairs)`` of the matched ``{...}``
    blocks — the best-effort block structure behind module-window
    scoping. ``pairs`` is the raw ``(start, end)`` list; the sorted
    twins answer depth queries via bisect. Braces inside string/regex
    literals can unbalance the scan; the consumers below all fail OPEN
    (toward the pre-scoping whole-script behavior) in that case."""
    stack: list = []
    pairs: list = []
    for i, ch in enumerate(text):
        if ch == "{":
            stack.append(i)
        elif ch == "}" and stack:
            pairs.append((stack.pop(), i + 1))
    starts = sorted(s for s, _e in pairs)
    ends = sorted(e for _s, e in pairs)
    return starts, ends, pairs


def _module_window(text: str, pos: int, structure=None) -> tuple:
    """``(lo, hi)`` bounds of the OUTERMOST balanced ``{...}`` block
    containing ``pos`` — the enclosing module/factory body in a
    concatenated UMD bundle. Identifiers (factory params like the
    ubiquitous minified ``e``) are only meaningful inside their own
    factory, so alias resolution must not cross this boundary.
    Outermost, not innermost: the export assignment is routinely
    wrapped in a guard (``if(typeof window!=="undefined"){window.X=e}``)
    whose inner block would exclude the rest of the factory body —
    sibling factories in a concatenated bundle are still separate
    top-level blocks either way. When ``pos`` sits at top level (or
    the brace scan is unbalanced) the whole script is the window."""
    _starts, _ends, pairs = (
        structure if structure is not None else _matched_brace_pairs(text)
    )
    best = (0, len(text))
    for s, e in pairs:
        if s <= pos < e and (best == (0, len(text)) or s < best[0]):
            best = (s, e)
    return best


def _aliases_of(
    text: str, g: str, define_pos: int = 0, window: Optional[tuple] = None
) -> set:
    """Local identifiers the enclosing module assigns TO global ``g``
    (UMD shape ``!function(e){e.VERSION="3.8.0"; window.Reveal = e}({})``):
    a ``VERSION`` literal qualified by such an alias belongs to ``g``
    itself, not to another library in the bundle.

    Two containment rules keep minified bundles from donating another
    module's VERSION to the target: the global is anchored with
    ``(?<![\\w$.])`` (``MyReveal = e`` and ``Plugin.Reveal = e`` are
    not assignments to the global), and the search is scoped to the
    module/factory block enclosing ``define_pos`` — a second factory
    reusing the same minified parameter name (``e``) must not have its
    parameter accepted as an alias of this module's export.
    ``window`` is an optional precomputed ``_module_window`` result
    (the brace scan is O(len(text)); callers that also need the window
    pass it in instead of paying the scan twice)."""
    lo, hi = window if window is not None else _module_window(
        text, define_pos
    )
    return {
        am.group(1)
        for am in re.finditer(
            rf"(?<![\w$.])(?:window\s*\.\s*)?(?<![\w$]){re.escape(g)}"
            rf"\s*=(?![=])\s*([A-Za-z_$][\w$]*)\b",
            text[lo:hi],
        )
        if am.group(1) != g
    }


def _script_version_of(
    text: str, g: str, define_pos: int
) -> Optional[str]:
    """The VERSION value script ``text`` carries FOR global ``g``
    (whose define site starts at ``define_pos``): an explicit
    ``g.VERSION = "lit"`` wins; otherwise VERSION literals (direct or
    one identifier hop, ``VERSION:t`` + ``t="4.2.1"``) are candidates
    — except those qualified with ANOTHER global (``Plugin.VERSION``
    in a bundle must not donate Reveal's version). Among candidates,
    the first at/after the define site is the defining object's own;
    with none there, a script-wide UNIQUE value is still unambiguous.
    Multiple distinct values before the define site → None (fail
    closed: no verdict rather than a guessed one)."""
    m = re.search(
        rf"\b{re.escape(g)}\.VERSION\s*=\s*['\"]([0-9][\w.\-]*)['\"]",
        text,
    )
    if m:
        return m.group(1)
    import bisect as _bisect

    structure = _matched_brace_pairs(text)
    starts, ends, _pairs = structure
    lo, hi = window = _module_window(text, define_pos, structure)
    aliases = _aliases_of(text, g, define_pos, window=window)

    def qual_ok(q: Optional[str], pos: int) -> bool:
        # g itself qualifies anywhere. An alias qualifies inside its
        # own module window — the same minified identifier in a
        # SIBLING factory is a different object — and at TOP LEVEL:
        # a top-level module body shares one scope with its (possibly
        # guard-wrapped) export assignment, so scoping it to the guard
        # block would drop the module's own VERSION.
        if q is None or q == g:
            return True
        if q not in aliases:
            return False
        if lo <= pos < hi:
            return True
        # depth 0 = inside no matched block (matched pairs nest, so
        # started-minus-ended counts the enclosing blocks)
        return (
            _bisect.bisect_right(starts, pos)
            - _bisect.bisect_right(ends, pos)
        ) == 0

    vals: list = []
    for vm in _VERSION_LITERAL_RE.finditer(text):
        q = _qualifier_before(text, vm.start())
        if q is not None and not qual_ok(q, vm.start()):
            continue
        vals.append((vm.start(), vm.group(1)))
    # identifier hops are candidates ALONGSIDE direct literals — a
    # pre-define literal of another object must not shadow the target's
    # own hoisted ``VERSION:t``
    for im in _VERSION_IDENT_RE.finditer(text):
        q = _qualifier_before(text, im.start())
        if q is not None and not qual_ok(q, im.start()):
            continue
        ident = re.escape(im.group(1))
        lit = re.search(
            rf"\b{ident}\s*=\s*['\"]([0-9][\w.\-]*)['\"]", text
        )
        if lit:
            vals.append((im.start(), lit.group(1)))
    vals.sort()
    for pos, val in vals:
        if pos >= define_pos:
            return val
    distinct = {v for _pos, v in vals}
    if len(distinct) == 1:
        return distinct.pop()
    return None


def _eval_version_check(sess: "_Session", spec: dict) -> Optional[str]:
    """Evaluate the version comparison against the VERSION value in
    the page's load-time scripts. Only scripts that DEFINE the library
    global (``var/let/const/window.GLOBAL =`` / ``GLOBAL:`` / UMD
    export) are consulted — a script that merely calls into the
    library must not donate an unrelated object's VERSION. A page
    without a fetchable defining script (no library, or the library on
    a cross-origin CDN — the emulation's standing same-origin bound)
    yields None: no output, like the browser's ReferenceError. JS
    string comparison is lexicographic over code units, exactly
    Python's str comparison for this ASCII domain."""
    g = re.escape(spec["global"])
    # `=(?![=])`: an assignment defines, a comparison (`Reveal ==`)
    # merely consults — consumers must not be treated as define sites
    define_re = re.compile(
        rf"(?:\b(?:var|let|const)\s+{g}\b|window\.{g}\s*=(?![=])|"
        rf"\b{g}\s*=(?![=])|[{{,]\s*{g}\s*:|exports\.{g}\s*=(?![=]))"
    )
    version = None
    for _label, text in _page_scripts(sess):
        dm = define_re.search(text)
        if dm is None:
            continue
        version = _script_version_of(text, spec["global"], dm.start())
        if version is not None:
            break
    if version is None:
        return None
    ops = {
        "<=": lambda a, b: a <= b,
        ">=": lambda a, b: a >= b,
        "<": lambda a, b: a < b,
        ">": lambda a, b: a > b,
        "==": lambda a, b: a == b,
        "===": lambda a, b: a == b,
    }
    result = any(
        all(ops[op](version, lit) for op, lit in and_terms)
        for and_terms in spec["or_groups"]
    )
    return "true" if result else "false"


def _hook_spec(code: str) -> Optional[dict]:
    """Classify a ``hook: true`` script by the instrumentation it
    installs, or None when the hook's behavior can't be emulated
    statically (e.g. prototype-pollution's location-driven loop).

    Recognition is structural (what APIs the wrapper intercepts), not
    textual equality — upstream reformatting of the same hook keeps
    working; genuinely different hooks stay js-required."""
    if "Object.prototype" in code and "__proto__" in code:
        # PPScan's location-driven pollution loop (prototype-pollution-
        # check.yaml): probe markers/payload parsed from the hook so an
        # upstream token rotation keeps working; a structurally
        # different pollution hook stays js-required
        q = re.search(
            r"searchParams\.append\(\s*['\"]__proto__\[(\w+)\]['\"]\s*,"
            r"\s*['\"](\w+)['\"]",
            code,
        )
        h = re.search(r"hash\s*=\s*['\"]__proto__\[(\w+)\]=(\w+)", code)
        if q and h and "location" in code:
            return {
                "kind": "proto-pollution",
                "qmark": q.group(1),
                "hmark": h.group(1),
                "value": q.group(2),
            }
        return None
    if "location" in code and "__proto__" in code:
        return None  # unrecognized pollution-style hook
    if (
        "Window.prototype.addEventListener" in code
        and re.search(r"type\s*===?\s*['\"]message['\"]", code)
    ):
        return {"kind": "listen-message"}
    if re.search(r"window\.postMessage\s*=", code) and re.search(
        r"origin\s*==?=?\s*['\"]\*['\"]", code
    ):
        return {"kind": "post-star"}
    if "window.name" in code and re.search(
        r"innerHTML|document\.write|eval", code
    ):
        return {"kind": "window-name-sink"}
    if re.search(
        r"location\.hash|location\.search|document\.URL", code
    ) and re.search(r"innerHTML|document\.write|eval", code):
        # location-domxss idiom: the hook logs URL-derived strings
        # (hash/search/href) flowing into the DOM-XSS sinks — same
        # static read-back as the window.name tracker, one source over
        # (the __proto__-style pollution hooks matched above, so a
        # location-driven pollution loop never lands here)
        return {"kind": "location-sink"}
    return None


def _screenshot_consumed(t: Template, step: dict) -> bool:
    """Whether anything reads the capture: a matcher/extractor part
    named after the screenshot step (or the literal ``screenshot``).
    Only then does the template semantically require a real render."""
    args = step.get("args") or {}
    name = str(step.get("name") or args.get("to") or "screenshot").lower()
    parts = {name, "screenshot"}
    for op in t.operations:
        for m in op.matchers:
            if (m.part or "").lower() in parts:
                return True
        for ex in op.extractors:
            if (ex.part or "").lower() in parts:
                return True
    return False


def classify(t: Template) -> Optional[str]:
    """None when the template executes browserlessly, else the reason
    it can't (js-required / unsupported-action-* / no-steps)."""
    if t.protocol != "headless":
        return "not-headless"
    saw_steps = False
    needs_js_env = False  # response-header rewrites etc.
    saw_hook = saw_alerts_read = False
    for op in t.operations:
        for step in op.steps:
            saw_steps = True
            act = str(step.get("action") or "")
            args = step.get("args") or {}
            if act in ("navigate", "waitload", "sleep"):
                continue
            if act == "setheader":
                # request headers we can send; response-header
                # rewriting (CSP relaxation for the hook's injected
                # frames) only matters to a JS runtime — a no-op under
                # hook emulation, js-required otherwise
                if str(args.get("part") or "request") != "request":
                    needs_js_env = True
                continue
            if act == "screenshot":
                # the capture needs a renderer; the FLOW doesn't. When
                # nothing consumes the image the step is an honest
                # no-op — otherwise keep the skip with its reason
                if _screenshot_consumed(t, step):
                    return "js-required-screenshot"
                continue
            if act in ("text", "click"):
                if str(args.get("by") or "") not in ("x", "xpath"):
                    return "unsupported-selector"
                continue
            if act == "script":
                code = str(args.get("code") or "")
                if args.get("hook"):
                    if _hook_spec(code) is None:
                        return "js-required"
                    saw_hook = True
                    continue
                if _attr_collect_spec(code) is not None:
                    continue
                if _ALERTS_READ_RE.match(code):
                    saw_alerts_read = True
                    continue
                if _version_check_spec(code) is not None:
                    continue
                return "js-required"
            return f"unsupported-action-{act or '?'}"
    if not saw_steps:
        return "no-steps"
    if saw_hook and not saw_alerts_read:
        return "js-required"  # hook without the known read-back idiom
    if needs_js_env and not saw_hook:
        return "js-required"
    return None


# ---------------------------------------------------------------------------
# execution

# Process-wide bounded pool of emulation contexts — the browser-pool
# analogue of the engine's walk pool (docs/HOST_WALK.md): every
# HeadlessScanner in the process shares it, so concurrent scans can't
# multiply thread counts, and an async round rides it while the device
# engine chews its own batches.
_POOL_LOCK = threading.Lock()  # guards: _POOL, _POOL_THREADS
_POOL: Optional[ThreadPoolExecutor] = None
_POOL_THREADS: Optional[int] = None


def headless_threads() -> int:
    """Effective shared-pool width: :func:`configure_headless` arg >
    ``SWARM_HEADLESS_THREADS`` > 16. 0 pins the serial reference
    path (every round runs inline, no pool)."""
    with _POOL_LOCK:
        n = _POOL_THREADS
    if n is None:
        env = os.environ.get("SWARM_HEADLESS_THREADS")
        n = int(env) if env else 16
    return max(0, int(n))


def configure_headless(threads: Optional[int]) -> None:
    """Re-point the shared emulation pool at runtime (bench A/B,
    tests): shuts any existing pool down, then re-decides lazily on
    next use. ``None`` restores env-derived sizing."""
    global _POOL, _POOL_THREADS
    with _POOL_LOCK:
        pool, _POOL = _POOL, None
        _POOL_THREADS = threads
    if pool is not None:
        pool.shutdown(wait=True)


def _shared_pool() -> Optional[ThreadPoolExecutor]:
    """The process pool, lazily built at the configured width; None
    when the width is 0 (serial reference)."""
    n = headless_threads()
    if n <= 0:
        return None
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="headless"
            )
        return _POOL


_DEFAULT_HEADERS = (
    ("User-Agent", "Mozilla/5.0 (X11; Linux x86_64) swarm-tpu-headless"),
    ("Accept", "*/*"),
)
_URL_PROPS = {"src", "href", "action"}  # browser resolves these


@dataclasses.dataclass
class HeadlessHit:
    host: str
    port: int
    template_id: str
    extractions: list
    tls: bool
    matcher_names: list = dataclasses.field(default_factory=list)


class _Page:
    """One fetched page: parsed DOM + parent links for form lookup."""

    def __init__(self, url: str, status: int, header: bytes, body: bytes):
        from swarm_tpu.fingerprints.extractors import parse_html

        self.url = url
        self.status = status
        self.header = header
        self.body = body
        self.root = parse_html(body.decode("utf-8", "replace"))
        self.parent: dict = {}
        if self.root is not None:
            stack = [self.root]
            while stack:
                node = stack.pop()
                for child in node:
                    self.parent[id(child)] = node
                    stack.append(child)

    def xpath(self, path: str):
        from swarm_tpu.fingerprints.extractors import xpath_nodes

        if self.root is None:
            return None
        nodes = xpath_nodes(self.root, path)
        return nodes[0] if nodes else None

    def form_of(self, node):
        while node is not None:
            if node.tag == "form":
                return node
            node = self.parent.get(id(node))
        return None


class _Session:
    """Cookie jar + header state for one (target, template) run."""

    def __init__(self, host, ip, port, tls, timeout, connect_timeout):
        self.host = host
        self.ip = ip
        self.port = port
        self.tls = tls
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self.cookies: dict = {}
        self.headers: dict = {}
        self.page: Optional[_Page] = None
        self.hooks: list = []  # installed hook-emulation specs
        default = (tls and port == 443) or (not tls and port == 80)
        self.base_url = (
            f"{'https' if tls else 'http'}://{host}"
            + ("" if default else f":{port}")
        )

    def fetch(self, url: str, method="GET", body=b"", content_type=None,
              redirects=5) -> bool:
        sp = urlsplit(url)
        path = (sp.path or "/") + (f"?{sp.query}" if sp.query else "")
        # browsers omit the scheme-default port from Host: a followed
        # redirect to http://h:80/... must still send "Host: h" or a
        # strict name-based vhost silently serves its default site
        host_hdr = sp.netloc or self.host
        default = 443 if sp.scheme.lower() == "https" else 80
        try:
            if sp.hostname and sp.port == default:
                host_hdr = sp.hostname
        except ValueError:
            # malformed/out-of-range port in a caller URL: keep the
            # verbatim netloc Host header (mirrors _origin) instead of
            # crashing the whole run()
            pass
        lines = [f"{method} {path} HTTP/1.1", f"Host: {host_hdr}"]
        sent = {"host"}
        for k, v in list(self.headers.items()) + list(_DEFAULT_HEADERS):
            if k.lower() in sent:
                continue
            sent.add(k.lower())
            lines.append(f"{k}: {v}")
        if self.cookies:
            lines.append(
                "Cookie: "
                + "; ".join(f"{k}={v}" for k, v in self.cookies.items())
            )
        if body or method not in ("GET", "HEAD"):
            if content_type:
                lines.append(f"Content-Type: {content_type}")
            lines.append(f"Content-Length: {len(body)}")
        lines.append("Connection: close")
        payload = ("\r\n".join(lines) + "\r\n\r\n").encode() + body
        raw = _request_once(
            self.ip or self.host, self.port, self.tls, payload,
            self.timeout, self.connect_timeout,
        )
        if raw is None:
            return False
        status, header, rbody = parse_http_response(raw)
        for m in re.finditer(
            rb"(?im)^set-cookie:\s*([^=;\s]+)=([^;\r\n]*)", header
        ):
            self.cookies[m.group(1).decode("latin-1")] = (
                m.group(2).decode("latin-1")
            )
        loc = re.search(rb"(?im)^location:\s*(\S+)", header)
        if status in (301, 302, 303, 307, 308) and loc and redirects > 0:
            target = urljoin(url, loc.group(1).decode("latin-1"))
            # same-origin only: the jar and socket are bound to the
            # scan target, and a scanner must not wander off-host
            if _same_origin(target, url):
                nxt_method = "GET" if status in (301, 302, 303) else method
                nxt_body = b"" if status in (301, 302, 303) else body
                return self.fetch(
                    target, nxt_method, nxt_body, content_type,
                    redirects - 1,
                )
        self.page = _Page(url, status, header, rbody)
        return True

    def fetch_resource(self, url: str) -> Optional["_Page"]:
        """Subresource fetch (external scripts): same request machinery
        and cookie jar, but the session's page state is untouched."""
        saved = self.page
        try:
            ok = self.fetch(url)
            return self.page if ok else None
        finally:
            self.page = saved


def _run_steps(t: Template, steps, sess: _Session, outputs: dict) -> bool:
    """Execute one op's step list; False on a dead/failed navigation."""
    for step in steps:
        act = str(step.get("action") or "")
        args = step.get("args") or {}
        if act in ("waitload", "sleep", "screenshot"):
            # screenshot: classify admitted only unconsumed captures —
            # the flow continues, the image is never read
            continue
        if act == "setheader":
            if str(args.get("part") or "request") != "request":
                continue  # response rewriting: no-op without a renderer
            key, val = str(args.get("key") or ""), str(args.get("value") or "")
            if key:
                sess.headers[key] = val
            continue
        if act == "navigate":
            url = str(args.get("url") or "{{BaseURL}}")
            url = url.replace("{{BaseURL}}", sess.base_url)
            url = url.replace("{{RootURL}}", sess.base_url)
            url = url.replace("{{Hostname}}", sess.host)
            if not sess.fetch(urljoin(sess.base_url + "/", url)):
                return False
            continue
        if act == "text":
            node = sess.page.xpath(str(args.get("xpath") or "")) if sess.page else None
            if node is not None:
                val = str(args.get("value") or "")
                node.set("value", val)
                if node.tag.lower() == "textarea":
                    node.text = val  # itertext() must yield the typed value
                    for child in list(node):
                        node.remove(child)
            continue
        if act == "click":
            page = sess.page
            node = page.xpath(str(args.get("xpath") or "")) if page else None
            if node is None:
                continue
            tag = node.tag.lower()
            typ = (node.get("type") or "").lower()
            if tag == "a" and node.get("href"):
                target = urljoin(page.url, node.get("href"))
                # same-origin only (matches the redirect policy): the
                # socket is bound to the scan target, and a foreign
                # Host header would silently produce vhost mismatches
                if not _same_origin(target, page.url):
                    continue
                if not sess.fetch(target):
                    return False
            elif (tag == "input" and typ in ("submit", "image")) or (
                tag == "button" and typ in ("", "submit")
            ):
                form = page.form_of(node)
                if form is None:
                    continue
                if not _submit(sess, page, form, clicked=node):
                    return False
            # any other element: focus — no page effect
            continue
        if act == "script":
            code = str(args.get("code") or "")
            if args.get("hook"):
                hook = _hook_spec(code)
                if hook is not None:
                    sess.hooks.append(hook)
                continue
            if _ALERTS_READ_RE.match(code):
                name = str(step.get("name") or args.get("name") or "alerts")
                outputs[name] = _emulate_alerts(sess)
                continue
            spec = _attr_collect_spec(code)
            if spec is not None and sess.page is not None:
                name = str(step.get("name") or args.get("name") or "script")
                outputs[name] = _collect_attrs(sess.page, spec)
                continue
            vspec = _version_check_spec(code)
            if vspec is not None and sess.page is not None:
                verdict = _eval_version_check(sess, vspec)
                if verdict is not None:
                    name = str(
                        step.get("name") or args.get("name") or "script"
                    )
                    outputs[name] = verdict
                # library absent: no output — the matcher over this
                # part cannot fire, matching the browser's thrown
                # ReferenceError producing no result
            continue
    return True


def _submit(sess: _Session, page: _Page, form, clicked) -> bool:
    method = (form.get("method") or "get").lower()
    action = urljoin(page.url, form.get("action") or page.url)
    if not _same_origin(action, page.url):
        return True  # cross-origin form: out of scan scope, no-op
    fields: list = []
    for el in form.iter():
        name = el.get("name")
        if not name:
            continue
        tag = el.tag.lower()
        typ = (el.get("type") or "").lower()
        if tag == "input":
            if typ in ("submit", "image", "button"):
                if el is clicked:
                    fields.append((name, el.get("value") or ""))
                continue
            if typ in ("checkbox", "radio") and el.get("checked") is None:
                continue
            fields.append((name, el.get("value") or ""))
        elif tag == "textarea":
            typed = el.get("value")
            fields.append(
                (name, typed if typed is not None else "".join(el.itertext()))
            )
        elif tag == "select":
            opts = [o for o in el.iter() if o.tag.lower() == "option"]
            sel = next(
                (o for o in opts if o.get("selected") is not None),
                opts[0] if opts else None,
            )
            if sel is not None:
                fields.append((name, sel.get("value") or "".join(sel.itertext())))
    data = urlencode(fields)
    if method == "post":
        return sess.fetch(
            action, "POST", data.encode(),
            content_type="application/x-www-form-urlencoded",
        )
    # GET submit REPLACES the action's query with the serialized
    # fields (browser semantics) — appending would produce a request
    # real Chrome never sends
    sp = urlsplit(action)
    return sess.fetch(
        urlunsplit((sp.scheme, sp.netloc, sp.path, data, ""))
    )


def _collect_attrs(page: _Page, spec: dict) -> str:
    vals: list = []
    if page.root is not None:
        for el in page.root.iter():
            if not any(el.get(a) is not None for a in spec["select"]):
                continue
            for a in spec["attrs"]:
                raw = el.get(a)
                if raw:
                    # browser property getters resolve URL-valued
                    # attributes against the document base
                    vals.append(
                        urljoin(page.url, raw) if a in _URL_PROPS else raw
                    )
                    break
    if spec["dedupe"]:
        vals = list(dict.fromkeys(vals))
    return spec["prefix"] + spec["sep"].join(vals) + spec["suffix"]


# ---------------------------------------------------------------------------
# hook emulation: static load-time instrumentation of the page's
# actual script content (see module docstring for the honesty bound)

_MAX_EXT_SCRIPTS = 5
_MAX_SCRIPT_BYTES = 512 * 1024


def _go_fmt(v) -> str:
    """Serialize the way nuclei's Go side prints an Evaluate result
    (fmt.Sprint of the JSON-decoded value): maps as ``map[k:v ...]``
    with sorted keys, arrays space-joined in brackets — the corpus's
    ``part: alerts`` word matchers are written against THIS shape
    (e.g. ``at Window.addEventListener``, ``sink:``)."""
    if isinstance(v, dict):
        return "map[" + " ".join(
            f"{k}:{_go_fmt(x)}" for k, x in sorted(v.items())
        ) + "]"
    if isinstance(v, (list, tuple)):
        return "[" + " ".join(_go_fmt(x) for x in v) + "]"
    return str(v)


def _page_scripts(sess: "_Session", page: Optional["_Page"] = None) -> list:
    """(label, text) of every load-time script the page runs: inline
    ``<script>`` bodies, ``on*`` handler attributes, and same-origin
    external scripts (fetched, bounded). ``page`` defaults to the
    session's current page (probe passes hand in a fetched page)."""
    page = page if page is not None else sess.page
    out: list = []
    if page is None or page.root is None:
        return out
    ext: list = []
    for el in page.root.iter():
        tag = str(getattr(el, "tag", "")).lower()
        for attr, val in (el.attrib or {}).items():
            if attr.lower().startswith("on") and val:
                out.append((f"{page.url}#{attr.lower()}", val))
        if tag != "script":
            continue
        src = el.get("src")
        if src:
            target = urljoin(page.url, src)
            if _same_origin(target, page.url) and target not in ext:
                ext.append(target)
            continue
        text = (el.text or "") + "".join(
            (c.tail or "") for c in el
        )
        if text.strip():
            out.append((page.url, text))
    for target in ext[:_MAX_EXT_SCRIPTS]:
        res = sess.fetch_resource(target)
        if res is not None and res.body:
            out.append(
                (target, res.body[:_MAX_SCRIPT_BYTES].decode("latin-1"))
            )
    return out


_LISTEN_RE = re.compile(r"addEventListener\s*\(\s*['\"]message['\"]")
_ONMESSAGE_RE = re.compile(r"\bonmessage\s*=")
_POSTMSG_RE = re.compile(r"\bpostMessage\s*\(")
_NAME_ALIAS_RE = re.compile(
    r"(?:var|let|const)\s+(\w+)\s*=\s*window\.name\b"
)
#: location-source aliases: ``var h = location.hash`` (the optional
#: trailing accessor — .substr(1), .slice(1) — still taints the alias)
_LOC_ALIAS_RE = re.compile(
    r"(?:var|let|const)\s+(\w+)\s*=\s*"
    r"(?:window\.)?(location\.hash|location\.search|document\.URL)\b"
)


def _source_sinks(text: str, sources: list) -> list:
    """(sink, source, snippet) for flows of the given source
    expressions into eval / document.write / innerHTML — direct or via
    one local alias. ``sources`` is ``[(label, pattern), ...]``."""
    out = []
    for label, name in sources:
        for sink, pat in (
            ("eval", rf"\beval\s*\(\s*[^;\n]*?\b{name}\b"),
            ("document.write", rf"document\.write\s*\(\s*[^;\n]*?\b{name}\b"),
            ("innerHTML", rf"\.innerHTML\s*[+]?=\s*[^;\n]*?\b{name}\b"),
        ):
            for m in re.finditer(pat, text):
                out.append((sink, label, m.group(0)[:120]))
    return out


def _window_name_sinks(text: str) -> list:
    """(sink, snippet) for flows of window.name into eval /
    document.write / innerHTML — direct or via one local alias."""
    sources = [("window.name", r"window\.name")]
    sources += [
        ("window.name", re.escape(m.group(1)))
        for m in _NAME_ALIAS_RE.finditer(text)
    ]
    return [
        (sink, snippet)
        for sink, _src, snippet in _source_sinks(text, sources)
    ]


def _location_sinks(text: str) -> list:
    """(sink, source, snippet) for flows of location.hash /
    location.search / document.URL into the DOM-XSS sinks — direct or
    via one local alias (the location-domxss hook's read-back)."""
    sources = [
        ("location.hash", r"location\.hash"),
        ("location.search", r"location\.search"),
        ("document.URL", r"document\.URL"),
    ]
    sources += [
        (m.group(2), re.escape(m.group(1)))
        for m in _LOC_ALIAS_RE.finditer(text)
    ]
    return _source_sinks(text, sources)


# --- prototype-pollution property model -----------------------------------
#
# PPScan (the hook in prototype-pollution-check.yaml) detects pollution
# dynamically: navigate with __proto__'d query params, then again with
# a __proto__'d fragment, and check Object.prototype for the payload.
# Without a JS runtime the navigation half runs for real (both probe
# URLs are fetched through the session) and the observation half is a
# static property model over the probe page's load-time scripts: a
# script pollutes Object.prototype from the URL iff it parses a
# location-derived string into object keys with a prototype-unguarded
# merge (deparam/parseQuery, split('&') + bracket assignment, or a
# deep extend over the split) — the client-side parser classes PPScan
# exists to catch. Documented bound: parsers reached only through
# dynamically built code are invisible, same as DOM nodes built by JS.

_POLLUTE_PARSE_RE = re.compile(
    r"\bdeparam\s*\(|\.parseQuery\s*\(|\bparse_str\s*\("
)
_POLLUTE_SPLIT_RE = re.compile(
    r"\.split\(\s*(?:['\"][&;=]['\"]|/[^/\n]*[&;][^/\n]*/)\s*\)"
)
# any computed-key assignment — `obj[k] =`, `obj[keys[i]] =` (nested
# brackets included, hence the lookback on the closing bracket only)
_POLLUTE_ASSIGN_RE = re.compile(r"\]\s*=(?![=>])")
_POLLUTE_EXTEND_RE = re.compile(r"\bextend\s*\(\s*true\s*,")
_POLLUTE_GUARD_RE = re.compile(
    r"hasOwnProperty\s*\(|['\"]__proto__['\"]|Object\.create\(\s*null\s*\)"
    r"|['\"]constructor['\"]"
)
_LOC_SEARCH_RE = re.compile(
    r"location\.search|location\.href|document\.URL\b"
    r"|window\.location(?![\w.])"
)
_LOC_HASH_RE = re.compile(
    r"location\.hash|location\.href|document\.URL\b"
    r"|window\.location(?![\w.])"
)


def _pollution_script_model(text: str) -> set:
    """Which location sources (``search`` / ``hash``) this script
    parses into object keys with a prototype-UNguarded merge; empty
    when the script doesn't parse the URL or guards its keys."""
    if _POLLUTE_GUARD_RE.search(text):
        return set()
    vulnerable = bool(
        _POLLUTE_PARSE_RE.search(text)
        or (
            _POLLUTE_SPLIT_RE.search(text)
            and _POLLUTE_ASSIGN_RE.search(text)
        )
        or (
            _POLLUTE_EXTEND_RE.search(text)
            and _POLLUTE_SPLIT_RE.search(text)
        )
    )
    if not vulnerable:
        return set()
    out = set()
    if _LOC_SEARCH_RE.search(text):
        out.add("search")
    if _LOC_HASH_RE.search(text):
        out.add("hash")
    return out


def _pollution_probe(sess: "_Session", hook: dict) -> list:
    """Run PPScan's two navigations for real and apply the property
    model; returns the ``logger(location.href)`` values a polluted run
    would record (URLs carrying the __proto__ markers)."""
    page = sess.page
    if page is None:
        return []
    val = hook["value"]
    out: list = []
    sp = urlsplit(page.url)
    # probe 1: searchParams.append on the current URL (query reaches
    # the server — the polluted page may differ from the base page)
    extra = (
        f"__proto__[{hook['qmark']}]={val}"
        f"&__proto__.{hook['qmark']}={val}"
    )
    q = f"{sp.query}&{extra}" if sp.query else extra
    qurl = urlunsplit((sp.scheme, sp.netloc, sp.path or "/", q, ""))
    qpage = sess.fetch_resource(qurl)
    if qpage is not None:
        srcs: set = set()
        for _label, text in _page_scripts(sess, page=qpage):
            srcs |= _pollution_script_model(text)
        if "search" in srcs:
            out.append(qurl)
    # probe 2: origin + pathname with the markers in the FRAGMENT —
    # never sent on the wire, so the fetched page is the bare path and
    # only hash/href-reading parsers can see the payload
    hurl = urlunsplit((sp.scheme, sp.netloc, sp.path or "/", "", ""))
    hfrag = (
        f"__proto__[{hook['hmark']}]={val}"
        f"&__proto__.{hook['hmark']}={val}&dummy"
    )
    hpage = sess.fetch_resource(hurl)
    if hpage is not None:
        srcs = set()
        for _label, text in _page_scripts(sess, page=hpage):
            srcs |= _pollution_script_model(text)
        if "hash" in srcs:
            out.append(hurl + "#" + hfrag)
    return out


def _emulate_alerts(sess: "_Session") -> str:
    """The ``window.alerts`` array the installed hooks would hold after
    load, synthesized from the page's static script content."""
    page = sess.page
    if page is None or not sess.hooks:
        return "[]"
    scripts = _page_scripts(sess)
    alerts: list = []
    for hook in sess.hooks:
        kind = hook["kind"]
        if kind == "listen-message":
            for label, text in scripts:
                n = len(_LISTEN_RE.findall(text)) + len(
                    _ONMESSAGE_RE.findall(text)
                )
                alerts.extend(
                    [f"at Window.addEventListener ({label})",
                     f"at {page.url}"]
                    for _ in range(n)
                )
        elif kind == "post-star":
            for label, text in scripts:
                for m in _POSTMSG_RE.finditer(text):
                    window = text[m.end(): m.end() + 200]
                    if re.search(r"['\"]\*['\"]", window):
                        alerts.append({
                            "args": {"origin": "*"},
                            "stack": [f"at window.postMessage ({label})"],
                        })
        elif kind == "window-name-sink":
            for label, text in scripts:
                for sink, snippet in _window_name_sinks(text):
                    alerts.append({
                        "code": snippet,
                        "sink": sink,
                        "source": "window.name",
                        "stack": [f"at {label}"],
                    })
        elif kind == "location-sink":
            for label, text in scripts:
                for sink, source, snippet in _location_sinks(text):
                    alerts.append({
                        "code": snippet,
                        "sink": sink,
                        "source": source,
                        "stack": [f"at {label}"],
                    })
        elif kind == "proto-pollution":
            alerts.extend(_pollution_probe(sess, hook))
    return _go_fmt(alerts)


_PART_ALIAS = {"resp": "response", "page": "response", "data": "response"}


class HeadlessScanner:
    """Run the browserless headless subset against live targets.

    Integrated by worker/active.py the same way the ssl/session passes
    are: templates :func:`classify` accepts execute here; the rest keep
    the honest skip marker.
    """

    def __init__(self, templates: Sequence[Template], probe_spec=None):
        self.templates = [t for t in templates if classify(t) is None]
        spec = probe_spec or {}
        self.timeout = float(spec.get("read_timeout_ms", 5000)) / 1000.0
        self.connect_timeout = (
            float(spec.get("connect_timeout_ms", 3000)) / 1000.0
        )
        # per-round in-flight cap on the SHARED pool (a wide fleet scan
        # must not starve every other scanner's rounds); the pool's own
        # width bounds the process
        self.concurrency = int(spec.get("headless_concurrency", 16))

    def run(self, targets) -> list:
        """targets: (host, ip, port, tls) tuples (the liveness shape).
        One batched round over the shared pool — bit-identical to the
        serial reference (results assemble in job order; every job
        owns its session)."""
        return self._run_round(targets)

    def run_async(self, targets) -> Future:
        """Start a round without blocking: the active scanner launches
        this right after liveness and joins after its device waves, so
        emulation I/O overlaps device batches. The round runs on a
        dedicated coordinator thread (never a pool slot — a width-1
        pool must not deadlock on its own coordinator) fanning jobs
        into the shared pool."""
        fut: Future = Future()

        def round_main() -> None:
            try:
                fut.set_result(self._run_round(targets))
            except BaseException as e:  # surfaced at .result()
                fut.set_exception(e)

        threading.Thread(
            target=round_main, name="headless-round", daemon=True
        ).start()
        return fut

    def _run_round(self, targets) -> list:
        if not self.templates or not targets:
            return []
        jobs = [
            (t, tgt) for tgt in targets for t in self.templates
        ]
        pool = _shared_pool()
        if pool is None:  # serial reference path
            results = [self._exec(*j) for j in jobs]
            return [h for h in results if h is not None]
        results = []
        cap = max(1, self.concurrency)
        for i in range(0, len(jobs), cap):
            futs = [
                pool.submit(self._exec, *j) for j in jobs[i: i + cap]
            ]
            results.extend(f.result() for f in futs)
        return [h for h in results if h is not None]

    # ------------------------------------------------------------------
    def _exec(self, t: Template, target) -> Optional[HeadlessHit]:
        host, ip, port, tls = target
        sess = _Session(
            host, ip, port, tls, self.timeout, self.connect_timeout
        )
        for op in t.operations:
            outputs: dict = {}
            if not _run_steps(t, op.steps, sess, outputs):
                return None
            if sess.page is None:
                return None
            row = Response(
                host=host, port=port, status=sess.page.status,
                body=sess.page.body, header=sess.page.header, tls=tls,
            )
            verdicts = []
            names = []
            for m in op.matchers:
                if (m.part or "") in outputs:
                    # matcher over a named script's emulated output
                    # (part: alerts) — same oracle, output as content
                    mm = dataclasses.replace(m, part="body")
                    out_row = Response(
                        host=host, port=port, status=sess.page.status,
                        body=outputs[m.part].encode("utf-8", "replace"),
                    )
                    v = cpu_ref.match_matcher(mm, out_row)
                else:
                    mm = dataclasses.replace(
                        m, part=_PART_ALIAS.get(m.part or "", m.part)
                    )
                    v = cpu_ref.match_matcher(mm, row)
                v = bool(v) if v is not None else False
                verdicts.append(v)
                if v and m.name:
                    names.append(m.name)
            if op.matchers:
                ok = (
                    all(verdicts)
                    if op.matchers_condition == "and"
                    else any(verdicts)
                )
                if not ok:
                    continue
            elif not op.extractors:
                continue
            extractions: list = []
            for ex in op.extractors:
                if ex.part in outputs:
                    val = outputs[ex.part]
                    if ex.type == "kval":
                        # nuclei stores a named script's output under
                        # its name; kval over that part yields it
                        if any(
                            k.lower().replace("-", "_")
                            == ex.part.lower().replace("-", "_")
                            for k in ex.kval
                        ):
                            extractions.append(val)
                    elif ex.type == "regex":
                        for pat in ex.regex:
                            try:
                                extractions.extend(
                                    mo.group(ex.group)
                                    for mo in re.finditer(pat, val)
                                )
                            except (re.error, IndexError):
                                continue  # RE2-only syntax / bad group
                    continue
                extractions.extend(cpu_ref.extract_one(ex, row))
            if op.matchers or extractions:
                return HeadlessHit(
                    host=host, port=port, template_id=t.id,
                    extractions=extractions, tls=tls,
                    matcher_names=names,
                )
        return None
