from swarm_tpu.worker.runtime import main

main()
