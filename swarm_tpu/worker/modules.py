"""Module registry — the plugin system for scan types.

Wire-compatible with the reference's ``worker/modules/*.json`` command
templates (``{input}``/``{output}`` substitution, ``worker/worker.py:27-33``)
and extended with a TPU backend:

    {"command": "nmap -T5 ... -oN {output} -iL {input}"}     # subprocess
    {"backend": "tpu", "templates": "/path/to/corpus",       # device batch
     "input_format": "jsonl"}
    {"backend": "probe", "probe": {...},                     # native I/O only
     "output_format": "httpx_json"}

The TPU backend replaces the shell-out with a device-batched
fingerprint match (the reference's compute was nmap/-sV/nuclei inside
the subprocess — SURVEY.md §2.2). ``input_format``:

- ``jsonl``: each input line is a JSON response row
  ``{host, port, status, body?, header?, banner?}`` (body/header/banner
  base64 when *_b64 variants used). Produced by the native probe
  front-end or any external collector.
- ``targets``: each line is a bare ``host[:port]`` target; requires the
  native I/O front-end to grab banners first (wired in executor.py).
"""

from __future__ import annotations

import base64
import json
import os
from pathlib import Path
from typing import Optional

from swarm_tpu.fingerprints.model import Response


class ModuleSpec:
    def __init__(self, name: str, raw: dict):
        self.name = name
        self.raw = raw
        self.backend = raw.get("backend", "command")
        self.command_template: Optional[str] = raw.get("command")
        # allow $SWARM_TEMPLATES_DIR-style indirection in module files
        self._templates_raw: Optional[str] = raw.get("templates")
        self._templates_resolved: Optional[str] = None
        self.input_format: str = raw.get("input_format", "jsonl")
        self.output_format: str = raw.get("output_format", "matches_jsonl")
        self.probe: dict = raw.get("probe", {})

    @property
    def templates_dir(self) -> Optional[str]:
        """Resolved template-corpus path, verified to exist.

        A template-backed module whose corpus is unresolvable (unset
        SWARM_TEMPLATES_DIR, or a path that isn't a directory) must
        fail LOUDLY at job time — the reference worker ships the whole
        corpus in its image (/root/reference/worker/Dockerfile:11) and
        nuclei errors out without templates; silently matching nothing
        would look like a clean empty scan.

        Validation runs once per spec (the runtime reads this several
        times per job); the first success is cached."""
        if self._templates_raw is None:
            return None
        if self._templates_resolved is not None:
            return self._templates_resolved
        d = os.path.expandvars(self._templates_raw)
        if "$" in d:
            raise ValueError(
                f"module {self.name}: templates path "
                f"{self._templates_raw!r} references an unset "
                "environment variable (set SWARM_TEMPLATES_DIR or bake "
                "the corpus into the image — docker/worker.Dockerfile "
                "TEMPLATES_SRC)"
            )
        if not os.path.isdir(d):
            raise ValueError(
                f"module {self.name}: templates directory {d!r} does "
                "not exist (corpus not bundled/mounted?)"
            )
        self._templates_resolved = d
        return d

    def command(self, input_path: str, output_path: str) -> str:
        """Substitute {input}/{output} (reference worker.py:27-33)."""
        if not self.command_template:
            raise ValueError(f"module {self.name} has no command")
        return self.command_template.replace("{input}", input_path).replace(
            "{output}", output_path
        )


class ModuleRegistry:
    def __init__(self, modules_dir: str | Path):
        self.modules_dir = Path(modules_dir)

    def load(self, name: str) -> ModuleSpec:
        safe = Path(name).name  # no path traversal via module names
        path = self.modules_dir / f"{safe}.json"
        with path.open() as f:
            return ModuleSpec(safe, json.load(f))

    def names(self) -> list[str]:
        if not self.modules_dir.is_dir():
            return []
        return sorted(p.stem for p in self.modules_dir.glob("*.json"))


# ---------------------------------------------------------------------------
# Response row (de)serialization for the jsonl input format
# ---------------------------------------------------------------------------


def _bytes_field(obj: dict, name: str) -> bytes:
    if f"{name}_b64" in obj:
        return base64.b64decode(obj[f"{name}_b64"])
    value = obj.get(name)
    if value is None:
        return b""
    if isinstance(value, bytes):
        return value
    return str(value).encode("utf-8", "surrogateescape")


def parse_response_line(line: str) -> Optional[Response]:
    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
    except ValueError:
        # bare target line — pass through as an empty response so the
        # row count is stable; real probing is the front-end's job
        host, _, port = line.partition(":")
        return Response(host=host, port=int(port) if port.isdigit() else 0)
    banner = _bytes_field(obj, "banner") if ("banner" in obj or "banner_b64" in obj) else None
    return Response(
        host=str(obj.get("host", "")),
        port=int(obj.get("port", 0) or 0),
        status=int(obj.get("status", 0) or 0),
        body=_bytes_field(obj, "body"),
        header=_bytes_field(obj, "header"),
        banner=banner,
        alive=bool(obj.get("alive", True)),
    )


def format_match_line(row: Response, matches) -> str:
    out = {
        "host": row.host,
        "port": row.port,
        "matches": matches.template_ids,
        "extractions": matches.extractions,
    }
    if not row.alive:
        out["unreachable"] = True
    return json.dumps(out, sort_keys=True)
