"""Output formatters for the probe/match modules.

The reference's modules each emitted a distinct output shape into the
chunk file (`/root/reference/worker/modules/*.json`):

- ``dnsx``   — resolved hostnames, one per line (dnsx default output)
- ``httprobe`` — live ``http(s)://host[:port]`` URLs (httprobe stdout)
- ``httpx`` / ``http2`` / ``web`` — httpx ``-json`` JSON-lines with url,
  status code, title, webserver, content length
- ``nuclei`` — ``[template-id] [protocol] [severity] url`` match lines

These formatters reproduce those shapes from the native front-end's
Response rows so downstream consumers of chunk files keep working when
the execution engine underneath is the TPU batch path.
"""

from __future__ import annotations

import json
import re
from typing import Iterable, Sequence

from swarm_tpu.fingerprints.model import Response, Template

_TITLE_RE = re.compile(rb"<title[^>]*>(.*?)</title>", re.IGNORECASE | re.DOTALL)
_SERVER_RE = re.compile(rb"^server:[ \t]*(.+?)[ \t\r]*$", re.IGNORECASE | re.MULTILINE)


def url_of(row: Response) -> str:
    """Canonical URL for a probed row (httprobe/httpx conventions).

    A row that records how it was actually probed (``row.tls``) renders
    that scheme; otherwise the port heuristic applies."""
    if row.tls is not None:
        scheme = "https" if row.tls else "http"
    else:
        scheme = "https" if row.port in (443, 8443) else "http"
    default = 443 if scheme == "https" else 80
    if row.port in (default, 0):
        return f"{scheme}://{row.host}"
    return f"{scheme}://{row.host}:{row.port}"


def format_dnsx(resolutions: Iterable[tuple[str, list[str]]], with_a: bool = False) -> str:
    """dnsx default output: one line per name that resolved.

    ``with_a`` mirrors ``dnsx -a -resp``: ``name [ip]`` per address.
    """
    lines = []
    for name, addrs in resolutions:
        if not addrs:
            continue
        if with_a:
            lines.extend(f"{name} [{a}]" for a in addrs)
        else:
            lines.append(name)
    return "\n".join(lines) + ("\n" if lines else "")


def format_httprobe(rows: Sequence[Response]) -> str:
    """httprobe stdout: one live URL per row whose connect succeeded."""
    lines = [url_of(row) for row in rows if row.alive]
    return "\n".join(lines) + ("\n" if lines else "")


def extract_title(body: bytes) -> str:
    m = _TITLE_RE.search(body)
    if not m:
        return ""
    return m.group(1).decode("utf-8", "replace").strip()


def extract_server(header: bytes) -> str:
    m = _SERVER_RE.search(header)
    return m.group(1).decode("utf-8", "replace") if m else ""


def format_httpx_json(rows: Sequence[Response]) -> str:
    """httpx ``-json`` JSON-lines (the fields the reference pipeline used)."""
    lines = []
    for row in rows:
        # httpx emits only successfully probed URLs: the connect must have
        # succeeded AND a parseable HTTP status line must have come back
        # (a silent open socket, or an SSH/SMTP banner, produces nothing)
        if not row.alive or row.status == 0:
            continue
        obj = {
            "url": url_of(row),
            "host": row.host,
            "port": str(row.port),
            "status_code": row.status,
            "title": extract_title(row.body),
            "webserver": extract_server(row.header),
            "content_length": row.content_length,
        }
        lines.append(json.dumps(obj, sort_keys=True))
    return "\n".join(lines) + ("\n" if lines else "")


def format_nuclei(
    rows: Sequence[Response],
    results: Sequence,
    severity_of: dict[str, str],
    protocol_of: dict[str, str],
) -> str:
    """nuclei ``-o`` output: ``[template-id] [protocol] [severity] url``."""
    lines = []
    for row, matches in zip(rows, results):
        for tid in matches.template_ids:
            proto = protocol_of.get(tid, "http")
            sev = severity_of.get(tid, "info")
            target = url_of(row) if proto == "http" else f"{row.host}:{row.port}"
            lines.append(f"[{tid}] [{proto}] [{sev}] {target}")
    return "\n".join(lines) + ("\n" if lines else "")


def format_nmap_report(infos: Sequence) -> str:
    """nmap ``-oN``-shaped service report (the output consumers of the
    reference's nmap module parse): a per-host report block with a
    PORT/STATE/SERVICE/VERSION table over open ports."""
    by_host: dict[str, list] = {}
    for info in infos:
        if info.open:
            by_host.setdefault(info.host, []).append(info)
    blocks = []
    for host, ports in by_host.items():
        lines = [
            f"Nmap scan report for {host}",
            "PORT      STATE SERVICE        VERSION",
        ]
        for info in sorted(ports, key=lambda x: x.port):
            version = " ".join(
                x for x in (info.product, info.version) if x
            )
            if info.info:
                version = (version + f" ({info.info})").strip()
            svc = (info.service or "unknown") + ("?" if info.soft else "")
            lines.append(
                f"{str(info.port) + '/tcp':<9} open  {svc:<14} {version}".rstrip()
            )
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks) + ("\n" if blocks else "")


def severity_index(templates: Sequence[Template]) -> tuple[dict[str, str], dict[str, str]]:
    """(template_id → severity, template_id → protocol) lookup tables."""
    sev = {t.id: t.severity for t in templates}
    proto = {t.id: t.protocol for t in templates}
    return sev, proto
