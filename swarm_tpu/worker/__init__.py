"""Worker runtime: poll loop, module registry, TPU batch executor."""
