"""Typed DNS queries with dig-style answer rendering (dns templates).

The native engine handles bulk A-record resolution
(``native/scanio.cpp: swarm_dns_resolve`` — the dnsx-equivalent hot
path). DNS *templates* are a long tail (17 in the corpus, SURVEY.md
§2.3) that query one specific record type (CNAME/MX/TXT/CAA/NS/PTR/A)
and match substrings of the rendered response — so this client favors
completeness of rdata rendering over raw throughput: one UDP socket,
all queries in flight, answers collected by id.

Rendered text is dig-like (``name. ttl IN TYPE rdata`` lines) — the
corpus matchers look for substrings like ``zendesk.com`` or
``amazonaws.com`` in the answer section, plus rcode words
(``SERVFAIL``/``REFUSED`` — servfail-refused-hosts.yaml), all present
in this rendering.
"""

from __future__ import annotations

import dataclasses
import random
import select
import socket
import struct
import time
from typing import Optional, Sequence

QTYPES = {
    "A": 1, "NS": 2, "CNAME": 5, "SOA": 6, "PTR": 12, "MX": 15,
    "TXT": 16, "AAAA": 28, "DS": 43, "CAA": 257,
}
_TYPE_NAMES = {v: k for k, v in QTYPES.items()}
_RCODES = {0: "NOERROR", 1: "FORMERR", 2: "SERVFAIL", 3: "NXDOMAIN",
           4: "NOTIMP", 5: "REFUSED"}


@dataclasses.dataclass
class DnsAnswer:
    name: str
    type_name: str
    ttl: int
    rdata: str

    def line(self) -> str:
        return f"{self.name}\t{self.ttl}\tIN\t{self.type_name}\t{self.rdata}"


@dataclasses.dataclass
class DnsReply:
    qname: str
    qtype: str
    rcode: str
    answers: list[DnsAnswer]

    def render(self) -> bytes:
        """dig-like text the matchers run over."""
        lines = [
            f";; ->>HEADER<<- opcode: QUERY, status: {self.rcode}",
            f";; QUESTION SECTION:\n;{self.qname}.\tIN\t{self.qtype}",
        ]
        if self.answers:
            lines.append(";; ANSWER SECTION:")
            lines.extend(a.line() for a in self.answers)
        return "\n".join(lines).encode("utf-8", "surrogateescape")


def _encode_qname(name: str) -> Optional[bytes]:
    out = b""
    for label in name.strip(".").split("."):
        try:
            raw = (
                label.encode("ascii")
                if label.isascii()
                else label.encode("idna")
            )
        except UnicodeError:
            return None
        if not raw or len(raw) > 63:
            return None
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def _read_name(
    pkt: bytes, off: int, depth: int = 0, hard_end: Optional[int] = None
) -> tuple[str, int]:
    """Decompress a domain name; returns (name, next offset).

    ``hard_end`` bounds the *inline* walk (an rdata boundary) — labels
    running past it are malformed and truncate the name. Compression
    pointers may legitimately jump anywhere earlier in the packet."""
    labels: list[str] = []
    limit = len(pkt) if hard_end is None else min(hard_end, len(pkt))
    while True:
        if off >= limit or depth > 16:
            return ".".join(labels), off
        length = pkt[off]
        if length == 0:
            return ".".join(labels), off + 1
        if (length & 0xC0) == 0xC0:
            if off + 2 > limit:
                return ".".join(labels), off + 2
            ptr = ((length & 0x3F) << 8) | pkt[off + 1]
            tail, _ = _read_name(pkt, ptr, depth + 1)
            if tail:
                labels.append(tail)
            return ".".join(labels), off + 2
        if off + 1 + length > limit:  # inline label crosses the boundary
            return ".".join(labels), limit
        labels.append(
            pkt[off + 1 : off + 1 + length].decode("latin-1")
        )
        off += 1 + length


def _render_rdata(pkt: bytes, off: int, rdlen: int, rtype: int) -> str:
    end = off + rdlen
    try:
        if rtype == 1 and rdlen == 4:  # A
            return socket.inet_ntoa(pkt[off:end])
        if rtype == 28 and rdlen == 16:  # AAAA
            return socket.inet_ntop(socket.AF_INET6, pkt[off:end])
        if rtype in (2, 5, 12):  # NS / CNAME / PTR
            return _read_name(pkt, off, hard_end=end)[0]
        if rtype == 15:  # MX: pref + name
            pref = struct.unpack("!H", pkt[off : off + 2])[0]
            return f"{pref} {_read_name(pkt, off + 2, hard_end=end)[0]}"
        if rtype == 16:  # TXT: length-prefixed strings, clamped to rdata
            parts = []
            pos = off
            while pos < end:
                ln = min(pkt[pos], end - pos - 1)
                parts.append(
                    '"' + pkt[pos + 1 : pos + 1 + ln].decode("latin-1") + '"'
                )
                pos += 1 + ln
            return " ".join(parts)
        if rtype == 257:  # CAA: flags, tag, value
            flags = pkt[off]
            tag_len = min(pkt[off + 1], max(0, end - off - 2))
            tag = pkt[off + 2 : off + 2 + tag_len].decode("latin-1")
            value = pkt[off + 2 + tag_len : end].decode("latin-1")
            return f'{flags} {tag} "{value}"'
        if rtype == 6:  # SOA
            mname, pos = _read_name(pkt, off, hard_end=end)
            rname, pos = _read_name(pkt, pos, hard_end=end)
            serial = struct.unpack("!I", pkt[pos : pos + 4])[0]
            return f"{mname} {rname} {serial}"
    except (IndexError, struct.error, OSError):
        pass
    return pkt[off:end].hex()


def parse_reply(pkt: bytes, qname: str, qtype: str) -> Optional[DnsReply]:
    if len(pkt) < 12:
        return None
    flags, qd, an = struct.unpack("!HHH", pkt[2:8])
    rcode = _RCODES.get(flags & 0xF, str(flags & 0xF))
    off = 12
    for _ in range(qd):
        _, off = _read_name(pkt, off)
        off += 4
    answers: list[DnsAnswer] = []
    for _ in range(an):
        name, off = _read_name(pkt, off)
        if off + 10 > len(pkt):
            break
        rtype, _rclass, ttl, rdlen = struct.unpack(
            "!HHIH", pkt[off : off + 10]
        )
        off += 10
        if off + rdlen > len(pkt):
            break
        answers.append(
            DnsAnswer(
                name=name + ".",
                type_name=_TYPE_NAMES.get(rtype, f"TYPE{rtype}"),
                ttl=ttl,
                rdata=_render_rdata(pkt, off, rdlen, rtype),
            )
        )
        off += rdlen
    return DnsReply(qname=qname, qtype=qtype, rcode=rcode, answers=answers)


def reverse_name(ip: str) -> str:
    return ".".join(reversed(ip.split("."))) + ".in-addr.arpa"


def query_batch(
    queries: Sequence[tuple[str, str]],
    resolvers: Sequence[str],
    timeout_ms: int = 2000,
    retries: int = 1,
    port: int = 53,
) -> list[Optional[DnsReply]]:
    """[(qname, qtype)] → replies (None = no/invalid response).

    Batches larger than the usable 16-bit id namespace are split into
    sequential sub-batches so arbitrarily large query lists work.
    """
    out: list[Optional[DnsReply]] = []
    for lo in range(0, len(queries), _MAX_BATCH):
        out.extend(
            _query_batch_one(
                queries[lo : lo + _MAX_BATCH],
                resolvers,
                timeout_ms=timeout_ms,
                retries=retries,
                port=port,
            )
        )
    return out


_MAX_BATCH = 60000  # ids per socket, below the 65536 id namespace


def _query_batch_one(
    queries: Sequence[tuple[str, str]],
    resolvers: Sequence[str],
    timeout_ms: int,
    retries: int,
    port: int,
) -> list[Optional[DnsReply]]:
    """One shared-socket wave of at most _MAX_BATCH queries.

    Transaction ids are a random permutation of the id space (not the
    query index), drawn from the OS CSPRNG — an off-path forger must
    guess the id, and observing earlier waves must not let it
    reconstruct PRNG state to predict later ones.
    """
    n = len(queries)
    out: list[Optional[DnsReply]] = [None] * n
    if n == 0 or not resolvers:
        return out
    ids = random.SystemRandom().sample(range(65536), n)
    id_to_idx = {qid: i for i, qid in enumerate(ids)}
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
    except OSError:
        pass
    resolver_addrs = {(r, port) for r in resolvers}
    try:
        pending = set(range(n))
        packets: list[Optional[bytes]] = []
        for i, (qname, qtype) in enumerate(queries):
            enc = _encode_qname(qname)
            tcode = QTYPES.get(qtype.upper())
            if enc is None or tcode is None:
                packets.append(None)
                pending.discard(i)
                continue
            packets.append(
                struct.pack("!HHHHHH", ids[i], 0x0100, 1, 0, 0, 0)
                + enc
                + struct.pack("!HH", tcode, 1)
            )

        def accept(data: bytes, addr) -> None:
            # forged-reply hygiene for a security scanner: the source
            # must be a resolver we queried, QR must be set, and the
            # echoed question must match what we asked
            if addr not in resolver_addrs or len(data) < 12:
                return
            rid = id_to_idx.get(struct.unpack("!H", data[:2])[0])
            if rid is None or rid not in pending:
                return
            flags = struct.unpack("!H", data[2:4])[0]
            if not flags & 0x8000:  # not a response
                return
            qname, qtype = queries[rid]
            echoed, off = _read_name(data, 12)
            if echoed.lower().rstrip(".") != qname.lower().rstrip("."):
                return
            if off + 2 > len(data) or struct.unpack(
                "!H", data[off : off + 2]
            )[0] != QTYPES.get(qtype.upper()):
                return
            reply = parse_reply(data, qname, qtype)
            if reply is not None:
                out[rid] = reply
                pending.discard(rid)

        def drain() -> None:
            while True:
                try:
                    data, addr = sock.recvfrom(4096)
                except (BlockingIOError, OSError):
                    return
                accept(data, addr)

        for attempt in range(retries + 1):
            if not pending:
                break
            for sent, i in enumerate(sorted(pending)):
                pkt = packets[i]
                if pkt is None:
                    continue
                resolver = resolvers[(i + attempt) % len(resolvers)]
                try:
                    sock.sendto(pkt, (resolver, port))
                except OSError:
                    continue
                # interleave receives: replies arrive during the send
                # blast and would overflow the kernel buffer otherwise
                if sent % 128 == 127:
                    drain()
            deadline = time.monotonic() + timeout_ms / 1000.0
            while pending:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                ready, _, _ = select.select([sock], [], [], left)
                if not ready:
                    break
                drain()
    finally:
        sock.close()
    return out
