"""ssl-protocol template execution (nuclei ``ssl`` templates).

The reference corpus carries 5 ssl templates
(``worker/artifacts/templates/ssl/*.yaml``): a TLS handshake is made to
each target — optionally version-pinned per operation
(deprecated-tls.yaml pins sslv3/tls10/tls11) — and matchers/extractors
run over a JSON document describing the negotiated session and the
server certificate (tls_version, not_after, common_name,
issuer_common_name, dns_names, …). dsl matchers like
``unixtime() > not_after`` (expired-ssl.yaml) and
``common_name == issuer_common_name`` (self-signed-ssl.yaml) are
evaluated host-side with the session document merged into the dsl
environment; json extractors reuse the engine's jq-path evaluator.

Network I/O is a handful of handshakes per target — host threads, not
device work; the device engine is for the byte-matching corpus, and
these 5 templates are scalar predicates over handshake metadata.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import ssl as pyssl

from swarm_tpu.fingerprints.regexlin import quiet_warnings
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

from swarm_tpu.fingerprints import dslc
from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref

# nuclei version-pin names → python ssl constants. SSLv3 has no
# client-side support in modern OpenSSL: a pin we cannot dial is an
# automatic no-match for that operation (same observable result as
# "server refused the old protocol"). TLSv1/TLSv1_1 are deprecated
# enum members and may disappear from a future Python — resolve them
# defensively so a missing member degrades to the same un-dialable-pin
# no-match instead of an ImportError time-bomb.
_VERSIONS = {}
for _pin, _member in (
    ("tls10", "TLSv1"),
    ("tls11", "TLSv1_1"),
    ("tls12", "TLSv1_2"),
    ("tls13", "TLSv1_3"),
):
    with quiet_warnings(DeprecationWarning):
        _v = getattr(pyssl.TLSVersion, _member, None)
    if _v is not None:
        _VERSIONS[_pin] = _v
del _pin, _member, _v

# Ports that are KNOWN plaintext protocols: the ssl fan-out excludes
# these from a module's probe ports (a TLS handshake there can only
# burn its timeout) and keeps everything else — nonstandard TLS ports
# (4433, appliance admin UIs, …) stay covered.
PLAINTEXT_PORTS = frozenset(
    {
        21, 22, 23, 25, 53, 69, 79, 80, 110, 111, 119, 123, 135, 137,
        139, 143, 161, 389, 445, 512, 513, 514, 515, 554, 587, 873,
        1080, 2049, 3000, 3128, 3306, 5000, 5060, 5432, 5900, 6000,
        6379, 8000, 8008, 8080, 8081, 8088, 9090, 9100, 9200, 11211,
        27017, 50000,
    }
)

_WIRE_TO_NUCLEI = {
    "SSLv3": "ssl30",
    "TLSv1": "tls10",
    "TLSv1.1": "tls11",
    "TLSv1.2": "tls12",
    "TLSv1.3": "tls13",
}


@dataclasses.dataclass
class SslFinding:
    template_id: str
    host: str
    port: int
    severity: str = "info"
    extractions: list[str] = dataclasses.field(default_factory=list)
    # named matchers that fired (workflow gates consume these — ssl
    # docs can't be re-confirmed through the generic cpu oracle)
    matcher_names: list[str] = dataclasses.field(default_factory=list)


def _cert_doc(der: bytes) -> dict:
    """Certificate fields in nuclei's tls-document shape."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import ExtensionOID, NameOID

    cert = x509.load_der_x509_certificate(der)
    cn = [
        a.value for a in cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
    ]
    issuer_cn = [
        a.value for a in cert.issuer.get_attributes_for_oid(NameOID.COMMON_NAME)
    ]
    dns_names: list[str] = []
    try:
        san = cert.extensions.get_extension_for_oid(
            ExtensionOID.SUBJECT_ALTERNATIVE_NAME
        )
        dns_names = san.value.get_values_for_type(x509.DNSName)
    except x509.ExtensionNotFound:
        pass
    return {
        "common_name": cn,
        "issuer_common_name": issuer_cn,
        "subject_dn": cert.subject.rfc4514_string(),
        "issuer_dn": cert.issuer.rfc4514_string(),
        "dns_names": dns_names,
        "not_before": int(cert.not_valid_before_utc.timestamp()),
        "not_after": int(cert.not_valid_after_utc.timestamp()),
        "serial": str(cert.serial_number),
        "fingerprint_sha256": cert.fingerprint(hashes.SHA256()).hex(),
        "self_signed": cert.subject == cert.issuer,
    }


def handshake(
    host: str,
    port: int,
    min_version: str = "",
    max_version: str = "",
    timeout: float = 4.0,
) -> Optional[dict]:
    """One TLS handshake; returns the session/cert document, or None
    when the connection or the (possibly version-pinned) handshake
    fails."""
    ctx = pyssl.SSLContext(pyssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = pyssl.CERT_NONE
    try:
        # legacy-protocol probing needs permissive ciphers
        ctx.set_ciphers("ALL:@SECLEVEL=0")
    except pyssl.SSLError:
        pass
    try:
        # legacy pins are deliberate here (probing what the SERVER
        # still speaks); quiet_warnings is the lock-serialized guard —
        # this runs in a ThreadPoolExecutor, where bare catch_warnings
        # would race on the process-global filter list
        with quiet_warnings(DeprecationWarning):
            if min_version:
                ctx.minimum_version = _VERSIONS[min_version]
            if max_version:
                ctx.maximum_version = _VERSIONS[max_version]
    except (KeyError, ValueError):
        return None  # pin not dialable on this client (e.g. sslv3)
    try:
        with socket.create_connection((host, port), timeout=timeout) as sock:
            with ctx.wrap_socket(sock, server_hostname=host) as tls:
                der = tls.getpeercert(binary_form=True)
                version = tls.version() or ""
                cipher = (tls.cipher() or ("", "", 0))[0]
    except (OSError, pyssl.SSLError, ValueError):
        return None
    doc = {
        "host": host,
        "port": str(port),
        "tls_version": _WIRE_TO_NUCLEI.get(version, version.lower()),
        "cipher": cipher,
    }
    if der:
        try:
            doc.update(_cert_doc(der))
        except Exception:
            # embedded-device garbage DER must not kill the scan; the
            # session half of the doc (version/cipher) is still usable
            pass
    return doc


def _parse_target(line: str) -> Optional[tuple[str, Optional[int]]]:
    """→ (host, explicit_port_or_None); None for blanks/comments. The
    caller applies its port default/fan-out to portless targets."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if "://" in line:
        line = line.split("://", 1)[1]
    line = line.split("/", 1)[0]
    if line.startswith("["):
        # bracketed IPv6, with or without :port
        host, _, rest = line[1:].partition("]")
        if rest.startswith(":"):
            try:
                return host, int(rest[1:])
            except ValueError:
                return host, None
        return host, None
    if line.count(":") > 1:
        return line, None  # bare IPv6 address, no port syntax possible
    if ":" in line:
        host, _, p = line.rpartition(":")
        try:
            return host, int(p)
        except ValueError:
            return line, None
    return line, None


class SslScanner:
    """Execute ssl-protocol templates against host[:port] targets."""

    def __init__(
        self,
        templates: Sequence[Template],
        concurrency: int = 32,
        timeout: float = 4.0,
    ):
        self.templates = [t for t in templates if t.protocol == "ssl"]
        self.concurrency = max(1, concurrency)
        self.timeout = timeout

    # ------------------------------------------------------------------
    def _eval_operation(
        self, op, doc: dict, host: str, port: int
    ) -> tuple[bool, list[str], list[str]]:
        """(matched, extracted, fired_matcher_names) for one ssl op
        given a session doc."""
        body = json.dumps(doc, separators=(",", ":")).encode()
        row = Response(host=host, port=port, body=body, tls=True)
        # internal named extractors feed the dsl environment
        # (self-signed-ssl.yaml: common_name / issuer_common_name)
        env = dslc.build_env(row)
        for k, v in doc.items():
            if isinstance(v, (str, int, float, bool)):
                env.setdefault(k, v)
        out: list[str] = []
        for ex in op.extractors:
            values = cpu_ref._extract(
                dataclasses.replace(op, extractors=[ex]), row
            )
            if ex.internal and ex.name:
                if values:
                    env[ex.name] = values[0]
            else:
                out.extend(values)
        if not op.matchers:
            # extractor-only entries fire when anything extracted
            # (tls-version.yaml / ssl-dns-names.yaml)
            return bool(out), out, []
        verdicts: list[bool] = []
        fired_names: list[str] = []
        for m in op.matchers:
            if m.type == "dsl":
                vs = []
                for expr in m.dsl:
                    ast = dslc.try_parse(expr)
                    if ast is None:
                        vs.append(False)
                        continue
                    try:
                        vs.append(bool(dslc.evaluate(ast, env)))
                    except Exception:
                        # exotic expression errors degrade to no-match,
                        # never abort the scan (cpu_ref convention)
                        vs.append(False)
                v = all(vs) if m.condition == "and" else any(vs)
                verdicts.append((not v) if m.negative else v)
            else:
                v = cpu_ref.match_matcher(m, row)
                verdicts.append(bool(v))
            if verdicts[-1] and m.name:
                fired_names.append(m.name)
        matched = (
            all(verdicts) if op.matchers_condition == "and" else any(verdicts)
        )
        return matched, out, fired_names

    def _scan_target(self, host: str, port: int) -> list[SslFinding]:
        findings: list[SslFinding] = []
        # handshake cache: unpinned + per-distinct-pin (deprecated-tls
        # makes 3 pinned dials; everything else shares the free one)
        docs: dict[tuple[str, str], Optional[dict]] = {}

        def doc_for(op) -> Optional[dict]:
            key = (op.ssl_min_version, op.ssl_max_version)
            if key not in docs:
                docs[key] = handshake(
                    host, port, key[0], key[1], timeout=self.timeout
                )
            return docs[key]

        for t in self.templates:
            hits: list[str] = []
            names: list[str] = []
            matched = False
            for op in t.operations:
                doc = doc_for(op)
                if doc is None:
                    continue
                ok, values, fired = self._eval_operation(op, doc, host, port)
                if ok:
                    matched = True
                    hits.extend(values)
                    names.extend(fired)
            if matched:
                findings.append(
                    SslFinding(
                        template_id=t.id,
                        host=host,
                        port=port,
                        severity=t.severity,
                        extractions=hits,
                        matcher_names=sorted(set(names)),
                    )
                )
        return findings

    def scan(
        self,
        lines: Sequence[str],
        default_ports: Optional[Sequence[int]] = None,
    ) -> tuple[list[SslFinding], dict]:
        """``default_ports`` applies to portless target lines (the
        active module passes its probe ports so ssl templates follow
        the scan's port fan-out instead of assuming 443)."""
        defaults = list(dict.fromkeys(int(p) for p in default_ports or [443]))
        targets = []
        seen = set()
        for line in lines:
            t = _parse_target(line)
            if t is None:
                continue
            host, port = t
            for p in [port] if port is not None else defaults:
                if (host, p) not in seen:
                    seen.add((host, p))
                    targets.append((host, p))
        findings: list[SslFinding] = []
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            for result in pool.map(
                lambda hp: self._scan_target(*hp), targets
            ):
                findings.extend(result)
        stats = {
            "targets": len(targets),
            "templates": len(self.templates),
            "hits": len(findings),
        }
        return findings, stats


def format_lines(findings: Sequence[SslFinding]) -> list[str]:
    lines = []
    for h in findings:
        extra = (
            " [" + ",".join(repr(v) for v in h.extractions) + "]"
            if h.extractions
            else ""
        )
        lines.append(
            f"[{h.template_id}] [ssl] [{h.severity}] {h.host}:{h.port}{extra}"
        )
    return lines


def format_findings(findings: Sequence[SslFinding]) -> bytes:
    lines = format_lines(findings)
    return ("\n".join(lines) + "\n").encode() if lines else b""
