"""File-template scanning engine (nuclei ``file`` protocol).

The reference corpus carries 76 ``file``-protocol templates under
``worker/artifacts/templates/file/`` plus the standalone
``worker/artifacts/s3-bucket.yaml:7-18`` (regex extractors for S3 bucket
URLs); the reference executes them via the nuclei binary
(``worker/modules/nuclei.json``). Here they run TPU-first: every input
file's bytes become one response row, all matcher-bearing templates are
evaluated in one device batch by :class:`~swarm_tpu.ops.engine.MatchEngine`
(exact, oracle-confirmed), and the corpus's extractor-only templates
(which nuclei treats as "fire if anything extracts") run host-side over
the extension-gated file subset.

Measured corpus surface (SURVEY.md §2.3): file matchers are word (43) +
regex (128) only, with per-entry ``extensions`` gates — word/regex is
exactly the device matcher's home turf.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional, Sequence

from swarm_tpu.fingerprints.model import Response, Template
from swarm_tpu.ops import cpu_ref

# nuclei's default max file size for the file protocol; larger files are
# truncated (matchers beyond the cap would need unbounded device shapes).
DEFAULT_MAX_FILE_SIZE = 5 << 20
DEFAULT_MAX_FILES = 100_000


@dataclasses.dataclass
class FileFinding:
    """One (template, file) hit."""

    template_id: str
    path: str
    severity: str = "info"
    extractions: list[str] = dataclasses.field(default_factory=list)


def _ext_of(path: Path) -> str:
    return path.suffix.lower().lstrip(".")


class FileScanner:
    """Scan local files against ``file``-protocol templates.

    ``templates`` may be a whole corpus — non-file protocols are
    ignored, so callers can pass a full templates dir's parse result.
    """

    def __init__(
        self,
        templates: Sequence[Template],
        max_file_size: int = DEFAULT_MAX_FILE_SIZE,
        max_files: int = DEFAULT_MAX_FILES,
        engine=None,
        scan_root: Optional[str] = None,
    ):
        # optional confinement: when set, input paths outside this root
        # are ignored — job chunks come over the wire, and a scan job
        # must not be able to read arbitrary worker files
        self.scan_root = (
            Path(scan_root).resolve() if scan_root else None
        )
        file_templates = [t for t in templates if t.protocol == "file"]
        self.templates = file_templates
        self.matcher_templates = [
            t for t in file_templates
            if any(op.matchers for op in t.operations)
        ]
        # nuclei semantics: a file template with only extractors fires
        # when any extractor yields output (the engine itself treats
        # no-matcher templates as never-match, compile.py "no matchers
        # anywhere"); these run host-side on the extension-gated subset.
        self.extractor_only = [
            t for t in file_templates
            if not any(op.matchers for op in t.operations)
            and any(op.extractors for op in t.operations)
        ]
        self.max_file_size = max_file_size
        self.max_files = max_files
        # Extension gate per template: union over its operations;
        # an entry with no extensions list is treated as "all".
        self._ext_gate: dict[str, set] = {}
        for t in file_templates:
            exts: set = set()
            for op in t.operations:
                exts.update(op.extensions or ["all"])
            self._ext_gate[t.id] = exts
        self._severity = {t.id: t.severity for t in file_templates}
        if engine is not None:
            self.engine = engine
        elif self.matcher_templates:
            from swarm_tpu.ops.engine import MatchEngine

            self.engine = MatchEngine(self.matcher_templates)
        else:
            self.engine = None

    # ------------------------------------------------------------------
    def _applicable(self, template_id: str, ext: str) -> bool:
        gate = self._ext_gate.get(template_id)
        if not gate:
            return True
        return "all" in gate or ext in gate

    def expand_paths(self, paths: Sequence[str]) -> list[Path]:
        """Files from a mixed list of file/directory paths (recursive),
        de-duplicated, bounded by ``max_files``."""
        out: list[Path] = []
        seen: set = set()
        def is_file(q: Path) -> bool:
            # pathlib only swallows ENOENT-class errors; EACCES (e.g. an
            # unreadable /proc symlink) would abort the whole walk
            try:
                if not q.is_file():
                    return False
            except OSError:
                return False
            if self.scan_root is not None:
                # confinement holds for every candidate, not just the
                # input path: a symlink inside the root must not reach
                # files outside it
                try:
                    q.resolve().relative_to(self.scan_root)
                except (ValueError, OSError):
                    return False
            return True

        for raw in paths:
            raw = raw.strip()
            if not raw or raw.startswith("#"):
                continue  # blank line would be Path('.') — scan nothing
            p = Path(raw)
            if self.scan_root is not None:
                try:
                    p.resolve().relative_to(self.scan_root)
                except (ValueError, OSError):
                    continue  # outside the confinement root
            try:
                candidates = (
                    sorted(q for q in p.rglob("*") if is_file(q))
                    if p.is_dir()
                    else [p] if is_file(p) else []
                )
            except OSError:
                continue
            for q in candidates:
                if q in seen:
                    continue
                seen.add(q)
                out.append(q)
                if len(out) >= self.max_files:
                    return out
        return out

    # ------------------------------------------------------------------
    def scan_paths(
        self, paths: Sequence[str]
    ) -> tuple[list[FileFinding], dict]:
        files = self.expand_paths(paths)
        # corpus-wide extension gate: skip reading files no template
        # could apply to (unless some template accepts "all")
        all_exts: set = set()
        for gate in self._ext_gate.values():
            all_exts |= gate
        scan_everything = "all" in all_exts or not self._ext_gate
        rows: list[Response] = []
        kept: list[Path] = []
        for f in files:
            if not scan_everything and _ext_of(f) not in all_exts:
                continue
            try:
                with open(f, "rb") as fh:  # capped read, not whole-file
                    data = fh.read(self.max_file_size)
            except OSError:
                continue
            kept.append(f)
            # host carries the path so output/debug rows are attributable
            rows.append(Response(host=str(f), body=data))
        findings: list[FileFinding] = []
        # 1) matcher-bearing templates: one exact device batch
        if self.engine is not None and rows:
            for f, row, rm in zip(kept, rows, self.engine.match(rows)):
                ext = _ext_of(f)
                for tid in rm.template_ids:
                    if not self._applicable(tid, ext):
                        continue
                    findings.append(
                        FileFinding(
                            template_id=tid,
                            path=str(f),
                            severity=self._severity.get(tid, "info"),
                            extractions=rm.extractions.get(tid, []),
                        )
                    )
        # 2) extractor-only templates, host-side on the gated subset
        for f, row in zip(kept, rows):
            ext = _ext_of(f)
            for t in self.extractor_only:
                if not self._applicable(t.id, ext):
                    continue
                values: list[str] = []
                for op in t.operations:
                    values.extend(cpu_ref._extract(op, row))
                if values:
                    findings.append(
                        FileFinding(
                            template_id=t.id,
                            path=str(f),
                            severity=self._severity.get(t.id, "info"),
                            extractions=values,
                        )
                    )
        stats = {
            "files_scanned": len(kept),
            "templates": len(self.templates),
            "matcher_templates": len(self.matcher_templates),
            "extractor_only_templates": len(self.extractor_only),
            "hits": len(findings),
        }
        return findings, stats


def format_findings(findings: Sequence[FileFinding]) -> bytes:
    """nuclei-style output lines:
    ``[template-id] [file] [severity] path ["extracted",...]``."""
    lines = []
    for h in findings:
        extra = (
            " [" + ",".join(repr(v) for v in h.extractions) + "]"
            if h.extractions
            else ""
        )
        lines.append(f"[{h.template_id}] [file] [{h.severity}] {h.path}{extra}")
    return ("\n".join(lines) + "\n").encode() if lines else b""
