"""Worker poll loop — reference ``worker/worker.py`` rebuilt.

Same observable protocol: poll ``/get-job``, walk the job through
``starting → downloading → executing → uploading → complete`` (or
``cmd failed`` / ``upload failed - *``) via ``/update-job``, with the
reference's cadence (0.8 s between jobs, 10 s when idle). Differences:

- chunk data moves over the server HTTP API by default (the reference
  requires AWS credentials on every worker); direct S3 remains possible
  via a custom transport.
- the ``tpu`` module backend executes the chunk as a device batch with
  the in-process MatchEngine instead of a subprocess.
- ``max_jobs`` actually works (the reference parsed and ignored it, and
  its post-loop thread spawn was dead code — SURVEY.md §2.1 defects).
"""

from __future__ import annotations

import json
import re
import subprocess
import tempfile
import time
from pathlib import Path
from typing import Optional

import requests

from swarm_tpu.config import Config
from swarm_tpu.datamodel import SCAN_ID_RE, JobStatus
from swarm_tpu.resilience.faults import (
    FaultInjected,
    fault_point,
    install_plan,
)
from swarm_tpu.resilience.heartbeat import LeaseHeartbeat
from swarm_tpu.resilience.spool import OutputSpool
from swarm_tpu.resilience.transport import (
    RetryingServerClient,
    TransportError,
)
from swarm_tpu.telemetry import REGISTRY, emit_event
from swarm_tpu.telemetry import tracing
from swarm_tpu.telemetry.fleet_export import (
    WORKER_DRAIN,
    WORKER_DRAIN_SECONDS,
)
from swarm_tpu.utils.trace import PhaseTimer, maybe_device_profile
from swarm_tpu.worker.modules import (
    ModuleRegistry,
    ModuleSpec,
    format_match_line,
    parse_response_line,
)

# Worker-side metric families (exposed on /metrics when the worker runs
# in-process with the server; remote workers' phase timings additionally
# reach the server via the job perf fields → swarm_job_phase_seconds)
_PHASE_SECONDS = REGISTRY.histogram(
    "swarm_worker_phase_seconds",
    "Per-phase job pipeline seconds measured on the worker",
    ("phase",),
)
_JOBS_PROCESSED = REGISTRY.counter(
    "swarm_worker_jobs_total",
    "Jobs processed by this worker, by outcome",
    ("outcome",),
)
_LAST_POLL = REGISTRY.gauge(
    "swarm_worker_last_poll_timestamp",
    "Unix time of this worker's most recent /get-job poll (heartbeat)",
)
_ROWS_PER_SEC = REGISTRY.gauge(
    "swarm_worker_rows_per_second",
    "Rows/sec of the most recently completed device job",
)
_ROWS_TOTAL = REGISTRY.counter(
    "swarm_worker_rows_total", "Device-engine rows processed by this worker"
)
_POLL_ERRORS = REGISTRY.counter(
    "swarm_worker_poll_errors_total",
    "Polls that failed with a transport error (server down ≠ idle queue)",
)
_SERVER_GENERATION = REGISTRY.gauge(
    "swarm_worker_server_generation",
    "Control-plane generation last observed on a successful poll",
)
_SERVER_RESTARTS = REGISTRY.counter(
    "swarm_worker_server_restarts_total",
    "Control-plane generation changes observed by this worker",
)

#: span batches up to this size ride the completed-job ``perf`` field;
#: larger batches (long scans) ship out of band via ``POST /spans``
_SPAN_INLINE_MAX = 256


class ServerClient:
    """HTTP client for the worker-facing server API.

    Failure typing (docs/RESILIENCE.md): connection failures and 5xx
    responses raise :class:`TransportError` so callers can tell "server
    down" from "queue empty" / contract rejections — previously a dead
    server looked exactly like an idle queue. Each operation declares a
    ``transport.*`` fault point for the injection harness.
    """

    def __init__(self, server_url: str, api_key: str, timeout: float = 30.0):
        self.base = server_url.rstrip("/")
        self.timeout = timeout
        self.session = requests.Session()
        self.session.headers["Authorization"] = f"Bearer {api_key}"
        #: control-plane generation from the most recent /get-job
        #: answer's X-Swarm-Generation header (None until the first
        #: successful poll, or against a pre-journal server). The poll
        #: loop watches it to detect server restarts
        #: (docs/DURABILITY.md).
        self.last_server_generation: Optional[int] = None
        #: drain order from the most recent /get-job answer's
        #: X-Swarm-Drain header (docs/RESILIENCE.md §Preemption): the
        #: reason string ("drain"/"preempted"/...) or None. The poll
        #: loop routes it into JobProcessor.request_drain.
        self.last_drain_reason: Optional[str] = None

    def _request(self, op: str, method: str, path: str, detail=None, **kw):
        fault_point(f"transport.{op}", detail=detail, exc=TransportError)
        try:
            resp = self.session.request(
                method, f"{self.base}{path}", timeout=self.timeout, **kw
            )
        except requests.RequestException as e:
            raise TransportError(f"{op}: {e}") from e
        if resp.status_code >= 500:
            raise TransportError(f"{op}: server error {resp.status_code}")
        return resp

    def get_job(self, worker_id: str) -> Optional[dict]:
        resp = self._request(
            "get_job", "GET", "/get-job", params={"worker_id": worker_id}
        )
        gen = resp.headers.get("X-Swarm-Generation")
        if gen is not None:
            try:
                self.last_server_generation = int(gen)
            except ValueError:
                pass
        self.last_drain_reason = resp.headers.get("X-Swarm-Drain")
        return resp.json() if resp.status_code == 200 else None

    def update_job(self, job_id: str, changes: dict, worker_id: Optional[str] = None) -> bool:
        if worker_id is not None:
            changes = {**changes, "worker_id": worker_id}  # fencing token
        resp = self._request(
            "update_job", "POST", f"/update-job/{job_id}", json=changes
        )
        return resp.status_code == 200

    def get_input_chunk(self, scan_id: str, chunk_index: int) -> Optional[bytes]:
        resp = self._request(
            "get_chunk", "GET", f"/get-input-chunk/{scan_id}/{chunk_index}"
        )
        return resp.content if resp.status_code == 200 else None

    def put_output_chunk(self, scan_id: str, chunk_index: int, data: bytes) -> bool:
        resp = self._request(
            "put_chunk", "POST", f"/put-output-chunk/{scan_id}/{chunk_index}",
            detail=f"{scan_id}_{chunk_index}", data=data,
        )
        return resp.status_code == 200

    def post_spans(self, scan_id: str, spans: list) -> bool:
        """Ship a span batch out of band (docs/OBSERVABILITY.md
        §Tracing) — used when an attempt's batch is too large to ride
        the completed-job perf field, or the attempt failed and there
        is no perf field to ride."""
        resp = self._request(
            "post_spans", "POST", "/spans", detail=scan_id,
            json={"scan_id": scan_id, "spans": spans},
        )
        return resp.status_code == 200

    def renew_lease(
        self, job_id: str, worker_id: str, saturation: Optional[float] = None
    ) -> bool:
        """Heartbeat one lease; False = the lease is no longer ours.
        ``saturation`` (0..1, optional) reports the scheduler's
        in-flight saturation so the gateway's admission pressure rises
        before the queue does (docs/GATEWAY.md)."""
        body = {"worker_id": worker_id}
        if saturation is not None:
            body["saturation"] = saturation
        resp = self._request(
            "renew_lease", "POST", f"/renew-lease/{job_id}",
            detail=job_id, json=body,
        )
        return resp.status_code == 200

    def deregister(self, worker_id: str) -> bool:
        """Tell the server this worker is exiting NOW: its leases hand
        back immediately and its saturation report is dropped — no
        grace-window wait (docs/RESILIENCE.md §Preemption)."""
        resp = self._request(
            "deregister", "POST", "/deregister",
            detail=worker_id, json={"worker_id": worker_id},
        )
        return resp.status_code == 200


class JobProcessor:
    def __init__(
        self,
        cfg: Config,
        client: Optional[ServerClient] = None,
        registry: Optional[ModuleRegistry] = None,
        work_dir: Optional[str] = None,
    ):
        self.cfg = cfg
        if cfg.fault_plan:
            install_plan(cfg.fault_plan)  # deterministic chaos (tests/soak)
        if client is None:
            # production default: retrying transport (jittered backoff +
            # per-operation breakers) over the raw HTTP client
            client = RetryingServerClient(
                ServerClient(cfg.resolve_url(), cfg.api_key),
                retries=cfg.transport_retries,
                backoff_s=cfg.transport_backoff_s,
                backoff_max_s=cfg.transport_backoff_max_s,
                breaker_threshold=cfg.transport_breaker_threshold,
                breaker_cooldown_s=cfg.transport_breaker_cooldown_s,
            )
        self.client = client
        self.registry = registry or ModuleRegistry(cfg.modules_dir)
        self.work_dir = Path(work_dir or tempfile.mkdtemp(prefix="swarm_worker_"))
        self.work_dir.mkdir(parents=True, exist_ok=True)
        self.spool = OutputSpool(cfg.spool_dir or self.work_dir / "spool")
        self._engines: dict[str, object] = {}  # templates_dir -> MatchEngine
        self._scan_perf_extra: dict = {}  # per-job scan counters (perf fields)
        self.jobs_done = 0
        #: cooperative shutdown for threaded workers (chaos soak test)
        self.stop_requested = False
        #: graceful-drain order (docs/RESILIENCE.md §Preemption): the
        #: reason string, set by SIGTERM, the X-Swarm-Drain poll
        #: header, or tests. The poll loop finishes its current lease,
        #: then runs :meth:`drain` and exits.
        self.drain_requested: Optional[str] = None
        #: outcome of the drain that ended process_jobs (None until
        #: then): "completed" | "spooled" | "idle" | "aborted"
        self.drain_outcome: Optional[str] = None
        #: True while a leased chunk is being processed — decides the
        #: drain outcome ("completed" vs "idle")
        self._job_in_flight = False
        #: True when the drain order arrived mid-chunk: the lease was
        #: finished first, so the drain outcome reports "completed"
        self._drained_mid_job = False
        self._last_heartbeat: Optional[LeaseHeartbeat] = None
        #: most recently observed scheduler in-flight saturation (0..1;
        #: None until a pipelined engine reports) — heartbeats carry it
        #: to the gateway's admission pressure signal
        self._last_saturation: Optional[float] = None
        #: control-plane generation seen on the last successful poll
        #: (None until the first; docs/DURABILITY.md)
        self._seen_generation: Optional[int] = None

    # ------------------------------------------------------------------
    def prewarm(self, module_name: str) -> bool:
        """Build a module's engine/scanner before the first job: an
        empty-input execution exercises exactly the construction path
        (corpus load + device compile), so with the persistent XLA
        cache the first real job runs at steady-state latency."""
        try:
            module = self.registry.load(module_name)
            dispatch = {
                "tpu": self._execute_tpu,
                "probe": self._execute_probe,
                "service": self._execute_service,
                "jarm": self._execute_jarm,
                "active": self._execute_active,
                "file": self._execute_file,
                "ssl": self._execute_ssl,
            }.get(module.backend)
            if dispatch is None:
                return False  # command modules have nothing to warm
            dispatch(module, b"")
            return True
        except Exception as e:
            print(f"prewarm {module_name} failed: {e}")
            return False

    # ------------------------------------------------------------------
    def request_drain(self, reason: str) -> None:
        """Ask the poll loop to drain: finish the current lease, flush
        or spool, deregister, exit. Callable from a signal handler or
        another thread — it only sets a flag. First reason wins."""
        if self.drain_requested is None:
            self.drain_requested = reason
            if self._job_in_flight:
                self._drained_mid_job = True

    def process_jobs(self) -> None:
        """The infinite poll loop (reference worker.py:113-126)."""
        while not self.stop_requested:
            if self.drain_requested is not None:
                # the current lease (if any) finished on the previous
                # iteration — process_chunk is synchronous, so reaching
                # this check means nothing is in flight
                self.drain(self.drain_requested)
                return
            try:
                _LAST_POLL.set(time.time())
                job = self.client.get_job(self.cfg.worker_id)
            except TransportError as e:
                # server down is NOT "queue empty": count it distinctly
                # (the retry layer already burned its backoff budget)
                _POLL_ERRORS.inc()
                print(f"server unreachable: {e}")
                time.sleep(self.cfg.poll_interval_idle_s)
                continue
            except Exception as e:
                print(f"error getting job: {e}")
                time.sleep(self.cfg.poll_interval_idle_s)
                continue
            # a successful poll re-registered this worker's WorkerInfo
            # server-side (next_job saves it on every poll); what's
            # left is OUR side of a control-plane restart
            self._note_server_generation()
            # drain order riding the poll answer (docs/RESILIENCE.md
            # §Preemption): the server stopped offering us jobs — loop
            # back to the drain check instead of sleeping out an idle
            # interval first
            drain = getattr(self.client, "last_drain_reason", None)
            if drain:
                self.request_drain(drain)
                continue
            # the poll proved the server reachable: flush any finished
            # chunks spooled while it was down (idempotent via fencing)
            self._replay_spool()
            try:
                if job:
                    self._job_in_flight = True
                    try:
                        self.process_chunk(job)
                    finally:
                        self._job_in_flight = False
                    # max_jobs bounds *attempts*: a failing job must not
                    # leave a --max-jobs worker polling forever
                    self.jobs_done += 1
                    if self.cfg.max_jobs and self.jobs_done >= self.cfg.max_jobs:
                        return
                else:
                    time.sleep(self.cfg.poll_interval_idle_s)
            except Exception as e:
                print(f"error processing job: {e}")
                time.sleep(self.cfg.poll_interval_idle_s)
            time.sleep(self.cfg.poll_interval_busy_s)

    def _note_server_generation(self) -> None:
        """Detect a control-plane restart (docs/DURABILITY.md): the
        X-Swarm-Generation header on the poll that just succeeded. On a
        change, this worker's WorkerInfo/status is ALREADY re-registered
        (the poll itself wrote it — /get-statuses is never stale past
        the first post-restart poll); locally we close the transport
        breakers the dead process earned, so the heartbeat and upload
        paths resume cleanly instead of waiting out a stale cooldown."""
        gen = getattr(self.client, "last_server_generation", None)
        if gen is None or gen == self._seen_generation:
            return
        prior = self._seen_generation
        self._seen_generation = gen
        _SERVER_GENERATION.set(gen)
        if prior is None:
            return  # first contact, not a restart
        _SERVER_RESTARTS.inc()
        breakers = getattr(self.client, "breakers", None)
        if breakers is not None:
            breakers.reset_all()
        emit_event(
            "worker.server_restarted",
            worker_id=self.cfg.worker_id,
            generation=gen,
            prior_generation=prior,
        )
        print(
            f"server restarted (generation {prior} -> {gen}); "
            "re-registered and reset transport breakers"
        )

    def _replay_spool(self) -> None:
        if not len(self.spool):
            return
        try:
            cleared = self.spool.replay(self.client)
        except Exception as e:
            print(f"spool replay failed: {e}")
            return
        if cleared:
            print(f"spool: replayed {cleared} finished chunk(s)")

    def drain(self, reason: str) -> str:
        """The graceful exit sequence (docs/RESILIENCE.md §Preemption).
        The current lease already finished — process_jobs only calls
        this between chunks — so what's left is flushing any spooled
        chunks while the server is still reachable, then deregistering
        (the server hands back leases and drops our saturation report
        immediately, no grace-window wait). The ``worker.drain`` fault
        point ABORTS the sequence when armed: the kill-after-grace
        case, where the node dies mid-drain and recovery rides the
        on-disk spool + fencing instead of this happy path."""
        t0 = time.monotonic()
        outcome = "completed" if self._drained_mid_job else "idle"
        try:
            fault_point("worker.drain", detail=self.cfg.worker_id)
            self._replay_spool()
            if len(self.spool):
                # replay couldn't clear everything (server gone again):
                # the chunks stay spooled on disk for the next process
                outcome = "spooled"
            try:
                self.client.deregister(self.cfg.worker_id)
            except Exception as e:
                print(f"drain: deregister undeliverable: {e}")
        except FaultInjected:
            # injected mid-drain death: no deregister, no replay — the
            # server's lease expiry and the on-disk spool own recovery
            outcome = "aborted"
        self.drain_outcome = outcome
        elapsed = time.monotonic() - t0
        WORKER_DRAIN.labels(outcome=outcome).inc()
        WORKER_DRAIN_SECONDS.labels().observe(elapsed)
        emit_event(
            "worker.stopped",
            worker_id=self.cfg.worker_id,
            reason=reason,
            outcome=outcome,
            jobs_done=self.jobs_done,
            drain_seconds=round(elapsed, 4),
        )
        print(f"worker drained ({reason}): {outcome} in {elapsed:.2f}s")
        return outcome

    # ------------------------------------------------------------------
    def process_chunk(self, job: dict) -> None:
        job_id = job.get("job_id") or f"{job['scan_id']}_{job['chunk_index']}"
        scan_id, chunk_index = job["scan_id"], int(job["chunk_index"])
        trace_id = job.get("trace_id")
        # defense in depth: the server validates scan ids, but these flow
        # into filesystem paths and {input}/{output} command substitution
        if not SCAN_ID_RE.match(str(scan_id)):
            self.client.update_job(job_id, {"status": JobStatus.CMD_FAILED})
            _JOBS_PROCESSED.labels(outcome=JobStatus.CMD_FAILED).inc()
            return
        timer = PhaseTimer()
        # per-attempt span collector (None when tracing is off or the
        # job carries no trace id — the completed-job wire payload is
        # then byte-identical to the untraced build)
        ctx = tracing.attempt_context(
            trace_id,
            job_id=job_id,
            attempt=job.get("attempts"),
            worker_id=self.cfg.worker_id,
            module=job.get("module"),
        )

        def _ship_spans(extra):
            """Close the attempt root; inline the batch on the perf
            field when small, else POST /spans (also the only path for
            failed attempts, which carry no perf)."""
            spans = ctx.finish()
            if not spans:
                return extra
            perf = extra.get("perf")
            if isinstance(perf, dict) and len(spans) <= _SPAN_INLINE_MAX:
                return {**extra, "perf": {**perf, "spans": spans}}
            try:
                self.client.post_spans(scan_id, spans)
            except Exception as e:
                print(f"span batch undeliverable: {e}")
            return extra

        def update(status, **extra):
            if status in JobStatus.TERMINAL and ctx is not None:
                extra = _ship_spans(extra)
            try:
                ok = self.client.update_job(
                    job_id,
                    {"status": status, **extra},
                    worker_id=self.cfg.worker_id,
                )
            except TransportError as e:
                # server unreachable mid-job: a lost phase update is
                # harmless (the lease covers us); a lost COMPLETE is
                # handled by the caller via the spool. None ≠ False —
                # False means the server actively rejected (fencing).
                print(f"update {status!r} undeliverable: {e}")
                ok = None
            if status not in JobStatus.TERMINAL:
                emit_event(
                    "job.phase",
                    trace_id=trace_id,
                    job_id=job_id,
                    worker_id=self.cfg.worker_id,
                    phase=status,
                )
            if status in JobStatus.TERMINAL:
                # one observation per phase per job: the job-level
                # latency distributions /metrics serves
                seconds, _counters = timer.snapshot()
                for phase, secs in seconds.items():
                    _PHASE_SECONDS.labels(phase=phase).observe(secs)
                _JOBS_PROCESSED.labels(outcome=status).inc()
                emit_event(
                    "job.worker_done",
                    trace_id=trace_id,
                    job_id=job_id,
                    worker_id=self.cfg.worker_id,
                    status=status,
                    perf=extra.get("perf"),
                )
            return ok

        self._engine_stats_mark = None
        self._scan_perf_extra = {}
        emit_event(
            "job.start",
            trace_id=trace_id,
            job_id=job_id,
            worker_id=self.cfg.worker_id,
            module=job.get("module"),
        )

        # lease heartbeat: renew from a background ticker while the
        # chunk runs so a long batch never races the server's
        # _requeue_expired into a double execution (docs/RESILIENCE.md)
        hb = LeaseHeartbeat(
            self.client,
            job_id,
            self.cfg.worker_id,
            self.cfg.heartbeat_interval_s or self.cfg.lease_seconds / 3.0,
            saturation_fn=lambda: self._last_saturation,
        )
        self._last_heartbeat = hb
        hb.start()
        try:
            with tracing.activate(ctx):
                self._run_chunk(
                    job, job_id, scan_id, chunk_index, timer, update
                )
        finally:
            hb.stop()

    def _run_chunk(
        self, job: dict, job_id: str, scan_id: str, chunk_index: int,
        timer: PhaseTimer, update,
    ) -> None:
        """Download → execute → upload under an active heartbeat."""
        update(JobStatus.STARTING)
        update(JobStatus.DOWNLOADING)
        with timer.phase("download"), tracing.span("download"):
            data = self.client.get_input_chunk(scan_id, chunk_index)
        if data is None:
            update(JobStatus.CMD_FAILED)
            return

        update(JobStatus.EXECUTING)
        try:
            module = self.registry.load(job["module"])
        except (OSError, ValueError) as e:
            print(f"module load failed: {e}")
            update(JobStatus.CMD_FAILED)
            return

        # kept as a named object: after a successful execute the engine
        # stats deltas are folded into device/walk child spans under it
        exec_span = tracing.span("execute", module=job.get("module"))
        try:
            with timer.phase("execute"), maybe_device_profile(job_id), exec_span:
                # chaos lever: fail (or delay) this chunk's execution —
                # detail carries the job id so a plan can poison one job
                fault_point("executor.run", detail=job_id)
                if module.backend == "tpu":
                    output = self._execute_tpu(
                        module, data, qos=job.get("qos")
                    )
                elif module.backend == "probe":
                    output = self._execute_probe(module, data)
                elif module.backend == "service":
                    output = self._execute_service(module, data)
                elif module.backend == "jarm":
                    output = self._execute_jarm(module, data)
                elif module.backend == "active":
                    output = self._execute_active(
                        module, data, chunk_index=chunk_index
                    )
                elif module.backend == "file":
                    output = self._execute_file(module, data)
                elif module.backend == "ssl":
                    output = self._execute_ssl(module, data)
                else:
                    output = self._execute_command(
                        module, scan_id, chunk_index, data
                    )
        except Exception as e:
            print(f"execution failed: {e}")
            update(JobStatus.CMD_FAILED)
            return
        if output is None:
            update(JobStatus.CMD_FAILED)
            return

        update(JobStatus.UPLOADING)
        unreachable = False
        with timer.phase("upload"), tracing.span("upload"):
            try:
                ok = self.client.put_output_chunk(scan_id, chunk_index, output)
            except TransportError:
                # server unreachable after the retry budget: the chunk's
                # compute is paid for — never lose it (spool below)
                ok = False
                unreachable = True
            except requests.RequestException:
                ok = False
        if ok or unreachable:
            perf = timer.perf()
            perf["input_bytes"] = len(data)
            perf["output_bytes"] = len(output)
            perf.update(self._engine_perf_delta())
            perf.update(self._scan_perf_extra)
            ctx = tracing.current_context()
            if ctx is not None:
                self._synth_engine_spans(ctx, perf, exec_span)
            # this worker's non-closed breakers (transport + device)
            # ride the perf fields to the server, so /get-statuses
            # shows remote-fleet degradation the server-side /healthz
            # breaker board (process-local) cannot see
            from swarm_tpu.resilience.breaker import breaker_states

            open_breakers = {
                k: v for k, v in breaker_states().items() if v != "closed"
            }
            if open_breakers:
                perf["breakers_open"] = open_breakers
            rows = perf.get("rows")
            exec_s = perf.get("execute_s")
            import math

            if (
                isinstance(rows, (int, float))
                and math.isfinite(rows)
                and rows > 0
            ):
                _ROWS_TOTAL.inc(rows)
                if exec_s and math.isfinite(exec_s):
                    _ROWS_PER_SEC.set(rows / exec_s)
            done = True
            if ok:
                done = update(JobStatus.COMPLETE, perf=perf)
            if unreachable or done is None:
                # finished work outlives the outage: spool the output +
                # completion and replay on reconnect — idempotent, and
                # the fencing token discards it if the job was re-leased
                self._spool_finished(
                    job_id, scan_id, chunk_index, output, perf
                )
        else:
            update(JobStatus.UPLOAD_FAILED_UNKNOWN)

    def _spool_finished(
        self, job_id: str, scan_id: str, chunk_index: int,
        output: bytes, perf: dict,
    ) -> None:
        self.spool.put(
            job_id, scan_id, chunk_index, self.cfg.worker_id, output,
            perf=perf,
        )
        _JOBS_PROCESSED.labels(outcome="spooled").inc()
        emit_event(
            "job.spooled",
            job_id=job_id,
            worker_id=self.cfg.worker_id,
            scan_id=scan_id,
            chunk_index=chunk_index,
        )
        print(f"server unreachable; spooled finished chunk {job_id}")

    def _synth_engine_spans(self, ctx, perf: dict, exec_span) -> None:
        """Fold the engine's accumulated device/walk timings into child
        spans of the execute span. The device holds no wall clock of
        its own, so the phases are laid out contiguously from the
        execute start; the DURATIONS are the authoritative EngineStats
        deltas (device phase A/B included when the engine reports
        them), which is what the critical-path attribution consumes."""
        parent = getattr(exec_span, "span_id", None)
        start = getattr(exec_span, "start", None)
        if parent is None or start is None:
            return
        device_s = perf.get("device_s") or 0.0
        walk_s = perf.get("host_confirm_s") or 0.0
        if device_s > 0:
            dev_id = ctx.add_synth(
                "device", start, device_s, parent_id=parent,
                rows=perf.get("rows"), mesh=perf.get("mesh"),
                pipeline=perf.get("pipeline"),
            )
            pa = perf.get("phase_a_s") or 0.0
            pb = perf.get("phase_b_s") or 0.0
            if pa > 0:
                ctx.add_synth(
                    "device.phase_a", start, pa, parent_id=dev_id
                )
            if pb > 0:
                ctx.add_synth(
                    "device.phase_b", start + pa, pb, parent_id=dev_id
                )
        if walk_s > 0:
            ctx.add_synth("walk", start + device_s, walk_s, parent_id=parent)

    def _mark_engine_stats(self, engine) -> None:
        """Snapshot the cumulative engine counters at job start so
        :meth:`_engine_perf_delta` can report this job's delta."""
        ds = engine.stats
        self._engine_stats_mark = (
            engine,
            ds.rows,
            ds.device_seconds,
            ds.host_confirm_seconds,
            getattr(ds, "phase_a_seconds", 0.0),
            getattr(ds, "phase_b_seconds", 0.0),
        )

    def _engine_perf_delta(self) -> dict:
        """Device-engine stats accumulated during this job (tpu backend
        caches engines across jobs, so report the delta since job start)."""
        mark = self._engine_stats_mark
        if mark is None:
            return {}
        engine, rows0, dev0, confirm0, pa0, pb0 = mark
        ds = engine.stats
        out = {
            "rows": ds.rows - rows0,
            "device_s": round(ds.device_seconds - dev0, 6),
            "host_confirm_s": round(ds.host_confirm_seconds - confirm0, 6),
        }
        # split-phase device attribution, when the matcher reported it
        # (single-device compacted path); feeds the device.phase_a/b
        # child spans. Tracing-gated: with tracing off the perf wire
        # payload must stay byte-identical to the untraced build.
        if tracing.enabled():
            pa = round(getattr(ds, "phase_a_seconds", 0.0) - pa0, 6)
            pb = round(getattr(ds, "phase_b_seconds", 0.0) - pb0, 6)
            if pa > 0:
                out["phase_a_s"] = pa
            if pb > 0:
                out["phase_b_s"] = pb
        mesh = getattr(engine, "mesh", None)
        if mesh is not None:
            out["mesh"] = "x".join(
                f"{ax}{int(mesh.shape[ax])}" for ax in mesh.axis_names
            )
        # scheduler mode + feed health ride the job perf fields so
        # operators can see the A/B state per job (/get-statuses)
        out["pipeline"] = getattr(engine, "pipeline", "off")
        sched = getattr(engine, "_sched", None)
        if sched is not None:
            snap = sched.stats.snapshot()
            out["sched"] = snap
            # stall/wall = the fraction of scheduler wall time the
            # submit thread waited on a FULL in-flight window — the
            # honest "accelerator is saturated" scalar the gateway's
            # admission pressure consumes (perf here, heartbeats live)
            wall = snap.get("wall_seconds") or 0.0
            if wall > 0:
                saturation = min(1.0, snap.get("stall_seconds", 0.0) / wall)
                out["inflight_saturation"] = round(saturation, 4)
                self._last_saturation = saturation
        return out

    # ------------------------------------------------------------------
    def _execute_active(
        self, module: ModuleSpec, data: bytes, chunk_index: int = 0
    ) -> bytes:
        """Active template-request scanning (nuclei's execution mode):
        each template's own requests are issued per target, responses
        device-matched, hits attributed per request (worker/active.py)."""
        from swarm_tpu.fingerprints.model import Response
        from swarm_tpu.worker import formats
        from swarm_tpu.worker.active import ActiveScanner

        if not module.templates_dir:
            raise ValueError(f"active module {module.name} missing 'templates'")
        engine = self._engine_for(module.templates_dir)
        self._mark_engine_stats(engine)
        # keyed by probe spec + vars too: two modules sharing a
        # templates dir but differing in ports/timeouts/concurrency or
        # operator-supplied template vars must not alias
        user_vars = module.raw.get("vars") or None
        probe_key = json.dumps(
            [module.probe or {}, user_vars], sort_keys=True
        )
        key = f"active::{module.templates_dir}::{probe_key}"
        scanner = self._engines.get(key)
        if scanner is None:
            scanner = ActiveScanner(engine, module.probe, user_vars=user_vars)
            self._engines[key] = scanner
        target_lines = data.decode("utf-8", "surrogateescape").splitlines()
        hits, stats = scanner.run(target_lines)
        sev, proto = formats.severity_index(engine.templates)
        lines = []
        for h in hits:
            p = proto.get(h.template_id, "http")
            target = (
                formats.url_of(Response(host=h.host, port=h.port, tls=h.tls))
                + h.path
                if p == "http"
                else f"{h.host}:{h.port}"
            )
            extra = " [" + ",".join(h.extractions) + "]" if h.extractions else ""
            lines.append(
                f"[{h.template_id}] [{p}] [{sev.get(h.template_id, 'info')}] "
                f"{target}{extra}"
            )
        print(
            f"active scan: {stats['rows_probed']} requests over "
            f"{stats.get('live_targets', 0)} live targets, {len(lines)} hits"
        )
        # operator-visible scan counters in the job's perf fields
        # (/get-statuses -> swarm jobs): targets, probe volume, and OOB
        # activity so blind-class findings are explainable
        self._scan_perf_extra = {
            k: stats[k]
            for k in (
                "targets", "live_targets", "rows_probed",
                "oob_probes", "oob_interactions", "session_hits",
                "workflow_hits",
            )
            if k in stats
        }
        self._scan_perf_extra["hits"] = len(lines)
        # Scope honesty, once per scan (chunk 0 only — these are
        # per-scan facts; repeating them in every chunk would flood a
        # sharded scan's merged /raw with duplicates):
        if chunk_index == 0:
            # interactsh-referencing templates cannot fully evaluate
            # without an interaction server; their non-OOB requests (if
            # any) still run, so the marker scopes itself to the OOB part
            for tid in scanner.oob_limited:
                lines.append(
                    f"# [{tid}] [oob-skipped] interactsh-dependent "
                    "checks not evaluated (no out-of-band interaction "
                    "server)"
                )
            # headless templates outside the browserless JS-free
            # subset (worker/headless.py) need a real browser engine
            # (JS runtime, renderer, or selectors we don't emulate)
            for tid in scanner.plan.skipped.get("protocol-headless", []):
                lines.append(
                    f"# [{tid}] [headless-skipped] requires a browser "
                    "engine; not evaluated"
                )
            # compact coverage summary: one line per remaining skip
            # class (file/ssl run under their own modules; sessions
            # execute the chain classes, so those aren't listed here)
            for reason, ids in sorted(stats["skipped_templates"].items()):
                if reason.startswith("protocol-") or reason in (
                    "oob-interactsh",
                ):
                    continue  # surfaced above / handled elsewhere
                lines.append(
                    f"# [coverage] {reason}: {ids} templates not executed"
                )
        return ("\n".join(lines) + "\n").encode() if lines else b""

    # ------------------------------------------------------------------
    def _execute_file(self, module: ModuleSpec, data: bytes) -> bytes:
        """File-template scanning: input chunk lines are file/directory
        paths, matched against the corpus's ``file``-protocol templates
        (worker/filescan.py) in one exact device batch."""
        from swarm_tpu.fingerprints import load_corpus
        from swarm_tpu.worker.filescan import FileScanner, format_findings

        if not module.templates_dir:
            raise ValueError(f"file module {module.name} missing 'templates'")
        scan_root = module.raw.get("scan_root") or None
        key = f"file::{module.templates_dir}::{scan_root}"
        scanner = self._engines.get(key)
        if scanner is None:
            templates, _errors = load_corpus(module.templates_dir)
            scanner = FileScanner(templates, scan_root=scan_root)
            self._engines[key] = scanner
        findings, stats = scanner.scan_paths(
            data.decode("utf-8", "surrogateescape").splitlines()
        )
        print(
            f"file scan: {stats['files_scanned']} files x "
            f"{stats['templates']} templates, {stats['hits']} hits"
        )
        return format_findings(findings)

    # ------------------------------------------------------------------
    def _execute_ssl(self, module: ModuleSpec, data: bytes) -> bytes:
        """ssl-protocol template execution: version-pinned handshakes +
        matchers over the session/cert document (worker/sslscan.py)."""
        from swarm_tpu.fingerprints import load_corpus
        from swarm_tpu.worker.sslscan import SslScanner, format_findings

        if not module.templates_dir:
            raise ValueError(f"ssl module {module.name} missing 'templates'")
        probe = module.probe or {}
        key = (
            f"ssl::{module.templates_dir}::"
            f"{json.dumps(probe, sort_keys=True)}"
        )
        scanner = self._engines.get(key)
        if scanner is None:
            templates, _errors = load_corpus(module.templates_dir)
            scanner = SslScanner(
                templates,
                concurrency=int(probe.get("concurrency", 32)),
                timeout=float(probe.get("connect_timeout_ms", 4000)) / 1000.0,
            )
            self._engines[key] = scanner
        findings, stats = scanner.scan(
            data.decode("utf-8", "surrogateescape").splitlines()
        )
        print(
            f"ssl scan: {stats['targets']} targets x {stats['templates']} "
            f"templates, {stats['hits']} hits"
        )
        return format_findings(findings)

    # ------------------------------------------------------------------
    def _execute_jarm(self, module: ModuleSpec, data: bytes) -> bytes:
        """Active TLS fingerprinting (JARM + JA3S) with device-side
        density-peaks clustering of the resulting fingerprints
        (BASELINE.json config #5). Output: one line per target with its
        fingerprint, cluster label, and cluster size."""
        from swarm_tpu.ops import cluster as cl
        from swarm_tpu.worker.executor import ProbeExecutor

        fps = ProbeExecutor(module.probe).run_jarm(
            data.decode("utf-8", "surrogateescape").splitlines()
        )
        alive = [fp for fp in fps if fp.alive]
        lab: list[int] = []
        sizes: dict[int, int] = {}
        if alive:
            radius = float(module.raw.get("cluster_radius", 32.0))
            packed = cl.pack_strings([fp.jarmx for fp in alive])
            labels, _rho = cl.density_cluster(packed, radius)
            lab = [int(x) for x in labels]
            for label in lab:
                sizes[label] = sizes.get(label, 0) + 1
        lines = []
        alive_iter = iter(lab)
        for fp in fps:
            if fp.alive:
                label = next(alive_iter)
                lines.append(
                    f"{fp.line()} cluster={label} cluster_size={sizes[label]}"
                )
            else:
                lines.append(fp.line())
        return ("\n".join(lines) + "\n").encode() if lines else b""

    # ------------------------------------------------------------------
    def _execute_command(
        self, module: ModuleSpec, scan_id: str, chunk_index: int, data: bytes
    ) -> Optional[bytes]:
        """Subprocess path — behavior-parity with reference worker.py:79-90."""
        job_dir = self.work_dir / scan_id
        job_dir.mkdir(parents=True, exist_ok=True)
        input_file = job_dir / f"chunk_{chunk_index}.txt"
        output_file = job_dir / f"chunk_{chunk_index}.out.txt"
        input_file.write_bytes(data)
        command = module.command(str(input_file), str(output_file))
        proc = subprocess.run(
            command, shell=True, stdout=subprocess.PIPE, stderr=subprocess.PIPE
        )
        if proc.returncode != 0:
            print(f"Error executing command: {command}")
            print(proc.stderr.decode("utf-8", "replace"))
            return None
        return output_file.read_bytes() if output_file.is_file() else proc.stdout

    # ------------------------------------------------------------------
    def _engine_for(self, templates_dir: str):
        engine = self._engines.get(templates_dir)
        if engine is None:
            from swarm_tpu.fingerprints.dbcache import load_or_compile
            from swarm_tpu.ops.engine import MatchEngine
            from swarm_tpu.parallel.multihost import (
                maybe_initialize_distributed,
            )

            # multi-host engine bring-up (docs/SHARDING.md): join the
            # DCN process group BEFORE the engine's auto-mesh resolves,
            # so jax.devices() spans every host's chips and the mesh is
            # slice-wide. Idempotent and a no-op without the
            # SWARM_COORDINATOR/-NUM_PROCESSES/-PROCESS_ID triplet —
            # embedded workers (started without main()) get the same
            # bring-up as the CLI path.
            maybe_initialize_distributed()
            # disk-cached corpus compile (+ persistent XLA cache): a
            # warm worker builds the full-corpus engine in ~a second.
            # cfg.pipeline routes bulk matching through the continuous-
            # batching scheduler (swarm_tpu/sched) when "on".
            templates, db = load_or_compile(templates_dir)
            engine = MatchEngine(
                templates, db=db, pipeline=self.cfg.pipeline
            )
            # fleet-wide result tier (docs/CACHING.md): rows any worker
            # has ever resolved short-circuit before device dispatch.
            # SWARM_CACHE_BACKEND=off (default) skips this entirely; a
            # tier that can't be built must not kill engine bring-up —
            # the cache is an accelerator, never a dependency.
            from swarm_tpu.cache import build_result_cache

            try:
                client = build_result_cache(self.cfg)
                if client is not None:
                    engine.attach_result_cache(client)
            except Exception as e:
                print(f"result cache unavailable ({e}); running L1-only")
            # AOT executable cache (docs/AOT.md): a joining worker
            # FETCHES the fleet's published executables at bring-up
            # instead of compiling them per shape class — the cold-
            # start cliff becomes a one-time fleet-wide cost.
            # SWARM_AOT_BACKEND=off (default) skips this entirely; a
            # store that can't be built or prewarmed must not kill
            # engine bring-up (breaker-wrapped, never blocks).
            from swarm_tpu.aot import build_aot_client

            try:
                aot = build_aot_client(self.cfg)
                if aot is not None:
                    engine.attach_aot(aot)
                    if self.cfg.aot_prewarm:
                        import time as _time

                        t0 = _time.perf_counter()
                        n = engine.aot_prewarm()
                        if n:
                            print(
                                f"AOT prewarm: {n} executables loaded "
                                f"in {_time.perf_counter() - t0:.2f}s"
                            )
            except Exception as e:
                print(f"AOT executable cache unavailable ({e}); "
                      "compiling locally")
            self._engines[templates_dir] = engine
        return engine

    def _execute_tpu(
        self, module: ModuleSpec, data: bytes, qos: Optional[str] = None
    ) -> bytes:
        """Device-batch path: chunk rows → MatchEngine → JSONL hits.

        ``input_format: targets`` first runs the native probe front-end
        (resolve + connect + banner/HTTP fetch) to build the rows.
        ``qos`` is the job's latency class (docs/GATEWAY.md §QoS): on
        the pipelined path interactive chunks ride the scheduler's
        express buckets with the deadline flush armed."""
        if not module.templates_dir:
            raise ValueError(f"tpu module {module.name} missing 'templates'")
        engine = self._engine_for(module.templates_dir)
        self._mark_engine_stats(engine)
        text = data.decode("utf-8", "surrogateescape")
        if module.input_format == "targets":
            # double-buffered: probe wave i+1 while matching wave i
            from swarm_tpu.worker.streaming import stream_match

            rows, results, _stats = stream_match(
                engine,
                text.splitlines(),
                probe_spec=module.probe,
                wave_targets=int(module.raw.get("wave_targets", 1024)),
            )
        elif engine.pipeline == "on":
            # continuous-batching path (docs/PIPELINE.md): line decode
            # runs on the scheduler's prefetch thread — chunk i+1
            # parses while chunk i's batch rides the device — and rows
            # are re-binned into padding buckets with memo short-
            # circuiting. Results are bit-identical to the direct path.
            lines = text.splitlines()
            step = engine.batch_rows
            payloads = [
                (ci, lines[s : s + step])
                for ci, s in enumerate(range(0, len(lines), step))
            ] or [(0, [])]
            rows_by_chunk: dict = {}

            def decode(payload):
                ci, chunk_lines = payload
                out = []
                for line in chunk_lines:
                    row = parse_response_line(line)
                    if row is not None:
                        out.append(row)
                rows_by_chunk[ci] = out
                return out

            rows = []
            results = []
            sched = engine.scheduler()
            # operator deadline knobs reach the planner here (the
            # scheduler is engine-lazy; the engine ctor never sees cfg)
            sched.config.qos_deadline_ms = self.cfg.qos_deadline_ms
            sched.config.max_age_ms = self.cfg.sched_max_age_ms
            # "sched" = the continuous-batching drive window (planning,
            # coalescing, deadline flushes); the engine's device/walk
            # attribution rides the synthesized child spans instead
            with tracing.span("sched", qos=qos, pipeline="on"):
                for ci, res in enumerate(
                    sched.run(payloads, decode=decode, qos=qos)
                ):
                    rows.extend(rows_by_chunk.pop(ci))
                    results.extend(res)
        else:
            rows = []
            for line in text.splitlines():
                row = parse_response_line(line)
                if row is not None:
                    rows.append(row)
            results = engine.match(rows)
        # workflow gating over the already-matched rows (ops/workflows):
        # one wf line per row where a trigger gated matching subtemplates
        wf_lines: list[str] = []
        if any(t.protocol == "workflow" for t in engine.templates):
            from swarm_tpu.ops.workflows import WorkflowRunner

            wkey = f"wfrunner::{module.templates_dir}"
            runner = self._engines.get(wkey)
            if runner is None:
                runner = WorkflowRunner(engine.templates, engine=engine)
                self._engines[wkey] = runner
            jsonl = module.output_format != "nuclei"
            for row, rm in zip(rows, results):
                if not rm.template_ids:
                    continue  # nothing matched: no workflow can trigger
                per = runner.evaluate_hits(
                    set(rm.template_ids), lambda _tid, _r=row: [_r]
                )
                for wid, sub_ids in sorted(per.items()):
                    if jsonl:  # keep the jsonl contract machine-readable
                        wf_lines.append(
                            json.dumps(
                                {
                                    "workflow": wid,
                                    "host": row.host,
                                    "port": row.port,
                                    "matches": sub_ids,
                                },
                                sort_keys=True,
                            )
                        )
                    else:
                        wf_lines.append(
                            f"[{wid}] [workflow] {row.host}:{row.port} "
                            f"[{','.join(sub_ids)}]"
                        )
        if module.output_format == "nuclei":
            from swarm_tpu.worker import formats

            sev, proto = formats.severity_index(engine.templates)
            out = formats.format_nuclei(rows, results, sev, proto)
            if wf_lines:
                out = out + "\n".join(wf_lines) + "\n"
            return out.encode()
        out_lines = [
            format_match_line(row, matches) for row, matches in zip(rows, results)
        ]
        out_lines += wf_lines
        return ("\n".join(out_lines) + "\n").encode() if out_lines else b""

    # ------------------------------------------------------------------
    def _execute_probe(self, module: ModuleSpec, data: bytes) -> bytes:
        """Native-I/O-only path (dnsx/httprobe/httpx/web module parity):
        probe the targets with the C++ front-end and emit the module's
        output format — no template matching involved."""
        from swarm_tpu.worker import formats
        from swarm_tpu.worker.executor import ProbeExecutor

        lines = data.decode("utf-8", "surrogateescape").splitlines()
        executor = ProbeExecutor(module.probe)
        if module.probe.get("type") == "dns":
            resolutions = executor.resolve(lines)
            return formats.format_dnsx(
                resolutions, with_a=bool(module.probe.get("with_a"))
            ).encode()
        rows = executor.run(lines)
        if module.output_format == "httprobe":
            return formats.format_httprobe(rows).encode()
        if module.output_format == "httpx_json":
            return formats.format_httpx_json(rows).encode()
        raise ValueError(
            f"module {module.name}: unknown output_format {module.output_format!r}"
        )

    # ------------------------------------------------------------------
    def _execute_service(self, module: ModuleSpec, data: bytes) -> bytes:
        """Service/version detection (the nmap -sV replacement): native
        banner probing with payloads from the probes DB, device-batched
        match prefilter, host version extraction."""
        from swarm_tpu.ops.service import ServiceClassifier
        from swarm_tpu.worker.executor import ProbeExecutor

        key = f"svc::{module.raw.get('probes_db') or ''}"
        classifier = self._engines.get(key)
        if classifier is None:
            classifier = ServiceClassifier(db_path=module.raw.get("probes_db"))
            self._engines[key] = classifier
        rows, sent = ProbeExecutor(module.probe).run_service(
            data.decode("utf-8", "surrogateescape").splitlines(), classifier
        )
        infos = classifier.classify(rows, sent)
        if module.output_format == "nmap":
            from swarm_tpu.worker import formats

            return formats.format_nmap_report(infos).encode()
        lines = [info.line() for info in infos if info.open]
        return ("\n".join(lines) + "\n").encode() if lines else b""


def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="swarm_tpu worker")
    parser.add_argument("--server-url", default=None)
    parser.add_argument("--api-key", default=None)
    parser.add_argument("--worker-id", default=None)
    parser.add_argument("--modules-dir", default=None)
    parser.add_argument("--max-jobs", type=int, default=None)
    parser.add_argument("--config", default=None)
    args = parser.parse_args(argv)
    cfg = Config.load(
        path=args.config,
        server_url=args.server_url,
        api_key=args.api_key,
        worker_id=args.worker_id,
        modules_dir=args.modules_dir,
        max_jobs=args.max_jobs,
    )
    # An operator-set JAX_PLATFORMS env must actually stick: site-hook
    # platform plugins can override the env var alone (see utils/jaxpin)
    from swarm_tpu.utils.jaxpin import pin_platform_from_env

    pin_platform_from_env()
    # multi-host worker: join the DCN process group when configured
    # (SWARM_COORDINATOR/-NUM_PROCESSES/-PROCESS_ID) so the tpu
    # backend's mesh spans every host's chips; no-op single-host
    from swarm_tpu.parallel.multihost import maybe_initialize_distributed
    from swarm_tpu.utils.xlacache import (
        enable_compilation_cache,
        install_cache_metrics,
    )

    enable_compilation_cache()  # warm restarts skip the corpus recompile
    # swarm_xla_cache_{hit,miss}_total: fleet restarts must show on
    # /metrics whether the persistent cache is actually serving —
    # installed even when the cache dir is disabled (counters then
    # simply stay dark, instead of silently missing from the scrape)
    install_cache_metrics()
    if maybe_initialize_distributed():
        print("multi-host: jax.distributed initialized")
    proc = JobProcessor(cfg)
    # SIGTERM routes through the DRAIN path, not a mid-upload death
    # (docs/RESILIENCE.md §Preemption): the handler only sets a flag,
    # the poll loop finishes its current lease, flushes or spools, and
    # deregisters before exiting. Best-effort install — embedded runs
    # off the main thread can't own signals.
    import signal

    try:
        signal.signal(
            signal.SIGTERM, lambda _sig, _frm: proc.request_drain("sigterm")
        )
    except ValueError:
        pass
    for name in filter(None, (n.strip() for n in cfg.prewarm_modules.split(","))):
        if proc.prewarm(name):
            print(f"prewarmed module {name}")
    proc.process_jobs()


if __name__ == "__main__":
    main()
