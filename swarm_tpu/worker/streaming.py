"""Streaming probe→device pipeline: double-buffered waves.

BASELINE.json config #4 is a masscan-style stream — targets flow in,
banners flow to the device, verdicts flow out — where neither side may
idle: the native epoll front-end (which releases the GIL for the whole
scan call) probes wave *i+1* while the device matches wave *i*.

The unit of overlap is a **wave** of targets. A bounded queue provides
the double buffer: depth 1 means the producer is at most one wave
ahead, so memory stays at two waves of rows regardless of input size.
Results preserve input wave order (the consumer drains in FIFO), so the
streamed output is byte-identical to the sequential path.

The reference's analog is tool-internal concurrency plus unix pipes
(``dnsx | httpx`` in worker/modules/web.json — SURVEY.md §2.4
"pipeline parallelism"); here the pipe crosses the host/device boundary.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Iterator, Optional, Sequence


@dataclasses.dataclass
class StreamStats:
    waves: int = 0
    rows: int = 0
    probe_seconds: float = 0.0  # producer busy time
    match_seconds: float = 0.0  # consumer busy time
    wall_seconds: float = 0.0

    @property
    def overlap_seconds(self) -> float:
        """Time saved vs running the two stages back to back."""
        return max(0.0, self.probe_seconds + self.match_seconds - self.wall_seconds)


class StreamingPipeline:
    """Drive ``probe(wave) -> rows`` and ``consume(rows) -> out`` as a
    two-stage pipeline over waves of targets.

    ``probe`` runs on a producer thread (native scan I/O releases the
    GIL, so probing genuinely overlaps jit'd device work on the main
    thread). ``consume`` runs on the caller's thread and sees waves in
    submission order. Exceptions on either side propagate to the caller.
    """

    def __init__(
        self,
        probe: Callable[[Sequence[str]], object],
        consume: Callable[[object], object],
        wave_targets: int = 1024,
        queue_depth: int = 1,
    ):
        self.probe = probe
        self.consume = consume
        self.wave_targets = max(1, int(wave_targets))
        self.queue_depth = max(1, int(queue_depth))
        self.stats = StreamStats()

    def run(self, target_lines: Sequence[str]) -> list[object]:
        return list(self.iter_results(target_lines))

    def iter_results(self, target_lines: Sequence[str]) -> Iterator[object]:
        lines = list(target_lines)
        waves = [
            lines[i : i + self.wave_targets]
            for i in range(0, len(lines), self.wave_targets)
        ] or [[]]
        q: queue.Queue = queue.Queue(maxsize=self.queue_depth)
        error: list[BaseException] = []
        stop = threading.Event()
        t_start = time.perf_counter()

        def put(item) -> None:
            # blocks at queue_depth (bounded lookahead) but stays
            # interruptible so a dead consumer can't strand the thread
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def producer() -> None:
            try:
                for wave in waves:
                    if stop.is_set():
                        return
                    t0 = time.perf_counter()
                    rows = self.probe(wave)
                    self.stats.probe_seconds += time.perf_counter() - t0
                    put(rows)
            except BaseException as e:  # propagate through the queue
                error.append(e)
            finally:
                put(_DONE)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                rows = q.get()
                if rows is _DONE:
                    break
                t0 = time.perf_counter()
                out = self.consume(rows)
                self.stats.match_seconds += time.perf_counter() - t0
                self.stats.waves += 1
                try:
                    self.stats.rows += len(rows)  # type: ignore[arg-type]
                except TypeError:
                    pass
                yield out
        finally:
            stop.set()
            thread.join()
            self.stats.wall_seconds = time.perf_counter() - t_start
        if error:
            raise error[0]


_DONE = object()


def stream_match(
    engine,
    target_lines: Sequence[str],
    probe_spec: Optional[dict] = None,
    wave_targets: int = 1024,
) -> tuple[list, list, StreamStats]:
    """targets → (rows, per-row match results, stats), streamed.

    The worker's targets-mode device path: ProbeExecutor waves feed
    MatchEngine batches with probe/match overlap. Output is identical
    to ``engine.match(executor.run(lines))`` run sequentially.
    """
    from swarm_tpu.worker.executor import ProbeExecutor

    executor = ProbeExecutor(probe_spec)
    pipeline = StreamingPipeline(
        probe=executor.run,
        consume=lambda rows: (rows, engine.match(rows)),
        wave_targets=wave_targets,
    )
    all_rows: list = []
    all_results: list = []
    for rows, results in pipeline.run(target_lines):
        all_rows.extend(rows)
        all_results.extend(results)
    return all_rows, all_results, pipeline.stats
