"""Self-hosted out-of-band interaction listener (interactsh analog).

The reference delegates OOB detection to nuclei + a public interactsh
server: templates embed ``{{interactsh-url}}`` in their requests, a
vulnerable target calls that URL back over HTTP or resolves it over
DNS, and matchers then check ``interactsh_protocol`` /
``interactsh_request`` (SURVEY.md §2.3: 144 interactsh matchers; the
corpus only ever matches the protocols "http" and "dns").

This module is the self-hosted equivalent: one in-process listener
serving both protocols, correlation-token URL minting, and a poll API
the active scanner drains after its waves. No third-party interactsh
service is involved — the operator points ``advertise_host`` (and
optionally a delegated ``domain``) at the worker itself.

Correlation model: every minted token is a unique DNS-safe string that
appears verbatim in whatever the target sends back (HTTP path/Host or
DNS qname). Incoming payloads are scanned with one regex for
token-shaped substrings and matched against the registry — O(payload),
independent of how many tokens are outstanding.

URL forms (what ``{{interactsh-url}}`` renders to):
- with ``domain``:  ``<token>.<domain>``  — DNS-correlatable; requires
  the operator to delegate the domain's NS to this listener.
- without: ``<advertise_host>:<http_port>/<token>`` — HTTP-only
  correlation (no DNS delegation needed), enough for SSRF/redirect
  classes; log4j-style DNS-interaction templates need the domain form.
"""

from __future__ import annotations

import dataclasses
import os
import re
import secrets
import socket
import ssl
import struct
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

#: token shape: "si" + 14 hex chars — fixed-width, lowercase, DNS-safe,
#: and specific enough that free text never collides with the registry
_TOKEN_RE = re.compile(rb"si[0-9a-f]{14}")

#: hostile-target bounds: the token is handed to the SCANNED host, so
#: everything it sends back is attacker-controlled — cap both the raw
#: bytes kept per interaction and the interactions kept per token, or a
#: malicious target could OOM the worker during the poll window
_MAX_RAW_BYTES = 64 * 1024
_MAX_INTERACTIONS_PER_TOKEN = 32


@dataclasses.dataclass
class Interaction:
    protocol: str  # "http" | "dns"
    raw_request: bytes
    remote_addr: str
    at: float


class OOBListener:
    """HTTP + DNS callback listener with token correlation."""

    def __init__(
        self,
        advertise_host: str = "127.0.0.1",
        http_port: int = 0,
        dns_port: Optional[int] = 0,
        domain: Optional[str] = None,
        answer_ip: Optional[str] = None,
    ):
        self.advertise_host = advertise_host
        self.domain = domain.strip(".").lower() if domain else None
        # A-record the DNS responder answers with (chained interactions:
        # resolve → connect); defaults to the advertised host when that
        # is an address, else loopback
        self.answer_ip = answer_ip or (
            advertise_host if _is_ipv4(advertise_host) else "127.0.0.1"
        )
        self._lock = threading.Lock()  # guards: _interactions (reads)
        self._interactions: dict[bytes, list[Interaction]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._dns_sock: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._closed = False
        self._http_port_arg = http_port
        self._dns_port_arg = dns_port
        self.http_port = 0
        self.dns_port = 0

    # ------------------------------------------------------------------
    def start(self) -> "OOBListener":
        listener = self

        tls_ctx = _self_signed_tls_context()

        class Handler(BaseHTTPRequestHandler):
            def setup(self) -> None:
                # TLS auto-detect runs HERE, on the per-connection
                # handler thread — peeking (or handshaking) in the
                # accept loop would let one slow client stall every
                # other callback. The timeout stays on the socket so an
                # idle connection times its thread out instead of
                # leaking it.
                self.request.settimeout(10)
                if tls_ctx is not None:
                    try:
                        first = self.request.recv(1, socket.MSG_PEEK)
                        if first == b"\x16":  # TLS ClientHello
                            self.request = tls_ctx.wrap_socket(
                                self.request, server_side=True
                            )
                    except OSError:
                        pass  # plain read path will fail it cleanly
                super().setup()

            # one catch-all: every method records an interaction
            def _serve(self) -> None:
                # everything after the headers is attacker/target-
                # controlled: a malformed Content-Length or a body that
                # never arrives must not prevent the record (that would
                # turn a vulnerable host into a false negative), and a
                # slow body must not eat the scanner's poll window
                raw = self.raw_requestline + bytes(self.headers)
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    length = 0
                if 0 < length <= _MAX_RAW_BYTES:
                    try:
                        self.request.settimeout(2)
                        body = self.rfile.read(length)
                        if body:
                            raw += b"\r\n" + body
                    except OSError:
                        pass
                listener._record("http", raw, self.client_address[0])
                body = b"<html><head></head><body>ok</body></html>"
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *_a) -> None:  # no stderr spam per hit
                pass

        # dynamically legal: do_GET etc. resolved per-method at runtime
        for method in ("GET", "POST", "PUT", "HEAD", "OPTIONS", "DELETE", "PATCH"):
            setattr(Handler, f"do_{method}", Handler._serve)

        # One port, both schemes: templates embed http:// OR https://
        # around {{interactsh-url}}; Handler.setup peeks the first byte
        # (0x16 = TLS ClientHello) on the handler thread and wraps
        # conditionally — the dual-stack trick real interactsh servers
        # achieve with separate 80/443 listeners. Callbacks are "http"
        # protocol interactions either way (nuclei parity). TLS needs
        # the cryptography package for the self-signed cert; without it
        # the port is plain-HTTP only.
        self._httpd = ThreadingHTTPServer(("0.0.0.0", self._http_port_arg), Handler)
        self.http_port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)

        if self._dns_port_arg is not None:
            self._dns_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._dns_sock.bind(("0.0.0.0", self._dns_port_arg))
            self.dns_port = self._dns_sock.getsockname()[1]
            t = threading.Thread(target=self._dns_loop, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._closed = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._dns_sock is not None:
            try:
                # unblock recvfrom with a self-addressed empty datagram
                poke = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                poke.sendto(b"", ("127.0.0.1", self.dns_port))
                poke.close()
            except OSError:
                pass
            self._dns_sock.close()

    def __enter__(self) -> "OOBListener":
        return self.start()

    def __exit__(self, *_exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def new_token(self) -> str:
        token = "si" + secrets.token_hex(7)
        with self._lock:
            self._interactions[token.encode()] = []
        return token

    def url_for(self, token: str) -> str:
        """What ``{{interactsh-url}}`` renders to for this token.

        Domain mode appends ``:http_port`` unless the listener sits on
        a standard web port — otherwise SSRF-class http:// callbacks
        would dial :80 where nothing listens. The port suffix is wrong
        for bare-hostname contexts (dns:// URIs), so operators wanting
        maximal template compatibility should bind (or NAT) 80/443.
        """
        if self.domain:
            if self.http_port in (80, 443):
                return f"{token}.{self.domain}"
            return f"{token}.{self.domain}:{self.http_port}"
        return f"{self.advertise_host}:{self.http_port}/{token}"

    def poll(self, token: str) -> list[Interaction]:
        """Drain the token's interactions (keeps the token registered)."""
        with self._lock:
            got = self._interactions.get(token.encode())
            if not got:
                return []
            out, got[:] = list(got), []
            return out

    def release(self, token: str) -> None:
        with self._lock:
            self._interactions.pop(token.encode(), None)

    def pending(self) -> int:
        with self._lock:
            return sum(1 for v in self._interactions.values() if v)

    # ------------------------------------------------------------------
    def _record(self, protocol: str, raw: bytes, remote: str) -> None:
        now = time.time()
        raw = raw[:_MAX_RAW_BYTES]
        hits = set(_TOKEN_RE.findall(raw.lower()))
        if not hits:
            return
        with self._lock:
            for token in hits:
                bucket = self._interactions.get(token)
                if (
                    bucket is not None
                    and len(bucket) < _MAX_INTERACTIONS_PER_TOKEN
                ):
                    bucket.append(Interaction(protocol, raw, remote, now))

    # ------------------------------------------------------------------
    def _dns_loop(self) -> None:
        sock = self._dns_sock
        assert sock is not None
        while not self._closed:
            try:
                data, addr = sock.recvfrom(4096)
            except OSError:
                return
            if self._closed or len(data) < 12:
                continue
            qname = _parse_qname(data)
            if qname is None:
                continue
            self._record("dns", qname, addr[0])
            reply = _build_a_reply(data, qname, self.answer_ip)
            if reply is not None:
                try:
                    sock.sendto(reply, addr)
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# process-wide shared listeners: the worker runtime caches one
# ActiveScanner per (templates, probe-spec, vars) key for process
# lifetime; per-scanner listeners would leak sockets per key and make a
# fixed-port spec EADDRINUSE on the second scanner. One listener per
# distinct OOB config serves every scanner that asks for it (tokens are
# minted per probe, so sharing cannot cross-correlate scans).

_SHARED: dict = {}  # guarded-by: _SHARED_LOCK (reads)
_SHARED_LOCK = threading.Lock()


def shared_listener(**kw) -> OOBListener:
    """Process-wide OOBListener for this exact config (started)."""
    import json

    key = json.dumps(kw, sort_keys=True)
    with _SHARED_LOCK:
        lst = _SHARED.get(key)
        if lst is None:
            lst = OOBListener(**kw).start()
            _SHARED[key] = lst
        return lst


def _self_signed_tls_context() -> Optional[ssl.SSLContext]:
    """Server SSLContext with a fresh self-signed cert, or None when
    the cryptography package is unavailable (plain-HTTP-only mode).
    Callers of an OOB URL never validate this cert — the vulnerable
    fetcher is the one dialing out."""
    try:
        import datetime

        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        return None
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "oob.listener")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=365))
        .sign(key, hashes.SHA256())
    )
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    # load_cert_chain only reads files — stage the PEMs in a private
    # tempdir and remove them once loaded
    with tempfile.TemporaryDirectory(prefix="swarm_oob_tls_") as td:
        cert_pem = os.path.join(td, "cert.pem")
        key_pem = os.path.join(td, "key.pem")
        with open(cert_pem, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
        with open(key_pem, "wb") as f:
            f.write(
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.TraditionalOpenSSL,
                    serialization.NoEncryption(),
                )
            )
        ctx.load_cert_chain(cert_pem, key_pem)
    return ctx


# ---------------------------------------------------------------------------
# tiny wire helpers (query side lives in worker/dnsquery.py; the
# responder is intentionally minimal: one question, A-record answer)


def _is_ipv4(s: str) -> bool:
    try:
        socket.inet_aton(s)
        return True
    except OSError:
        return False


def _parse_qname(pkt: bytes) -> Optional[bytes]:
    """First question's qname as dotted lowercase bytes; None = bad."""
    labels = []
    pos = 12
    try:
        while True:
            n = pkt[pos]
            if n == 0:
                break
            if n > 63:  # compression pointers can't appear in a question
                return None
            labels.append(pkt[pos + 1 : pos + 1 + n])
            pos += 1 + n
            if pos > len(pkt) or len(labels) > 64:
                return None
    except IndexError:
        return None
    return b".".join(labels).lower() if labels else None


def _build_a_reply(query: bytes, qname: bytes, answer_ip: str) -> Optional[bytes]:
    """Echo the question, answer one A record (TTL 0)."""
    try:
        tid = query[:2]
        # question section: name + qtype + qclass
        qend = 12 + sum(len(lbl) + 1 for lbl in qname.split(b".")) + 1 + 4
        question = query[12:qend]
    except (IndexError, struct.error):
        return None
    header = tid + struct.pack(
        ">HHHHH",
        0x8580,  # QR | AA | RD|RA echoed loosely; NOERROR
        1,  # QDCOUNT
        1,  # ANCOUNT
        0,
        0,
    )
    answer = (
        b"\xc0\x0c"  # pointer to qname at offset 12
        + struct.pack(">HHIH", 1, 1, 0, 4)  # A, IN, TTL 0, RDLENGTH 4
        + socket.inet_aton(answer_ip)
    )
    return header + question + answer
