"""The sharded match step: dp × tp × sp over a device mesh.

This is the multi-chip SERVING path (the reference scaled by adding
droplets; this scales by sharding one batch across a TPU slice):

- **data**: rows sharded; no cross-shard traffic until result gather.
- **model**: every rank probes the same windows against its 1/R slice
  of each word table's sorted h1 range (disjoint group ranges, disjoint
  candidate sets, per-rank blooms). Slot bits combine with one
  ``psum`` over ICI — the collective cost is B × NS bits per step.
- **seq**: response bytes sharded; each rank owns the candidate windows
  starting in its slice and exchanges halos of ``max_entry_len`` bytes
  with both neighbors via ``ppermute`` (the ring/halo pattern of
  context parallelism) so words spanning shard boundaries are found by
  exactly the rank that owns their gram position.

The verdict stage runs replicated on every (model, seq) rank after the
psum — it is tiny next to the probe stage.

Production dispatch is SPLIT-PHASE with survivor compaction and
OVERLAPPED reduction, the mesh twin of ``DeviceDB.dispatch``
(docs/SHARDING.md, docs/DEVICE_MATCH.md), as three executables:

- **phase A** runs every rank's stacked bloom probe into a survivor
  RANK plane. On seq meshes the halo ``ppermute`` is FUSED into this
  probe and the extended ``[B, W + 2·halo]`` stream views are carried
  forward — phase B never re-exchanges, so a seq batch pays ONE halo
  round, not two. Each rank also emits its own clamped max-survivor
  count; the host reads the tiny per-rank vector (R × 4 bytes, no
  cross-rank collective) and maxes it to pick phase B's ladder width
  (``compile.survivor_bucket``). Multi-process meshes keep the
  ``pmax``'d replicated scalar (a host can only read its own shard).
- **phase B probe** extracts/verifies at survivor size and stops at
  the per-rank bit planes — no psum, no verdict tail. One wrapper per
  ladder rung serves every width bucket of the shape class (the cache
  key is the stream NAMES, not shapes), so live rung executables stay
  bounded per mesh shape and AOT-store fetches cover each width.
- **reduction** (psum + replicated verdict tail + fused-plane pack)
  is dispatched SEPARATELY and DEFERRED: ``dispatch`` returns a
  handle holding the launch thunk, and the next ``dispatch`` flushes
  it right after its own phase A enqueues — batch N's cross-rank
  reduction rides the device behind phase A of batch N+1, so the
  host's between-phase read never waits on the previous batch's
  collectives. ``collect`` forces the handle if no later dispatch
  already did. One reduction executable serves EVERY ladder rung.

Per-batch uploads go through the dispatch staging pool and are
DONATED to their last consumer together with the inter-phase rank
planes; the fused single-kernel pjit step is kept as the bit-identical
reference twin (``SWARM_SHARD_COMPACT=0`` / ``SWARM_SHARD_DONATE=0``,
or the ``compact=``/``donate=`` args; ``SWARM_SHARD_OVERLAP=0`` keeps
the split kernels but launches the reduction inline).
``dispatch``/``collect`` split the blocking host read out of the
launch, so the continuous-batching scheduler keeps ≥2 mesh batches in
flight exactly as on the single-device path.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops import hashing
from swarm_tpu.ops.match import (
    MAX_COMPILED,
    _StagingPool,
    _StreamCtx,
    _col_starts_of,
    _env_flag,
    compact_candidates,
    eval_verdicts,
    fuse_planes,
    global_candidate_budget,
    host_batch_leaves,
    lru_fetch,
    lru_store,
    match_slots_args,
    prefilter_counts,
    split_fused,
    tiny_slot_bits,
    verify_candidates,
)
from swarm_tpu.ops.md5 import md5_words


def shard_tables_np(db: fpc.CompiledDB, ranks: int) -> list[dict]:
    """Split every table's sorted h1-group range into ``ranks`` contiguous
    slices with identical padded shapes, one pytree leaf-list per table:
    arrays get a leading [ranks] axis to shard over 'model'.

    Padding uses a sentinel h1 of 0xFFFFFFFF with zero entry counts, so
    a padded group can never be "found" twice (searchsorted may land on
    it, but count 0 yields no entries).
    """
    stacked: list[dict] = []
    for table in db.tables:
        G = table.num_groups
        g_per = max(1, -(-G // ranks))
        gmax = g_per
        emax = 1
        slices = []
        for r in range(ranks):
            lo = min(r * g_per, G)
            hi = min(lo + g_per, G)
            if hi > lo:
                e_lo = int(table.entry_start[lo])
                e_hi = int(
                    table.entry_start[hi - 1] + table.entry_count[hi - 1]
                )
            else:
                e_lo = e_hi = 0
            slices.append((lo, hi, e_lo, e_hi))
            emax = max(emax, e_hi - e_lo)
        arrs = {
            "group_h1": np.full((ranks, gmax), 0xFFFFFFFF, dtype=np.uint32),
            "entry_start": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_count": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_slot": np.zeros((ranks, emax), dtype=np.int32),
            "entry_off": np.zeros((ranks, emax), dtype=np.int32),
            "entry_len": np.full((ranks, emax), 1 << 30, dtype=np.int32),
            "entry_suf_delta": np.zeros((ranks, emax), dtype=np.int32),
            "entry_suf_h1": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_suf_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "bloom": np.zeros((ranks, hashing.BLOOM_WORDS), dtype=np.uint32),
        }
        for r, (lo, hi, e_lo, e_hi) in enumerate(slices):
            n_g, n_e = hi - lo, e_hi - e_lo
            if n_g == 0:
                continue
            arrs["group_h1"][r, :n_g] = table.group_h1[lo:hi]
            arrs["entry_start"][r, :n_g] = table.entry_start[lo:hi] - e_lo
            arrs["entry_count"][r, :n_g] = table.entry_count[lo:hi]
            for name, src in (
                ("entry_h2", table.entry_h2),
                ("entry_slot", table.entry_slot),
                ("entry_off", table.entry_off),
                ("entry_len", table.entry_len),
                ("entry_suf_delta", table.entry_suf_delta),
                ("entry_suf_h1", table.entry_suf_h1),
                ("entry_suf_h2", table.entry_suf_h2),
            ):
                arrs[name][r, :n_e] = src[e_lo:e_hi]
            arrs["bloom"][r] = hashing.build_bloom_np(
                np.repeat(table.group_h1[lo:hi], table.entry_count[lo:hi]),
                table.entry_h2[e_lo:e_hi],
            )
        stacked.append(arrs)
    return stacked


def shard_stacked_np(db: fpc.CompiledDB, ranks: int) -> dict:
    """Model-sharded twin of ``compile.stack_tables_np``: one stacked
    table-major pytree per rank, with a leading [ranks] axis to shard
    over 'model'. Built on :func:`shard_tables_np` (same slicing, same
    per-rank blooms, same sentinels) and padded to rank-global
    Gmax/Emax so every rank's executable sees one shape."""
    per_table = shard_tables_np(db, ranks)
    T = len(per_table)
    if T == 0:
        base = fpc.stack_tables_np([])
        return {
            k: np.repeat(v[None], ranks, axis=0) for k, v in base.items()
        }
    gmax = max(t["group_h1"].shape[1] for t in per_table)
    emax = max(t["entry_h2"].shape[1] for t in per_table)
    out = {
        "group_h1": np.full((ranks, T, gmax), 0xFFFFFFFF, dtype=np.uint32),
        "entry_start": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_count": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_slot": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_off": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_len": np.full((ranks, T, emax), 1 << 30, dtype=np.int32),
        "entry_suf_delta": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_suf_h1": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_suf_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "bloom": np.zeros(
            (ranks, T, hashing.BLOOM_WORDS), dtype=np.uint32
        ),
        "n_groups": np.zeros((ranks, T), dtype=np.int32),
    }
    for t_idx, arrs in enumerate(per_table):
        g = arrs["group_h1"].shape[1]
        e = arrs["entry_h2"].shape[1]
        for name in (
            "group_h1", "entry_start", "entry_count",
        ):
            out[name][:, t_idx, :g] = arrs[name]
        for name in (
            "entry_h2", "entry_slot", "entry_off", "entry_len",
            "entry_suf_delta", "entry_suf_h1", "entry_suf_h2",
        ):
            out[name][:, t_idx, :e] = arrs[name]
        out["bloom"][:, t_idx] = arrs["bloom"]
        # real (unpadded) group counts per rank — the binary-search
        # bound. Derived from the slices shard_tables_np actually
        # built (every real group has >= 1 entry, padding has 0), so
        # any future change to its slicing rule stays in lockstep.
        out["n_groups"][:, t_idx] = (arrs["entry_count"] > 0).sum(axis=1)
    return out


def max_entry_len(db: fpc.CompiledDB) -> int:
    out = int(hashing.GRAM_LONG)
    for table in db.tables:
        if table.entry_len.size:
            out = max(out, int(table.entry_len.max()))
    return out


def pad_streams_for_seq(streams: dict, seq_ranks: int, halo: int) -> None:
    """Widen streams IN PLACE so each seq rank's slice is at least one
    halo wide and 128-aligned — the invariant :class:`ShardedMatcher`
    enforces (narrow streams like the width-1 OOB placeholders must
    widen before seq sharding). The single shared implementation: the
    engine's encode path and the multichip dryrun both pad through
    here, so the rule cannot drift between them again."""
    import numpy as np

    from swarm_tpu.ops.encoding import round_up

    seq = max(seq_ranks, 1)
    for name, arr in streams.items():
        per_rank = max(round_up(arr.shape[1], seq) // seq, halo)
        target = round_up(per_rank, 128) * seq
        if target > arr.shape[1]:
            streams[name] = np.pad(arr, ((0, 0), (0, target - arr.shape[1])))


_SHARD_METRICS = None


def _shard_metrics():
    """Lazy ``swarm_shard_*`` family handle (kept out of import time so
    oracle-only users never touch the registry; the families themselves
    register at telemetry import — telemetry/shard_export.py)."""
    global _SHARD_METRICS
    if _SHARD_METRICS is None:
        from swarm_tpu.telemetry import shard_export

        _SHARD_METRICS = shard_export
    return _SHARD_METRICS


class _PendingShard:
    """One compacted batch's DEFERRED cross-rank reduction (psum +
    verdict tail), double-buffered behind the next batch's phase A.

    ``dispatch`` returns this handle with the reduction un-launched;
    whoever needs it next fires it exactly once:

    - the NEXT ``dispatch`` flushes it right after its own phase A
      enqueues (``launched_by == "dispatch"`` — the overlapped case);
    - otherwise ``collect``/``match`` force it (``"collect"``);
    - ``SWARM_SHARD_OVERLAP=0`` and multi-process meshes launch inline
      before ``dispatch`` returns (``"inline"``);
    - a corpus ``refresh`` drains any straggler (``"refresh"``).

    A launch failure is stored and re-raised at ``force`` so the error
    surfaces on the batch that owns it, not on the innocent batch whose
    dispatch happened to flush the buffer. The held rank planes are
    accounted in the staging pool (``hold_plane``/``release_plane``)
    while the reduction is in flight.
    """

    __slots__ = (
        "_matcher", "_thunk", "_lock", "_out", "_exc", "_done",
        "_held_bytes", "launched_by",
    )

    def __init__(self, matcher, thunk, held_bytes: int):
        self._matcher = matcher
        self._thunk = thunk
        self._lock = threading.Lock()
        self._out = None
        self._exc = None
        self._done = False
        self._held_bytes = int(held_bytes)
        self.launched_by: Optional[str] = None
        matcher.staging.hold_plane(self._held_bytes)

    def launch(self, by: str) -> None:
        """Fire the reduction thunk exactly once (idempotent; safe from
        the submit thread, the walk worker, and collect concurrently)."""
        with self._lock:
            if self._done:
                return
            try:
                self._out = self._thunk()
            except BaseException as e:  # surfaced at force()
                self._exc = e
            finally:
                self._done = True
                self._thunk = None
                self.launched_by = by
                self._matcher.staging.release_plane(self._held_bytes)
                self._matcher._clear_pending(self)

    def force(self):
        """Launch if nothing did yet, then yield the (device-resident)
        reduction output — or re-raise the launch failure."""
        self.launch("collect")
        if self._exc is not None:
            raise self._exc
        return self._out


@dataclasses.dataclass
class ShardedMatcher:
    """Builds and caches the pjit'd sharded match step for one mesh.

    Serving surface (docs/SHARDING.md): :meth:`dispatch` launches the
    split-phase compacted kernels asynchronously (the only blocking
    point is the tiny per-rank max-survivor read between phases) and
    returns a :class:`_PendingShard` whose cross-rank reduction stays
    un-launched until the next dispatch's phase A is in the queue;
    :meth:`collect` forces the handle and pays the one fused host
    read. ``MatchEngine.begin_packed``/``finish_packed`` route here
    exactly as they do to ``DeviceDB``, so the scheduler's in-flight
    budget and walk offload apply unchanged on the mesh. The fused
    single-kernel step stays as the bit-identical reference twin
    (``compact=False``); ``donate=False`` keeps the staged uploads
    alive past the launch; ``overlap=False`` launches the reduction
    inline (multi-process meshes always do — deferred collective
    launch order must stay identical on every process).
    """

    db: fpc.CompiledDB
    mesh: Mesh
    candidate_k: int = 128
    compact: Optional[bool] = None
    donate: Optional[bool] = None
    overlap: Optional[bool] = None

    def __post_init__(self):
        if self.compact is None:
            self.compact = _env_flag("SWARM_SHARD_COMPACT", True)
        if self.donate is None:
            self.donate = _env_flag("SWARM_SHARD_DONATE", True)
        if self.overlap is None:
            self.overlap = _env_flag("SWARM_SHARD_OVERLAP", True)
        self.staging = _StagingPool()
        self.compile_seconds = 0.0  # guarded-by: _counter_lock
        self.compile_count = 0  # guarded-by: _counter_lock
        #: AOT executable-cache fetch spy (docs/AOT.md): dispatches
        #: that LOADED a published executable instead of compiling —
        #: counted distinctly so the compile spy stays honest
        self.fetch_seconds = 0.0  # guarded-by: _counter_lock
        self.fetch_count = 0  # guarded-by: _counter_lock
        self._aot = None  # AotClient (attach_aot) — None = compile-only
        #: most recent compacted dispatch: survivor_max / verify_k /
        #: budget (the "phase B launches at survivor size" evidence)
        self.last_compact: dict = {}  # guarded-by: _counter_lock
        self._counter_lock = threading.Lock()
        self.ranks = {name: int(self.mesh.shape[name]) for name in self.mesh.axis_names}
        self.halo = max_entry_len(self.db) if self.ranks.get("seq", 1) > 1 else 0
        # the SAME argument-pytree convention as DeviceDB
        # (docs/DEVICE_MATCH.md): per-rank stacked word tables shard
        # over 'model'; the verdict/rx/slot arrays replicate. Uploaded
        # once here, passed as jit arguments every call — the compiled
        # step is corpus-size-free on the sharded path too.
        self.meta = fpc.layout_meta(self.db)
        self._tab_np = shard_stacked_np(self.db, self.ranks.get("model", 1))
        self._rep_np = {
            "slot_bytes": self.db.slot_bytes,
            "slot_len": self.db.slot_len,
            "tiny_bytes": self.db.tiny_bytes,
            "tiny_slot": self.db.tiny_slot,
            "verdict": fpc.verdict_arrays_np(self.db),
            "rx": fpc.rx_arrays_np(self.db),
        }
        # multi-host (jax.distributed) meshes span devices this process
        # cannot address: inputs must become GLOBAL jax.Arrays (every
        # process holds the full host copy; each device takes its
        # slice) and outputs gather back host-local. Single-process
        # meshes keep the plain local-array path.
        self.multiprocess = any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat
        )
        # deferred reduction launch order is host-controlled; on a
        # multi-controller mesh every process MUST enqueue collectives
        # in the same order, so overlap stays single-controller-only
        self.overlap = bool(self.overlap) and not self.multiprocess
        #: the one un-launched deferred reduction (double buffer depth
        #: 1: each dispatch flushes its predecessor before parking its
        #: own handle)
        self._pending: Optional[_PendingShard] = None  # guarded-by: _counter_lock
        # constant after construction — upload once, not per match call
        if self.multiprocess:
            self._tab_j = {
                k: self._global(v, P("model")) for k, v in self._tab_np.items()
            }
            self._rep_j = jax.tree_util.tree_map(
                lambda a: self._global(a, P()), self._rep_np
            )
        else:
            self._tab_j = {k: jnp.asarray(v) for k, v in self._tab_np.items()}
            self._rep_j = jax.tree_util.tree_map(jnp.asarray, self._rep_np)
        self._fn_cache: dict = {}  # guarded-by: _counter_lock
        for ax, size in self.ranks.items():
            _shard_metrics().MESH_AXIS.labels(axis=ax).set(size)

    def _global(self, arr, spec):
        """Host copy -> global array laid out per ``spec`` over the
        (possibly multi-process) mesh."""
        arr = np.asarray(arr)
        sharding = NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    # ------------------------------------------------------------------
    # trace-time building blocks shared by the fused twin and the
    # split-phase kernels — one implementation, so parity can't drift
    # ------------------------------------------------------------------
    def _smap(self):
        """(shard_map, kwargs) — jax.shard_map landed post-0.4.x; older
        jax ships it under experimental with check_rep instead of
        check_vma."""
        try:
            smap = jax.shard_map
            return smap, {"check_vma": False}
        except AttributeError:
            from jax.experimental.shard_map import shard_map as smap

            return smap, {"check_rep": False}

    # -- AOT executable cache (docs/AOT.md) ----------------------------
    def attach_aot(self, client) -> None:
        """Attach an :class:`~swarm_tpu.aot.AotClient` so every
        subsequently built mesh step fetches published executables
        before compiling. Single-controller multi-device meshes fetch
        exactly like the single-device path — the store digest already
        keys on device count + XLA flags and the trace salt keys on
        the mesh factorization, so every ladder rung of every mesh
        shape loads instead of compiling. ONLY multi-controller
        (jax.distributed) meshes stay compile-only: an executable
        image is only loadable on the topology it was compiled for,
        and cross-host coordination of the load is not worth the
        coupling — the per-host persistent XLA cache already covers
        that deployment. Live wrappers drop so the attach takes
        effect at the next dispatch."""
        with self._counter_lock:
            self._aot = None if self.multiprocess else client
            self._fn_cache.clear()

    def _trace_salt(self) -> str:
        """The sharded twin of ``DeviceDB._trace_salt``: layout
        metadata + kernel statics + the MESH (axis names/sizes — a
        (2,2,2) executable must never serve an (8,1,1) worker)."""
        db = self.db
        return repr(
            (
                self.meta,
                self.candidate_k,
                tuple(sorted(self.ranks.items())),
                self.halo,
                db.num_slots,
                db.num_templates,
                int(db.op_src.shape[0]),
                int(db.m_src.shape[0]),
                int(db.rx_seq_always.sum()),
            )
        )

    def _wrap_jit(self, fun, kernel_id: str, donate_argnums=()):
        if self._aot is None:
            if donate_argnums:
                return jax.jit(fun, donate_argnums=donate_argnums)
            return jax.jit(fun)
        from swarm_tpu.aot.jitcache import AotJit

        return AotJit(
            fun,
            kernel_id=kernel_id,
            salt=self._trace_salt(),
            client=self._aot,
            donate_argnums=donate_argnums,
            cap=4 * MAX_COMPILED,
        )

    def executable_count(self) -> int:
        """Live locally-compiled executables across every cached mesh
        step (the compile spy's cache-size view; fetched loads are
        counted by :meth:`fetched_executable_count` instead)."""
        with self._counter_lock:
            fns = list(self._fn_cache.values())
        return sum(
            int(fn._cache_size())
            for fn in fns
            if hasattr(fn, "_cache_size")
        )

    def fetched_executable_count(self) -> int:
        from swarm_tpu.aot.jitcache import fetched_size_of

        with self._counter_lock:
            fns = list(self._fn_cache.values())
        return sum(fetched_size_of(fn) for fn in fns)

    def aot_prewarm(self) -> int:
        """Pool every published executable for this program group
        (bring-up fetch; see ``DeviceDB.aot_prewarm``)."""
        client = self._aot
        return client.prewarm() if client is not None else 0

    # -- corpus refresh (docs/AOT.md) ----------------------------------
    def refresh(self, db_new: fpc.CompiledDB) -> dict:
        """Zero-downtime corpus refresh on the mesh: recompute the
        per-rank stacked/replicated host pytrees and re-upload ONLY
        the leaves whose bytes changed (byte-equal leaves keep their
        existing device arrays — the rank-sharded stack is rebuilt on
        host but the ICI/H2D traffic is delta-sized). The trace
        signature decides executable retention exactly as on the
        single-device path. Caller quiesces dispatches first."""
        # a still-deferred reduction captured the OLD corpus arrays —
        # drain it before the swap (callers quiesce dispatches, but a
        # parked handle outlives its dispatch by design)
        stale = self._take_pending()
        if stale is not None:
            stale.launch("refresh")
        old_salt = self._trace_salt()
        old_tab_np, old_rep_np = self._tab_np, self._rep_np
        old_tab_j, old_rep_j = self._tab_j, self._rep_j
        self.db = db_new
        self.meta = fpc.layout_meta(db_new)
        self.halo = (
            max_entry_len(db_new) if self.ranks.get("seq", 1) > 1 else 0
        )
        self._tab_np = shard_stacked_np(
            db_new, self.ranks.get("model", 1)
        )
        self._rep_np = {
            "slot_bytes": db_new.slot_bytes,
            "slot_len": db_new.slot_len,
            "tiny_bytes": db_new.tiny_bytes,
            "tiny_slot": db_new.tiny_slot,
            "verdict": fpc.verdict_arrays_np(db_new),
            "rx": fpc.rx_arrays_np(db_new),
        }

        def upload(new_np, old_np_map, old_j_map, spec_of):
            old_host = {
                jax.tree_util.keystr(p): leaf
                for p, leaf in jax.tree_util.tree_flatten_with_path(
                    old_np_map
                )[0]
            }
            old_dev = {
                jax.tree_util.keystr(p): leaf
                for p, leaf in jax.tree_util.tree_flatten_with_path(
                    old_j_map
                )[0]
            }
            flat, _ = jax.tree_util.tree_flatten_with_path(new_np)
            out = []
            n_up = 0
            for path, leaf in flat:
                key = jax.tree_util.keystr(path)
                old = old_host.get(key)
                if (
                    key in old_dev
                    and isinstance(old, np.ndarray)
                    and old.dtype == leaf.dtype
                    and old.shape == leaf.shape
                    and (old is leaf or np.array_equal(old, leaf))
                ):
                    out.append(old_dev[key])
                else:
                    n_up += 1
                    if self.multiprocess:
                        out.append(self._global(leaf, spec_of(path)))
                    else:
                        out.append(jnp.asarray(leaf))
            return (
                jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(new_np), out
                ),
                n_up,
            )

        self._tab_j, up_tab = upload(
            self._tab_np, old_tab_np, old_tab_j, lambda _p: P("model")
        )
        self._rep_j, up_rep = upload(
            self._rep_np, old_rep_np, old_rep_j, lambda _p: P()
        )
        old_leaves = jax.tree_util.tree_leaves((old_tab_np, old_rep_np))
        new_leaves = jax.tree_util.tree_leaves(
            (self._tab_np, self._rep_np)
        )
        keep = (
            old_salt == self._trace_salt()
            and len(old_leaves) == len(new_leaves)
            and all(
                o.shape == n.shape and o.dtype == n.dtype
                for o, n in zip(old_leaves, new_leaves)
            )
        )
        with self._counter_lock:
            if not keep:
                self._fn_cache.clear()
        return {
            "uploaded_leaves": up_tab + up_rep,
            "executables_kept": keep,
        }

    def _specs(self, streams: dict, lengths: dict):
        """(tab, rep, streams, lengths) partition specs for one batch
        shape — corpus slices over 'model', replicated verdict/rx,
        rows over 'data', response bytes over 'seq'."""
        return (
            {name: P("model") for name in self._tab_np},
            jax.tree_util.tree_map(lambda _a: P(), self._rep_np),
            {k: P("data", "seq") for k in streams},
            {k: P("data") for k in lengths},
        )

    def _exchange_halos(self, streams: dict):
        """Halo exchange over 'seq' (trace-time no-op when unsharded):
        each rank borrows ``halo`` bytes from both neighbors via
        ppermute so words spanning shard boundaries verify on exactly
        the rank that owns their gram position. Returns
        ``(streams_ext, offsets, back, fwd)``."""
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks <= 1:
            return streams, 0, 0, 0
        halo = self.halo
        seq_index = jax.lax.axis_index("seq")
        ext: dict = {}
        offsets: dict = {}
        for name, local in streams.items():
            fwd_halo = jax.lax.ppermute(
                local[:, :halo],
                "seq",
                [(r, r - 1) for r in range(1, seq_ranks)],
            )
            back_halo = jax.lax.ppermute(
                local[:, -halo:],
                "seq",
                [(r, r + 1) for r in range(seq_ranks - 1)],
            )
            ext[name] = jnp.concatenate([back_halo, local, fwd_halo], axis=1)
            offsets[name] = seq_index * local.shape[1]
        return ext, offsets, halo, halo

    def _combine_finish(
        self, value_bits, uncertain_bits, overflow, streams, lengths,
        status, rep, full,
    ):
        """Shared tail of every sharded route: psum the per-rank bit
        planes over the communicating axes, then the replicated verdict
        stage (device md5, device regex verify, verdict lowering) and
        the fused-plane pack. Runs at trace time inside the step."""
        db = self.db
        seq_ranks = self.ranks.get("seq", 1)
        combine_axes = tuple(
            ax for ax in ("model", "seq") if self.ranks.get(ax, 1) > 1
        )
        if combine_axes:
            value_bits = (
                jax.lax.psum(value_bits.astype(jnp.int32), combine_axes) > 0
            )
            uncertain_bits = (
                jax.lax.psum(uncertain_bits.astype(jnp.int32), combine_axes)
                > 0
            )
            overflow = (
                jax.lax.psum(overflow.astype(jnp.int32), combine_axes) > 0
            )

        # device md5 (ops/md5.py): the block chain is sequential in
        # the byte dimension, so a seq-sharded body is re-gathered
        # (tiled over ICI) just for the digest — cheap next to the
        # probe stage, and only when the corpus compares digests
        def full_stream(name):
            local = streams[name]
            if seq_ranks > 1:
                return jax.lax.all_gather(local, "seq", axis=1, tiled=True)
            return local

        digest = None
        if bool(db.m_md5_check.any()) and "body" in streams:
            digest = md5_words(full_stream("body"), lengths["body"])
        # device regex verify over the combined slot bits: like md5
        # it needs whole rows, so used streams gather over 'seq'
        rx = None
        if len(db.rx_m_ids):
            from swarm_tpu.ops.encoding import STREAMS
            from swarm_tpu.ops.regexdev import regex_verify

            used = {STREAMS[int(s)] for s in db.rx_seq_stream}
            gathered = {n: full_stream(n) for n in used}
            rx = regex_verify(
                db,
                gathered,
                lengths,
                value_bits,
                k_pairs=db.rx_k_pairs(status.shape[0]),
                arrays=rep["rx"],
            )
        out = eval_verdicts(
            db,
            value_bits,
            uncertain_bits,
            lengths,
            status,
            full=full,
            md5_digest=digest,
            rx=rx,
            arrays=rep["verdict"],
        )
        if full:
            # pack bit planes per data-rank (axis 1 is unsharded, so
            # packed bytes concatenate cleanly over 'data') and fuse
            # them with the overflow column into ONE output array —
            # the host then makes a single device read (split_fused)
            return fuse_planes(out, overflow)
        return (*out, overflow)

    # ------------------------------------------------------------------
    # executable builders (one per batch shape, LRU-bounded)
    # ------------------------------------------------------------------
    def _build_fused(self, streams: dict, lengths: dict, full: bool):
        """The fused single-kernel pjit step — the legacy reference
        twin (``compact=False``, or a corpus with no word tables)."""
        db = self.db
        meta = self.meta
        candidate_k = self.candidate_k

        # jit-captures: self, db, meta, candidate_k, full (host metadata
        # + scalars — trace-static; the corpus rides the tab/rep
        # ARGUMENTS, never the closure)
        def step(tab, rep, streams, lengths, status):
            streams_ext, offsets, back, fwd = self._exchange_halos(streams)
            arrays = {
                "tab": {k: v[0] for k, v in tab.items()},
                "slot_bytes": rep["slot_bytes"],
                "slot_len": rep["slot_len"],
                "tiny_bytes": rep["tiny_bytes"],
                "tiny_slot": rep["tiny_slot"],
            }
            value_bits, uncertain_bits, overflow = match_slots_args(
                db,
                meta,
                arrays,
                candidate_k,
                streams_ext,
                lengths,
                pos_offset=offsets,
                back_halo=back,
                fwd_halo=fwd,
            )
            return self._combine_finish(
                value_bits, uncertain_bits, overflow, streams, lengths,
                status, rep, full,
            )

        smap, smap_kwargs = self._smap()
        tab_specs, rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        out_specs = P("data") if full else (P("data"),) * 3
        fn = smap(
            step,
            mesh=self.mesh,
            in_specs=(
                tab_specs, rep_specs, stream_spec, lengths_spec, P("data"),
            ),
            out_specs=out_specs,
            **smap_kwargs,
        )
        return self._wrap_jit(fn, f"sh.fused.full={full}")

    def _ext_ctx(self, streams: dict, lengths: dict):
        """Trace-time twin of :meth:`_exchange_halos` for kernels that
        receive ALREADY-EXTENDED ``[B, W + 2·halo]`` views carried out
        of phase A: rebuild the stream context (window offsets in
        pre-halo coordinates, recovered from the carried width) and
        the local views WITHOUT a second ppermute round — the fused
        single-round halo exchange. Unsharded seq passes through.
        Returns ``(ctx, local_views, back, fwd)``; the local views are
        lazy slices whose bytes are bit-identical to the pre-exchange
        stream (``ext[:, h:-h] == local`` by construction), and XLA
        DCEs them where only their shapes are consumed."""
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks <= 1:
            return _StreamCtx(streams, lengths, 0), streams, 0, 0
        h = self.halo
        seq_index = jax.lax.axis_index("seq")
        local = {k: v[:, h:-h] for k, v in streams.items()}
        offsets = {
            k: seq_index * (v.shape[1] - 2 * h)
            for k, v in streams.items()
        }
        return _StreamCtx(streams, lengths, offsets), local, h, h

    def _reduce_needs_streams(self, streams) -> bool:
        """Whether the reduction tail re-reads response bytes (device
        md5 digest or device regex verify gather whole rows over
        'seq'). When False the deferred reduce takes no stream
        argument at all and the phase-B probe is the streams' last
        consumer (donation moves accordingly)."""
        db = self.db
        return bool(
            (bool(db.m_md5_check.any()) and "body" in streams)
            or len(db.rx_m_ids)
        )

    def _build_phase_a(self, streams: dict, lengths: dict, donate_streams: bool):
        """Standing sharded phase A: per-rank stacked bloom probe →
        survivor RANK plane + per-rank overflow + each rank's clamped
        max survivor count. The rank plane and overflow keep an
        explicit leading (model, seq) axis — every rank's candidate
        space is distinct, and the phase-B probe slices its own plane
        back out.

        On seq meshes the halo ppermute happens HERE, once: the
        extended ``[B, W + 2·halo]`` views ride the output straight
        into phase B (``_ext_ctx`` rebuilds offsets from the carried
        width), so one batch pays one halo round total.

        The survivor count stays per-rank (single 4-byte lane per
        device, specced over every axis) so the host read between
        phases costs R × 4 bytes and NO cross-rank collective; only
        multi-controller meshes keep the ``pmax``'d replicated scalar,
        because a process can only read its own shard of a global
        array."""
        meta = self.meta
        budget = global_candidate_budget(
            self.candidate_k, len(meta.table_stream)
        )
        carry = self.ranks.get("seq", 1) > 1

        # jit-captures: self, meta, budget, carry (layout metadata +
        # python scalars; all trace-static)
        def step_a(tab, streams, lengths):
            streams_ext, offsets, back, fwd = self._exchange_halos(streams)
            ctx = _StreamCtx(streams_ext, lengths, offsets)
            cnt, _cs = prefilter_counts(
                meta, {k: v[0] for k, v in tab.items()}, ctx, back, fwd
            )
            n_surv = cnt[:, -1]
            K = max(1, min(budget, cnt.shape[1]))
            overflow = n_surv > K
            nmax = jnp.max(jnp.minimum(n_surv, K))
            if self.multiprocess:
                # multi-controller: the host can only read its own
                # shard, so keep the replicated pmax'd scalar
                nmax_out = jax.lax.pmax(nmax, tuple(self.mesh.axis_names))
            else:
                nmax_out = nmax[None]  # per-rank lane; host maxes R ints
            if carry:
                return cnt[None], overflow[None], nmax_out, streams_ext
            return cnt[None], overflow[None], nmax_out

        smap, smap_kwargs = self._smap()
        tab_specs, _rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        rank_spec = P(("model", "seq"), "data")
        nmax_spec = P() if self.multiprocess else P(("data", "model", "seq"))
        out_specs = (rank_spec, rank_spec, nmax_spec)
        if carry:
            out_specs = out_specs + ({k: P("data", "seq") for k in streams},)
        fn = smap(
            step_a,
            mesh=self.mesh,
            in_specs=(tab_specs, stream_spec, lengths_spec),
            out_specs=out_specs,
            **smap_kwargs,
        )
        # streams are donated into phase A only when the extended
        # views replace them as every later kernel's input (seq mesh +
        # matcher-owned staged copies)
        donate = (1,) if donate_streams else ()
        return self._wrap_jit(
            fn,
            f"sh.A2.mp={int(self.multiprocess)}.don={int(donate_streams)}",
            donate_argnums=donate,
        )

    def _build_phase_b_probe(
        self, streams: dict, lengths: dict, kc: int, donate_streams: bool,
    ):
        """Sharded phase-B PROBE at the static ladder rung ``kc``:
        per-rank survivor extraction from the phase-A rank plane,
        gather-verify + tiny at survivor size — stopping at the
        per-rank bit planes. No psum, no verdict tail: the cross-rank
        reduction is a separate deferred executable
        (:meth:`_build_reduce`), which is what lets batch N's
        collectives overlap batch N+1's probe. The phase-A rank plane
        is always DONATED; the (possibly extended) streams are donated
        only when this probe is their last consumer."""
        db = self.db
        meta = self.meta
        budget = global_candidate_budget(
            self.candidate_k, len(meta.table_stream)
        )

        # jit-captures: self, db, meta, budget, kc (metadata and
        # scalars only — kc is the ladder rung this executable serves)
        def step_bp(tab, rep, streams, lengths, cnt_r):
            ctx, local, back, fwd = self._ext_ctx(streams, lengths)
            tabr = {k: v[0] for k, v in tab.items()}
            cnt = cnt_r[0]
            K = max(1, min(budget, cnt.shape[1]))
            col = compact_candidates(cnt, kc, K)
            # candidate axis = LOCAL window coordinates (pre-halo
            # widths), exactly what prefilter_counts concatenated —
            # _col_starts_of only reads shapes, so the local slices
            # cost nothing here
            col_starts = _col_starts_of(meta, local)
            value_bits, uncertain_bits = verify_candidates(
                meta,
                tabr,
                rep["slot_bytes"],
                rep["slot_len"],
                ctx,
                col,
                col_starts,
                db.num_slots,
                back,
                fwd,
            )
            value_bits = tiny_slot_bits(
                meta, rep["tiny_bytes"], rep["tiny_slot"], ctx, value_bits,
                back,
            )
            return value_bits[None], uncertain_bits[None]

        smap, smap_kwargs = self._smap()
        tab_specs, rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        rank_spec = P(("model", "seq"), "data")
        fn = smap(
            step_bp,
            mesh=self.mesh,
            in_specs=(
                tab_specs, rep_specs, stream_spec, lengths_spec, rank_spec,
            ),
            out_specs=(rank_spec, rank_spec),
            **smap_kwargs,
        )
        donate = (2, 4) if donate_streams else (4,)  # [streams,] cnt plane
        # kc rides the kernel id (it is baked into the step closure
        # here, not a static argnum) so every ladder rung publishes
        # its own artifact
        return self._wrap_jit(
            fn,
            f"sh.Bp.kc={kc}.don={int(donate_streams)}",
            donate_argnums=donate,
        )

    def _build_reduce(
        self, snames, lnames, full: bool, don_streams: bool,
        don_host: bool,
    ):
        """The ONE deferred reduction executable: psum the per-rank
        bit planes over the communicating axes + the replicated
        verdict tail + the fused-plane pack (:meth:`_combine_finish`).
        Rung-independent — EVERY ladder width of a shape class lands
        in this same program, so deferring it adds exactly one live
        executable per mesh shape. ``snames is None`` when the corpus
        needs no response bytes past the probe (no device md5, no
        device regex) — the common case, where the reduce ships only
        the rank planes + lengths/status."""
        full_flag = full
        carry = self.ranks.get("seq", 1) > 1
        h = self.halo

        # jit-captures: self, full_flag, carry, h (python scalars —
        # trace-static; corpus rides the rep ARGUMENT)
        def finish(rep, streams, lengths, status, vb_r, ub_r, ovf_r):
            local = streams
            if carry:
                # carried extended views → slice the exact pre-halo
                # bytes back out for the md5/regex row gathers
                local = {k: v[:, h:-h] for k, v in streams.items()}
            return self._combine_finish(
                vb_r[0], ub_r[0], ovf_r[0], local, lengths, status, rep,
                full_flag,
            )

        smap, smap_kwargs = self._smap()
        rep_specs = jax.tree_util.tree_map(lambda _a: P(), self._rep_np)
        lengths_spec = {k: P("data") for k in lnames}
        rank_spec = P(("model", "seq"), "data")
        out_specs = P("data") if full else (P("data"),) * 3
        if snames is not None:
            stream_spec = {k: P("data", "seq") for k in snames}
            step_r = finish
            in_specs = (
                rep_specs, stream_spec, lengths_spec, P("data"),
                rank_spec, rank_spec, rank_spec,
            )
            donate = (4, 5, 6)  # the matcher-owned rank planes, always
            if don_streams:
                donate = (1,) + donate
            if don_host:
                donate = donate + (2, 3)
        else:

            # jit-captures: finish (the closure above)
            def step_r(rep, lengths, status, vb_r, ub_r, ovf_r):
                return finish(rep, {}, lengths, status, vb_r, ub_r, ovf_r)

            in_specs = (
                rep_specs, lengths_spec, P("data"),
                rank_spec, rank_spec, rank_spec,
            )
            donate = (3, 4, 5)
            if don_host:
                donate = donate + (1, 2)
        fn = smap(
            step_r,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            **smap_kwargs,
        )
        return self._wrap_jit(
            fn,
            (
                f"sh.R.full={full}.s={int(snames is not None)}"
                f".don={int(don_streams)}{int(don_host)}"
            ),
            donate_argnums=tuple(sorted(donate)),
        )

    def _launch_reduce(
        self, streams_j, lengths_j, status_j, vb, ub, ovf, full: bool,
        donate_host: bool, snames, lnames,
    ):
        """Fetch/build + enqueue the deferred reduction for one batch
        (the :class:`_PendingShard` thunk body). ``streams_j`` is None
        when the verdict tail needs no response bytes; on seq meshes
        it is the carried extended views, sliced back to local inside
        the step."""
        t0 = time.perf_counter()
        needs = streams_j is not None
        carry = self.ranks.get("seq", 1) > 1
        # carried extended views are matcher-created inside phase A —
        # donatable regardless of who owns the original host batch
        don_s = bool(carry or donate_host)
        fr, fresh_r = self._get_fn(
            (
                "R", snames if needs else None, lnames, full, don_s,
                bool(donate_host),
            ),
            lambda: self._build_reduce(
                snames if needs else None, lnames, full, don_s,
                bool(donate_host),
            ),
        )
        if needs:
            out = fr(self._rep_j, streams_j, lengths_j, status_j, vb, ub, ovf)
        else:
            out = fr(self._rep_j, lengths_j, status_j, vb, ub, ovf)
        self._note_launch([(fr, fresh_r)], t0)
        return out

    # ------------------------------------------------------------------
    # deferred-reduction double buffer (depth 1; _PendingShard)
    # ------------------------------------------------------------------
    def _take_pending(self) -> Optional[_PendingShard]:
        with self._counter_lock:
            handle, self._pending = self._pending, None
        return handle

    def _set_pending(self, handle: _PendingShard) -> None:
        with self._counter_lock:
            self._pending = handle

    def _clear_pending(self, handle: _PendingShard) -> None:
        """Called by the handle itself once launched, so a handle
        forced by collect() can never be re-taken by a later
        dispatch (launch is idempotent anyway — this is hygiene)."""
        with self._counter_lock:
            if self._pending is handle:
                self._pending = None

    # ------------------------------------------------------------------
    def _get_fn(self, key, builder):
        """(fn, freshly_built) from the LRU-bounded executable cache.
        Ladder rungs multiply the live entries (one pjit per
        (shape, kc) pair), hence the same generous 4x churn bound
        DeviceDB applies to its jit caches. Runs under
        ``_counter_lock``: with the walk offload armed, the submit
        thread (dispatch) and the walk worker (a degraded batch's
        sync-path retry) can reach this cache concurrently, and
        ``lru_fetch``'s refresh pops/reinserts — an unlocked race
        could evict the same key twice or compile twin wrappers.
        Building the wrapper under the lock is cheap (jit/shard_map
        construction only; XLA compiles at first call)."""
        with self._counter_lock:
            fn = lru_fetch(self._fn_cache, key)
            fresh = fn is None
            if fresh:
                fn = builder()
                lru_store(self._fn_cache, key, fn, 4 * MAX_COMPILED)
        return fn, fresh

    def _check_seq_widths(self, streams: dict) -> None:
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks <= 1:
            return
        for name, arr in streams.items():
            per_rank = arr.shape[1] // seq_ranks
            if arr.shape[1] % seq_ranks:
                raise ValueError(
                    f"stream {name!r} width {arr.shape[1]} not divisible "
                    f"by seq ranks {seq_ranks}"
                )
            if per_rank < self.halo:
                # the halo slices local[:, :halo] would silently come
                # up short and misalign every window coordinate
                raise ValueError(
                    f"stream {name!r}: per-rank width {per_rank} < halo "
                    f"{self.halo} (longest table entry); widen the "
                    f"stream or lower the seq factor"
                )

    def _stage(self, streams: dict, lengths: dict, status):
        """Upload one batch through the dispatch staging pool: always a
        COPY (plain ``jnp.asarray`` single-process, global jax.Arrays
        spanning the mesh multi-process), so phase-B donation can never
        corrupt caller-owned numpy — the engine's recycled encode
        planes keep rotating untouched."""
        if not self.multiprocess:
            s_j, l_j, st_j, _staged = self.staging.stage(
                streams, lengths, status
            )
            return s_j, l_j, st_j
        s_j = {
            k: self._global(v, P("data", "seq")) for k, v in streams.items()
        }
        l_j = {k: self._global(v, P("data")) for k, v in lengths.items()}
        st_j = self._global(status, P("data"))
        self.staging.account(
            int(
                sum(getattr(v, "nbytes", 0) for v in streams.values())
                + sum(getattr(v, "nbytes", 0) for v in lengths.values())
                + int(getattr(status, "nbytes", 0))
            )
        )
        return s_j, l_j, st_j

    def _note_launch(self, launches, t0: float) -> None:
        """Compile/fetch accounting at the dispatch boundary (same
        contract as DeviceDB's spy: wall time of dispatches that made
        at least one new executable servable, attributed to the
        compile or the AOT-fetch pair by what the freshly built
        wrappers actually did — a deserialized load is NOT a
        compile). ``launches`` = [(fn, freshly_built), ...]; the
        wrappers have been CALLED by the time this runs."""
        from swarm_tpu.aot.jitcache import fetched_size_of

        fresh_fns = [fn for fn, fresh in launches if fresh]
        if not fresh_fns:
            return
        compiled = sum(
            int(fn._cache_size())
            for fn in fresh_fns
            if hasattr(fn, "_cache_size")
        )
        fetched = sum(fetched_size_of(fn) for fn in fresh_fns)
        dt = time.perf_counter() - t0
        with self._counter_lock:
            if fetched:
                self.fetch_seconds += dt
                self.fetch_count += 1
            if compiled:
                self.compile_seconds += dt
                self.compile_count += 1

    def _dispatch_metrics(
        self, streams: dict, halo_rounds_a: int = 1,
        halo_rounds_b: int = 0, saved_rounds: int = 0,
    ) -> None:
        """Per-dispatch traffic accounting. Halo bytes are labeled by
        PHASE so the bench can attribute the single-round fusion win:
        the compacted path pays one phase-A round and charges the
        round it no longer pays (vs the historical re-exchange in
        phase B) to the saved counter; the fused twin's one in-kernel
        exchange counts as phase a."""
        m = _shard_metrics()
        m.SHARD_DISPATCHES.inc(1)
        B = int(next(iter(streams.values())).shape[0])
        ns = max(self.db.num_slots, 1)
        if any(self.ranks.get(ax, 1) > 1 for ax in ("model", "seq")):
            # value + uncertain + overflow int32 lanes entering the
            # cross-rank psum (docs/SHARDING.md: B × NS bits per step)
            m.PSUM_BYTES.inc(B * (2 * ns + 1) * 4)
        if self.ranks.get("seq", 1) > 1:
            round_bytes = 2 * self.halo * B * len(streams)
            if halo_rounds_a:
                m.HALO_BYTES.labels(phase="a").inc(
                    halo_rounds_a * round_bytes
                )
            if halo_rounds_b:
                m.HALO_BYTES.labels(phase="b").inc(
                    halo_rounds_b * round_bytes
                )
            if saved_rounds:
                m.HALO_SAVED.inc(saved_rounds * round_bytes)

    # ------------------------------------------------------------------
    def dispatch(self, streams: dict, lengths: dict, status, full: bool = True):
        """Async half of :meth:`match`: stage the batch, launch the
        sharded kernels, and return WITHOUT a full host transfer — the
        continuous-batching scheduler dispatches batch i+1 here before
        walking batch i's verdicts; :meth:`collect` finalizes.

        On the compacted path the only blocking point is the phase-A
        max-survivor read (R × 4 bytes of per-rank lanes on a
        single-controller mesh) that picks the probe's ladder width;
        the cross-rank reduction comes back as an un-launched
        :class:`_PendingShard` and rides behind the NEXT dispatch's
        phase A — or behind :meth:`collect` when the window closes."""
        from swarm_tpu.resilience.faults import fault_point

        # same fault point as DeviceDB.dispatch: "the device path
        # failed" is one failure class whichever matcher serves it
        # (MatchEngine degrades to the CPU oracle either way)
        fault_point("device.dispatch")
        self._check_seq_widths(streams)
        # executable cache keys use stream NAMES, not shapes: the
        # builders only consume names (partition specs), so ONE
        # wrapper serves every width bucket of a shape class and the
        # per-shape executables live in the wrapper's own cache —
        # bounded rung count per mesh shape, and AOT fetch covers
        # each width signature under the same kernel id
        snames = tuple(sorted(streams))
        lkey = tuple(sorted(lengths))
        t0 = time.perf_counter()
        s_j, l_j, st_j = self._stage(streams, lengths, status)
        if not (self.compact and len(self.meta.table_stream)):
            # fused legacy/reference arm (also the no-tables corpus,
            # where there is nothing to compact)
            fn, fresh = self._get_fn(
                ("fused", snames, lkey, full),
                lambda: self._build_fused(streams, lengths, full),
            )
            out = fn(self._tab_j, self._rep_j, s_j, l_j, st_j)
            self._note_launch([(fn, fresh)], t0)
            self._dispatch_metrics(streams)
            return out

        donate_streams = self.donate and host_batch_leaves(
            streams, lengths, status
        )
        carry = self.ranks.get("seq", 1) > 1
        don_a = bool(donate_streams and carry)
        fa, fresh_a = self._get_fn(
            ("A", snames, lkey, don_a),
            lambda: self._build_phase_a(streams, lengths, don_a),
        )
        if carry:
            cnt, ovf, nmax, s_ext = fa(self._tab_j, s_j, l_j)
        else:
            cnt, ovf, nmax = fa(self._tab_j, s_j, l_j)
            s_ext = s_j
        # double buffer: with OUR phase A in the queue, flush the
        # previous batch's deferred reduction — its psum/verdict tail
        # executes behind the probe while this host thread blocks on
        # the survivor read below
        prev = self._take_pending()
        if prev is not None:
            prev.launch("dispatch")
            _shard_metrics().OVERLAPPED.inc(1)
        # the ONE host sync between phases: the survivor maxima that
        # size the probe to live work — the second blessed sync of the
        # jit-hygiene contract (tools/swarmlint). Single-controller
        # meshes read the per-rank lanes (R × 4 bytes, no collective);
        # multi-controller meshes read their pmax'd replicated scalar.
        if self.multiprocess:
            n_live = int(nmax)  # host-sync-ok: the blessed sharded 4-byte phase-A survivor scalar
        else:
            n_live = int(np.asarray(nmax).max())  # host-sync-ok: the blessed sharded phase-A survivor lanes (R × 4 bytes)
        budget = global_candidate_budget(
            self.candidate_k, len(self.meta.table_stream)
        )
        kc = fpc.survivor_bucket(n_live, budget)
        needs = self._reduce_needs_streams(streams)
        don_bp = bool((not needs) and (carry or donate_streams))
        fbp, fresh_bp = self._get_fn(
            ("Bp", snames, lkey, kc, don_bp),
            lambda: self._build_phase_b_probe(streams, lengths, kc, don_bp),
        )
        vb, ub = fbp(self._tab_j, self._rep_j, s_ext, l_j, cnt)
        self._note_launch([(fa, fresh_a), (fbp, fresh_bp)], t0)
        with self._counter_lock:
            self.last_compact = {
                "survivor_max": n_live,
                "verify_k": kc,
                "budget": budget,
            }
        m = _shard_metrics()
        m.SURVIVOR_MAX.set(n_live)
        self._dispatch_metrics(streams, saved_rounds=1)
        held = sum(int(getattr(a, "nbytes", 0)) for a in (vb, ub, ovf))
        r_streams = s_ext if needs else None
        handle = _PendingShard(
            self,
            lambda: self._launch_reduce(
                r_streams, l_j, st_j, vb, ub, ovf, full, donate_streams,
                snames, lkey,
            ),
            held,
        )
        if self.overlap:
            self._set_pending(handle)
        else:
            handle.launch("inline")
        return handle

    def collect(self, out):
        """Blocking half of the full-mode split: force the deferred
        reduction if no later dispatch flushed it, then one host read
        of the fused plane array (gathered host-local over DCN first
        on multi-process meshes), sliced into the engine's six
        outputs."""
        deferred = isinstance(out, _PendingShard)
        t0 = time.perf_counter()
        if deferred:
            out = out.force()
        if self.multiprocess:
            from jax.experimental import multihost_utils

            out = multihost_utils.global_array_to_host_local_array(
                out, self.mesh, P()
            )
        res = split_fused(self.db, np.asarray(out))
        if deferred:
            # launch-if-needed + device wait + the host read: how long
            # collect actually stalled on the reduction (≈0 when a
            # later dispatch already overlapped it)
            _shard_metrics().REDUCTION_WAIT.inc(time.perf_counter() - t0)
        return res

    # ------------------------------------------------------------------
    def match(self, streams: dict, lengths: dict, status, full: bool = False):
        """Synchronous convenience: :meth:`dispatch` + the blocking
        read. ``full=False`` returns the (t_value, t_unc, overflow)
        device tuple exactly as before the split."""
        out = self.dispatch(streams, lengths, status, full=full)
        if full:
            return self.collect(out)
        if isinstance(out, _PendingShard):
            out = out.force()
        if self.multiprocess:
            # global -> host-local (replicated) so every process can
            # read the full result; riding DCN once per batch
            from jax.experimental import multihost_utils

            out = tuple(
                multihost_utils.global_array_to_host_local_array(
                    o, self.mesh, P()
                )
                for o in out
            )
        return out
