"""The sharded match step: dp × tp × sp over a device mesh.

This is the multi-chip execution path (the reference scaled by adding
droplets; this scales by sharding one batch across a TPU slice):

- **data**: rows sharded; no cross-shard traffic until result gather.
- **model**: every rank probes the same windows against its 1/R slice
  of each word table's sorted h1 range (disjoint group ranges, disjoint
  candidate sets, per-rank blooms). Slot bits combine with one
  ``psum`` over ICI — the collective cost is B × NS bits per step.
- **seq**: response bytes sharded; each rank owns the candidate windows
  starting in its slice and exchanges halos of ``max_entry_len`` bytes
  with both neighbors via ``ppermute`` (the ring/halo pattern of
  context parallelism) so words spanning shard boundaries are found by
  exactly the rank that owns their gram position.

The verdict stage runs replicated on every (model, seq) rank after the
psum — it is tiny next to the probe stage.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops import hashing
from swarm_tpu.ops.match import eval_verdicts, match_slots_args
from swarm_tpu.ops.md5 import md5_words


def shard_tables_np(db: fpc.CompiledDB, ranks: int) -> list[dict]:
    """Split every table's sorted h1-group range into ``ranks`` contiguous
    slices with identical padded shapes, one pytree leaf-list per table:
    arrays get a leading [ranks] axis to shard over 'model'.

    Padding uses a sentinel h1 of 0xFFFFFFFF with zero entry counts, so
    a padded group can never be "found" twice (searchsorted may land on
    it, but count 0 yields no entries).
    """
    stacked: list[dict] = []
    for table in db.tables:
        G = table.num_groups
        g_per = max(1, -(-G // ranks))
        gmax = g_per
        emax = 1
        slices = []
        for r in range(ranks):
            lo = min(r * g_per, G)
            hi = min(lo + g_per, G)
            if hi > lo:
                e_lo = int(table.entry_start[lo])
                e_hi = int(
                    table.entry_start[hi - 1] + table.entry_count[hi - 1]
                )
            else:
                e_lo = e_hi = 0
            slices.append((lo, hi, e_lo, e_hi))
            emax = max(emax, e_hi - e_lo)
        arrs = {
            "group_h1": np.full((ranks, gmax), 0xFFFFFFFF, dtype=np.uint32),
            "entry_start": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_count": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_slot": np.zeros((ranks, emax), dtype=np.int32),
            "entry_off": np.zeros((ranks, emax), dtype=np.int32),
            "entry_len": np.full((ranks, emax), 1 << 30, dtype=np.int32),
            "entry_suf_delta": np.zeros((ranks, emax), dtype=np.int32),
            "entry_suf_h1": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_suf_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "bloom": np.zeros((ranks, hashing.BLOOM_WORDS), dtype=np.uint32),
        }
        for r, (lo, hi, e_lo, e_hi) in enumerate(slices):
            n_g, n_e = hi - lo, e_hi - e_lo
            if n_g == 0:
                continue
            arrs["group_h1"][r, :n_g] = table.group_h1[lo:hi]
            arrs["entry_start"][r, :n_g] = table.entry_start[lo:hi] - e_lo
            arrs["entry_count"][r, :n_g] = table.entry_count[lo:hi]
            for name, src in (
                ("entry_h2", table.entry_h2),
                ("entry_slot", table.entry_slot),
                ("entry_off", table.entry_off),
                ("entry_len", table.entry_len),
                ("entry_suf_delta", table.entry_suf_delta),
                ("entry_suf_h1", table.entry_suf_h1),
                ("entry_suf_h2", table.entry_suf_h2),
            ):
                arrs[name][r, :n_e] = src[e_lo:e_hi]
            arrs["bloom"][r] = hashing.build_bloom_np(
                np.repeat(table.group_h1[lo:hi], table.entry_count[lo:hi]),
                table.entry_h2[e_lo:e_hi],
            )
        stacked.append(arrs)
    return stacked


def shard_stacked_np(db: fpc.CompiledDB, ranks: int) -> dict:
    """Model-sharded twin of ``compile.stack_tables_np``: one stacked
    table-major pytree per rank, with a leading [ranks] axis to shard
    over 'model'. Built on :func:`shard_tables_np` (same slicing, same
    per-rank blooms, same sentinels) and padded to rank-global
    Gmax/Emax so every rank's executable sees one shape."""
    per_table = shard_tables_np(db, ranks)
    T = len(per_table)
    if T == 0:
        base = fpc.stack_tables_np([])
        return {
            k: np.repeat(v[None], ranks, axis=0) for k, v in base.items()
        }
    gmax = max(t["group_h1"].shape[1] for t in per_table)
    emax = max(t["entry_h2"].shape[1] for t in per_table)
    out = {
        "group_h1": np.full((ranks, T, gmax), 0xFFFFFFFF, dtype=np.uint32),
        "entry_start": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_count": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_slot": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_off": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_len": np.full((ranks, T, emax), 1 << 30, dtype=np.int32),
        "entry_suf_delta": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_suf_h1": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_suf_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "bloom": np.zeros(
            (ranks, T, hashing.BLOOM_WORDS), dtype=np.uint32
        ),
        "n_groups": np.zeros((ranks, T), dtype=np.int32),
    }
    for t_idx, arrs in enumerate(per_table):
        g = arrs["group_h1"].shape[1]
        e = arrs["entry_h2"].shape[1]
        for name in (
            "group_h1", "entry_start", "entry_count",
        ):
            out[name][:, t_idx, :g] = arrs[name]
        for name in (
            "entry_h2", "entry_slot", "entry_off", "entry_len",
            "entry_suf_delta", "entry_suf_h1", "entry_suf_h2",
        ):
            out[name][:, t_idx, :e] = arrs[name]
        out["bloom"][:, t_idx] = arrs["bloom"]
        # real (unpadded) group counts per rank — the binary-search
        # bound. Derived from the slices shard_tables_np actually
        # built (every real group has >= 1 entry, padding has 0), so
        # any future change to its slicing rule stays in lockstep.
        out["n_groups"][:, t_idx] = (arrs["entry_count"] > 0).sum(axis=1)
    return out


def max_entry_len(db: fpc.CompiledDB) -> int:
    out = int(hashing.GRAM_LONG)
    for table in db.tables:
        if table.entry_len.size:
            out = max(out, int(table.entry_len.max()))
    return out


def pad_streams_for_seq(streams: dict, seq_ranks: int, halo: int) -> None:
    """Widen streams IN PLACE so each seq rank's slice is at least one
    halo wide and 128-aligned — the invariant :class:`ShardedMatcher`
    enforces (narrow streams like the width-1 OOB placeholders must
    widen before seq sharding). The single shared implementation: the
    engine's encode path and the multichip dryrun both pad through
    here, so the rule cannot drift between them again."""
    import numpy as np

    from swarm_tpu.ops.encoding import round_up

    seq = max(seq_ranks, 1)
    for name, arr in streams.items():
        per_rank = max(round_up(arr.shape[1], seq) // seq, halo)
        target = round_up(per_rank, 128) * seq
        if target > arr.shape[1]:
            streams[name] = np.pad(arr, ((0, 0), (0, target - arr.shape[1])))


@dataclasses.dataclass
class ShardedMatcher:
    """Builds and caches the pjit'd sharded match step for one mesh."""

    db: fpc.CompiledDB
    mesh: Mesh
    candidate_k: int = 128

    def __post_init__(self):
        self.ranks = {name: int(self.mesh.shape[name]) for name in self.mesh.axis_names}
        self.halo = max_entry_len(self.db) if self.ranks.get("seq", 1) > 1 else 0
        # the SAME argument-pytree convention as DeviceDB
        # (docs/DEVICE_MATCH.md): per-rank stacked word tables shard
        # over 'model'; the verdict/rx/slot arrays replicate. Uploaded
        # once here, passed as jit arguments every call — the compiled
        # step is corpus-size-free on the sharded path too.
        self.meta = fpc.layout_meta(self.db)
        self._tab_np = shard_stacked_np(self.db, self.ranks.get("model", 1))
        self._rep_np = {
            "slot_bytes": self.db.slot_bytes,
            "slot_len": self.db.slot_len,
            "tiny_bytes": self.db.tiny_bytes,
            "tiny_slot": self.db.tiny_slot,
            "verdict": fpc.verdict_arrays_np(self.db),
            "rx": fpc.rx_arrays_np(self.db),
        }
        # multi-host (jax.distributed) meshes span devices this process
        # cannot address: inputs must become GLOBAL jax.Arrays (every
        # process holds the full host copy; each device takes its
        # slice) and outputs gather back host-local. Single-process
        # meshes keep the plain local-array path.
        self.multiprocess = any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat
        )
        # constant after construction — upload once, not per match call
        if self.multiprocess:
            self._tab_j = {
                k: self._global(v, P("model")) for k, v in self._tab_np.items()
            }
            self._rep_j = jax.tree_util.tree_map(
                lambda a: self._global(a, P()), self._rep_np
            )
        else:
            self._tab_j = {k: jnp.asarray(v) for k, v in self._tab_np.items()}
            self._rep_j = jax.tree_util.tree_map(jnp.asarray, self._rep_np)
        self._fn_cache: dict = {}

    def _global(self, arr, spec):
        """Host copy -> global array laid out per ``spec`` over the
        (possibly multi-process) mesh."""
        arr = np.asarray(arr)
        sharding = NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    # ------------------------------------------------------------------
    def _build(self, shape_key, full: bool = False):
        db, halo = self.db, self.halo
        meta = self.meta
        seq_ranks = self.ranks.get("seq", 1)
        candidate_k = self.candidate_k

        def step(tab, rep, streams, lengths, status):
            # --- halo exchange over 'seq' (no-op when unsharded) ---
            back = fwd = 0
            offsets = 0
            streams_ext = streams
            if seq_ranks > 1:
                seq_index = jax.lax.axis_index("seq")
                ext = {}
                offsets = {}
                for name, local in streams.items():
                    fwd_halo = jax.lax.ppermute(
                        local[:, :halo],
                        "seq",
                        [(r, r - 1) for r in range(1, seq_ranks)],
                    )
                    back_halo = jax.lax.ppermute(
                        local[:, -halo:],
                        "seq",
                        [(r, r + 1) for r in range(seq_ranks - 1)],
                    )
                    ext[name] = jnp.concatenate([back_halo, local, fwd_halo], axis=1)
                    offsets[name] = seq_index * local.shape[1]
                streams_ext = ext
                back = fwd = halo

            # --- probe with this rank's table slices (two-phase
            # argument-driven kernel, ops/match.py) ---
            arrays = {
                "tab": {k: v[0] for k, v in tab.items()},
                "slot_bytes": rep["slot_bytes"],
                "slot_len": rep["slot_len"],
                "tiny_bytes": rep["tiny_bytes"],
                "tiny_slot": rep["tiny_slot"],
            }
            value_bits, uncertain_bits, overflow = match_slots_args(
                db,
                meta,
                arrays,
                candidate_k,
                streams_ext,
                lengths,
                pos_offset=offsets,
                back_halo=back,
                fwd_halo=fwd,
            )

            # --- combine pattern-space + byte-space partial bits ---
            combine_axes = tuple(
                ax
                for ax in ("model", "seq")
                if self.ranks.get(ax, 1) > 1
            )
            if combine_axes:
                value_bits = jax.lax.psum(value_bits.astype(jnp.int32), combine_axes) > 0
                uncertain_bits = (
                    jax.lax.psum(uncertain_bits.astype(jnp.int32), combine_axes) > 0
                )
                overflow = jax.lax.psum(overflow.astype(jnp.int32), combine_axes) > 0

            # device md5 (ops/md5.py): the block chain is sequential in
            # the byte dimension, so a seq-sharded body is re-gathered
            # (tiled over ICI) just for the digest — cheap next to the
            # probe stage, and only when the corpus compares digests
            def full_stream(name):
                local = streams[name]
                if seq_ranks > 1:
                    return jax.lax.all_gather(
                        local, "seq", axis=1, tiled=True
                    )
                return local

            digest = None
            if bool(db.m_md5_check.any()) and "body" in streams:
                digest = md5_words(full_stream("body"), lengths["body"])
            # device regex verify over the combined slot bits: like md5
            # it needs whole rows, so used streams gather over 'seq'
            rx = None
            if len(db.rx_m_ids):
                from swarm_tpu.ops.encoding import STREAMS
                from swarm_tpu.ops.regexdev import regex_verify

                used = {STREAMS[int(s)] for s in db.rx_seq_stream}
                gathered = {n: full_stream(n) for n in used}
                rx = regex_verify(
                    db,
                    gathered,
                    lengths,
                    value_bits,
                    k_pairs=db.rx_k_pairs(status.shape[0]),
                    arrays=rep["rx"],
                )
            out = eval_verdicts(
                db,
                value_bits,
                uncertain_bits,
                lengths,
                status,
                full=full,
                md5_digest=digest,
                rx=rx,
                arrays=rep["verdict"],
            )
            if full:
                # pack bit planes per data-rank (axis 1 is unsharded, so
                # packed bytes concatenate cleanly over 'data') and fuse
                # them with the overflow column into ONE output array —
                # the host then makes a single device read (split_fused)
                from swarm_tpu.ops.match import fuse_planes

                return fuse_planes(out, overflow)
            return (*out, overflow)

        # jax.shard_map landed post-0.4.x; older jax ships it under
        # experimental with check_rep instead of check_vma
        try:
            smap = jax.shard_map
            smap_kwargs = {"check_vma": False}
        except AttributeError:
            from jax.experimental.shard_map import shard_map as smap

            smap_kwargs = {"check_rep": False}
        mesh = self.mesh
        stream_spec = {k: P("data", "seq") for k in shape_key["streams"]}
        tab_specs = {name: P("model") for name in self._tab_np}
        rep_specs = jax.tree_util.tree_map(lambda _a: P(), self._rep_np)
        out_specs = P("data") if full else (P("data"),) * 3
        fn = smap(
            step,
            mesh=mesh,
            in_specs=(
                tab_specs,
                rep_specs,
                stream_spec,
                {k: P("data") for k in shape_key["lengths"]},
                P("data"),
            ),
            out_specs=out_specs,
            **smap_kwargs,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------
    def match(self, streams: dict, lengths: dict, status, full: bool = False):
        from swarm_tpu.resilience.faults import fault_point

        # same fault point as DeviceDB.dispatch: "the device path
        # failed" is one failure class whichever matcher serves it
        # (MatchEngine degrades to the CPU oracle either way)
        fault_point("device.dispatch")
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks > 1:
            for name, arr in streams.items():
                per_rank = arr.shape[1] // seq_ranks
                if arr.shape[1] % seq_ranks:
                    raise ValueError(
                        f"stream {name!r} width {arr.shape[1]} not divisible "
                        f"by seq ranks {seq_ranks}"
                    )
                if per_rank < self.halo:
                    # the halo slices local[:, :halo] would silently come
                    # up short and misalign every window coordinate
                    raise ValueError(
                        f"stream {name!r}: per-rank width {per_rank} < halo "
                        f"{self.halo} (longest table entry); widen the "
                        f"stream or lower the seq factor"
                    )
        shape_key = {
            "streams": tuple(sorted((k, v.shape) for k, v in streams.items())),
            "lengths": tuple(sorted(lengths)),
        }
        cache_key = (shape_key["streams"], full)
        from swarm_tpu.ops.match import MAX_COMPILED, lru_fetch, lru_store

        fn = lru_fetch(self._fn_cache, cache_key)
        if fn is None:
            fn = self._build(
                {"streams": {k: None for k in streams}, "lengths": {k: None for k in lengths}},
                full=full,
            )
            # bound live executables like DeviceDB (shape churn would
            # grow RSS without limit — constants are captured per jit)
            lru_store(self._fn_cache, cache_key, fn, MAX_COMPILED)
        if self.multiprocess:
            args = (
                self._tab_j,
                self._rep_j,
                {k: self._global(v, P("data", "seq")) for k, v in streams.items()},
                {k: self._global(v, P("data")) for k, v in lengths.items()},
                self._global(status, P("data")),
            )
        else:
            args = (
                self._tab_j,
                self._rep_j,
                {k: jnp.asarray(v) for k, v in streams.items()},
                {k: jnp.asarray(v) for k, v in lengths.items()},
                jnp.asarray(status),
            )
        out = fn(*args)
        if self.multiprocess:
            # global -> host-local (replicated) so every process can
            # read the full result; riding DCN once per batch
            from jax.experimental import multihost_utils

            if full:
                out = multihost_utils.global_array_to_host_local_array(
                    out, self.mesh, P()
                )
            else:
                out = tuple(
                    multihost_utils.global_array_to_host_local_array(
                        o, self.mesh, P()
                    )
                    for o in out
                )
        if full:
            from swarm_tpu.ops.match import split_fused

            return split_fused(self.db, np.asarray(out))
        return out
