"""The sharded match step: dp × tp × sp over a device mesh.

This is the multi-chip SERVING path (the reference scaled by adding
droplets; this scales by sharding one batch across a TPU slice):

- **data**: rows sharded; no cross-shard traffic until result gather.
- **model**: every rank probes the same windows against its 1/R slice
  of each word table's sorted h1 range (disjoint group ranges, disjoint
  candidate sets, per-rank blooms). Slot bits combine with one
  ``psum`` over ICI — the collective cost is B × NS bits per step.
- **seq**: response bytes sharded; each rank owns the candidate windows
  starting in its slice and exchanges halos of ``max_entry_len`` bytes
  with both neighbors via ``ppermute`` (the ring/halo pattern of
  context parallelism) so words spanning shard boundaries are found by
  exactly the rank that owns their gram position.

The verdict stage runs replicated on every (model, seq) rank after the
psum — it is tiny next to the probe stage.

Production dispatch is SPLIT-PHASE with survivor compaction, the mesh
twin of ``DeviceDB.dispatch`` (docs/SHARDING.md, docs/DEVICE_MATCH.md):
a standing phase-A executable runs every rank's stacked bloom probe
into a survivor RANK plane, ``pmax``-reduces the batch's max survivor
count across the whole mesh, and the host reads back that ONE 4-byte
scalar to pick phase B's ladder width (``compile.survivor_bucket``);
phase B extracts/verifies at survivor size, psums the bit planes, and
runs the replicated verdict tail. Per-batch uploads go through the
dispatch staging pool and are DONATED to phase B together with the
inter-phase rank plane; the fused single-kernel pjit step is kept as
the bit-identical reference twin (``SWARM_SHARD_COMPACT=0`` /
``SWARM_SHARD_DONATE=0``, or the ``compact=``/``donate=`` args).
``dispatch``/``collect`` split the blocking host read out of the
launch, so the continuous-batching scheduler keeps ≥2 mesh batches in
flight exactly as on the single-device path.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swarm_tpu.fingerprints import compile as fpc
from swarm_tpu.ops import hashing
from swarm_tpu.ops.match import (
    MAX_COMPILED,
    _StagingPool,
    _StreamCtx,
    _col_starts_of,
    _env_flag,
    compact_candidates,
    eval_verdicts,
    fuse_planes,
    global_candidate_budget,
    host_batch_leaves,
    lru_fetch,
    lru_store,
    match_slots_args,
    prefilter_counts,
    split_fused,
    tiny_slot_bits,
    verify_candidates,
)
from swarm_tpu.ops.md5 import md5_words


def shard_tables_np(db: fpc.CompiledDB, ranks: int) -> list[dict]:
    """Split every table's sorted h1-group range into ``ranks`` contiguous
    slices with identical padded shapes, one pytree leaf-list per table:
    arrays get a leading [ranks] axis to shard over 'model'.

    Padding uses a sentinel h1 of 0xFFFFFFFF with zero entry counts, so
    a padded group can never be "found" twice (searchsorted may land on
    it, but count 0 yields no entries).
    """
    stacked: list[dict] = []
    for table in db.tables:
        G = table.num_groups
        g_per = max(1, -(-G // ranks))
        gmax = g_per
        emax = 1
        slices = []
        for r in range(ranks):
            lo = min(r * g_per, G)
            hi = min(lo + g_per, G)
            if hi > lo:
                e_lo = int(table.entry_start[lo])
                e_hi = int(
                    table.entry_start[hi - 1] + table.entry_count[hi - 1]
                )
            else:
                e_lo = e_hi = 0
            slices.append((lo, hi, e_lo, e_hi))
            emax = max(emax, e_hi - e_lo)
        arrs = {
            "group_h1": np.full((ranks, gmax), 0xFFFFFFFF, dtype=np.uint32),
            "entry_start": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_count": np.zeros((ranks, gmax), dtype=np.int32),
            "entry_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_slot": np.zeros((ranks, emax), dtype=np.int32),
            "entry_off": np.zeros((ranks, emax), dtype=np.int32),
            "entry_len": np.full((ranks, emax), 1 << 30, dtype=np.int32),
            "entry_suf_delta": np.zeros((ranks, emax), dtype=np.int32),
            "entry_suf_h1": np.zeros((ranks, emax), dtype=np.uint32),
            "entry_suf_h2": np.zeros((ranks, emax), dtype=np.uint32),
            "bloom": np.zeros((ranks, hashing.BLOOM_WORDS), dtype=np.uint32),
        }
        for r, (lo, hi, e_lo, e_hi) in enumerate(slices):
            n_g, n_e = hi - lo, e_hi - e_lo
            if n_g == 0:
                continue
            arrs["group_h1"][r, :n_g] = table.group_h1[lo:hi]
            arrs["entry_start"][r, :n_g] = table.entry_start[lo:hi] - e_lo
            arrs["entry_count"][r, :n_g] = table.entry_count[lo:hi]
            for name, src in (
                ("entry_h2", table.entry_h2),
                ("entry_slot", table.entry_slot),
                ("entry_off", table.entry_off),
                ("entry_len", table.entry_len),
                ("entry_suf_delta", table.entry_suf_delta),
                ("entry_suf_h1", table.entry_suf_h1),
                ("entry_suf_h2", table.entry_suf_h2),
            ):
                arrs[name][r, :n_e] = src[e_lo:e_hi]
            arrs["bloom"][r] = hashing.build_bloom_np(
                np.repeat(table.group_h1[lo:hi], table.entry_count[lo:hi]),
                table.entry_h2[e_lo:e_hi],
            )
        stacked.append(arrs)
    return stacked


def shard_stacked_np(db: fpc.CompiledDB, ranks: int) -> dict:
    """Model-sharded twin of ``compile.stack_tables_np``: one stacked
    table-major pytree per rank, with a leading [ranks] axis to shard
    over 'model'. Built on :func:`shard_tables_np` (same slicing, same
    per-rank blooms, same sentinels) and padded to rank-global
    Gmax/Emax so every rank's executable sees one shape."""
    per_table = shard_tables_np(db, ranks)
    T = len(per_table)
    if T == 0:
        base = fpc.stack_tables_np([])
        return {
            k: np.repeat(v[None], ranks, axis=0) for k, v in base.items()
        }
    gmax = max(t["group_h1"].shape[1] for t in per_table)
    emax = max(t["entry_h2"].shape[1] for t in per_table)
    out = {
        "group_h1": np.full((ranks, T, gmax), 0xFFFFFFFF, dtype=np.uint32),
        "entry_start": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_count": np.zeros((ranks, T, gmax), dtype=np.int32),
        "entry_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_slot": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_off": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_len": np.full((ranks, T, emax), 1 << 30, dtype=np.int32),
        "entry_suf_delta": np.zeros((ranks, T, emax), dtype=np.int32),
        "entry_suf_h1": np.zeros((ranks, T, emax), dtype=np.uint32),
        "entry_suf_h2": np.zeros((ranks, T, emax), dtype=np.uint32),
        "bloom": np.zeros(
            (ranks, T, hashing.BLOOM_WORDS), dtype=np.uint32
        ),
        "n_groups": np.zeros((ranks, T), dtype=np.int32),
    }
    for t_idx, arrs in enumerate(per_table):
        g = arrs["group_h1"].shape[1]
        e = arrs["entry_h2"].shape[1]
        for name in (
            "group_h1", "entry_start", "entry_count",
        ):
            out[name][:, t_idx, :g] = arrs[name]
        for name in (
            "entry_h2", "entry_slot", "entry_off", "entry_len",
            "entry_suf_delta", "entry_suf_h1", "entry_suf_h2",
        ):
            out[name][:, t_idx, :e] = arrs[name]
        out["bloom"][:, t_idx] = arrs["bloom"]
        # real (unpadded) group counts per rank — the binary-search
        # bound. Derived from the slices shard_tables_np actually
        # built (every real group has >= 1 entry, padding has 0), so
        # any future change to its slicing rule stays in lockstep.
        out["n_groups"][:, t_idx] = (arrs["entry_count"] > 0).sum(axis=1)
    return out


def max_entry_len(db: fpc.CompiledDB) -> int:
    out = int(hashing.GRAM_LONG)
    for table in db.tables:
        if table.entry_len.size:
            out = max(out, int(table.entry_len.max()))
    return out


def pad_streams_for_seq(streams: dict, seq_ranks: int, halo: int) -> None:
    """Widen streams IN PLACE so each seq rank's slice is at least one
    halo wide and 128-aligned — the invariant :class:`ShardedMatcher`
    enforces (narrow streams like the width-1 OOB placeholders must
    widen before seq sharding). The single shared implementation: the
    engine's encode path and the multichip dryrun both pad through
    here, so the rule cannot drift between them again."""
    import numpy as np

    from swarm_tpu.ops.encoding import round_up

    seq = max(seq_ranks, 1)
    for name, arr in streams.items():
        per_rank = max(round_up(arr.shape[1], seq) // seq, halo)
        target = round_up(per_rank, 128) * seq
        if target > arr.shape[1]:
            streams[name] = np.pad(arr, ((0, 0), (0, target - arr.shape[1])))


_SHARD_METRICS = None


def _shard_metrics():
    """Lazy ``swarm_shard_*`` family handle (kept out of import time so
    oracle-only users never touch the registry; the families themselves
    register at telemetry import — telemetry/shard_export.py)."""
    global _SHARD_METRICS
    if _SHARD_METRICS is None:
        from swarm_tpu.telemetry import shard_export

        _SHARD_METRICS = shard_export
    return _SHARD_METRICS


@dataclasses.dataclass
class ShardedMatcher:
    """Builds and caches the pjit'd sharded match step for one mesh.

    Serving surface (docs/SHARDING.md): :meth:`dispatch` launches the
    split-phase compacted kernels asynchronously (the only blocking
    point is the 4-byte pmax'd max-survivor scalar between phases);
    :meth:`collect` pays the one fused host read. ``MatchEngine.
    begin_packed``/``finish_packed`` route here exactly as they do to
    ``DeviceDB``, so the scheduler's in-flight budget and walk offload
    apply unchanged on the mesh. The fused single-kernel step stays as
    the bit-identical reference twin (``compact=False``), and
    ``donate=False`` keeps the staged uploads alive past the launch.
    """

    db: fpc.CompiledDB
    mesh: Mesh
    candidate_k: int = 128
    compact: Optional[bool] = None
    donate: Optional[bool] = None

    def __post_init__(self):
        if self.compact is None:
            self.compact = _env_flag("SWARM_SHARD_COMPACT", True)
        if self.donate is None:
            self.donate = _env_flag("SWARM_SHARD_DONATE", True)
        self.staging = _StagingPool()
        self.compile_seconds = 0.0  # guarded-by: _counter_lock
        self.compile_count = 0  # guarded-by: _counter_lock
        #: AOT executable-cache fetch spy (docs/AOT.md): dispatches
        #: that LOADED a published executable instead of compiling —
        #: counted distinctly so the compile spy stays honest
        self.fetch_seconds = 0.0  # guarded-by: _counter_lock
        self.fetch_count = 0  # guarded-by: _counter_lock
        self._aot = None  # AotClient (attach_aot) — None = compile-only
        #: most recent compacted dispatch: survivor_max / verify_k /
        #: budget (the "phase B launches at survivor size" evidence)
        self.last_compact: dict = {}  # guarded-by: _counter_lock
        self._counter_lock = threading.Lock()
        self.ranks = {name: int(self.mesh.shape[name]) for name in self.mesh.axis_names}
        self.halo = max_entry_len(self.db) if self.ranks.get("seq", 1) > 1 else 0
        # the SAME argument-pytree convention as DeviceDB
        # (docs/DEVICE_MATCH.md): per-rank stacked word tables shard
        # over 'model'; the verdict/rx/slot arrays replicate. Uploaded
        # once here, passed as jit arguments every call — the compiled
        # step is corpus-size-free on the sharded path too.
        self.meta = fpc.layout_meta(self.db)
        self._tab_np = shard_stacked_np(self.db, self.ranks.get("model", 1))
        self._rep_np = {
            "slot_bytes": self.db.slot_bytes,
            "slot_len": self.db.slot_len,
            "tiny_bytes": self.db.tiny_bytes,
            "tiny_slot": self.db.tiny_slot,
            "verdict": fpc.verdict_arrays_np(self.db),
            "rx": fpc.rx_arrays_np(self.db),
        }
        # multi-host (jax.distributed) meshes span devices this process
        # cannot address: inputs must become GLOBAL jax.Arrays (every
        # process holds the full host copy; each device takes its
        # slice) and outputs gather back host-local. Single-process
        # meshes keep the plain local-array path.
        self.multiprocess = any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat
        )
        # constant after construction — upload once, not per match call
        if self.multiprocess:
            self._tab_j = {
                k: self._global(v, P("model")) for k, v in self._tab_np.items()
            }
            self._rep_j = jax.tree_util.tree_map(
                lambda a: self._global(a, P()), self._rep_np
            )
        else:
            self._tab_j = {k: jnp.asarray(v) for k, v in self._tab_np.items()}
            self._rep_j = jax.tree_util.tree_map(jnp.asarray, self._rep_np)
        self._fn_cache: dict = {}  # guarded-by: _counter_lock
        for ax, size in self.ranks.items():
            _shard_metrics().MESH_AXIS.labels(axis=ax).set(size)

    def _global(self, arr, spec):
        """Host copy -> global array laid out per ``spec`` over the
        (possibly multi-process) mesh."""
        arr = np.asarray(arr)
        sharding = NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    # ------------------------------------------------------------------
    # trace-time building blocks shared by the fused twin and the
    # split-phase kernels — one implementation, so parity can't drift
    # ------------------------------------------------------------------
    def _smap(self):
        """(shard_map, kwargs) — jax.shard_map landed post-0.4.x; older
        jax ships it under experimental with check_rep instead of
        check_vma."""
        try:
            smap = jax.shard_map
            return smap, {"check_vma": False}
        except AttributeError:
            from jax.experimental.shard_map import shard_map as smap

            return smap, {"check_rep": False}

    # -- AOT executable cache (docs/AOT.md) ----------------------------
    def attach_aot(self, client) -> None:
        """Attach an :class:`~swarm_tpu.aot.AotClient` so every
        subsequently built mesh step fetches published executables
        before compiling. Multi-process meshes stay compile-only (an
        executable image is only loadable on the topology it was
        compiled for, and cross-host coordination of the load is not
        worth the coupling — the per-host persistent XLA cache already
        covers that deployment). Live wrappers drop so the attach
        takes effect at the next dispatch."""
        with self._counter_lock:
            self._aot = None if self.multiprocess else client
            self._fn_cache.clear()

    def _trace_salt(self) -> str:
        """The sharded twin of ``DeviceDB._trace_salt``: layout
        metadata + kernel statics + the MESH (axis names/sizes — a
        (2,2,2) executable must never serve an (8,1,1) worker)."""
        db = self.db
        return repr(
            (
                self.meta,
                self.candidate_k,
                tuple(sorted(self.ranks.items())),
                self.halo,
                db.num_slots,
                db.num_templates,
                int(db.op_src.shape[0]),
                int(db.m_src.shape[0]),
                int(db.rx_seq_always.sum()),
            )
        )

    def _wrap_jit(self, fun, kernel_id: str, donate_argnums=()):
        if self._aot is None:
            if donate_argnums:
                return jax.jit(fun, donate_argnums=donate_argnums)
            return jax.jit(fun)
        from swarm_tpu.aot.jitcache import AotJit

        return AotJit(
            fun,
            kernel_id=kernel_id,
            salt=self._trace_salt(),
            client=self._aot,
            donate_argnums=donate_argnums,
            cap=4 * MAX_COMPILED,
        )

    def executable_count(self) -> int:
        """Live locally-compiled executables across every cached mesh
        step (the compile spy's cache-size view; fetched loads are
        counted by :meth:`fetched_executable_count` instead)."""
        with self._counter_lock:
            fns = list(self._fn_cache.values())
        return sum(
            int(fn._cache_size())
            for fn in fns
            if hasattr(fn, "_cache_size")
        )

    def fetched_executable_count(self) -> int:
        from swarm_tpu.aot.jitcache import fetched_size_of

        with self._counter_lock:
            fns = list(self._fn_cache.values())
        return sum(fetched_size_of(fn) for fn in fns)

    def aot_prewarm(self) -> int:
        """Pool every published executable for this program group
        (bring-up fetch; see ``DeviceDB.aot_prewarm``)."""
        client = self._aot
        return client.prewarm() if client is not None else 0

    # -- corpus refresh (docs/AOT.md) ----------------------------------
    def refresh(self, db_new: fpc.CompiledDB) -> dict:
        """Zero-downtime corpus refresh on the mesh: recompute the
        per-rank stacked/replicated host pytrees and re-upload ONLY
        the leaves whose bytes changed (byte-equal leaves keep their
        existing device arrays — the rank-sharded stack is rebuilt on
        host but the ICI/H2D traffic is delta-sized). The trace
        signature decides executable retention exactly as on the
        single-device path. Caller quiesces dispatches first."""
        old_salt = self._trace_salt()
        old_tab_np, old_rep_np = self._tab_np, self._rep_np
        old_tab_j, old_rep_j = self._tab_j, self._rep_j
        self.db = db_new
        self.meta = fpc.layout_meta(db_new)
        self.halo = (
            max_entry_len(db_new) if self.ranks.get("seq", 1) > 1 else 0
        )
        self._tab_np = shard_stacked_np(
            db_new, self.ranks.get("model", 1)
        )
        self._rep_np = {
            "slot_bytes": db_new.slot_bytes,
            "slot_len": db_new.slot_len,
            "tiny_bytes": db_new.tiny_bytes,
            "tiny_slot": db_new.tiny_slot,
            "verdict": fpc.verdict_arrays_np(db_new),
            "rx": fpc.rx_arrays_np(db_new),
        }

        def upload(new_np, old_np_map, old_j_map, spec_of):
            old_host = {
                jax.tree_util.keystr(p): leaf
                for p, leaf in jax.tree_util.tree_flatten_with_path(
                    old_np_map
                )[0]
            }
            old_dev = {
                jax.tree_util.keystr(p): leaf
                for p, leaf in jax.tree_util.tree_flatten_with_path(
                    old_j_map
                )[0]
            }
            flat, _ = jax.tree_util.tree_flatten_with_path(new_np)
            out = []
            n_up = 0
            for path, leaf in flat:
                key = jax.tree_util.keystr(path)
                old = old_host.get(key)
                if (
                    key in old_dev
                    and isinstance(old, np.ndarray)
                    and old.dtype == leaf.dtype
                    and old.shape == leaf.shape
                    and (old is leaf or np.array_equal(old, leaf))
                ):
                    out.append(old_dev[key])
                else:
                    n_up += 1
                    if self.multiprocess:
                        out.append(self._global(leaf, spec_of(path)))
                    else:
                        out.append(jnp.asarray(leaf))
            return (
                jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(new_np), out
                ),
                n_up,
            )

        self._tab_j, up_tab = upload(
            self._tab_np, old_tab_np, old_tab_j, lambda _p: P("model")
        )
        self._rep_j, up_rep = upload(
            self._rep_np, old_rep_np, old_rep_j, lambda _p: P()
        )
        old_leaves = jax.tree_util.tree_leaves((old_tab_np, old_rep_np))
        new_leaves = jax.tree_util.tree_leaves(
            (self._tab_np, self._rep_np)
        )
        keep = (
            old_salt == self._trace_salt()
            and len(old_leaves) == len(new_leaves)
            and all(
                o.shape == n.shape and o.dtype == n.dtype
                for o, n in zip(old_leaves, new_leaves)
            )
        )
        with self._counter_lock:
            if not keep:
                self._fn_cache.clear()
        return {
            "uploaded_leaves": up_tab + up_rep,
            "executables_kept": keep,
        }

    def _specs(self, streams: dict, lengths: dict):
        """(tab, rep, streams, lengths) partition specs for one batch
        shape — corpus slices over 'model', replicated verdict/rx,
        rows over 'data', response bytes over 'seq'."""
        return (
            {name: P("model") for name in self._tab_np},
            jax.tree_util.tree_map(lambda _a: P(), self._rep_np),
            {k: P("data", "seq") for k in streams},
            {k: P("data") for k in lengths},
        )

    def _exchange_halos(self, streams: dict):
        """Halo exchange over 'seq' (trace-time no-op when unsharded):
        each rank borrows ``halo`` bytes from both neighbors via
        ppermute so words spanning shard boundaries verify on exactly
        the rank that owns their gram position. Returns
        ``(streams_ext, offsets, back, fwd)``."""
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks <= 1:
            return streams, 0, 0, 0
        halo = self.halo
        seq_index = jax.lax.axis_index("seq")
        ext: dict = {}
        offsets: dict = {}
        for name, local in streams.items():
            fwd_halo = jax.lax.ppermute(
                local[:, :halo],
                "seq",
                [(r, r - 1) for r in range(1, seq_ranks)],
            )
            back_halo = jax.lax.ppermute(
                local[:, -halo:],
                "seq",
                [(r, r + 1) for r in range(seq_ranks - 1)],
            )
            ext[name] = jnp.concatenate([back_halo, local, fwd_halo], axis=1)
            offsets[name] = seq_index * local.shape[1]
        return ext, offsets, halo, halo

    def _combine_finish(
        self, value_bits, uncertain_bits, overflow, streams, lengths,
        status, rep, full,
    ):
        """Shared tail of every sharded route: psum the per-rank bit
        planes over the communicating axes, then the replicated verdict
        stage (device md5, device regex verify, verdict lowering) and
        the fused-plane pack. Runs at trace time inside the step."""
        db = self.db
        seq_ranks = self.ranks.get("seq", 1)
        combine_axes = tuple(
            ax for ax in ("model", "seq") if self.ranks.get(ax, 1) > 1
        )
        if combine_axes:
            value_bits = (
                jax.lax.psum(value_bits.astype(jnp.int32), combine_axes) > 0
            )
            uncertain_bits = (
                jax.lax.psum(uncertain_bits.astype(jnp.int32), combine_axes)
                > 0
            )
            overflow = (
                jax.lax.psum(overflow.astype(jnp.int32), combine_axes) > 0
            )

        # device md5 (ops/md5.py): the block chain is sequential in
        # the byte dimension, so a seq-sharded body is re-gathered
        # (tiled over ICI) just for the digest — cheap next to the
        # probe stage, and only when the corpus compares digests
        def full_stream(name):
            local = streams[name]
            if seq_ranks > 1:
                return jax.lax.all_gather(local, "seq", axis=1, tiled=True)
            return local

        digest = None
        if bool(db.m_md5_check.any()) and "body" in streams:
            digest = md5_words(full_stream("body"), lengths["body"])
        # device regex verify over the combined slot bits: like md5
        # it needs whole rows, so used streams gather over 'seq'
        rx = None
        if len(db.rx_m_ids):
            from swarm_tpu.ops.encoding import STREAMS
            from swarm_tpu.ops.regexdev import regex_verify

            used = {STREAMS[int(s)] for s in db.rx_seq_stream}
            gathered = {n: full_stream(n) for n in used}
            rx = regex_verify(
                db,
                gathered,
                lengths,
                value_bits,
                k_pairs=db.rx_k_pairs(status.shape[0]),
                arrays=rep["rx"],
            )
        out = eval_verdicts(
            db,
            value_bits,
            uncertain_bits,
            lengths,
            status,
            full=full,
            md5_digest=digest,
            rx=rx,
            arrays=rep["verdict"],
        )
        if full:
            # pack bit planes per data-rank (axis 1 is unsharded, so
            # packed bytes concatenate cleanly over 'data') and fuse
            # them with the overflow column into ONE output array —
            # the host then makes a single device read (split_fused)
            return fuse_planes(out, overflow)
        return (*out, overflow)

    # ------------------------------------------------------------------
    # executable builders (one per batch shape, LRU-bounded)
    # ------------------------------------------------------------------
    def _build_fused(self, streams: dict, lengths: dict, full: bool):
        """The fused single-kernel pjit step — the legacy reference
        twin (``compact=False``, or a corpus with no word tables)."""
        db = self.db
        meta = self.meta
        candidate_k = self.candidate_k

        # jit-captures: self, db, meta, candidate_k, full (host metadata
        # + scalars — trace-static; the corpus rides the tab/rep
        # ARGUMENTS, never the closure)
        def step(tab, rep, streams, lengths, status):
            streams_ext, offsets, back, fwd = self._exchange_halos(streams)
            arrays = {
                "tab": {k: v[0] for k, v in tab.items()},
                "slot_bytes": rep["slot_bytes"],
                "slot_len": rep["slot_len"],
                "tiny_bytes": rep["tiny_bytes"],
                "tiny_slot": rep["tiny_slot"],
            }
            value_bits, uncertain_bits, overflow = match_slots_args(
                db,
                meta,
                arrays,
                candidate_k,
                streams_ext,
                lengths,
                pos_offset=offsets,
                back_halo=back,
                fwd_halo=fwd,
            )
            return self._combine_finish(
                value_bits, uncertain_bits, overflow, streams, lengths,
                status, rep, full,
            )

        smap, smap_kwargs = self._smap()
        tab_specs, rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        out_specs = P("data") if full else (P("data"),) * 3
        fn = smap(
            step,
            mesh=self.mesh,
            in_specs=(
                tab_specs, rep_specs, stream_spec, lengths_spec, P("data"),
            ),
            out_specs=out_specs,
            **smap_kwargs,
        )
        return self._wrap_jit(fn, f"sh.fused.full={full}")

    def _build_phase_a(self, streams: dict, lengths: dict):
        """Standing sharded phase A: per-rank stacked bloom probe →
        survivor RANK plane + per-rank overflow + the globally
        ``pmax``'d max survivor count (the ONE scalar the host reads
        between phases). The rank plane and overflow keep an explicit
        leading (model, seq) axis — every rank's candidate space is
        distinct, and phase B slices its own plane back out."""
        meta = self.meta
        budget = global_candidate_budget(
            self.candidate_k, len(meta.table_stream)
        )

        # jit-captures: self, meta, budget (layout metadata + a python
        # int; both trace-static)
        def step_a(tab, streams, lengths):
            streams_ext, offsets, back, fwd = self._exchange_halos(streams)
            ctx = _StreamCtx(streams_ext, lengths, offsets)
            cnt, _cs = prefilter_counts(
                meta, {k: v[0] for k, v in tab.items()}, ctx, back, fwd
            )
            n_surv = cnt[:, -1]
            K = max(1, min(budget, cnt.shape[1]))
            overflow = n_surv > K
            nmax = jnp.max(jnp.minimum(n_surv, K))
            # global max across the whole mesh: rows over 'data', each
            # rank's own candidate space over 'model'/'seq' — the host
            # reads ONE replicated scalar however the mesh factors
            nmax = jax.lax.pmax(nmax, tuple(self.mesh.axis_names))
            return cnt[None], overflow[None], nmax

        smap, smap_kwargs = self._smap()
        tab_specs, _rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        rank_spec = P(("model", "seq"), "data")
        fn = smap(
            step_a,
            mesh=self.mesh,
            in_specs=(tab_specs, stream_spec, lengths_spec),
            out_specs=(rank_spec, rank_spec, P()),
            **smap_kwargs,
        )
        return self._wrap_jit(fn, "sh.A")

    def _build_phase_b(
        self, streams: dict, lengths: dict, kc: int, full: bool,
        donate_streams: bool,
    ):
        """Sharded phase B at the static ladder rung ``kc``: per-rank
        survivor extraction from the phase-A rank plane, gather-verify
        + tiny at survivor size, psum, and the replicated verdict tail.
        The staged per-batch uploads and the inter-phase rank plane are
        DONATED so XLA reuses their buffers (``donate_streams=False``
        — caller-owned device inputs — still donates the rank plane,
        which this matcher owns)."""
        db = self.db
        meta = self.meta
        budget = global_candidate_budget(
            self.candidate_k, len(meta.table_stream)
        )

        # jit-captures: self, db, meta, budget, kc, full (metadata and
        # scalars only — kc is the ladder rung this executable serves)
        def step_b(tab, rep, streams, lengths, status, cnt_r, ovf_r):
            streams_ext, offsets, back, fwd = self._exchange_halos(streams)
            ctx = _StreamCtx(streams_ext, lengths, offsets)
            tabr = {k: v[0] for k, v in tab.items()}
            cnt = cnt_r[0]
            overflow = ovf_r[0]
            K = max(1, min(budget, cnt.shape[1]))
            col = compact_candidates(cnt, kc, K)
            # candidate axis = LOCAL window coordinates (pre-halo
            # widths), exactly what prefilter_counts concatenated
            col_starts = _col_starts_of(meta, streams)
            value_bits, uncertain_bits = verify_candidates(
                meta,
                tabr,
                rep["slot_bytes"],
                rep["slot_len"],
                ctx,
                col,
                col_starts,
                db.num_slots,
                back,
                fwd,
            )
            value_bits = tiny_slot_bits(
                meta, rep["tiny_bytes"], rep["tiny_slot"], ctx, value_bits,
                back,
            )
            return self._combine_finish(
                value_bits, uncertain_bits, overflow, streams, lengths,
                status, rep, full,
            )

        smap, smap_kwargs = self._smap()
        tab_specs, rep_specs, stream_spec, lengths_spec = self._specs(
            streams, lengths
        )
        rank_spec = P(("model", "seq"), "data")
        out_specs = P("data") if full else (P("data"),) * 3
        fn = smap(
            step_b,
            mesh=self.mesh,
            in_specs=(
                tab_specs, rep_specs, stream_spec, lengths_spec, P("data"),
                rank_spec, rank_spec,
            ),
            out_specs=out_specs,
            **smap_kwargs,
        )
        donate = (
            (2, 3, 4, 5, 6) if donate_streams else (5, 6)
        )  # streams, lengths, status, cnt, overflow | cnt, overflow
        # kc rides the kernel id (it is baked into the step closure
        # here, not a static argnum) so every ladder rung publishes
        # its own artifact
        return self._wrap_jit(
            fn, f"sh.B.kc={kc}.full={full}", donate_argnums=donate
        )

    # ------------------------------------------------------------------
    def _get_fn(self, key, builder):
        """(fn, freshly_built) from the LRU-bounded executable cache.
        Ladder rungs multiply the live entries (one pjit per
        (shape, kc) pair), hence the same generous 4x churn bound
        DeviceDB applies to its jit caches. Runs under
        ``_counter_lock``: with the walk offload armed, the submit
        thread (dispatch) and the walk worker (a degraded batch's
        sync-path retry) can reach this cache concurrently, and
        ``lru_fetch``'s refresh pops/reinserts — an unlocked race
        could evict the same key twice or compile twin wrappers.
        Building the wrapper under the lock is cheap (jit/shard_map
        construction only; XLA compiles at first call)."""
        with self._counter_lock:
            fn = lru_fetch(self._fn_cache, key)
            fresh = fn is None
            if fresh:
                fn = builder()
                lru_store(self._fn_cache, key, fn, 4 * MAX_COMPILED)
        return fn, fresh

    def _check_seq_widths(self, streams: dict) -> None:
        seq_ranks = self.ranks.get("seq", 1)
        if seq_ranks <= 1:
            return
        for name, arr in streams.items():
            per_rank = arr.shape[1] // seq_ranks
            if arr.shape[1] % seq_ranks:
                raise ValueError(
                    f"stream {name!r} width {arr.shape[1]} not divisible "
                    f"by seq ranks {seq_ranks}"
                )
            if per_rank < self.halo:
                # the halo slices local[:, :halo] would silently come
                # up short and misalign every window coordinate
                raise ValueError(
                    f"stream {name!r}: per-rank width {per_rank} < halo "
                    f"{self.halo} (longest table entry); widen the "
                    f"stream or lower the seq factor"
                )

    def _stage(self, streams: dict, lengths: dict, status):
        """Upload one batch through the dispatch staging pool: always a
        COPY (plain ``jnp.asarray`` single-process, global jax.Arrays
        spanning the mesh multi-process), so phase-B donation can never
        corrupt caller-owned numpy — the engine's recycled encode
        planes keep rotating untouched."""
        if not self.multiprocess:
            s_j, l_j, st_j, _staged = self.staging.stage(
                streams, lengths, status
            )
            return s_j, l_j, st_j
        s_j = {
            k: self._global(v, P("data", "seq")) for k, v in streams.items()
        }
        l_j = {k: self._global(v, P("data")) for k, v in lengths.items()}
        st_j = self._global(status, P("data"))
        self.staging.account(
            int(
                sum(getattr(v, "nbytes", 0) for v in streams.values())
                + sum(getattr(v, "nbytes", 0) for v in lengths.values())
                + int(getattr(status, "nbytes", 0))
            )
        )
        return s_j, l_j, st_j

    def _note_launch(self, launches, t0: float) -> None:
        """Compile/fetch accounting at the dispatch boundary (same
        contract as DeviceDB's spy: wall time of dispatches that made
        at least one new executable servable, attributed to the
        compile or the AOT-fetch pair by what the freshly built
        wrappers actually did — a deserialized load is NOT a
        compile). ``launches`` = [(fn, freshly_built), ...]; the
        wrappers have been CALLED by the time this runs."""
        from swarm_tpu.aot.jitcache import fetched_size_of

        fresh_fns = [fn for fn, fresh in launches if fresh]
        if not fresh_fns:
            return
        compiled = sum(
            int(fn._cache_size())
            for fn in fresh_fns
            if hasattr(fn, "_cache_size")
        )
        fetched = sum(fetched_size_of(fn) for fn in fresh_fns)
        dt = time.perf_counter() - t0
        with self._counter_lock:
            if fetched:
                self.fetch_seconds += dt
                self.fetch_count += 1
            if compiled:
                self.compile_seconds += dt
                self.compile_count += 1

    def _dispatch_metrics(self, streams: dict, halo_exchanges: int = 1) -> None:
        m = _shard_metrics()
        m.SHARD_DISPATCHES.inc(1)
        B = int(next(iter(streams.values())).shape[0])
        ns = max(self.db.num_slots, 1)
        if any(self.ranks.get(ax, 1) > 1 for ax in ("model", "seq")):
            # value + uncertain + overflow int32 lanes entering the
            # cross-rank psum (docs/SHARDING.md: B × NS bits per step)
            m.PSUM_BYTES.inc(B * (2 * ns + 1) * 4)
        if self.ranks.get("seq", 1) > 1:
            # the split-phase path pays the exchange in BOTH phases
            # (each kernel re-derives its extended stream views rather
            # than shipping [B, W+2h] buffers across the phase
            # boundary), so the counter charges every ppermute round
            m.HALO_BYTES.inc(
                halo_exchanges * 2 * self.halo * B * len(streams)
            )

    # ------------------------------------------------------------------
    def dispatch(self, streams: dict, lengths: dict, status, full: bool = True):
        """Async half of :meth:`match`: stage the batch, launch the
        sharded kernel(s), and return the (device-resident, still-
        computing) output WITHOUT a full host transfer — the
        continuous-batching scheduler dispatches batch i+1 here before
        walking batch i's verdicts; :meth:`collect` finalizes.

        On the compacted path the only blocking point is the phase-A
        max-survivor scalar read (4 bytes, ``pmax``'d across the whole
        mesh) that picks phase B's ladder width."""
        from swarm_tpu.resilience.faults import fault_point

        # same fault point as DeviceDB.dispatch: "the device path
        # failed" is one failure class whichever matcher serves it
        # (MatchEngine degrades to the CPU oracle either way)
        fault_point("device.dispatch")
        self._check_seq_widths(streams)
        skey = tuple(sorted((k, v.shape) for k, v in streams.items()))
        lkey = tuple(sorted(lengths))
        t0 = time.perf_counter()
        s_j, l_j, st_j = self._stage(streams, lengths, status)
        if not (self.compact and len(self.meta.table_stream)):
            # fused legacy/reference arm (also the no-tables corpus,
            # where there is nothing to compact)
            fn, fresh = self._get_fn(
                ("fused", skey, lkey, full),
                lambda: self._build_fused(streams, lengths, full),
            )
            out = fn(self._tab_j, self._rep_j, s_j, l_j, st_j)
            self._note_launch([(fn, fresh)], t0)
            self._dispatch_metrics(streams)
            return out

        donate_streams = self.donate and host_batch_leaves(
            streams, lengths, status
        )
        fa, fresh_a = self._get_fn(
            ("A", skey, lkey), lambda: self._build_phase_a(streams, lengths)
        )
        cnt, ovf, nmax = fa(self._tab_j, s_j, l_j)
        # the ONE host sync between phases: the globally pmax'd
        # survivor scalar that sizes phase B to live work — the second
        # blessed 4-byte sync (tools/swarmlint jit-hygiene contract)
        n_live = int(nmax)  # host-sync-ok: the blessed sharded 4-byte phase-A survivor scalar
        budget = global_candidate_budget(
            self.candidate_k, len(self.meta.table_stream)
        )
        kc = fpc.survivor_bucket(n_live, budget)
        fb, fresh_b = self._get_fn(
            ("B", skey, lkey, kc, full, donate_streams),
            lambda: self._build_phase_b(
                streams, lengths, kc, full, donate_streams
            ),
        )
        out = fb(self._tab_j, self._rep_j, s_j, l_j, st_j, cnt, ovf)
        self._note_launch([(fa, fresh_a), (fb, fresh_b)], t0)
        with self._counter_lock:
            self.last_compact = {
                "survivor_max": n_live,
                "verify_k": kc,
                "budget": budget,
            }
        m = _shard_metrics()
        m.SURVIVOR_MAX.set(n_live)
        self._dispatch_metrics(streams, halo_exchanges=2)
        return out

    def collect(self, out):
        """Blocking half of the full-mode split: one host read of the
        fused plane array (gathered host-local over DCN first on
        multi-process meshes), sliced into the engine's six outputs."""
        if self.multiprocess:
            from jax.experimental import multihost_utils

            out = multihost_utils.global_array_to_host_local_array(
                out, self.mesh, P()
            )
        return split_fused(self.db, np.asarray(out))

    # ------------------------------------------------------------------
    def match(self, streams: dict, lengths: dict, status, full: bool = False):
        """Synchronous convenience: :meth:`dispatch` + the blocking
        read. ``full=False`` returns the (t_value, t_unc, overflow)
        device tuple exactly as before the split."""
        out = self.dispatch(streams, lengths, status, full=full)
        if full:
            return self.collect(out)
        if self.multiprocess:
            # global -> host-local (replicated) so every process can
            # read the full result; riding DCN once per batch
            from jax.experimental import multihost_utils

            out = tuple(
                multihost_utils.global_array_to_host_local_array(
                    o, self.mesh, P()
                )
                for o in out
            )
        return out
