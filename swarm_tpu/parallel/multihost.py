"""Multi-host initialization: DCN process group for multi-slice runs.

The reference scales out by adding droplets that poll over HTTP
(``server/server.py:47-162``) — control plane over commodity DCN. The
TPU analog keeps that HTTP control plane untouched and adds, for a
worker that spans multiple TPU hosts, the JAX distributed runtime:
``jax.distributed.initialize`` connects the hosts so one
``jax.sharding.Mesh`` can span every chip in the slice, with XLA
placing collectives on ICI within a host/slice and DCN across hosts.

Opt-in via environment (nothing happens on single-host workers):

    SWARM_COORDINATOR=host:port   the rank-0 worker's address
    SWARM_NUM_PROCESSES=N         total participating worker processes
    SWARM_PROCESS_ID=K            this worker's rank (0-based)

The standard JAX cluster-autodetect environments (GKE/Cloud TPU pod
metadata) also work — when the SWARM_* triplet is absent but
``jax.distributed`` can autodetect, pass ``autodetect=True``.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional


def maybe_initialize_distributed(
    env: Optional[Mapping[str, str]] = None,
    autodetect: bool = False,
) -> bool:
    """Initialize the JAX multi-host runtime when configured.

    Returns True when ``jax.distributed.initialize`` was called (so
    ``jax.devices()`` now spans all hosts), False when running
    single-host. Safe to call more than once — a second call with the
    runtime already up is a no-op returning True.
    """
    env = os.environ if env is None else env
    coord = env.get("SWARM_COORDINATOR", "")
    nproc = env.get("SWARM_NUM_PROCESSES", "")
    pid = env.get("SWARM_PROCESS_ID", "")

    import jax

    state = getattr(jax._src.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return True  # already initialized

    configured = [bool(coord), bool(nproc), bool(pid)]
    if any(configured) and not all(configured):
        # a partial triplet silently running single-host would leave the
        # other hosts blocked at the coordinator barrier — fail loudly
        raise ValueError(
            "multi-host config incomplete: SWARM_COORDINATOR, "
            "SWARM_NUM_PROCESSES and SWARM_PROCESS_ID must all be set "
            f"(got coordinator={coord!r}, num_processes={nproc!r}, "
            f"process_id={pid!r})"
        )
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid),
        )
        return True
    if autodetect:
        try:
            jax.distributed.initialize()
            return True
        except Exception:
            return False
    return False
