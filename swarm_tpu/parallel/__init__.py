"""Multi-chip sharding: mesh construction and the sharded match step."""
