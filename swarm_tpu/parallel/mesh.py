"""Device mesh construction for the match workload.

Axes (the scan-workload analogs of ML parallelism, SURVEY.md §2.4):

- ``data``  — target rows (the reference's chunk-per-worker data
  parallelism, now a sharded batch axis; perfect scaling, results
  gathered per shard).
- ``model`` — hash-table groups (pattern-space parallelism: every rank
  probes the same windows against its 1/R slice of each table's sorted
  h1 range; slot bits OR-combine with one psum over ICI).
- ``seq``   — response byte axis (context parallelism for long bodies:
  each rank scans its byte slice with a ppermute halo exchange of the
  longest-pattern overlap — the ring-attention analog).

Pipeline/expert axes have no analog here (no layered weights, no
experts) — the reference likewise has nothing to shard (SURVEY.md §2.4).

Topology awareness: the communicating axes (``model``'s psum,
``seq``'s ppermute ring) should each ride ONE physical ICI axis of the
slice, not straddle the torus. ``make_mesh`` therefore reads the slice
shape — from each device's ``.coords`` (real TPU runtimes expose the
physical mesh coordinate) or the ``SWARM_SLICE_SHAPE`` env hint (e.g.
``"4x2x2"``, for simulated/CPU meshes) — and lays devices out so every
mesh axis is a contiguous physical axis (or a product of whole axes,
for ``data``, which never communicates). Without topology information
the previous pure-arithmetic split is the fallback.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

AXES = ("data", "model", "seq")


def factor_devices(n: int) -> tuple[int, int, int]:
    """Split n devices into (data, model, seq) — favor data, then
    model. The topology-blind fallback (no coords, no env hint)."""
    if n <= 1:
        return (1, 1, 1)
    seq = 2 if n % 2 == 0 and n >= 8 else 1
    rem = n // seq
    model = 2 if rem % 2 == 0 and rem >= 4 else 1
    data = rem // model
    return (data, model, seq)


def slice_layout(
    phys: tuple[int, ...]
) -> tuple[tuple[int, int, int], tuple[int, ...]]:
    """Map a physical slice shape onto the (data, model, seq) mesh.

    Returns ``(mesh_shape, axis_perm)``: ``axis_perm`` orders the
    physical axes as (data..., model, seq) so that a transpose+reshape
    of the coordinate-ordered device grid keeps each communicating
    mesh axis on ONE physical ICI axis.

    Policy: the two *smallest* >1 physical axes carry the
    communicating meshes — ``model`` (psum, the heavier collective)
    gets the larger of the two, ``seq`` the smaller — and everything
    else multiplies into ``data`` (no communication, so straddling
    axes is free). Examples: v4-8 slice (2,2,1) → (2, 2, 1);
    v4-32 (4,2,2) → (4, 2, 2); v5e-16 (4,4) → (4, 4, 1).
    """
    dims = [(d, i) for i, d in enumerate(phys)]
    nontrivial = sorted((d, i) for d, i in dims if d > 1)
    model = seq = None
    if len(nontrivial) >= 3:
        # two smallest carry comm; model = the larger of those two
        seq = nontrivial[0]
        model = nontrivial[1]
    elif len(nontrivial) == 2:
        model = nontrivial[0]
    elif len(nontrivial) == 1:
        # a 1-D slice: everything is one ring; keep it all data
        pass
    data_axes = [
        i for _d, i in dims
        if (model is None or i != model[1]) and (seq is None or i != seq[1])
    ]
    perm = tuple(
        data_axes
        + ([model[1]] if model else [])
        + ([seq[1]] if seq else [])
    )
    data = 1
    for i in data_axes:
        data *= phys[i]
    shape = (data, model[0] if model else 1, seq[0] if seq else 1)
    return shape, perm


def _env_slice_shape() -> Optional[tuple[int, ...]]:
    raw = os.environ.get("SWARM_SLICE_SHAPE", "").strip().lower()
    if not raw:
        return None
    try:
        dims = tuple(int(p) for p in raw.replace("*", "x").split("x"))
    except ValueError:
        return None
    return dims if dims and all(d >= 1 for d in dims) else None


def detect_slice_shape(devices: Sequence) -> Optional[tuple[int, ...]]:
    """Physical slice shape: env hint first, else device ``.coords``
    (present on real TPU devices). None when neither is available or
    the information doesn't cover exactly these devices."""
    env = _env_slice_shape()
    if env is not None:
        n = 1
        for d in env:
            n *= d
        return env if n == len(devices) else None
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is None for c in coords):
        return None
    arr = np.asarray(coords)
    if arr.ndim != 2:
        return None
    shape = tuple(int(m) + 1 for m in arr.max(axis=0))
    n = 1
    for d in shape:
        n *= d
    # coords must tile the box exactly once (multi-core-per-chip
    # runtimes repeat coords; that layout needs the env hint instead)
    if n != len(devices) or len({tuple(c) for c in coords}) != n:
        return None
    return shape


def _grid_order(devices: Sequence, phys: tuple[int, ...]) -> list:
    """Devices ordered so reshaping to ``phys`` aligns with physical
    coordinates (row-major over coords when present, else given
    order)."""
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is None for c in coords):
        return list(devices)
    pairs = zip([tuple(c) for c in coords], devices)
    return [d for _c, d in sorted(pairs, key=lambda t: t[0])]


def make_mesh(
    shape: Optional[tuple[int, int, int]] = None,
    devices: Optional[Sequence] = None,
):
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        phys = detect_slice_shape(devices)
        if phys is not None:
            mesh_shape, perm = slice_layout(phys)
            grid = np.array(
                _grid_order(devices, phys), dtype=object
            ).reshape(phys)
            arr = np.ascontiguousarray(grid.transpose(perm)).reshape(
                mesh_shape
            )
            return Mesh(arr, AXES)
        shape = factor_devices(len(devices))
    data, model, seq = shape
    count = data * model * seq
    if count > len(devices):
        raise ValueError(f"mesh {shape} needs {count} devices, have {len(devices)}")
    arr = np.array(devices[:count]).reshape(data, model, seq)
    return Mesh(arr, AXES)
