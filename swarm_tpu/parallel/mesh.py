"""Device mesh construction for the match workload.

Axes (the scan-workload analogs of ML parallelism, SURVEY.md §2.4):

- ``data``  — target rows (the reference's chunk-per-worker data
  parallelism, now a sharded batch axis; perfect scaling, results
  gathered per shard).
- ``model`` — hash-table groups (pattern-space parallelism: every rank
  probes the same windows against its 1/R slice of each table's sorted
  h1 range; slot bits OR-combine with one psum over ICI).
- ``seq``   — response byte axis (context parallelism for long bodies:
  each rank scans its byte slice with a ppermute halo exchange of the
  longest-pattern overlap — the ring-attention analog).

Pipeline/expert axes have no analog here (no layered weights, no
experts) — the reference likewise has nothing to shard (SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "model", "seq")


def factor_devices(n: int) -> tuple[int, int, int]:
    """Split n devices into (data, model, seq) — favor data, then model."""
    if n <= 1:
        return (1, 1, 1)
    seq = 2 if n % 2 == 0 and n >= 8 else 1
    rem = n // seq
    model = 2 if rem % 2 == 0 and rem >= 4 else 1
    data = rem // model
    return (data, model, seq)


def make_mesh(
    shape: Optional[tuple[int, int, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = factor_devices(len(devices))
    data, model, seq = shape
    count = data * model * seq
    if count > len(devices):
        raise ValueError(f"mesh {shape} needs {count} devices, have {len(devices)}")
    arr = np.array(devices[:count]).reshape(data, model, seq)
    return Mesh(arr, AXES)
