"""State, blob and document stores behind narrow interfaces.

The reference wires three external services directly into route handlers:
Redis for queue/state (``server/server.py:41``), S3 for chunk blobs
(``server/server.py:45``), MongoDB for durable summaries
(``server/server.py:43``). This module keeps those *roles* — and the
exact key layouts, so the data plane is wire-compatible — behind three
small interfaces with embedded default implementations (thread-safe,
zero external dependencies) plus optional adapters for the real services
when their client libraries are importable.

Embedded defaults matter for the TPU deployment story: a single-host TPU
worker fleet should not need a Redis/Mongo/S3 side-car to run a scan.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from pathlib import Path
from typing import Any, Iterable, Optional


# ---------------------------------------------------------------------------
# State store (Redis-role): hashes + lists, the five ops the server uses.
# ---------------------------------------------------------------------------


class StateStore:
    """Subset of Redis semantics used by the control plane.

    Key names carried over verbatim from the reference so a real Redis
    populated by this server is indistinguishable on the wire: ``jobs`` /
    ``workers`` hashes, ``job_queue`` / ``completed`` lists
    (``server/server.py:207,214,326,475``).
    """

    def hset(self, name: str, key: str, value: str) -> None:
        raise NotImplementedError

    def hget(self, name: str, key: str) -> Optional[str]:
        raise NotImplementedError

    def hmget(self, name: str, keys: list[str]) -> list[Optional[str]]:
        """Batched hget — ONE wire round trip on backends that support
        it (the result-cache tier's lookup path, docs/CACHING.md). The
        default loops hget, so adapters only override for speed."""
        return [self.hget(name, k) for k in keys]

    def hset_many(self, name: str, mapping: dict[str, str]) -> None:
        """Batched hset — ONE wire round trip on backends that support
        it (the result-cache tier's writeback path: a walked plane's
        worth of entries must not cost one RTT per row). The default
        loops hset, so adapters only override for speed."""
        for key, value in mapping.items():
            self.hset(name, key, value)

    def hincr(self, name: str, key: str, by: int = 1) -> int:
        """Atomically add ``by`` to an integer hash field (missing = 0)
        and return the new value — the fencing-token counter and epoch
        generation of the result-cache tier (docs/CACHING.md). Must be
        atomic WITHIN the backend (Redis HINCRBY; the embedded store's
        read-modify-write runs under its lock)."""
        raise NotImplementedError

    def hkeys(self, name: str) -> list[str]:
        raise NotImplementedError

    def hgetall(self, name: str) -> dict[str, str]:
        raise NotImplementedError

    def hdel(self, name: str, key: str) -> None:
        raise NotImplementedError

    def rpush(self, name: str, value: str) -> None:
        raise NotImplementedError

    def lpush(self, name: str, value: str) -> None:
        raise NotImplementedError

    def lclear(self, name: str) -> None:
        """Drop one list wholesale (Redis ``DEL``). Journal recovery
        (docs/DURABILITY.md) REBUILDS every dispatch list from the
        replayed job records; on a backend whose state survived the
        restart (real Redis) the stale lists must be cleared first or
        the rebuild would double-push every queued job."""
        raise NotImplementedError

    def lpop(self, name: str) -> Optional[str]:
        raise NotImplementedError

    def lrange(self, name: str, start: int, stop: int) -> list[str]:
        raise NotImplementedError

    def llen(self, name: str) -> int:
        raise NotImplementedError

    def flushall(self) -> None:
        raise NotImplementedError


class MemoryStateStore(StateStore):
    """Embedded thread-safe state store (hashes + lists)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()  # guards: _hashes (reads), _lists (reads)
        self._hashes: dict[str, dict[str, str]] = {}
        self._lists: dict[str, deque[str]] = {}

    def hset(self, name, key, value):
        # chaos lever (docs/RESILIENCE.md): a failing state-store write
        # surfaces as a 500 from whatever route attempted it
        from swarm_tpu.resilience.faults import fault_point

        fault_point("store.hset", detail=name)
        with self._lock:
            self._hashes.setdefault(name, {})[key] = value

    def hget(self, name, key):
        with self._lock:
            return self._hashes.get(name, {}).get(key)

    def hmget(self, name, keys):
        with self._lock:
            h = self._hashes.get(name, {})
            return [h.get(k) for k in keys]

    def hset_many(self, name, mapping):
        with self._lock:
            self._hashes.setdefault(name, {}).update(mapping)

    def hincr(self, name, key, by=1):
        with self._lock:
            h = self._hashes.setdefault(name, {})
            value = int(h.get(key, "0")) + int(by)
            h[key] = str(value)
            return value

    def hkeys(self, name):
        with self._lock:
            return list(self._hashes.get(name, {}).keys())

    def hgetall(self, name):
        with self._lock:
            return dict(self._hashes.get(name, {}))

    def hdel(self, name, key):
        with self._lock:
            self._hashes.get(name, {}).pop(key, None)

    def rpush(self, name, value):
        with self._lock:
            self._lists.setdefault(name, deque()).append(value)

    def lpush(self, name, value):
        with self._lock:
            self._lists.setdefault(name, deque()).appendleft(value)

    def lpop(self, name):
        with self._lock:
            q = self._lists.get(name)
            return q.popleft() if q else None

    def lclear(self, name):
        with self._lock:
            self._lists.pop(name, None)

    def lrange(self, name, start, stop):
        with self._lock:
            items = list(self._lists.get(name, ()))
        if stop == -1:
            return items[start:]
        return items[start : stop + 1]

    def llen(self, name):
        with self._lock:
            return len(self._lists.get(name, ()))

    def flushall(self):
        with self._lock:
            self._hashes.clear()
            self._lists.clear()


class RedisStateStore(StateStore):
    """Adapter over a real Redis (requires the ``redis`` package)."""

    def __init__(self, url: str) -> None:
        import redis  # gated: not part of the baked image

        self._r = redis.Redis.from_url(url)

    @staticmethod
    def _d(value: Optional[bytes]) -> Optional[str]:
        return value.decode() if value is not None else None

    def hset(self, name, key, value):
        self._r.hset(name, key, value)

    def hget(self, name, key):
        return self._d(self._r.hget(name, key))

    def hmget(self, name, keys):
        return [self._d(v) for v in self._r.hmget(name, keys)]

    def hset_many(self, name, mapping):
        self._r.hset(name, mapping=mapping)

    def hincr(self, name, key, by=1):
        return int(self._r.hincrby(name, key, by))

    def hkeys(self, name):
        return [k.decode() for k in self._r.hkeys(name)]

    def hgetall(self, name):
        return {k.decode(): v.decode() for k, v in self._r.hgetall(name).items()}

    def hdel(self, name, key):
        self._r.hdel(name, key)

    def rpush(self, name, value):
        self._r.rpush(name, value)

    def lpush(self, name, value):
        self._r.lpush(name, value)

    def lpop(self, name):
        return self._d(self._r.lpop(name))

    def lclear(self, name):
        self._r.delete(name)

    def lrange(self, name, start, stop):
        return [v.decode() for v in self._r.lrange(name, start, stop)]

    def llen(self, name):
        return self._r.llen(name)

    def flushall(self):
        self._r.flushall()


class LocalStateStore(StateStore):
    """File-backed state store: one JSON document per key name under a
    directory, every read-modify-write serialized by an ``fcntl``
    file lock, so MULTIPLE PROCESSES on one host share state with zero
    side-cars. Built for the AOT executable cache's no-sidecar fleet
    mode and the bench's fresh-process cold-start A/B (docs/AOT.md) —
    the hot control plane should still prefer Memory (in-process) or
    Redis (multi-host): every op here costs a file open + lock.

    ``hincr`` is atomic across processes (read-modify-write under the
    exclusive lock), which is what the fencing-token counter and epoch
    generation need.
    """

    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self._lockfile = self._root / ".lock"
        self._thread_lock = threading.Lock()

    def _path(self, name: str) -> Path:
        # flat namespace, filesystem-safe: hex-escape anything outside
        # [A-Za-z0-9._-] so "swarm:aot:x:…" can't traverse or collide
        safe = "".join(
            c if c.isalnum() or c in "._-" else f"%{ord(c):02x}"
            for c in name
        )
        return self._root / (safe + ".json")

    class _Locked:
        def __init__(self, store: "LocalStateStore"):
            self._store = store
            self._fh = None

        def __enter__(self):
            import fcntl

            self._store._thread_lock.acquire()
            try:
                self._fh = open(self._store._lockfile, "a+")
                fcntl.flock(self._fh, fcntl.LOCK_EX)
            except BaseException:
                # a failed open/flock (fd exhaustion, removed root)
                # must release the thread lock — __exit__ never runs
                # when __enter__ raises, and a stuck lock would hang
                # every later store op in the process instead of
                # letting the caller's breaker degrade
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
                self._store._thread_lock.release()
                raise
            return self

        def __exit__(self, *exc):
            import fcntl

            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()
            self._store._thread_lock.release()
            return False

    def _load(self, name: str) -> dict:
        try:
            return json.loads(self._path(name).read_text())
        except (OSError, ValueError):
            return {"h": {}, "l": []}

    def _save(self, name: str, doc: dict) -> None:
        p = self._path(name)
        tmp = p.with_name(p.name + f".tmp-{os.getpid()}")
        tmp.write_text(json.dumps(doc, separators=(",", ":")))
        os.replace(tmp, p)  # crash-atomic, same as LocalBlobStore.put

    def hset(self, name, key, value):
        from swarm_tpu.resilience.faults import fault_point

        fault_point("store.hset", detail=name)
        with self._Locked(self):
            doc = self._load(name)
            doc["h"][key] = value
            self._save(name, doc)

    def hget(self, name, key):
        with self._Locked(self):
            return self._load(name)["h"].get(key)

    def hmget(self, name, keys):
        with self._Locked(self):
            h = self._load(name)["h"]
            return [h.get(k) for k in keys]

    def hset_many(self, name, mapping):
        with self._Locked(self):
            doc = self._load(name)
            doc["h"].update(mapping)
            self._save(name, doc)

    def hincr(self, name, key, by=1):
        with self._Locked(self):
            doc = self._load(name)
            value = int(doc["h"].get(key, "0")) + int(by)
            doc["h"][key] = str(value)
            self._save(name, doc)
            return value

    def hkeys(self, name):
        with self._Locked(self):
            return list(self._load(name)["h"].keys())

    def hgetall(self, name):
        with self._Locked(self):
            return dict(self._load(name)["h"])

    def hdel(self, name, key):
        with self._Locked(self):
            doc = self._load(name)
            if key in doc["h"]:
                del doc["h"][key]
                self._save(name, doc)

    def rpush(self, name, value):
        with self._Locked(self):
            doc = self._load(name)
            doc["l"].append(value)
            self._save(name, doc)

    def lpush(self, name, value):
        with self._Locked(self):
            doc = self._load(name)
            doc["l"].insert(0, value)
            self._save(name, doc)

    def lpop(self, name):
        with self._Locked(self):
            doc = self._load(name)
            if not doc["l"]:
                return None
            value = doc["l"].pop(0)
            self._save(name, doc)
            return value

    def lclear(self, name):
        with self._Locked(self):
            doc = self._load(name)
            if doc["l"]:
                doc["l"] = []
                self._save(name, doc)

    def lrange(self, name, start, stop):
        with self._Locked(self):
            items = list(self._load(name)["l"])
        if stop == -1:
            return items[start:]
        return items[start : stop + 1]

    def llen(self, name):
        with self._Locked(self):
            return len(self._load(name)["l"])

    def flushall(self):
        with self._Locked(self):
            for p in self._root.glob("*.json"):
                if ".tmp-" not in p.name:
                    p.unlink(missing_ok=True)


# ---------------------------------------------------------------------------
# Blob store (S3-role): chunk input/output files.
# ---------------------------------------------------------------------------


class BlobStore:
    """Key layout matches the reference S3 bucket:
    ``{scan_id}/input/chunk_{i}.txt`` and ``{scan_id}/output/chunk_{i}.txt``
    (``server/server.py:446``, ``worker/worker.py:71,96``).
    """

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Remove one blob (missing keys are a no-op — journal
        compaction and reset may race a crash-recovery's leftovers)."""
        raise NotImplementedError

    def delete_all(self) -> None:
        raise NotImplementedError


class LocalBlobStore(BlobStore):
    """Directory-backed blob store (the embedded default)."""

    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> Path:
        p = (self._root / key).resolve()
        if not p.is_relative_to(self._root.resolve()):
            raise ValueError(f"blob key escapes store root: {key!r}")
        return p

    def put(self, key, data):
        from swarm_tpu.resilience.faults import fault_point

        fault_point("store.blob_put", detail=key)
        p = self._path(key)
        with self._lock:
            p.parent.mkdir(parents=True, exist_ok=True)
            # crash-atomic (docs/DURABILITY.md): a kill -9 mid-write
            # must never leave a truncated chunk or journal segment —
            # recovery reconciles "output blob present ⇒ job complete",
            # so a half blob would become a half result. Same-directory
            # temp + rename is atomic on POSIX.
            tmp = p.with_name(p.name + f".tmp-{os.getpid()}")
            tmp.write_bytes(data)
            os.replace(tmp, p)

    def get(self, key):
        return self._path(key).read_bytes()

    def exists(self, key):
        return self._path(key).is_file()

    def delete(self, key):
        try:
            self._path(key).unlink()
        except FileNotFoundError:
            pass

    def list(self, prefix):
        root = self._root.resolve()
        # Walk only the deepest existing directory implied by the prefix,
        # then string-filter the remainder — not the whole store.
        if not prefix or prefix.endswith("/"):
            base_dir = (root / prefix).resolve()
        else:
            base_dir = (root / prefix).resolve().parent
        if not base_dir.is_relative_to(root):
            base_dir = root
        if not base_dir.is_dir():
            return []
        out = []
        for p in base_dir.rglob("*"):
            if p.is_file() and ".tmp-" not in p.name:
                # in-flight atomic-put temp files are not blobs: a
                # racing list must never hand a half-written key to
                # raw_scan or journal replay
                rel = p.relative_to(root).as_posix()
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def delete_all(self):
        import shutil

        with self._lock:
            shutil.rmtree(self._root, ignore_errors=True)
            self._root.mkdir(parents=True, exist_ok=True)


class MemoryBlobStore(BlobStore):
    """In-memory blob store for tests."""

    def __init__(self) -> None:
        self._blobs: dict[str, bytes] = {}  # guarded-by: _lock (reads)
        self._lock = threading.Lock()

    def put(self, key, data):
        with self._lock:
            self._blobs[key] = bytes(data)

    def get(self, key):
        with self._lock:
            if key not in self._blobs:
                raise KeyError(key)
            return self._blobs[key]

    def exists(self, key):
        with self._lock:
            return key in self._blobs

    def list(self, prefix):
        with self._lock:
            return sorted(k for k in self._blobs if k.startswith(prefix))

    def delete(self, key):
        with self._lock:
            self._blobs.pop(key, None)  # blocking-ok: _blobs is the embedded store's own dict — this IS the O(1) store primitive

    def delete_all(self):
        with self._lock:
            self._blobs.clear()  # blocking-ok: embedded store primitive — in-memory dict clear under its own lock


class S3BlobStore(BlobStore):
    """Adapter over real S3 (requires ``boto3``)."""

    def __init__(self, bucket: str, **client_kwargs: Any) -> None:
        import boto3  # gated

        self._bucket = bucket
        self._s3 = boto3.client("s3", **client_kwargs)

    def put(self, key, data):
        self._s3.put_object(Bucket=self._bucket, Key=key, Body=data)

    def get(self, key):
        return self._s3.get_object(Bucket=self._bucket, Key=key)["Body"].read()

    def exists(self, key):
        try:
            self._s3.head_object(Bucket=self._bucket, Key=key)
            return True
        except Exception:
            return False

    def list(self, prefix):
        paginator = self._s3.get_paginator("list_objects_v2")
        keys: list[str] = []
        for page in paginator.paginate(Bucket=self._bucket, Prefix=prefix):
            keys.extend(o["Key"] for o in page.get("Contents", []))
        return sorted(keys)

    def delete(self, key):
        self._s3.delete_object(Bucket=self._bucket, Key=key)

    def delete_all(self):
        raise NotImplementedError("refusing to wipe a real bucket")


# ---------------------------------------------------------------------------
# Document store (Mongo-role): scan summaries + parsed chunks.
# ---------------------------------------------------------------------------


class DocCollection:
    def insert_one(self, doc: dict) -> None:
        raise NotImplementedError

    def find_one(self, query: dict) -> Optional[dict]:
        raise NotImplementedError

    def find(self, query: Optional[dict] = None) -> list[dict]:
        raise NotImplementedError


class DocStore:
    """Collection names carried from the reference ``asm`` database:
    ``scans`` summaries (``server/server.py:277-294``), per-scan parsed
    collections (``server/server.py:393``), ``jobs`` (``server/server.py:367``).
    """

    def collection(self, name: str) -> DocCollection:
        raise NotImplementedError

    def drop_all(self) -> None:
        raise NotImplementedError


class _MemoryCollection(DocCollection):
    def __init__(self) -> None:
        self._docs: list[dict] = []  # guarded-by: _lock (reads)
        self._lock = threading.Lock()

    @staticmethod
    def _matches(doc: dict, query: Optional[dict]) -> bool:
        return not query or all(doc.get(k) == v for k, v in query.items())

    def insert_one(self, doc):
        with self._lock:
            self._docs.append(dict(doc))  # blocking-ok: _docs is the embedded collection's own list — this IS the store primitive

    def find_one(self, query):
        with self._lock:
            for doc in self._docs:
                if self._matches(doc, query):
                    return dict(doc)
        return None

    def find(self, query=None):
        with self._lock:
            return [dict(d) for d in self._docs if self._matches(d, query)]


class MemoryDocStore(DocStore):
    def __init__(self) -> None:
        self._collections: dict[str, _MemoryCollection] = {}  # guarded-by: _lock (reads)
        self._lock = threading.Lock()

    def collection(self, name):
        with self._lock:
            return self._collections.setdefault(name, _MemoryCollection())

    def drop_all(self):
        with self._lock:
            self._collections.clear()


class _JsonlCollection(DocCollection):
    """Append-only JSONL file per collection — durable embedded docs."""

    def __init__(self, path: Path) -> None:
        self._path = path
        self._lock = threading.Lock()

    def insert_one(self, doc):
        with self._lock:
            self._path.parent.mkdir(parents=True, exist_ok=True)
            with self._path.open("a") as f:
                f.write(json.dumps(doc) + "\n")

    def _iter(self) -> Iterable[dict]:
        if not self._path.is_file():
            return
        with self._path.open() as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)

    def find_one(self, query):
        with self._lock:
            for doc in self._iter():
                if _MemoryCollection._matches(doc, query):
                    return doc
        return None

    def find(self, query=None):
        with self._lock:
            return [d for d in self._iter() if _MemoryCollection._matches(d, query)]


class LocalDocStore(DocStore):
    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        self._lock = threading.Lock()
        self._collections: dict[str, _JsonlCollection] = {}  # guarded-by: _lock (reads)

    def collection(self, name):
        safe = name.replace("/", "_")
        # Cache per name so all callers share one file lock.
        with self._lock:
            coll = self._collections.get(safe)
            if coll is None:
                coll = self._collections[safe] = _JsonlCollection(
                    self._root / f"{safe}.jsonl"
                )
            return coll

    def drop_all(self):
        import shutil

        with self._lock:
            self._collections.clear()
            shutil.rmtree(self._root, ignore_errors=True)


class _MongoCollection(DocCollection):
    """Conforms pymongo's Cursor/ObjectId behavior to the DocCollection
    contract: find() returns a list of plain dicts, insert_one does not
    mutate the caller's document."""

    def __init__(self, coll) -> None:
        self._coll = coll

    @staticmethod
    def _strip(doc: Optional[dict]) -> Optional[dict]:
        if doc is not None:
            doc = dict(doc)
            doc.pop("_id", None)
        return doc

    def insert_one(self, doc):
        self._coll.insert_one(dict(doc))

    def find_one(self, query):
        return self._strip(self._coll.find_one(query))

    def find(self, query=None):
        return [self._strip(d) for d in self._coll.find(query or {})]


class MongoDocStore(DocStore):
    """Adapter over real MongoDB (requires ``pymongo``)."""

    def __init__(self, url: str, db: str) -> None:
        import pymongo  # gated

        self._db = pymongo.MongoClient(url)[db]

    def collection(self, name):
        return _MongoCollection(self._db[name])

    def drop_all(self):
        raise NotImplementedError("refusing to drop a real database")


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------


def build_stores(cfg) -> tuple[StateStore, BlobStore, DocStore]:
    """Construct the three stores from a :class:`swarm_tpu.config.Config`."""
    if cfg.state_backend == "redis":
        state: StateStore = RedisStateStore(cfg.redis_url)
    else:
        state = MemoryStateStore()

    if cfg.blob_backend == "s3":
        blobs: BlobStore = S3BlobStore(cfg.s3_bucket)
    elif cfg.blob_backend == "memory":
        blobs = MemoryBlobStore()
    else:
        blobs = LocalBlobStore(cfg.blob_root)

    if cfg.doc_backend == "mongo":
        docs: DocStore = MongoDocStore(cfg.mongo_url, cfg.mongo_db)
    elif cfg.doc_backend == "memory":
        docs = MemoryDocStore()
    else:
        docs = LocalDocStore(cfg.doc_root)
    return state, blobs, docs
