"""Corpus-delta fan-out to monitor services (docs/MONITORING.md
§Out-of-cadence re-evaluation).

``MatchEngine.refresh_corpus`` calls :func:`notify_corpus_delta` after
re-binding the delta-compiled corpus; every registered
:class:`~swarm_tpu.monitor.service.MonitorService` responds with a
JOURNALED due-now touch (``put_monitor`` with ``next_fire_at = 0.0``)
so its next normal ``tick()`` fires one immediate diff epoch per
standing spec — under the same admission, shed and journal discipline
as a cadence fire. Nothing fires from inside the notification itself:
the touch only makes specs DUE, which is the whole crash contract —
kill-9 between notify and fire recovers a journaled spec that is
merely due, fired once, late, by the next server's first tick.

The registry holds weak references so a stopped or garbage-collected
service just disappears; notification never keeps a server alive, and
an engine refreshing its corpus in a process with no monitor service
(a worker, a bench) notifies nobody at zero cost.
"""

from __future__ import annotations

import threading
import traceback
import weakref
from typing import Optional

_LOCK = threading.Lock()  # guards: _LISTENERS
_LISTENERS: list = []  # weakref.ref entries to on_corpus_delta carriers


def register(listener) -> None:
    """Idempotently register ``listener`` — any object exposing
    ``on_corpus_delta(digest)`` — by weak reference."""
    with _LOCK:
        alive = [r for r in _LISTENERS if r() is not None]
        if not any(r() is listener for r in alive):
            alive.append(weakref.ref(listener))
        _LISTENERS[:] = alive


def unregister(listener) -> None:
    with _LOCK:
        _LISTENERS[:] = [
            r for r in _LISTENERS
            if r() is not None and r() is not listener
        ]


def notify_corpus_delta(digest: Optional[str] = None) -> int:
    """Fan a corpus delta out to every live listener; returns the
    number notified. Per-listener errors are printed and swallowed — a
    broken monitor service must degrade that service, never the
    engine's corpus refresh."""
    with _LOCK:
        targets = [r() for r in _LISTENERS]
        _LISTENERS[:] = [r for r in _LISTENERS if r() is not None]
    notified = 0
    for target in targets:
        if target is None:
            continue
        try:
            target.on_corpus_delta(digest)
            notified += 1
        except Exception:
            traceback.print_exc()
    return notified
