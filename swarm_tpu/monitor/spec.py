"""Monitor spec model: the durable unit of the monitoring control
plane (docs/MONITORING.md §Spec model).

A spec is a plain wire dict everywhere it moves — journal records,
state-store hash entries, HTTP bodies — and a :class:`MonitorSpec`
dataclass wherever code reasons about it. The wire form follows the
``Job`` discipline: unknown keys are ignored on read, absent keys get
defaults, so specs journaled by an older server replay cleanly on a
newer one.

Cadence state (``epoch``, ``next_fire_at``, ``last_scan_id``,
``refire``) lives ON the spec rather than beside it so a single
journal record captures both the schedule and its progress — kill-9
recovery reads one hash and knows exactly which epoch fired last and
when the next one is owed.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Optional

from swarm_tpu.datamodel import SCAN_ID_RE

#: monitor ids are a strict subset of scan-id grammar (no dots) so the
#: derived epoch scan id ``<monitor_id>.e<epoch>_<ts>`` still matches
#: SCAN_ID_RE and ``parse_scan_id`` splits its timestamp cleanly
MONITOR_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")

#: floor on the rescan cadence — protects the queue from a zero/negative
#: interval turning a monitor into a tight submission loop
MIN_INTERVAL_S = 0.05


@dataclasses.dataclass
class MonitorSpec:
    """One standing rescan: WHAT to scan (module + targets), AS WHOM
    (tenant + qos), HOW OFTEN (interval), plus journaled cadence
    progress. ``targets`` are raw target lines exactly as a one-shot
    ``POST /queue-scan`` file body would carry them."""

    monitor_id: str
    module: str
    targets: list
    interval_s: float
    tenant: str = "default"
    qos: Optional[str] = None  # None = bulk, the standing-workload default
    batch_size: int = 0  # 0 = server default, same contract as submissions
    paused: bool = False
    created_at: float = 0.0
    # --- cadence progress (mutated only through the journal) ---
    epoch: int = 0  # last epoch FIRED (0 = never)
    next_fire_at: float = 0.0  # 0 = due immediately
    last_scan_id: Optional[str] = None
    # set by recovery when the last epoch was journaled but its scan
    # never materialized (kill-9 between append and fire): the next
    # tick re-fires the SAME epoch under the SAME scan id, once, late
    refire: bool = False

    # ------------------------------------------------------------------
    def validate(self) -> Optional[str]:
        """Problem description, or None when the spec is well-formed."""
        if not MONITOR_ID_RE.match(self.monitor_id or ""):
            return "monitor_id must match [A-Za-z0-9_-]{1,64}"
        if not self.module or not SCAN_ID_RE.match(self.module):
            return "module is required"
        if not isinstance(self.targets, list) or not self.targets:
            return "targets must be a non-empty list"
        if not all(isinstance(t, str) for t in self.targets):
            return "targets must be strings"
        if not isinstance(self.interval_s, (int, float)) or (
            self.interval_s < MIN_INTERVAL_S
        ):
            return f"interval_s must be >= {MIN_INTERVAL_S}"
        if self.batch_size < 0:
            return "batch_size must be >= 0"
        return None

    def scan_id_for(self, epoch: int, now: Optional[float] = None) -> str:
        """Deterministic-per-fire scan id: ``<id>.e<epoch>_<ts>``.
        Recovery re-fires use the JOURNALED id (``last_scan_id``), not
        a fresh one, so a re-fired epoch lands on the same blobs."""
        ts = int(now if now is not None else time.time())
        return f"{self.monitor_id}.e{epoch}_{ts}"

    def due(self, now: float) -> bool:
        return (not self.paused) and now >= self.next_fire_at

    # --- wire round trip (journal / state store / HTTP) ---------------
    def to_wire(self) -> dict:
        return {
            "monitor_id": self.monitor_id,
            "module": self.module,
            "targets": list(self.targets),
            "interval_s": float(self.interval_s),
            "tenant": self.tenant,
            "qos": self.qos,
            "batch_size": int(self.batch_size),
            "paused": bool(self.paused),
            "created_at": float(self.created_at),
            "epoch": int(self.epoch),
            "next_fire_at": float(self.next_fire_at),
            "last_scan_id": self.last_scan_id,
            "refire": bool(self.refire),
        }

    @classmethod
    def from_wire(cls, data: dict) -> "MonitorSpec":
        """Lenient read: unknown keys ignored, absent keys defaulted —
        the same forward/backward tolerance as ``Job.from_wire``."""
        return cls(
            monitor_id=str(data.get("monitor_id") or ""),
            module=str(data.get("module") or ""),
            targets=list(data.get("targets") or []),
            interval_s=float(data.get("interval_s") or 0.0),
            tenant=str(data.get("tenant") or "default"),
            qos=data.get("qos") or None,
            batch_size=int(data.get("batch_size") or 0),
            paused=bool(data.get("paused")),
            created_at=float(data.get("created_at") or 0.0),
            epoch=int(data.get("epoch") or 0),
            next_fire_at=float(data.get("next_fire_at") or 0.0),
            last_scan_id=data.get("last_scan_id") or None,
            refire=bool(data.get("refire")),
        )
