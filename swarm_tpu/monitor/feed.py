"""Durable change feed: record blobs, epoch marks, resumable NDJSON
streaming (docs/MONITORING.md §Feed resume contract).

Diff records are ordinary blobs —
``_monitor/<id>/feed/e<epoch:08>.<idx:06>.json`` — so the feed rides
whatever durability the blob store already has: a server restart loses
nothing, and ``GET /monitor-feed`` resume is "re-list, skip the first
N keys", the same shape as scan-output streaming.

Each completed epoch also writes a MARK blob
(``_monitor/<id>/mark/e<epoch:08>.json``) *after* its records and its
plane update. The mark is the commit point: an epoch with records but
no mark was interrupted and will be re-run — deterministically, so the
re-run rewrites byte-identical record blobs (no duplicates, no gaps in
``seq``). Zero-change epochs write only the mark, which is how cadence
progress stays observable on an unchanged fleet.

Record ``seq`` equals the record's position in the key-sorted feed
(epochs zero-padded so string order is epoch order), which makes the
cursor trivially stable across disconnects AND restarts.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Iterator, Optional

#: blob-key namespace; underscore prefix keeps it disjoint from scan-id
#: keys (SCAN_ID_RE admits no leading context, but scan blob keys start
#: with the scan id, which cannot begin a ``_monitor/`` path)
FEED_PREFIX = "_monitor"


def feed_prefix(monitor_id: str) -> str:
    return f"{FEED_PREFIX}/{monitor_id}/feed/"


def record_key(monitor_id: str, epoch: int, idx: int) -> str:
    return f"{feed_prefix(monitor_id)}e{epoch:08d}.{idx:06d}.json"


def mark_key(monitor_id: str, epoch: int) -> str:
    return f"{FEED_PREFIX}/{monitor_id}/mark/e{epoch:08d}.json"


def _key_epoch(monitor_id: str, key: str) -> Optional[int]:
    name = key[len(feed_prefix(monitor_id)):]
    try:
        return int(name[1:9])
    except (ValueError, IndexError):
        return None


# ----------------------------------------------------------------------
def epoch_marked(blobs, monitor_id: str, epoch: int) -> bool:
    return blobs.exists(mark_key(monitor_id, epoch))


def marked_epochs(blobs, monitor_id: str) -> list:
    out = []
    prefix = f"{FEED_PREFIX}/{monitor_id}/mark/"
    for key in blobs.list(prefix):
        name = key[len(prefix):]
        try:
            out.append(int(name[1:9]))
        except (ValueError, IndexError):
            continue
    return sorted(out)


def seq_base(blobs, monitor_id: str, epoch: int) -> int:
    """Records in epochs strictly before ``epoch`` — the first seq of
    this epoch. Counting by epoch (not raw blob count) keeps a re-run
    of a crash-interrupted epoch at the same base even when some of its
    own record blobs already landed."""
    n = 0
    for key in blobs.list(feed_prefix(monitor_id)):
        ep = _key_epoch(monitor_id, key)
        if ep is not None and ep < epoch:
            n += 1
    return n


def feed_records(
    blobs, monitor_id: str, marked_only: bool = False
) -> list:
    """All feed records, oldest first. ``marked_only`` restricts to
    completed epochs — the form plane rebuilds fold over."""
    marks = set(marked_epochs(blobs, monitor_id)) if marked_only else None
    out = []
    for key in blobs.list(feed_prefix(monitor_id)):
        if marks is not None:
            ep = _key_epoch(monitor_id, key)
            if ep is None or ep not in marks:
                continue
        try:
            out.append(json.loads(blobs.get(key)))
        except (FileNotFoundError, KeyError, ValueError):
            continue
    return out


def write_records(blobs, monitor_id: str, records) -> None:
    """Persist one epoch's record blobs (idempotent: deterministic
    content under deterministic keys — a re-run overwrites with the
    same bytes)."""
    from swarm_tpu.monitor.diff import encode_record

    for idx, rec in enumerate(records):
        blobs.put(
            record_key(monitor_id, int(rec["epoch"]), idx), encode_record(rec)
        )


def write_mark(
    blobs, monitor_id: str, epoch: int, n_records: int, scan_id: str
) -> None:
    """Commit the epoch. Callers MUST order: records → plane → mark."""
    blobs.put(
        mark_key(monitor_id, epoch),
        json.dumps(
            {"epoch": epoch, "records": n_records, "scan_id": scan_id},
            separators=(",", ":"),
        ).encode("utf-8"),
    )


# ----------------------------------------------------------------------
def stream_feed(
    blobs,
    monitor_id: str,
    from_seq: int = 0,
    poll_s: float = 0.1,
    idle_timeout_s: float = 300.0,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    alive: Optional[Callable[[], bool]] = None,
) -> Iterator[bytes]:
    """Ordered NDJSON over the feed from cursor ``from_seq``, then
    long-poll for more — the monitor twin of ``gateway.streaming
    .stream_scan``. Every data line is a stored record verbatim; the
    terminal control line is either ``{"event":"timeout","next_seq":N}``
    (idle too long — reconnect with ``?from=N`` to resume losslessly)
    or ``{"event":"end","next_seq":N}`` (the monitor was removed and
    the feed is fully drained). A feed has no natural end otherwise:
    standing monitors emit forever."""
    cursor = max(0, int(from_seq))
    last_progress = clock()
    while True:
        keys = blobs.list(feed_prefix(monitor_id))
        if cursor < len(keys):
            progressed = False
            for key in keys[cursor:]:
                try:
                    raw = blobs.get(key)
                except (FileNotFoundError, KeyError):
                    break  # racing writer: re-list and retry
                yield raw if raw.endswith(b"\n") else raw + b"\n"
                cursor += 1
                progressed = True
            if progressed:
                last_progress = clock()
                continue
        if alive is not None and not alive():
            yield (
                json.dumps(
                    {"event": "end", "next_seq": cursor},
                    separators=(",", ":"),
                ).encode("utf-8")
                + b"\n"
            )
            return
        if clock() - last_progress >= idle_timeout_s:
            yield (
                json.dumps(
                    {"event": "timeout", "next_seq": cursor},
                    separators=(",", ":"),
                ).encode("utf-8")
                + b"\n"
            )
            return
        sleep(poll_s)
