"""Monitor ticker: cadence firing, epoch completion tracking, diff
dispatch (docs/MONITORING.md §Epoch lifecycle).

Ownership split: DURABLE state (the spec registry with its cadence
progress) is queue-owned and journaled — ``JobQueueService`` holds the
``put_monitor``/``fire_monitor_epoch`` mutations. This service owns
only the VOLATILE loop around it: a daemon ticker that fires due specs
tenant-fairly through the server's admission callback, watches fired
epochs for completion, and runs the diff → feed → plane → mark
pipeline when they finish. Everything here can die with the process;
``start()`` reconstructs it all from the journal-recovered specs and
the blob-store feed.

Firing discipline (the no-double-fire contract):

- a spec is due when ``now >= next_fire_at``; firing sets
  ``next_fire_at = now + interval`` (never ``+= k*interval``), so a
  monitor that slept through N intervals fires ONCE, late;
- the epoch advance is journaled before any job exists
  (``fire_monitor_epoch``), so kill-9 leaves either a fired epoch
  (recovery resumes its diff) or a journaled-but-unfired one (recovery
  flags ``refire``; the next tick re-fires the SAME epoch under the
  SAME scan id — once, late, onto the same blobs);
- a shed admission fires nothing and advances nothing: the spec stays
  due and retries next tick, rate-limited like any submission.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Optional

from swarm_tpu.datamodel import JobStatus, chunk_generator, chunk_output_key
from swarm_tpu.monitor import feed as monitor_feed
from swarm_tpu.monitor import notify as monitor_notify
from swarm_tpu.monitor.diff import (
    MonitorPlaneStore,
    diff_epoch,
    extract_verdicts,
    plane_from_records,
)
from swarm_tpu.monitor.spec import MonitorSpec
from swarm_tpu.telemetry.events import emit_event
from swarm_tpu.telemetry.monitor_export import (
    MONITOR_DIFF_RECORDS,
    MONITOR_EPOCHS,
    MONITOR_RESCAN_HIT_RATIO,
)


class MonitorService:
    """One per server process. ``submit`` is the server's epoch-submit
    callback: admission + per-target cache lookup + journaled fire,
    returning ``{"chunks": n, "cached_chunks": k}`` or None on shed."""

    def __init__(
        self,
        queue,
        cfg,
        submit: Callable[[MonitorSpec, str, int], Optional[dict]],
        tier=None,
        clock: Callable[[], float] = time.time,
    ):
        self._queue = queue
        self._cfg = cfg
        self._submit = submit
        self._clock = clock
        self._plane = MonitorPlaneStore(
            tier, writer_id=getattr(cfg, "worker_id", None) or "server"
        )
        self._lock = threading.Lock()  # guards: _pending, _tenant_cursor
        # serializes whole tick()/drain() passes: the ticker thread and
        # a test/bench driving the service directly must not both read
        # the same due spec and fire it twice under different scan ids
        self._pass_lock = threading.Lock()  # guards: (tick/drain pass exclusion)
        # monitor_id -> {"epoch","scan_id","n_chunks","cached_chunks"} for
        # fired epochs whose diff has not been committed (mark absent)
        self._pending: dict = {}
        self._tenant_cursor = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self, run_thread: bool = True) -> None:
        """Reconcile recovered state, then (optionally) spawn the
        ticker thread. Tests and the bench drive ``tick``/``drain``
        directly with ``run_thread=False``."""
        self._reconcile()
        # corpus-delta subscription: a live engine's refresh_corpus in
        # this process turns into a journaled due-now touch (below)
        monitor_notify.register(self)
        if run_thread and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="monitor-ticker", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        monitor_notify.unregister(self)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------------
    # corpus-delta out-of-cadence re-evaluation
    # ------------------------------------------------------------------
    def on_corpus_delta(self, digest: Optional[str] = None) -> int:
        """A corpus refresh can change any template's verdict, so every
        unpaused standing spec is affected: persist a due-now touch
        (``next_fire_at = 0.0``) through the journaled ``put_monitor``
        path, and the next normal ``tick()`` fires one immediate diff
        epoch per spec under the usual admission/shed/journal
        discipline — the fire itself restores the cadence
        (``next_fire_at = now + interval``), so one delta costs one
        epoch, not a faster schedule.

        Nothing fires here. The touch being DURABLE before any fire is
        the crash contract: kill-9 between notify and fire recovers a
        spec that is merely due — the next server's first tick fires
        it once, late, exactly like a missed cadence. Returns the
        number of specs touched."""
        now = self._clock()
        touched = 0
        for spec in self.list_specs():
            if spec.paused or spec.due(now):
                continue  # paused stays parked; already-due fires anyway
            wire = spec.to_wire()
            wire["next_fire_at"] = 0.0
            self._queue.put_monitor(wire)
            touched += 1
            emit_event(
                "monitor.corpus_delta_touch",
                monitor_id=spec.monitor_id,
                tenant=spec.tenant,
                corpus_digest=digest,
            )
        return touched

    def _run(self) -> None:
        tick_s = max(0.01, float(getattr(self._cfg, "monitor_tick_s", 0.25)))
        while not self._stop.wait(tick_s):
            try:
                self.tick()
                self.drain()
            except Exception:
                # the ticker must outlive any single bad epoch — a
                # monitor bug degrades that monitor, never the server
                traceback.print_exc()

    def _reconcile(self) -> None:
        """Post-recovery bootstrap: every spec whose last epoch has no
        mark is either pending (scan exists — resume its diff) or a
        dead fire (no scan materialized — recovery already flagged
        ``refire``, nothing to track here)."""
        for spec in self.list_specs():
            if spec.epoch <= 0 or spec.last_scan_id is None or spec.refire:
                continue
            if monitor_feed.epoch_marked(
                self._queue.blobs, spec.monitor_id, spec.epoch
            ):
                continue
            n_chunks = sum(
                1 for _ in chunk_generator(list(spec.targets), spec.batch_size)
            )
            with self._lock:
                self._pending[spec.monitor_id] = {
                    "epoch": spec.epoch,
                    "scan_id": spec.last_scan_id,
                    "n_chunks": n_chunks,
                    "cached_chunks": 0,
                }

    # ------------------------------------------------------------------
    # spec registry views
    # ------------------------------------------------------------------
    def list_specs(self) -> list:
        return [
            MonitorSpec.from_wire(w) for w in self._queue.list_monitors()
        ]

    # ------------------------------------------------------------------
    # firing
    # ------------------------------------------------------------------
    # blocking-ok: the pass lock exists to serialize whole firing passes
    # (ticker thread vs direct tick callers) — holding it across the
    # journaled fire IS the exclusion this service needs
    def tick(self, now: Optional[float] = None) -> int:
        """Fire every due spec, tenant-fairly: due specs are grouped by
        tenant and fired round-robin across tenants from a rotating
        cursor, so one tenant's thousand monitors cannot starve another
        tenant's one when a backlog of due epochs drains."""
        with self._pass_lock:
            return self._tick_locked(
                self._clock() if now is None else now
            )

    def _tick_locked(self, now: float) -> int:
        due = [s for s in self.list_specs() if s.due(now)]
        if not due:
            return 0
        by_tenant: dict = {}
        for spec in due:
            by_tenant.setdefault(spec.tenant, []).append(spec)
        tenants = sorted(by_tenant)
        with self._lock:
            start = self._tenant_cursor % len(tenants)
            self._tenant_cursor += 1
        ordered: list = []
        lanes = [by_tenant[t] for t in tenants[start:] + tenants[:start]]
        while any(lanes):
            for lane in lanes:
                if lane:
                    ordered.append(lane.pop(0))
        fired = 0
        for spec in ordered:
            with self._lock:
                if spec.monitor_id in self._pending:
                    continue  # prior epoch's diff still in flight
            if self._fire(spec, now):
                fired += 1
        return fired

    def _fire(self, spec: MonitorSpec, now: float) -> bool:
        if spec.refire and spec.last_scan_id:
            # re-fire the journaled-but-unfired epoch under its
            # journaled identity: once, late, same blobs
            epoch, scan_id = spec.epoch, spec.last_scan_id
        else:
            epoch, scan_id = spec.epoch + 1, spec.scan_id_for(spec.epoch + 1, now)
        result = self._submit(spec, scan_id, epoch)
        if result is None:
            return False  # shed: still due, retries next tick
        MONITOR_EPOCHS.inc()
        with self._lock:
            self._pending[spec.monitor_id] = {
                "epoch": epoch,
                "scan_id": scan_id,
                "n_chunks": int(result.get("chunks") or 0),
                "cached_chunks": int(result.get("cached_chunks") or 0),
            }
        emit_event(
            "monitor.epoch_fired",
            monitor_id=spec.monitor_id,
            epoch=epoch,
            scan_id=scan_id,
            tenant=spec.tenant,
            chunks=result.get("chunks"),
            cached_chunks=result.get("cached_chunks"),
        )
        return True

    # ------------------------------------------------------------------
    # diffing
    # ------------------------------------------------------------------
    # blocking-ok: same pass-exclusion story as tick — two concurrent
    # drains would commit the same epoch twice (idempotent but wasteful)
    def drain(self) -> int:
        """Run the diff pipeline for every fired epoch whose scan has
        reached a terminal state. Returns committed epoch count."""
        with self._pass_lock:
            return self._drain_locked()

    def _drain_locked(self) -> int:
        with self._lock:
            pending = dict(self._pending)
        done = 0
        for monitor_id, entry in pending.items():
            spec_wire = self._queue.get_monitor(monitor_id)
            if spec_wire is None:
                with self._lock:
                    self._pending.pop(monitor_id, None)
                continue
            outputs = self._epoch_outputs(entry)
            if outputs is None:
                continue  # still running
            self._commit_epoch(
                MonitorSpec.from_wire(spec_wire), entry, outputs
            )
            with self._lock:
                self._pending.pop(monitor_id, None)
            done += 1
        return done

    def _epoch_outputs(self, entry: dict) -> Optional[dict]:
        """Chunk offset → output bytes once every chunk is terminal;
        None while any chunk is still live. Failed / dead-lettered
        chunks land with no output entry (their targets carry prior
        state through the diff)."""
        scan_id = entry["scan_id"]
        n_chunks = entry["n_chunks"]
        blobs = self._queue.blobs
        outputs: dict = {}
        for i in range(n_chunks):
            key = chunk_output_key(scan_id, i)
            if blobs.exists(key):
                try:
                    outputs[i] = blobs.get(key)
                    continue
                except (FileNotFoundError, KeyError):
                    pass
            status = self._queue.chunk_status(scan_id, i)
            if status is None or status not in JobStatus.TERMINAL:
                return None
        return outputs

    def _commit_epoch(
        self, spec: MonitorSpec, entry: dict, outputs: dict
    ) -> None:
        """records → plane → mark, in that order (docs/MONITORING.md
        §Crash points): every prefix of that sequence re-runs to the
        same bytes, so recovery after any kill point is idempotent."""
        monitor_id, epoch = spec.monitor_id, entry["epoch"]
        blobs = self._queue.blobs
        targets = [t.rstrip("\n") for t in spec.targets]
        chunks = list(chunk_generator(targets, spec.batch_size))
        verdicts = extract_verdicts(chunks, outputs)
        prev_plane = self._prior_plane(spec, epoch)
        records, next_plane = diff_epoch(
            monitor_id,
            epoch,
            prev_plane,
            verdicts,
            targets,
            monitor_feed.seq_base(blobs, monitor_id, epoch),
        )
        monitor_feed.write_records(blobs, monitor_id, records)
        self._plane.store(
            monitor_id,
            spec.module,
            next_plane,
            [r["target"] for r in records],
            epoch,
        )
        monitor_feed.write_mark(
            blobs, monitor_id, epoch, len(records), entry["scan_id"]
        )
        kinds: dict = {}
        for r in records:
            kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        for kind, n in kinds.items():
            MONITOR_DIFF_RECORDS.labels(kind=kind).inc(n)
        n_chunks = max(1, entry["n_chunks"])
        MONITOR_RESCAN_HIT_RATIO.labels().set(
            entry["cached_chunks"] / n_chunks
        )
        emit_event(
            "monitor.epoch_diffed",
            monitor_id=monitor_id,
            epoch=epoch,
            scan_id=entry["scan_id"],
            records=len(records),
            **{f"records_{k}": v for k, v in kinds.items()},
        )

    def _prior_plane(self, spec: MonitorSpec, epoch: int) -> dict:
        """The plane as of epoch-1: the tier copy when it is provably
        that epoch's (fast path), else a fold of the feed's MARKED
        records (authoritative; also the cold-tier / crash-re-run
        path — a partially committed epoch N must never see its own
        partial plane as 'prior')."""
        loaded = self._plane.load(spec.monitor_id, spec.module)
        if loaded is not None:
            plane, plane_epoch = loaded
            if plane_epoch == epoch - 1:
                return plane
        return plane_from_records(
            monitor_feed.feed_records(
                self._queue.blobs, spec.monitor_id, marked_only=True
            )
        )
