"""Verdict-diff engine: per-target planes, epoch deltas, deterministic
records (docs/MONITORING.md §Diff records).

Each epoch the engine holds two things: the PRIOR plane — one
``{"v": verdict, "fs": first_seen_epoch}`` entry per target that
currently has a finding — and the CURRENT epoch's extracted verdicts.
The delta between them is the entire feed output: unchanged targets
produce nothing, which is what makes a 95%-unchanged fleet's rescan
cost a cache lookup instead of a report.

Determinism is the load-bearing property. ``diff_epoch`` is a pure
function of (prior plane, current verdicts, target order), record
``seq`` numbers are positional, and JSON key order is fixed — so a
crash-interrupted epoch re-run rewrites byte-identical record blobs
(idempotent recovery, no duplicate or lost records) and a brute-force
replay over the stored outputs reproduces the feed exactly (the
``bench.py --phase monitor`` gate).

Planes persist through the shared result tier under family ``"m"``
(fenced, epoch-scoped per monitor) with the change feed itself as the
authoritative rebuild source: folding every *marked* epoch's records
reconstructs the plane from nothing, which is exactly what recovery
and cold-tier starts do.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Optional, Sequence

from swarm_tpu.cache.tier import (
    SharedResultTier,
    _FORMAT,
    _lp,
    _process_token,
)
from swarm_tpu.gateway.qoscache import split_output_segments

#: tier value family for monitor verdict planes ("v" = verdict planes,
#: "c" = confirm verdicts, "g" = gateway scan entries — docs/CACHING.md)
FAMILY = "m"


def target_digest(module: str, target: str) -> str:
    """Content address of one (module, target) verdict-plane entry —
    same length-prefixed discipline as every other tier key."""
    out = bytearray(_FORMAT)
    _lp(out, b"montarget")
    _lp(out, module.encode("utf-8", "surrogateescape"))
    _lp(out, target.encode("utf-8", "surrogateescape"))
    return hashlib.sha256(bytes(out)).hexdigest()


def _index_digest(monitor_id: str) -> str:
    """Per-monitor plane index entry: which targets currently hold a
    finding, and through which epoch the plane is valid."""
    out = bytearray(_FORMAT)
    _lp(out, b"monindex")
    _lp(out, monitor_id.encode("utf-8", "surrogateescape"))
    return hashlib.sha256(bytes(out)).hexdigest()


# ----------------------------------------------------------------------
def extract_verdicts(
    chunks: Sequence[Sequence[str]],
    outputs: dict,
) -> dict:
    """Per-target verdict text from a completed epoch's chunk outputs.

    ``outputs`` maps chunk offset -> raw output bytes; offsets with no
    output (failed / dead-lettered chunks) contribute no verdicts, so
    their targets keep the prior epoch's state rather than flapping.
    When a chunk's output carries exactly one line per input target the
    verdict is that target's line; otherwise the whole chunk output is
    the coarse verdict for each of its targets (still deterministic,
    just chunk-granular). Duplicate targets keep the first occurrence.
    """
    verdicts: dict = {}
    for offset, chunk in enumerate(chunks):
        raw = outputs.get(offset)
        if raw is None:
            continue
        segments = split_output_segments(raw, len(chunk))
        for i, target in enumerate(chunk):
            if target in verdicts:
                continue
            seg = segments[i] if segments is not None else raw
            text = seg.decode("utf-8", "surrogateescape")
            if text.endswith("\n"):
                text = text[:-1]
            verdicts[target] = text
    return verdicts


def diff_epoch(
    monitor_id: str,
    epoch: int,
    prev_plane: dict,
    verdicts: dict,
    target_order: Sequence[str],
    seq_base: int,
) -> tuple[list, dict]:
    """Pure epoch delta: ``(records, next_plane)``.

    Record order is fixed — spec-order for targets still in the spec,
    then lexicographic for targets that left it — and ``seq`` is
    ``seq_base + position``, so identical inputs always yield
    byte-identical records (the idempotent-recovery contract).

    An empty verdict means "no finding": empty-on-first-sight emits
    nothing, empty-after-a-finding emits ``resolved`` and drops the
    plane entry (a later reappearance is ``new`` again with a fresh
    ``first_seen``).
    """
    next_plane = dict(prev_plane)
    seen: set = set()
    order: list = []
    for t in target_order:
        if t not in seen:
            seen.add(t)
            order.append(t)
    staged: list = []  # (kind, target, verdict, prev, first_seen)
    for t in order:
        if t not in verdicts:
            continue  # no output this epoch: carry prior state, no record
        v = verdicts[t]
        prior = prev_plane.get(t)
        if prior is None:
            if v == "":
                continue
            staged.append(("new", t, v, "", epoch))
            next_plane[t] = {"v": v, "fs": epoch}
        elif v == "":
            staged.append(("resolved", t, "", prior["v"], prior["fs"]))
            next_plane.pop(t, None)
        elif v != prior["v"]:
            staged.append(("changed", t, v, prior["v"], prior["fs"]))
            next_plane[t] = {"v": v, "fs": prior["fs"]}
    for t in sorted(t for t in prev_plane if t not in seen):
        prior = prev_plane[t]
        staged.append(("resolved", t, "", prior["v"], prior["fs"]))
        next_plane.pop(t, None)
    records = [
        {
            "seq": seq_base + i,
            "monitor_id": monitor_id,
            "epoch": epoch,
            "kind": kind,
            "target": t,
            "verdict": v,
            "prev": prev,
            "first_seen": fs,
            "last_seen": epoch,
        }
        for i, (kind, t, v, prev, fs) in enumerate(staged)
    ]
    return records, next_plane


def encode_record(record: dict) -> bytes:
    """The canonical NDJSON line: compact separators, insertion key
    order — the byte form stored in the feed AND sent on the wire."""
    return json.dumps(record, separators=(",", ":")).encode("utf-8") + b"\n"


def plane_from_records(records) -> dict:
    """Fold feed records (oldest first) back into a plane — the
    brute-force inverse of ``diff_epoch``, used for cold-tier rebuilds
    and crash re-runs. Only pass records of MARKED (completed) epochs:
    a crash-interrupted epoch's partial records must not leak into the
    prior plane its re-run diffs against."""
    plane: dict = {}
    for rec in records:
        if rec.get("kind") == "resolved":
            plane.pop(rec.get("target"), None)
        else:
            plane[rec["target"]] = {
                "v": rec["verdict"],
                "fs": rec["first_seen"],
            }
    return plane


# ----------------------------------------------------------------------
class MonitorPlaneStore:
    """Tier adapter for monitor verdict planes: fenced, epoch-scoped
    per monitor (``mon.g<generation>.<monitor_id>``), fail-open — a
    dead or cold tier degrades to the feed-rebuild path, never to an
    error. Thread contract mirrors ``GatewayScanCache``: bind state
    under ``_lock``, tier IO outside it."""

    _EPOCH_TTL_S = 60.0

    def __init__(self, tier: Optional[SharedResultTier], writer_id: str = "monitor"):
        self._tier = tier
        self._writer = f"mon:{writer_id}"
        self._lock = threading.Lock()  # guards: _gen, _gen_read_at, _token
        self._gen: Optional[int] = None
        self._gen_read_at = 0.0
        self._token: Optional[int] = None

    def _ensure_bound(self) -> Optional[tuple[int, int]]:
        import time

        if self._tier is None:
            return None
        now = time.monotonic()
        with self._lock:
            if (
                self._gen is not None
                and self._token is not None
                and now - self._gen_read_at < self._EPOCH_TTL_S
            ):
                return self._gen, self._token
        try:
            gen = self._tier.epoch_generation()
            token = _process_token(self._tier, self._writer)
        except Exception:
            return None
        with self._lock:
            self._gen = gen
            self._gen_read_at = now
            self._token = token
        return gen, token

    @staticmethod
    def _epoch_ns(gen: int, monitor_id: str) -> str:
        return f"mon.g{gen}.{monitor_id}"

    def load(self, monitor_id: str, module: str) -> Optional[tuple[dict, int]]:
        """``(plane, plane_epoch)`` from the tier, or None when cold /
        unreachable / partially evicted — the caller rebuilds from the
        feed instead. ~Two batched reads per epoch (index + entries):
        the whole steady-state lookup cost."""
        bound = self._ensure_bound()
        if bound is None:
            return None
        gen, _token = bound
        ns = self._epoch_ns(gen, monitor_id)
        try:
            got = self._tier.get_many(FAMILY, ns, [_index_digest(monitor_id)])
        except Exception:
            return None
        raw = got.get(_index_digest(monitor_id))
        if raw is None:
            return None
        try:
            idx = json.loads(raw)
            targets = list(idx["targets"])
            plane_epoch = int(idx["epoch"])
        except (ValueError, KeyError, TypeError):
            return None
        if not targets:
            return {}, plane_epoch
        digests = [target_digest(module, t) for t in targets]
        try:
            entries = self._tier.get_many(FAMILY, ns, digests)
        except Exception:
            return None
        plane: dict = {}
        for t, d in zip(targets, digests):
            v = entries.get(d)
            if v is None:
                return None  # evicted entry: the plane is no longer whole
            try:
                plane[t] = json.loads(v)
            except (ValueError, TypeError):
                return None
        return plane, plane_epoch

    def store(
        self,
        monitor_id: str,
        module: str,
        plane: dict,
        changed_targets: Sequence[str],
        epoch: int,
    ) -> bool:
        """Write the changed entries plus the index (fenced,
        best-effort). A zero-change epoch writes only the one index
        entry — that advance is what keeps the next epoch's prior-plane
        fast path warm (``plane_epoch == epoch-1``)."""
        bound = self._ensure_bound()
        if bound is None:
            return False
        gen, token = bound
        ns = self._epoch_ns(gen, monitor_id)
        pairs = [
            (target_digest(module, t), json.dumps(plane[t], separators=(",", ":")))
            for t in changed_targets
            if t in plane
        ]
        pairs.append(
            (
                _index_digest(monitor_id),
                json.dumps(
                    {"targets": sorted(plane), "epoch": epoch},
                    separators=(",", ":"),
                ),
            )
        )
        try:
            outcome, _stored = self._tier.put_many(
                FAMILY, ns, pairs, self._writer, token
            )
        except Exception:
            return False
        return outcome == "stored"
