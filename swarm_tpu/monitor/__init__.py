"""Continuous monitoring: journaled standing rescans over the existing
engine (docs/MONITORING.md).

A monitor spec turns a one-shot scan into a standing workload: the
(tenant, module, targets, interval, qos) tuple is registered through
``POST /monitor``, journaled like every queue mutation, and fired as
scan *epochs* on its cadence through the normal admission path. Each
epoch's per-target verdicts are diffed against the prior epoch's and
only the changes flow out — as compact NDJSON records over
``GET /monitor-feed/<monitor_id>`` (resume-from-cursor, durable across
restarts).

One dataflow system, many workloads: monitoring is a control-plane
lane over the existing queue/journal/cache engine, not a second fleet.
"""

from swarm_tpu.monitor.spec import MonitorSpec
from swarm_tpu.monitor.diff import diff_epoch, extract_verdicts
from swarm_tpu.monitor.feed import feed_records, stream_feed

__all__ = [
    "MonitorSpec",
    "diff_epoch",
    "extract_verdicts",
    "feed_records",
    "stream_feed",
]
