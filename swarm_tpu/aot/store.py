"""Artifact store + breaker-wrapped client for the AOT executable cache.

Storage rides the same Redis/S3-role pair as the result tier
(docs/CACHING.md), under its own ``swarm:aot`` namespace with the SAME
epoch + fencing-token discipline (:class:`AotStore` subclasses the
tier): artifact payloads (serialized executables, binary, potentially
MBs) always live in the BLOB store; the state store holds a small
JSON index entry per artifact so a joining worker can enumerate what
is published for its program group without touching a single blob.

Key schema (docs/AOT.md): every artifact digest is sha256 over

- the **program group** — kernel source salt (:func:`kernel_code_salt`)
  + the jax/jaxlib/XLA environment (:func:`jax_fingerprint`): a jaxlib
  upgrade or device change can never load a stale binary;
- the **kernel id** (``dd.A`` / ``dd.B`` / ``dd.fused`` / ``sh.*`` for
  the mesh twins) + the trace salt (layout metadata, candidate budget,
  mesh shape — everything the traced program depends on besides array
  shapes);
- the **static args** (the phase-B ladder rung ``kc``, full/donate
  flags) and the **aval signature** of every argument (shapes/dtypes —
  the corpus-FREE program still has corpus-SIZED argument shapes).

Corpus *content* is deliberately absent: the PR 3 argument convention
made the programs corpus-free, so one published executable serves
every corpus whose layout SHAPES match — a corpus refresh that keeps
shapes does not even miss. The epoch exists for the operator
"poisoned artifact" lever: ``bump_epoch`` moves every reader/writer
to a fresh namespace (docs/AOT.md runbook), exactly like the result
tier.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional

from swarm_tpu.cache.tier import SharedResultTier, _process_token
from swarm_tpu.telemetry.aot_export import (
    AOT_ARTIFACT_BYTES,
    AOT_BRINGUP_SECONDS,
    AOT_FETCHES,
    AOT_PUBLISHES,
)

#: wire format version — salts every digest AND prefixes every
#: payload, so a serialization change can never load stale artifacts
_FORMAT = b"swarm-aot-v1"

#: kernel source files whose bytes salt the program group: any edit to
#: the traced programs (or the layout builder that shapes their
#: arguments) invalidates every published artifact. Relative to the
#: repo's ``swarm_tpu`` package directory.
_KERNEL_FILES = (
    "ops/match.py",
    "ops/regexdev.py",
    "ops/md5.py",
    "ops/hashing.py",
    "ops/encoding.py",
    "fingerprints/compile.py",
    "parallel/sharded.py",
)


def kernel_code_salt() -> str:
    """sha256 hex over the kernel/layout source files — the "same
    traced program" half of the program group."""
    import pathlib

    h = hashlib.sha256(_FORMAT)
    pkg = pathlib.Path(__file__).resolve().parent.parent
    for name in _KERNEL_FILES:
        h.update(name.encode())
        try:
            h.update((pkg / name).read_bytes())
        except OSError:
            h.update(b"?")
    return h.hexdigest()


def jax_fingerprint() -> str:
    """The jax/jaxlib/XLA environment an executable is only valid in:
    versions, backend platform, device kind and count, and the XLA
    flags that shape codegen (``XLA_FLAGS`` carries e.g. the forced
    host-platform device count). A serialized executable is a compiled
    binary — loading it under ANY other fingerprint is undefined, so
    the fingerprint rides the digest and a mismatch is a clean miss."""
    import os

    import jax
    import jaxlib
    import numpy as np

    devs = jax.devices()
    return "|".join(
        (
            jax.__version__,
            jaxlib.__version__,
            np.__version__,
            devs[0].platform,
            getattr(devs[0], "device_kind", "?"),
            str(len(devs)),
            str(jax.process_count()),
            os.environ.get("XLA_FLAGS", ""),
        )
    )


class AotStore(SharedResultTier):
    """Artifact store over the state/blob role pair — the result
    tier's epoch + fencing plumbing (inherited) with an artifact data
    plane: payloads in the blob store, JSON index entries in the state
    hash ``{prefix}:x:{epoch}``, blob keys ``aot/{epoch}/{digest}``."""

    _INDEX_FAMILY = "x"

    def __init__(self, state, blobs, prefix: str = "swarm:aot"):
        if blobs is None:
            raise ValueError("AotStore needs a blob store for payloads")
        super().__init__(state, blobs, prefix=prefix)

    def _index_name(self, epoch: str) -> str:
        return self._hash_name(self._INDEX_FAMILY, epoch)

    def _artifact_key(self, epoch: str, digest: str) -> str:
        return f"aot/{epoch}/{digest}"

    def list_index(self, epoch: str) -> dict:
        """digest → raw JSON index entry for every published artifact
        in one epoch namespace (the prewarm enumeration — one hgetall,
        no blob traffic)."""
        return self._state.hgetall(self._index_name(epoch))

    def get_artifact(
        self, epoch: str, digest: str
    ) -> Optional[tuple[str, bytes]]:
        """→ (index entry, payload bytes) or None. A live index entry
        whose blob vanished is a miss (same rule as the tier's spilled
        values)."""
        meta = self._state.hget(self._index_name(epoch), digest)
        if meta is None:
            return None
        try:
            payload = self._blobs.get(self._artifact_key(epoch, digest))
        except Exception:
            return None
        return meta, payload

    # pairs: writer_token / _blobs.put; pairs: writer_token / _state.hset (fence re-check, docs/AOT.md)
    def put_artifact(
        self, epoch: str, digest: str, meta: str, payload: bytes,
        writer_id: str, token: int,
    ) -> str:
        """Publish one artifact under the writer's fencing token —
        checked BEFORE the write (stale-writer reject) and AGAIN after
        it (a writer superseded mid-write learns it was fenced). The
        payload blob lands before the index entry, so a reader can
        never see an index entry whose blob is still in flight; the
        mid-write bytes are not unwound for the same reason as the
        result tier's (docs/CACHING.md): within an epoch an artifact
        is a pure function of its digest, so a superseded same-epoch
        writer's bytes are identical to the live successor's."""
        if self.writer_token(writer_id) != token:
            return "fenced"
        self._blobs.put(self._artifact_key(epoch, digest), payload)
        self._state.hset(self._index_name(epoch), digest, meta)
        if self.writer_token(writer_id) != token:
            return "fenced"
        return "stored"


class AotClient:
    """A worker's view of the artifact store: epoch-bound, breaker-
    wrapped, telemetry-counted — the exact contract of the result
    tier's client (docs/CACHING.md): a dead/slow backend trips the
    breaker and every lookup degrades to "compile locally", it never
    blocks a dispatch. Chaos levers ``aot.fetch`` / ``aot.put``
    (docs/RESILIENCE.md) inject that failure mode.

    Loaded executables live in a process-wide **pool** (digest →
    loaded callable): :meth:`prewarm` fills it from the store index at
    engine bring-up, and :class:`~swarm_tpu.aot.jitcache.AotJit`
    consults it before touching the store on the dispatch path.

    Thread contract: dispatch (scheduler submit thread), a degraded
    batch's retry (walk worker) and prewarm (bring-up) can all reach
    the client — the pool and counters sit under ``_lock``.
    """

    #: loaded-executable pool bound: dict order is insertion order, the
    #: oldest entries drop past the cap — the same bounded-RSS rule the
    #: per-wrapper AotJit LRU enforces (an evicted executable simply
    #: re-fetches from the store if that shape comes back)
    _POOL_MAX = 128

    def __init__(
        self,
        store: AotStore,
        worker_id: str = "worker",
        publish: bool = True,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
    ):
        from swarm_tpu.resilience.breaker import CircuitBreaker

        self._store = store
        self._worker_id = worker_id
        self.publish_enabled = bool(publish)
        self._breaker = CircuitBreaker(
            f"aot.store.{worker_id}",
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
        )
        self._lock = threading.Lock()  # guards: _pool (reads), _counters, _group, _epoch, _epoch_read_at, _warned
        self._pool: dict = {}
        self._counters = {
            "fetch_hits": 0,
            "fetch_misses": 0,
            "deserialize_errors": 0,
            "published": 0,
            "publish_fenced": 0,
            "prewarmed": 0,
        }
        self._group: Optional[str] = None
        self._epoch: Optional[str] = None
        self._epoch_read_at = 0.0
        self._warned = False

    # -- identity ------------------------------------------------------
    #: how long a read epoch is trusted before the generation counter
    #: is re-read — the propagation ceiling for an operator
    #: ``bump_epoch`` on a live fleet (docs/AOT.md runbook)
    _EPOCH_TTL_S = 60.0

    def group(self) -> str:
        """The program group digest (code salt + jax fingerprint) —
        computed once; everything published/fetched by this process
        lives under it."""
        with self._lock:
            if self._group is None:
                h = hashlib.sha256(_FORMAT)
                h.update(kernel_code_salt().encode())
                h.update(jax_fingerprint().encode())
                self._group = h.hexdigest()[:24]
            return self._group

    def key_digest(self, kernel_id: str, salt: str, static_repr: str,
                   aval_sig: str) -> str:
        """The full artifact digest for one (kernel, statics, shapes)
        triple under this process's program group."""
        h = hashlib.sha256(_FORMAT)
        for part in (self.group(), kernel_id, salt, static_repr, aval_sig):
            h.update(part.encode())
            h.update(b"\x00")
        return h.hexdigest()

    def _epoch_name(self) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            if (
                self._epoch is not None
                and now - self._epoch_read_at < self._EPOCH_TTL_S
            ):
                return self._epoch
        gen = self._guarded(
            "aot.fetch", "epoch", self._store.epoch_generation
        )
        with self._lock:
            if gen is None:
                return self._epoch  # stale-by-≤TTL or None: degrade
            self._epoch = f"g{gen}"
            self._epoch_read_at = now
            return self._epoch

    # -- breaker plumbing ---------------------------------------------
    # may-block: wraps one artifact-store op behind the breaker
    def _guarded(self, point: str, detail: str, fn):
        from swarm_tpu.resilience.faults import fault_point

        br = self._breaker
        if not br.allow():
            return None
        try:
            fault_point(point, detail=detail)
            out = fn()
        except Exception as e:
            br.record_failure()
            with self._lock:
                warn = not self._warned
                self._warned = True
            if warn:
                print(
                    f"AOT executable cache degraded to compile-only "
                    f"({type(e).__name__}: {e}) "
                    f"[breaker {br.name}: {br.state}]"
                )
            return None
        br.record_success()
        with self._lock:
            self._warned = False
        return out

    # requires-lock: _lock (every caller inserts under the lock)
    def _pool_put(self, digest: str, loaded) -> None:
        self._pool.pop(digest, None)
        while len(self._pool) >= self._POOL_MAX:
            self._pool.pop(next(iter(self._pool)))
        self._pool[digest] = loaded

    # -- fetch path ----------------------------------------------------
    def fetch_loaded(self, digest: str):
        """→ a loaded executable for ``digest``, or None (miss /
        degraded / deserialize failure — the caller compiles). Pool
        hits never touch the store; store hits are deserialized here
        and pooled for any later same-shape kernel."""
        with self._lock:
            loaded = self._pool.get(digest)
            if loaded is not None:
                self._counters["fetch_hits"] += 1
        if loaded is not None:
            AOT_FETCHES.labels(outcome="hit").inc(1)
            return loaded
        epoch = self._epoch_name()
        if epoch is None:
            return None
        got = self._guarded(
            "aot.fetch", "artifact",
            lambda: self._store.get_artifact(epoch, digest),
        )
        if got is None:
            with self._lock:
                self._counters["fetch_misses"] += 1
            AOT_FETCHES.labels(outcome="miss").inc(1)
            return None
        _meta, payload = got
        t0 = time.perf_counter()
        loaded = self._load_payload(payload)
        if loaded is None:
            with self._lock:
                self._counters["deserialize_errors"] += 1
            AOT_FETCHES.labels(outcome="deserialize_error").inc(1)
            return None
        AOT_BRINGUP_SECONDS.labels(source="fetch").observe(
            time.perf_counter() - t0
        )
        AOT_ARTIFACT_BYTES.set(len(payload))
        with self._lock:
            self._counters["fetch_hits"] += 1
            self._pool_put(digest, loaded)
        AOT_FETCHES.labels(outcome="hit").inc(1)
        return loaded

    def _load_payload(self, payload: bytes):
        """Deserialize one artifact; None on ANY failure (foreign
        topology, corrupt bytes, version skew) — a bad artifact must
        cost a compile, never an exception on the dispatch path."""
        from swarm_tpu.aot.jitcache import load_compiled

        try:
            return load_compiled(payload)
        except Exception:
            return None

    def _load_verify(self, payload: bytes) -> None:
        """Raise if ``payload`` does not deserialize on this backend
        (the publish gate; the loaded probe is discarded)."""
        from swarm_tpu.aot.jitcache import load_compiled

        load_compiled(payload)

    def note_compile_seconds(self, seconds: float) -> None:
        """Record a local compile on the AOT-managed path (the miss
        arm of the bring-up histogram)."""
        AOT_BRINGUP_SECONDS.labels(source="compile").observe(seconds)

    # -- publish path --------------------------------------------------
    def publish(self, digest: str, meta: dict, compiled) -> str:
        """Serialize + publish one locally compiled executable.
        Returns the outcome (``stored`` / ``fenced`` / ``error`` /
        ``disabled``); failures are counted and swallowed — publishing
        is strictly best-effort."""
        import json

        from swarm_tpu.aot.jitcache import serialize_compiled

        if not self.publish_enabled:
            return "disabled"
        epoch = self._epoch_name()
        if epoch is None:
            AOT_PUBLISHES.labels(outcome="error").inc(1)
            return "error"
        try:
            payload = serialize_compiled(compiled)
            # round-trip verification: a payload that cannot load HERE
            # cannot load anywhere (same topology) — publishing it
            # would poison the store with deserialize_error misses for
            # every joining worker. Load cost is milliseconds next to
            # the compile that just happened.
            self._load_verify(payload)
        except Exception:
            # some executables don't serialize (backend-dependent);
            # they simply stay process-local
            AOT_PUBLISHES.labels(outcome="error").inc(1)
            return "error"
        meta = dict(meta)
        meta["g"] = self.group()
        meta["n"] = len(payload)
        writer = f"{self._worker_id}:aot"
        body = json.dumps(meta, separators=(",", ":"))

        def put():
            token = _process_token(self._store, writer)
            return self._store.put_artifact(
                epoch, digest, body, payload, writer, token
            )

        out = self._guarded("aot.put", "artifact", put)
        if out is None:
            AOT_PUBLISHES.labels(outcome="error").inc(1)
            return "error"
        with self._lock:
            if out == "stored":
                self._counters["published"] += 1
            else:
                self._counters["publish_fenced"] += 1
            # the compiled object IS the loaded form — pool it so a
            # sibling engine in this process fetches without the store
            self._pool_put(digest, compiled)
        AOT_PUBLISHES.labels(outcome=out).inc(1)
        AOT_ARTIFACT_BYTES.set(len(payload))
        return out

    # -- bring-up ------------------------------------------------------
    def prewarm(self) -> int:
        """Load every artifact published for this process's program
        group into the pool (worker bring-up: fetch-and-load INSTEAD
        of compiling — docs/AOT.md). Artifacts that fail to load are
        counted and skipped; a dead store prewarms nothing. Returns
        the number of executables now pooled."""
        import json

        epoch = self._epoch_name()
        if epoch is None:
            return 0
        index = self._guarded(
            "aot.fetch", "index", lambda: self._store.list_index(epoch)
        )
        if not index:
            return 0
        group = self.group()
        n = 0
        for digest, raw in index.items():
            try:
                meta = json.loads(raw)
            except ValueError:
                continue
            if meta.get("g") != group:
                continue
            with self._lock:
                if digest in self._pool:
                    n += 1
                    continue
            got = self._guarded(
                "aot.fetch", "artifact",
                lambda d=digest: self._store.get_artifact(epoch, d),
            )
            if got is None:
                continue
            t0 = time.perf_counter()
            loaded = self._load_payload(got[1])
            if loaded is None:
                with self._lock:
                    self._counters["deserialize_errors"] += 1
                AOT_FETCHES.labels(outcome="deserialize_error").inc(1)
                continue
            AOT_BRINGUP_SECONDS.labels(source="fetch").observe(
                time.perf_counter() - t0
            )
            with self._lock:
                self._pool_put(digest, loaded)
                self._counters["prewarmed"] += 1
            n += 1
        return n

    def counters(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["pool"] = len(self._pool)
            out["breaker"] = self._breaker.state
            return out


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------

_FACTORY_LOCK = threading.Lock()
_MEMORY_STORE: Optional[AotStore] = None  # guarded-by: _FACTORY_LOCK (reads)
#: one store object per backend location in this process — the fencing
#: registry is keyed per store OBJECT (cache.tier._process_token), so
#: same-identity clients must share the instance (docs/CACHING.md)
_SHARED_STORES: dict = {}  # guarded-by: _FACTORY_LOCK (reads)


def _memory_store() -> AotStore:
    global _MEMORY_STORE
    from swarm_tpu.stores import MemoryBlobStore, MemoryStateStore

    with _FACTORY_LOCK:
        if _MEMORY_STORE is None:
            _MEMORY_STORE = AotStore(MemoryStateStore(), MemoryBlobStore())
        return _MEMORY_STORE


def _local_store(root: str) -> AotStore:
    from swarm_tpu.stores import LocalBlobStore, LocalStateStore

    with _FACTORY_LOCK:
        store = _SHARED_STORES.get(("local", root))
        if store is None:
            store = _SHARED_STORES[("local", root)] = AotStore(
                LocalStateStore(f"{root}/state"),
                LocalBlobStore(f"{root}/blobs"),
            )
        return store


def _redis_store(url: str, blob_dir: str, s3_bucket: str) -> AotStore:
    from swarm_tpu.stores import (
        LocalBlobStore,
        RedisStateStore,
        S3BlobStore,
    )

    with _FACTORY_LOCK:
        key = ("redis", url, blob_dir, s3_bucket)
        store = _SHARED_STORES.get(key)
        if store is None:
            if s3_bucket:
                blobs = S3BlobStore(s3_bucket)
            else:
                blobs = LocalBlobStore(blob_dir or "/tmp/swarm_aot_blobs")
            store = _SHARED_STORES[key] = AotStore(
                RedisStateStore(url), blobs
            )
        return store


def build_aot_client(cfg) -> Optional[AotClient]:
    """Construct the AOT client from a :class:`swarm_tpu.config.
    Config` (``SWARM_AOT_*`` knobs); None when the cache is off.

    Backends: ``memory`` (per-process, tests), ``local`` (file-backed
    under ``aot_dir`` — cross-process on one host with zero side-cars;
    the bench's fresh-process A/B rides this), ``redis`` (fleet-wide:
    state via ``aot_url``/``redis_url``, payload blobs via the S3 role
    when ``s3_bucket`` is set, else a shared directory)."""
    backend = (getattr(cfg, "aot_backend", "off") or "off").lower()
    if backend in ("off", "", "0", "none", "false"):
        return None
    if backend == "memory":
        store = _memory_store()
    elif backend == "local":
        root = getattr(cfg, "aot_dir", "") or "/tmp/swarm_aot"
        store = _local_store(root)
    elif backend == "redis":
        store = _redis_store(
            getattr(cfg, "aot_url", "") or cfg.redis_url,
            getattr(cfg, "aot_dir", ""),
            getattr(cfg, "s3_bucket", ""),
        )
    else:
        raise ValueError(f"unknown aot_backend {backend!r}")
    return AotClient(
        store,
        worker_id=getattr(cfg, "worker_id", "worker"),
        publish=getattr(cfg, "aot_publish", True),
        breaker_threshold=getattr(cfg, "aot_breaker_threshold", 3),
        breaker_cooldown_s=getattr(cfg, "aot_breaker_cooldown_s", 30.0),
    )
