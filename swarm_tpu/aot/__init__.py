"""AOT executable cache: compiled XLA kernels as distributable data.

Compile time is the fleet's worst cold-start cliff (6.4 s cold on the
CPU box, 124–133 s compile+first-call on real chips — BENCH_r05 /
MULTICHIP_r05), paid per worker per shape class, exactly when the
autoscale advisor adds workers under load. This package serializes the
phase-A / phase-B-ladder / fused-twin executables
(``jax.jit(...).lower().compile()`` + executable serialization) and
ships them through the existing Redis/S3-role stores under the
``swarm_tpu/cache`` epoch + fencing-token discipline, so a joining
worker FETCHES and loads instead of compiling — falling back to a live
compile on any miss or deserialize failure (breaker-wrapped; the cache
is an accelerator, never a dependency). docs/AOT.md has the key
schema, invalidation rules and the operator runbook.
"""

from swarm_tpu.aot.store import (  # noqa: F401
    AotClient,
    AotStore,
    build_aot_client,
    jax_fingerprint,
    kernel_code_salt,
)
from swarm_tpu.aot.jitcache import AotJit, aval_signature  # noqa: F401
