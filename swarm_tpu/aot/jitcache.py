"""AotJit: a ``jax.jit``-shaped wrapper with explicit per-shape
executable management.

``jax.jit`` compiles implicitly on first call of each argument-shape
set and offers no hook between "shape is new" and "compile it". This
wrapper makes that moment explicit so the AOT executable cache
(docs/AOT.md) can interpose: on a new shape it first consults the
:class:`~swarm_tpu.aot.store.AotClient` (prewarm pool → store fetch →
deserialize), and only COMPILES (``fn.lower(*args).compile()``) on a
genuine miss — publishing the fresh executable back to the store so
the next joining worker fetches instead.

Spy contract (the DeviceDB/ShardedMatcher compile-count spies,
docs/DEVICE_MATCH.md): ``_cache_size()`` counts LOCALLY COMPILED live
executables only — a deserialized load is counted by
``_fetched_size()`` instead, so ``tools/profile_device.py`` and the
width-bucket-sharing test stay honest on the fetch path. ``lower()``
and ``clear_cache()`` delegate/extend the wrapped jit, so the HLO
inspection path and the shape-churn eviction guard work unchanged.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Optional

import jax

#: payload header — versioned so a wire change can never feed stale
#: bytes into the unpickler (the digest salts the same constant, so in
#: practice a mismatch is unreachable; the header is belt-and-braces
#: for artifacts handled outside the store)
_MAGIC = b"SWAOT1\x00"


def serialize_compiled(compiled) -> bytes:
    """One ``jax.stages.Compiled`` → portable bytes (the XLA
    executable image + the in/out pytree defs it was lowered with)."""
    from jax.experimental.serialize_executable import serialize

    payload, in_tree, out_tree = serialize(compiled)
    return _MAGIC + pickle.dumps((payload, in_tree, out_tree))


def load_compiled(blob: bytes):
    """Bytes → a callable loaded executable. Raises on any mismatch
    (header, unpickle, device topology) — callers treat every failure
    as a cache miss."""
    from jax.experimental.serialize_executable import deserialize_and_load

    if not blob.startswith(_MAGIC):
        raise ValueError("bad AOT artifact header")
    payload, in_tree, out_tree = pickle.loads(blob[len(_MAGIC):])
    return deserialize_and_load(payload, in_tree, out_tree)


def aval_signature(tree) -> str:
    """Deterministic string of a pytree's structure + leaf avals
    (shape, dtype) — the shape half of the artifact digest. Weak types
    never arise on the dispatch path (every per-batch leaf is staged
    through ``jnp.asarray`` of host numpy), so shape+dtype is the full
    aval story here."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    parts = [str(treedef)]
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        parts.append(f"{dtype}[{','.join(map(str, shape))}]")
    return ";".join(parts)


class _Entry:
    __slots__ = ("compiled", "fetched")

    def __init__(self, compiled, fetched: bool):
        self.compiled = compiled
        self.fetched = fetched


class AotJit:
    """Explicitly managed twin of ``jax.jit(fun, ...)``.

    Presents the slice of the jit wrapper surface the matchers use —
    ``__call__``, ``lower``, ``_cache_size``, ``clear_cache`` — plus
    ``_fetched_size`` (deserialized loads, counted distinctly from
    compiles). Executables are keyed by (static args repr, aval
    signature); the LRU is bounded at ``cap`` (the same generous
    shape-churn guard DeviceDB applies — jit never evicts either, and
    adversarial shape variety must not grow RSS without bound).

    Thread-safe: the matchers already serialize launches under their
    compile-spy locks, but ``profile_phases``/tests may call from
    other threads — materialization runs under ``_lock``.
    """

    def __init__(
        self,
        fun,
        kernel_id: str,
        salt: str = "",
        client=None,
        static_argnums: tuple = (),
        donate_argnums: tuple = (),
        cap: int = 32,
    ):
        self._jit = jax.jit(
            fun,
            static_argnums=static_argnums,
            donate_argnums=donate_argnums,
        )
        self._static = tuple(sorted(int(i) for i in static_argnums))
        self._kernel_id = kernel_id
        # the full trace salt: caller context + this wrapper's own
        # static/donate configuration (two wrappers over one fun with
        # different donation lower DIFFERENT programs)
        self._salt = (
            f"{salt}|static={self._static}"
            f"|donate={tuple(sorted(int(i) for i in donate_argnums))}"
        )
        self._client = client
        self._cap = int(cap)
        self._lock = threading.RLock()  # guards: _exe (reads)
        self._exe: dict = {}

    # -- spy surface (jit-compatible) ---------------------------------
    def _cache_size(self) -> int:
        with self._lock:
            return sum(1 for e in self._exe.values() if not e.fetched)

    def _fetched_size(self) -> int:
        with self._lock:
            return sum(1 for e in self._exe.values() if e.fetched)

    def clear_cache(self) -> None:
        with self._lock:
            self._exe.clear()
        self._jit.clear_cache()

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    # -- call path -----------------------------------------------------
    def _split(self, args):
        """(static values, dynamic args) by position."""
        static = tuple(args[i] for i in self._static)
        dyn = tuple(
            a for i, a in enumerate(args) if i not in self._static
        )
        return static, dyn

    def __call__(self, *args):
        static, dyn = self._split(args)
        akey = (repr(static), aval_signature(dyn))
        with self._lock:
            entry = self._exe.get(akey)
            if entry is None:
                entry = self._materialize(akey, args, static, dyn)
                while len(self._exe) >= self._cap:
                    self._exe.pop(next(iter(self._exe)))
                self._exe[akey] = entry
        # the Compiled call itself is thread-safe and runs outside any
        # serialization concern: static args are baked into the
        # executable, only the dynamic args are passed
        return entry.compiled(*dyn)

    # requires-lock: _lock (only called from __call__'s locked block)
    def _materialize(self, akey, args, static, dyn) -> _Entry:
        client = self._client
        digest = None
        if client is not None:
            digest = client.key_digest(
                self._kernel_id, self._salt, akey[0], akey[1]
            )
            loaded = client.fetch_loaded(digest)
            if loaded is not None:
                return _Entry(loaded, True)
        t0 = time.perf_counter()
        with self._compile_ctx():
            compiled = self._jit.lower(*args).compile()
        dt = time.perf_counter() - t0
        if client is not None:
            client.note_compile_seconds(dt)
            client.publish(
                digest,
                {"k": self._kernel_id, "s": akey[0], "a": akey[1]},
                compiled,
            )
        return _Entry(compiled, False)

    def _compile_ctx(self):
        """Publisher-path compiles bypass jax's PERSISTENT compilation
        cache: an executable that was itself deserialized from that
        cache re-serializes into a non-self-contained image (XLA:CPU
        "Symbols not found" at load time — observed on jaxlib 0.4.36),
        which would poison the store with unloadable artifacts (the
        publish round-trip verification would then drop EVERY publish
        instead). A fresh compile serializes cleanly; non-publishing
        clients keep the cache (their executables never leave the
        process).

        The config flag alone is not enough: ``compilation_cache.
        is_cache_used`` memoizes its decision once per process, so
        the scoped override also flips that memoized state for the
        duration of the compile (restored after; a concurrent compile
        on another thread at most loses one cache lookup — perf, not
        correctness)."""
        import contextlib

        client = self._client
        if client is None or not client.publish_enabled:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def no_persistent_cache():
            try:
                from jax._src import config as jax_config

                cfg_ctx = jax_config.enable_compilation_cache(False)
            except Exception:
                cfg_ctx = contextlib.nullcontext()
            with cfg_ctx:
                try:
                    from jax._src import compilation_cache as cc

                    with cc._cache_initialized_mutex:
                        saved = (cc._cache_checked, cc._cache_used)
                        cc._cache_checked, cc._cache_used = True, False
                except Exception:
                    cc = None
                try:
                    yield
                finally:
                    if cc is not None:
                        with cc._cache_initialized_mutex:
                            cc._cache_checked, cc._cache_used = saved

        return no_persistent_cache()

    def preload(self, args: tuple, compiled, fetched: bool = True) -> None:
        """Register a ready executable for ``args``' shape (tests and
        tooling; the production path pools by digest in the client)."""
        static, dyn = self._split(args)
        with self._lock:
            self._exe[(repr(static), aval_signature(dyn))] = _Entry(
                compiled, fetched
            )


def fetched_size_of(fn) -> int:
    """``_fetched_size`` of a jit-or-AotJit wrapper (plain jit has no
    fetch path → 0)."""
    getter = getattr(fn, "_fetched_size", None)
    return int(getter()) if getter is not None else 0
