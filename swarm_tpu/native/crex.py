"""ctypes driver for the native crex regex VM (native/crex.cpp).

CDLL (not PyDLL): every call releases the GIL, so extraction work can
shard across host threads with true parallelism. Programs come from
ops/crexc.compile_crex; text is raw part bytes (the latin-1
correspondence the whole match stack uses).

Call-path design: ctypes argument marshalling dominates at these call
rates — ndpointer argtype validation plus numpy-scalar conversion
measured ~26 us/call vs ~4 us with raw pre-bound pointers — so the
program/mask pointers are cached on the program object and all scalars
cross as plain ints / c_int64.

Finditer/search return None on resource exhaustion (step budget, frame
stack, span cap overflow) — the caller must fall back to Python ``re``
for that (pattern, content) pair; exactness is never traded for speed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
# alternate-build override (tools/sanitize_natives.sh) — mirrors
# native/scanio.py: load prebuilt .so from the named dir, skip make.
# Snapshot ONCE at import (empty = unset), same as the path itself.
_DIR_OVERRIDDEN = bool(os.environ.get("SWARM_NATIVE_DIR"))
_NATIVE_DIR = Path(os.environ.get("SWARM_NATIVE_DIR") or _SRC_NATIVE_DIR)
_LIB_PATH = _NATIVE_DIR / "libcrex.so"

_lib: Optional[ctypes.CDLL] = None  # guarded-by: _load_lock
_lib_failed = False  # guarded-by: _load_lock
# first use can come from several extraction-pool threads at once: the
# make invocation and the CDLL load must happen exactly once
_load_lock = threading.Lock()

STEP_BUDGET = 4_000_000  # per finditer/search call, then fallback
_BUDGET = ctypes.c_int64(STEP_BUDGET)

#: budget exhaustions tolerated per program before the VM stops being
#: tried for that pattern — burning the full step budget costs real
#: time (tens to hundreds of ms) per call before the exact re
#: fallback runs, so a pattern that keeps blowing up (catastrophic
#: backtracking shapes) must not pay that tax on every row. Cheap
#: frame/trail-stack overflows (content-size-driven, ~0.1 ms, C code
#: -4) deliberately do NOT count: short contents still run natively.
MAX_BUDGET_FAILS = 3


def usable(cp) -> bool:
    """Whether the native VM should still be tried for this program."""
    return cp is not None and getattr(cp, "_budget_fails", 0) < MAX_BUDGET_FAILS


def _note_budget_fail(cp) -> None:
    cp._budget_fails = getattr(cp, "_budget_fails", 0) + 1


def ensure_crex() -> Optional[ctypes.CDLL]:
    """Load libcrex.so (building via make on first use); None when the
    native lib is unavailable (Python fallback runs). Thread-safe:
    concurrent first calls serialize on _load_lock."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _load_lock:
        return _ensure_crex_locked()


def _ensure_crex_locked() -> Optional[ctypes.CDLL]:  # requires-lock: _load_lock
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    if _DIR_OVERRIDDEN:
        if not _LIB_PATH.exists():
            # deliberate prebuilt set named but crex missing from it —
            # fail LOUDLY like scanio does, or a sanitizer run would
            # quietly fall back to the pure-Python engine and report
            # green with zero coverage of crex.cpp
            raise FileNotFoundError(
                f"SWARM_NATIVE_DIR set but {_LIB_PATH} does not exist"
            )
    else:
        try:
            import sys as _sys

            subprocess.run(
                ["make", "-C", str(_SRC_NATIVE_DIR), f"PY={_sys.executable}"],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError):
            if not _LIB_PATH.exists():
                _lib_failed = True
                return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        _lib_failed = True
        return None
    # ABI handshake: a stale .so (make failed above but an old build
    # survived on disk) silently returns WRONG matches if the opcode
    # numbering moved — refuse anything but the compiler's version
    from swarm_tpu.ops.crexc import CREX_ABI

    try:
        abi_fn = lib.sw_crex_abi
        abi_fn.restype = ctypes.c_int32
        abi = abi_fn()
    except AttributeError:  # pre-handshake build: stale by definition
        abi = -1
    if abi != CREX_ABI:
        _lib_failed = True
        return None
    # no argtypes on purpose: pointers are pre-bound c_void_p, scalars
    # plain ints (see module docstring) — validation cost is the point
    lib.sw_crex_finditer.restype = ctypes.c_int64
    lib.sw_crex_finditer_batch.restype = ctypes.c_int64
    lib.sw_crex_search.restype = ctypes.c_int32
    lib.sw_crex_exists.restype = ctypes.c_int32
    try:
        lib.sw_crex_exists_batch.restype = None
    except AttributeError:
        # pre-batch .so survived a failed make: the per-call exists()
        # path still works, only the batched walk dispatch degrades
        pass
    _lib = lib
    return lib


def _bind(cp) -> tuple:
    """Cache raw pointers + scalar fields on the program object.

    Published as ONE tuple attribute (atomic assignment): programs are
    shared across the extraction pool's threads via analyze()'s
    memoized PatternInfo, and a multi-attribute guard could observe a
    half-bound object. Benign if two threads race the build — both
    tuples are equivalent and either assignment wins whole."""
    bound = (
        cp.prog.ctypes.data_as(ctypes.c_void_p),
        cp.masks.ctypes.data_as(ctypes.c_void_p),
        int(cp.prog.shape[0]),
    )
    cp._bound = bound
    return bound


_scratch = threading.local()


def _out_buf(need: int) -> np.ndarray:
    buf = getattr(_scratch, "buf", None)
    if buf is None or buf.shape[0] < need:
        buf = np.empty(max(need, 4096), dtype=np.int32)
        _scratch.buf = buf
        _scratch.ptr = buf.ctypes.data_as(ctypes.c_void_p)
    return buf


def finditer_spans(cp, data: bytes, group: int) -> Optional[list]:
    """(start, end) span per match of ``group`` (0 = whole match;
    unparticipated -> (-1, -1)), exactly re.finditer order — or None
    when the native path can't answer (caller falls back to re)."""
    lib = ensure_crex()
    if lib is None:
        return None
    pp, mp, nprog = getattr(cp, "_bound", None) or _bind(cp)
    # unknown group index -> whole match (re.finditer IndexError
    # semantics, mirrored by fastre.finditer_values' except clause)
    g2 = 2 * group if group and group in cp.group_exists else 0
    # realistic match counts are tiny: start from a small cap and grow
    # on the -3 overflow return (mirrors finditer_spans_batch) instead
    # of pre-sizing for the ~16x-content-size theoretical worst case —
    # the per-thread scratch persists, so worst-case pre-sizing left a
    # lasting RSS spike per pool thread on multi-MB parts. The hard
    # ceiling (one empty + one non-empty match per position, plus the
    # trailing empty) bounds the retry loop.
    hard_cap = 2 * len(data) + 3
    cap = min(4096, hard_cap)
    while True:
        out = _out_buf(2 * cap)
        n = lib.sw_crex_finditer(
            pp, nprog, mp, data, len(data), g2, cp.n_saves,
            _scratch.ptr, ctypes.c_int64(cap), _BUDGET,
        )
        if n == -3 and cap < hard_cap:
            cap = min(cap * 4, hard_cap)
            continue
        break
    if n < 0:
        if n == -2:
            _note_budget_fail(cp)
        return None
    flat = out[: 2 * n].tolist()
    return list(zip(flat[0::2], flat[1::2]))


def finditer_spans_batch(
    cp, parts: "list[bytes]", group: int
) -> Optional[list]:
    """Per-item span lists for ONE pattern over many contents — one
    GIL-released dispatch for the whole batch. Items that did not
    complete natively come back as None entries — the caller must
    re-run exactly those under Python ``re``. A step-budget blowup on
    one item bails the REST of the batch too (all later items None,
    not attempted: burning a fresh budget per item inside one call
    would block the pool for minutes); cheap frame/trail overflows
    only fail their own item. Returns None only when the lib itself is
    unavailable."""
    lib = ensure_crex()
    if lib is None or not parts:
        return None if lib is None else []
    pp, mp, nprog = getattr(cp, "_bound", None) or _bind(cp)
    g2 = 2 * group if group and group in cp.group_exists else 0
    n = len(parts)
    datas = (ctypes.c_char_p * n)(*parts)
    lens = np.fromiter((len(p) for p in parts), dtype=np.int32, count=n)
    counts = np.empty(n, dtype=np.int64)
    lens_p = lens.ctypes.data_as(ctypes.c_void_p)
    counts_p = counts.ctypes.data_as(ctypes.c_void_p)
    cap = 4096
    while True:
        out = np.empty(2 * cap, dtype=np.int32)
        total = lib.sw_crex_finditer_batch(
            pp, nprog, mp, datas, lens_p, n, g2, cp.n_saves,
            out.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(cap),
            counts_p, _BUDGET,
        )
        if total == -3:
            cap *= 4
            continue
        break
    flat = out[: 2 * total].tolist()
    res: list = []
    off = 0
    budget_fail = False
    for c in counts.tolist():
        if c < 0:
            budget_fail = budget_fail or c == -2
            res.append(None)
            continue
        res.append(
            list(zip(flat[2 * off : 2 * (off + c) : 2],
                     flat[2 * off + 1 : 2 * (off + c) : 2]))
        )
        off += c
    if budget_fail:
        _note_budget_fail(cp)  # once per call, not per item
    return res


def _dfa_handle(cp, lib, pp, mp, nprog) -> int:
    """The program's lazy-DFA context handle (0 = doesn't qualify),
    built once and cached on the program object. A racing second
    build constructs one redundant context; attribute assignment is
    atomic and both get finalizers, so neither leaks."""
    dfa = getattr(cp, "_dfa", None)
    if dfa is None:
        lib.sw_crex_dfa_new.restype = ctypes.c_void_p
        dfa = lib.sw_crex_dfa_new(pp, nprog, mp) or 0
        if dfa:
            # the context must die WITH the program object: a program
            # from a saturated compile cache is throwaway, and an
            # orphaned context would leak its state tables
            import weakref

            weakref.finalize(cp, lib.sw_crex_dfa_free,
                             ctypes.c_void_p(dfa))
        cp._dfa = dfa
    return dfa


def exists(cp, data: bytes) -> Optional[bool]:
    """Linear-time ``re.search(pattern, text) is not None``. ``cp``
    must come from crexc.compile_crex_nfa (counter-free).

    Two native tiers, both exact and budget-free: the lazy DFA
    (subset construction with byte equivalence classes, built once
    per pattern and cached on the program object — ~ns/byte steady
    state) for anchor-free programs, then the bitset Thompson scan
    (O(len x program)) for the rest or when the DFA hits its state
    cap. Returns None when the lib is unavailable or the program
    isn't simulable (caller falls back)."""
    lib = ensure_crex()
    if lib is None or cp is None:
        return None
    pp, mp, nprog = getattr(cp, "_bound", None) or _bind(cp)
    dfa = _dfa_handle(cp, lib, pp, mp, nprog)
    if dfa:
        rc = lib.sw_crex_dfa_exists(ctypes.c_void_p(dfa), data, len(data))
        if rc >= 0:
            return bool(rc)
    rc = lib.sw_crex_exists(pp, nprog, mp, data, len(data))
    if rc < 0:
        return None
    return bool(rc)


def exists_batch(cp, parts: "list[bytes]") -> Optional["np.ndarray"]:
    """Per-part exact ``re.search is not None`` verdicts for ONE
    counter-free program — one GIL-released dispatch for the whole
    row group (the walk's batched regex confirm; per-call dispatch
    overhead dominated at confirm rates the same way it did for
    extraction). Returns an int8 array: 1/0 exact verdict, -1 = that
    part needs the Python fallback. None when the lib (or the batch
    symbol) is unavailable — caller falls back wholesale."""
    lib = ensure_crex()
    if lib is None or cp is None:
        return None
    fn = getattr(lib, "sw_crex_exists_batch", None)
    if fn is None:
        return None
    n = len(parts)
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    pp, mp, nprog = getattr(cp, "_bound", None) or _bind(cp)
    dfa = _dfa_handle(cp, lib, pp, mp, nprog)
    datas = (ctypes.c_char_p * n)(*parts)
    lens = np.fromiter((len(p) for p in parts), dtype=np.int32, count=n)
    out = np.empty(n, dtype=np.int8)
    fn(
        ctypes.c_void_p(dfa) if dfa else None, pp, nprog, mp, datas,
        lens.ctypes.data_as(ctypes.c_void_p), n,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def search(cp, data: bytes) -> Optional[bool]:
    """``re.search(pattern, text) is not None`` — or None on resource
    exhaustion (caller falls back)."""
    lib = ensure_crex()
    if lib is None:
        return None
    pp, mp, nprog = getattr(cp, "_bound", None) or _bind(cp)
    rc = lib.sw_crex_search(
        pp, nprog, mp, data, len(data), cp.n_saves, _BUDGET,
    )
    if rc < 0:
        if rc == -2:
            _note_budget_fail(cp)
        return None
    return bool(rc)


__all__ = [
    "ensure_crex", "exists", "exists_batch", "finditer_spans",
    "finditer_spans_batch", "search", "usable", "MAX_BUDGET_FAILS",
    "STEP_BUDGET",
]
