"""ctypes binding for the native async scan I/O engine (native/scanio.cpp).

The native layer replaces the reference's shelled-out scanning binaries
(``worker/modules/*.json`` → nmap/dnsx/httpx/httprobe, SURVEY.md §2.2)
with one epoll event loop producing flat numpy buffers — the
fixed-shape ``(host, port, banner)`` arrays the device match pipeline
consumes. The libscanio CDLL calls release the GIL (ctypes does this
for foreign calls), so a worker can overlap probing with device
compute; the libfastpack batch-packer below is PyDLL-loaded and HOLDS
the GIL (it walks Python bytes objects).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import socket
import struct
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

STATUS_OPEN = 0
STATUS_CLOSED = 1
STATUS_TIMEOUT = 2
STATUS_ERROR = 3
STATUS_TLS_FAILED = 5  # TCP connected, TLS handshake failed / unavailable

_SRC_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
# Alternate-build override (tools/sanitize_natives.sh): point every
# loader at a directory of DELIBERATELY prebuilt .so (e.g. the
# ASan+UBSan set) and skip the auto-make — the operator built exactly
# what they want loaded. Captured ONCE at import (empty = unset): the
# path and the skip-make decision must come from the same snapshot, or
# setting the var after import would skip the build yet silently
# dlopen the source-tree .so.
_DIR_OVERRIDDEN = bool(os.environ.get("SWARM_NATIVE_DIR"))
_NATIVE_DIR = Path(os.environ.get("SWARM_NATIVE_DIR") or _SRC_NATIVE_DIR)
_LIB_PATH = _NATIVE_DIR / "libscanio.so"
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _load_lock
# first use can come from several engine/walk-pool threads at once:
# the make invocation and the dlopen must happen exactly once (same
# contract as native/crex.py; concurrent `make` can corrupt the .so
# another thread is mid-dlopen on)
_load_lock = threading.Lock()


def ensure_lib() -> ctypes.CDLL:
    """Load libscanio.so, building it with make on first use.
    Thread-safe: concurrent first calls serialize on _load_lock."""
    global _lib
    if _lib is not None:
        return _lib
    with _load_lock:
        return _ensure_lib_locked()


def _ensure_lib_locked() -> ctypes.CDLL:  # requires-lock: _load_lock
    global _lib
    if _lib is not None:
        return _lib
    # invoke make when possible (mtime-incremental, so a stale prebuilt
    # .so from an older checkout picks up new symbols); a deployment
    # without a toolchain falls back to the shipped .so
    if not _DIR_OVERRIDDEN:
        try:
            import sys as _sys

            subprocess.run(
                ["make", "-C", str(_SRC_NATIVE_DIR), f"PY={_sys.executable}"],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError):
            if not _LIB_PATH.exists():
                raise
    elif not _LIB_PATH.exists():
        raise FileNotFoundError(
            f"SWARM_NATIVE_DIR set but {_LIB_PATH} does not exist"
        )
    lib = ctypes.CDLL(str(_LIB_PATH))
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    i8p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32 = ctypes.c_int32
    lib.swarm_tcp_scan.argtypes = [
        u32p, u16p, i32,              # ips, ports, n
        u8p, i64p, i32p, i32p,        # payload blob/off/len, pay_idx
        i32, i32, i32, i32,           # conc, connect_to, read_to, cap
        u8p, i32p, i8p, i32p,         # banners, blens, status, rtt
    ]
    lib.swarm_tcp_scan.restype = i32
    lib.swarm_tcp_scan_tls.argtypes = [
        u32p, u16p, i32,              # ips, ports, n
        u8p, i64p, i32p, i32p,        # payload blob/off/len, pay_idx
        i8p, u8p, i32p, i32p,         # tls_mask, sni blob/off/len
        i32, i32, i32, i32,           # conc, connect_to, read_to, cap
        u8p, i32p, i8p, i32p,         # banners, blens, status, rtt
    ]
    lib.swarm_tcp_scan_tls.restype = i32
    lib.swarm_tls_available.argtypes = []
    lib.swarm_tls_available.restype = i32
    lib.swarm_dns_resolve.argtypes = [
        u8p, i32p, i32p, i32,         # names, off, len, n
        u32p, i32, i32,               # resolvers, nres, port
        i32, i32, i32,                # timeout, retries, max_addrs
        u32p, i32p, i8p,              # addrs, naddrs, status
    ]
    lib.swarm_dns_resolve.restype = i32
    _lib = lib
    return lib


# ---------------------------------------------------------------------------
# Python-aware batch packer (libfastpack.so via PyDLL — GIL held, the
# functions walk the bytes lists directly: no per-element conversions).
# ---------------------------------------------------------------------------

_FASTPACK_PATH = _NATIVE_DIR / "libfastpack.so"
_fastpack: Optional[ctypes.PyDLL] = None  # guarded-by: _load_lock


def ensure_fastpack() -> ctypes.PyDLL:
    """Thread-safe like :func:`ensure_lib` (one dlopen, ever)."""
    global _fastpack
    if _fastpack is not None:
        return _fastpack
    ensure_lib()  # same make invocation builds both shared objects
    with _load_lock:
        return _ensure_fastpack_locked()


def _ensure_fastpack_locked() -> ctypes.PyDLL:  # requires-lock: _load_lock
    global _fastpack
    if _fastpack is not None:
        return _fastpack
    lib = ctypes.PyDLL(str(_FASTPACK_PATH))
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32 = ctypes.c_int32
    lib.sw_lens_list.argtypes = [ctypes.py_object, i64p]
    lib.sw_lens_list.restype = ctypes.c_int
    lib.sw_pack_list.argtypes = [ctypes.py_object, i32, u8p, i64p]
    lib.sw_pack_list.restype = ctypes.c_int
    lib.sw_concat3_list.argtypes = [
        ctypes.py_object, ctypes.py_object, u8p, i32, u8p
    ]
    lib.sw_concat3_list.restype = ctypes.c_int
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    upp = np.ctypeslib.ndpointer(np.uintp, flags="C_CONTIGUOUS")
    lib.sw_rows_meta.argtypes = [
        ctypes.py_object, i64p, i64p, i32p, u8p, upp, upp
    ]
    lib.sw_rows_meta.restype = ctypes.c_int
    lib.sw_rows_pack.argtypes = [
        ctypes.c_int64, upp, i64p, upp, i64p, u8p,
        i32, u8p, i32, u8p, i32, u8p,
    ]
    lib.sw_rows_pack.restype = ctypes.c_int
    lib.sw_rows_dedup.argtypes = [ctypes.py_object, i64p, i64p]
    lib.sw_rows_dedup.restype = ctypes.c_int64
    lib.sw_rows_alive.argtypes = [ctypes.py_object, u8p]
    lib.sw_rows_alive.restype = ctypes.c_int64
    vp = ctypes.c_void_p
    lib.sw_memo_new.argtypes = [ctypes.c_int64, i32]
    lib.sw_memo_new.restype = vp
    lib.sw_memo_free.argtypes = [vp]
    lib.sw_memo_free.restype = None
    lib.sw_memo_clear.argtypes = [vp]
    lib.sw_memo_clear.restype = None
    lib.sw_memo_len.argtypes = [vp]
    lib.sw_memo_len.restype = ctypes.c_int64
    lib.sw_memo_contains.argtypes = [vp, ctypes.py_object]
    lib.sw_memo_contains.restype = ctypes.c_int
    lib.sw_memo_contains_batch.argtypes = [vp, ctypes.py_object, u8p]
    lib.sw_memo_contains_batch.restype = ctypes.c_int64
    lib.sw_memo_insert.argtypes = [vp, ctypes.py_object, u8p, ctypes.py_object]
    lib.sw_memo_insert.restype = ctypes.c_int
    lib.sw_memo_insert_batch.argtypes = [
        vp, ctypes.py_object, u8p, u8p, ctypes.py_object,
    ]
    lib.sw_memo_insert_batch.restype = ctypes.c_int64
    lib.sw_plane_bits.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i64p, i64p,
        ctypes.c_int64,
    ]
    lib.sw_plane_bits.restype = ctypes.c_int64
    lib.sw_ext_resolve.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, u8p, u8p,
        i64p, i64p, u8p, u8p, ctypes.c_int64, i64p, i64p, i64p, u8p,
        ctypes.c_int64,
    ]
    lib.sw_ext_resolve.restype = ctypes.c_int64
    lib.sw_memo_lookup.argtypes = [
        vp, ctypes.py_object, u8p, i64p, i64p,
        ctypes.py_object, ctypes.py_object,
    ]
    lib.sw_memo_lookup.restype = ctypes.c_int64
    try:
        lib.sw_confirm_needles_batch.argtypes = [
            ctypes.py_object, u8p, i64p, i32, i32, i32, u8p,
        ]
        lib.sw_confirm_needles_batch.restype = ctypes.c_int
    except AttributeError:
        # stale pre-batch .so (make failed but an old build survived):
        # the walk's batched word confirm degrades to the Python path
        pass
    _fastpack = lib
    return lib


def lens_list(parts: list) -> np.ndarray:
    out = np.empty(len(parts), dtype=np.int64)
    if ensure_fastpack().sw_lens_list(parts, out) != 0:
        raise TypeError("parts must be a list of bytes")
    return out


def pack_list(
    parts: list, width: int, out: np.ndarray,
    lens: "np.ndarray | None" = None,
) -> np.ndarray:
    """Pack a bytes list into the zero-prefilled padded matrix; returns
    each row's FULL (pre-clip) length. Callers that already hold the
    length array pass it as ``lens`` (identical overwrite) to skip the
    throwaway allocation on the hot path."""
    if lens is None:
        lens = np.empty(len(parts), dtype=np.int64)
    if ensure_fastpack().sw_pack_list(parts, np.int32(width), out, lens) != 0:
        raise TypeError("parts must be a list of bytes")
    return lens


def rows_meta(
    rows: list,
    blens: np.ndarray,
    hlens: np.ndarray,
    status: np.ndarray,
    concat: np.ndarray,
    bptr: np.ndarray,
    hptr: np.ndarray,
) -> bool:
    """One C pass over the Response list: body/header lengths
    (banner-aliased, matching model.Response.part()), status codes, the
    per-row concat flag, and the raw byte pointers of each part
    (``bptr``/``hptr``, np.uintp) for :func:`rows_pack`. The pointers
    are owned by the rows — keep the list untouched until packing is
    done. Returns True when any row carries OOB interaction data."""
    rc = ensure_fastpack().sw_rows_meta(
        rows, blens, hlens, status, concat, bptr, hptr
    )
    if rc < 0:
        raise TypeError("rows must be Response objects with bytes parts")
    return bool(rc)


def rows_pack(
    n: int,
    bptr: np.ndarray,
    blens: np.ndarray,
    hptr: np.ndarray,
    hlens: np.ndarray,
    concat: np.ndarray,
    wb: int,
    body_out: np.ndarray,
    wh: int,
    header_out: np.ndarray,
    wa: int,
    all_out: np.ndarray,
) -> None:
    """Pack body/header/'all' matrices from the pointers
    :func:`rows_meta` cached, writing every byte of every row
    (payload + zero tail) — output buffers may be dirty/recycled.
    Pure memcpy with the GIL released. ``wa`` 0 skips 'all'."""
    ensure_fastpack().sw_rows_pack(
        np.int64(n), bptr, blens, hptr, hlens, concat,
        np.int32(wb), body_out, np.int32(wh), header_out,
        np.int32(wa), all_out,
    )


def rows_dedup(rows: list) -> "tuple[np.ndarray, np.ndarray]":
    """Content-dedup a list of Response rows in one C pass — the native
    twin of engine._dedup_rows with identical key semantics (exact
    compare on banner/body/header/status/oob fields; the internal hash
    only picks buckets). Returns ``(uniq, back)``: ``uniq[s]`` is the
    first row index of unique slot s, ``back[i]`` the slot of row i."""
    n = len(rows)
    back = np.empty(n, dtype=np.int64)
    uniq = np.empty(n, dtype=np.int64)
    nu = ensure_fastpack().sw_rows_dedup(rows, back, uniq)
    if nu < 0:
        raise TypeError("rows must be Response objects with bytes parts")
    return uniq[:nu], back


class VerdictMemo:
    """Resident verdict cache (native/fastpack.cpp): content-keyed LRU
    whose lookup pass serves known rows by memcpy into the batch's
    verdict plane and in-batch-dedups the misses — the steady-state hot
    path of the exact engine with zero per-row Python work for known
    content. Key semantics are exactly engine._content_key's (full
    compare; the internal hash only routes). Single-threaded per
    instance under the GIL (PyDLL)."""

    def __init__(self, capacity: int, row_bytes: int):
        self._lib = ensure_fastpack()
        self.row_bytes = int(row_bytes)
        self.capacity = int(capacity)
        self._h = self._lib.sw_memo_new(
            np.int64(capacity), np.int32(row_bytes)
        )
        if not self._h:
            raise MemoryError("sw_memo_new failed")

    def lookup(self, rows: list, bits_out: np.ndarray):
        """Serve known rows into ``bits_out`` ([n, row_bytes], any prior
        content — served and dead rows are fully overwritten, miss rows
        are NOT touched). Returns
        ``(state, miss_uniq, extractions, deferred)``:
        ``state[i]`` is -1 for a memo-served row, -2 for a DEAD row
        (``alive`` falsy — zero verdicts written, no memo traffic),
        else its miss-slot id; ``miss_uniq[s]`` is the first row index
        of miss slot s. Served rows' extras come back APPLIED:
        ``extractions`` is ``{(row, tid): thawed-list}`` (fresh lists —
        callers may mutate) and ``deferred`` the ``(row, t_idx)``
        row-dependent template pairs. Consumers must treat -1 and -2
        distinctly (only -1 is a memo hit; -2 rows are skipped by the
        host-always tail). Inserted extras objects MUST be the
        ``(ment, mdef)`` tuple shape the engine stores (or None)."""
        n = len(rows)
        state = np.empty(n, dtype=np.int64)
        miss_uniq = np.empty(max(n, 1), dtype=np.int64)
        extractions: dict = {}
        deferred: list = []
        nm = self._lib.sw_memo_lookup(
            self._h, rows, bits_out, state, miss_uniq, extractions,
            deferred,
        )
        if nm < 0:
            raise TypeError("rows must be Response objects")
        return state, miss_uniq[:nm].tolist(), extractions, deferred

    def insert(self, row, bits_row: np.ndarray, extras) -> None:
        # the lookup pass unpacks extras as (ment, mdef) in C — reject
        # other shapes HERE, at the call that supplied the bad object
        # (a later lookup would fail far from the cause)
        if extras is not None and not (
            isinstance(extras, tuple)
            and len(extras) == 2
            and isinstance(extras[0], tuple)
            and isinstance(extras[1], tuple)
        ):
            raise ValueError(
                "extras must be a (ment, mdef) tuple pair or None"
            )
        if self._lib.sw_memo_insert(self._h, row, bits_row, extras) != 0:
            raise TypeError("memo insert failed")

    def insert_batch(
        self,
        rows: list,
        bits_plane: np.ndarray,
        skip: np.ndarray,
        extras_list: list,
    ) -> int:
        """Insert every non-skipped row of a walked plane in ONE native
        call (row i's bits at ``bits_plane[i]``). ``extras_list[i]`` is
        the (ment, mdef) tuple or None; validated here like
        :meth:`insert`. Returns the inserted count."""
        if len(rows) != len(extras_list) or len(rows) != len(skip):
            raise ValueError("rows/skip/extras_list length mismatch")
        if (
            bits_plane.dtype != np.uint8
            or bits_plane.ndim != 2
            or bits_plane.shape[0] < len(rows)
            or bits_plane.shape[1] != self.row_bytes
        ):
            raise ValueError(
                f"bits_plane must be uint8 [>={len(rows)}, "
                f"{self.row_bytes}]"
            )
        for extras in extras_list:
            if extras is not None and not (
                isinstance(extras, tuple)
                and len(extras) == 2
                and isinstance(extras[0], tuple)
                and isinstance(extras[1], tuple)
            ):
                raise ValueError(
                    "extras must be a (ment, mdef) tuple pair or None"
                )
        if not bits_plane.flags["C_CONTIGUOUS"]:
            bits_plane = np.ascontiguousarray(bits_plane)
        n = self._lib.sw_memo_insert_batch(
            self._h, rows, bits_plane, skip, extras_list
        )
        if n < 0:
            raise TypeError("memo batch insert failed")
        return int(n)

    def contains_batch(self, rows: list) -> np.ndarray:
        """uint8 mask: ``mask[i]`` nonzero iff ``rows[i]``'s content is
        resident — one native call for the whole chunk, no LRU side
        effects (the scheduler's plan-time memo split)."""
        mask = np.zeros(max(len(rows), 1), dtype=np.uint8)
        if rows:
            rc = self._lib.sw_memo_contains_batch(self._h, rows, mask)
            if rc < 0:
                raise TypeError("rows must be Response objects")
        return mask[: len(rows)]

    def contains(self, row) -> bool:
        rc = self._lib.sw_memo_contains(self._h, row)
        if rc < 0:
            raise TypeError("row must be a Response object")
        return bool(rc)

    def clear(self) -> None:
        self._lib.sw_memo_clear(self._h)

    def __len__(self) -> int:
        return int(self._lib.sw_memo_len(self._h))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sw_memo_free(h)
            self._h = None


def plane_bits(plane: np.ndarray, limit: int):
    """(rows, bits) index arrays of the set bits of a packed uint8
    [n, nb] plane (MSB-first, bit < limit), row-major — one C pass."""
    if not plane.flags["C_CONTIGUOUS"]:
        plane = np.ascontiguousarray(plane)
    lib = ensure_fastpack()
    cap = max(256, 2 * int(np.count_nonzero(plane)) * 8)
    while True:
        rs = np.empty(cap, dtype=np.int64)
        ts = np.empty(cap, dtype=np.int64)
        n = lib.sw_plane_bits(
            plane, plane.shape[0], plane.shape[1], limit, rs, ts, cap
        )
        if n >= 0:
            return rs[:n], ts[:n]
        cap *= 4


def ext_resolve(
    masked: np.ndarray,
    limit: int,
    rowdep: np.ndarray,
    skip_rows: np.ndarray,
    indptr: np.ndarray,
    opids: np.ndarray,
    pop_value: np.ndarray,
    pop_unc: np.ndarray,
):
    """(rows, templates, op_ids, states) for every extractor-plane hit
    whose op needs Python work — state 1 certainly-true (extract),
    state 2 undecided (resolve first). One C pass (sw_ext_resolve).

    Planes are normalized (not asserted) to C order: callers hand in
    arrays derived from device read-backs whose layout XLA chooses, so
    F-ordered inputs are legal here and copied row-major once."""
    masked = np.ascontiguousarray(masked)
    pop_value = np.ascontiguousarray(pop_value)
    pop_unc = np.ascontiguousarray(pop_unc)
    lib = ensure_fastpack()
    cap = max(256, 16 * int(np.count_nonzero(masked)))
    while True:
        bs = np.empty(cap, dtype=np.int64)
        ts = np.empty(cap, dtype=np.int64)
        ops = np.empty(cap, dtype=np.int64)
        states = np.empty(cap, dtype=np.uint8)
        n = lib.sw_ext_resolve(
            masked, masked.shape[0], masked.shape[1], limit, rowdep,
            skip_rows, indptr, opids, pop_value, pop_unc,
            pop_value.shape[1], bs, ts, ops, states, cap,
        )
        if n >= 0:
            return bs[:n], ts[:n], ops[:n], states[:n]
        cap *= 4


def confirm_needles_batch(
    parts: list, needles: "list[bytes]", ci: bool, cond_and: bool,
) -> Optional[np.ndarray]:
    """Raw (pre-negation) and/or-combined needle verdicts of ONE
    word/binary matcher over a list of part bytes — one C pass with
    the GIL released (the walk's batched confirm). ``needles`` must be
    pre-lowered when ``ci`` (bytes.lower() semantics); never call with
    an empty needle list (the oracle defines that as False before the
    combine — handle it in the caller). Returns a uint8 verdict array,
    or None when the batch symbol is missing (stale .so — caller falls
    back to the serial confirm)."""
    lib = ensure_fastpack()
    fn = getattr(lib, "sw_confirm_needles_batch", None)
    if fn is None or not needles:
        return None
    n = len(parts)
    offs = np.zeros(len(needles) + 1, dtype=np.int64)
    np.cumsum([len(nd) for nd in needles], out=offs[1:])
    blob = np.frombuffer(b"".join(needles) or b"\0", dtype=np.uint8)
    out = np.empty(max(n, 1), dtype=np.uint8)
    if fn(parts, blob, offs, len(needles),
          1 if ci else 0, 1 if cond_and else 0, out) != 0:
        raise TypeError("parts must be a list of bytes")
    return out[:n]


def rows_alive(rows: list) -> "tuple[int, np.ndarray]":
    """(alive_count, uint8 mask) in one C pass over Response rows."""
    mask = np.empty(len(rows), dtype=np.uint8)
    n = ensure_fastpack().sw_rows_alive(rows, mask)
    if n < 0:
        raise TypeError("rows must be Response objects")
    return int(n), mask


def concat3_list(
    headers: list, bodies: list, concat: np.ndarray, width: int,
    out: np.ndarray,
) -> None:
    """Assemble the 'all' stream (header + CRLF + body, or body alone
    when ``concat[i]`` is 0) straight from the bytes lists."""
    if (
        ensure_fastpack().sw_concat3_list(
            headers, bodies, concat, np.int32(width), out
        )
        != 0
    ):
        raise TypeError("headers/bodies must be matching lists of bytes")


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScanResult:
    """Flat-buffer result of one tcp_scan batch (row i ↔ target i)."""

    banners: np.ndarray   # uint8 [n, banner_cap]
    banner_len: np.ndarray  # int32 [n]
    status: np.ndarray    # int8 [n] — STATUS_*
    rtt_us: np.ndarray    # int32 [n], -1 if never connected

    def banner(self, i: int) -> bytes:
        return self.banners[i, : self.banner_len[i]].tobytes()

    @property
    def open_mask(self) -> np.ndarray:
        return self.status == STATUS_OPEN


def parse_ipv4(hosts: Sequence[str]) -> np.ndarray:
    """Dotted-quad strings → uint32 network-order array."""
    out = np.empty(len(hosts), dtype=np.uint32)
    for i, h in enumerate(hosts):
        out[i] = struct.unpack("=I", socket.inet_aton(h))[0]
    return out


def format_ipv4(addrs: np.ndarray) -> list[str]:
    return [socket.inet_ntoa(struct.pack("=I", int(a))) for a in addrs]


def tls_available() -> bool:
    """Whether libssl could be loaded (TLS-wrapped probing works)."""
    return bool(ensure_lib().swarm_tls_available())


def tcp_scan(
    ips: np.ndarray | Sequence[str],
    ports: np.ndarray | Sequence[int],
    payloads: Optional[Sequence[Optional[bytes]]] = None,
    *,
    tls: Optional[Sequence[bool]] = None,
    sni: Optional[Sequence[Optional[str]]] = None,
    max_concurrency: int = 512,
    connect_timeout_ms: int = 1500,
    read_timeout_ms: int = 2000,
    banner_cap: int = 4096,
) -> ScanResult:
    """Batch TCP connect scan + banner/payload probe.

    ``payloads[i]`` (optional) is written right after connect — an HTTP
    request for httpx-style probing, a protocol nudge for banner
    grabbing, or None to listen silently (nmap-style banner wait).
    ``tls[i]`` wraps target i in TLS first (payload sent and banner read
    through the encrypted channel); ``sni[i]`` sets its SNI hostname.
    Targets where the handshake fails report STATUS_TLS_FAILED.
    """
    lib = ensure_lib()
    if len(ips) and isinstance(ips[0], str):
        ips = parse_ipv4(ips)  # type: ignore[arg-type]
    ips = np.ascontiguousarray(ips, dtype=np.uint32)
    ports_a = np.ascontiguousarray(ports, dtype=np.uint16)
    n = ips.shape[0]
    if ports_a.shape[0] != n:
        raise ValueError("ips and ports must be the same length")

    # dedupe payloads into one blob
    pay_idx = np.full(n, -1, dtype=np.int32)
    blob_parts: list[bytes] = []
    offsets: list[int] = []
    lens: list[int] = []
    seen: dict[bytes, int] = {}
    total = 0
    if payloads is not None:
        for i, p in enumerate(payloads):
            if not p:
                continue
            idx = seen.get(p)
            if idx is None:
                idx = len(offsets)
                seen[p] = idx
                offsets.append(total)
                lens.append(len(p))
                blob_parts.append(p)
                total += len(p)
            pay_idx[i] = idx
    blob = np.frombuffer(b"".join(blob_parts) or b"\0", dtype=np.uint8).copy()
    pay_off = np.asarray(offsets or [0], dtype=np.int64)
    pay_len = np.asarray(lens or [0], dtype=np.int32)

    # TLS mask + SNI name blob
    tls_mask = np.zeros(n, dtype=np.int8)
    if tls is not None:
        tls_mask[: len(tls)] = [1 if t else 0 for t in tls]
    sni_parts: list[bytes] = []
    sni_off = np.zeros(n, dtype=np.int32)
    sni_len = np.zeros(n, dtype=np.int32)
    stotal = 0
    if sni is not None:
        for i, name in enumerate(sni):
            if not name:
                continue
            try:
                enc = (
                    name.encode("idna")
                    if any(ord(c) > 127 for c in name)
                    else name.encode("ascii")
                )
            except UnicodeError:
                continue  # unencodable label → probe without SNI
            sni_off[i] = stotal
            sni_len[i] = len(enc)
            sni_parts.append(enc)
            stotal += len(enc)
    sni_blob = np.frombuffer(b"".join(sni_parts) or b"\0", dtype=np.uint8).copy()

    banners = np.zeros((n, banner_cap), dtype=np.uint8)
    blens = np.zeros(n, dtype=np.int32)
    status = np.zeros(n, dtype=np.int8)
    rtt = np.zeros(n, dtype=np.int32)
    if n:
        rc = lib.swarm_tcp_scan_tls(
            ips, ports_a, n,
            blob, pay_off, pay_len, pay_idx,
            tls_mask, sni_blob, sni_off, sni_len,
            max_concurrency, connect_timeout_ms, read_timeout_ms, banner_cap,
            banners, blens, status, rtt,
        )
        if rc != 0:
            raise OSError(f"swarm_tcp_scan failed (rc={rc})")
    return ScanResult(banners=banners, banner_len=blens, status=status, rtt_us=rtt)


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DnsResult:
    addrs: np.ndarray    # uint32 [n, max_addrs] network order
    naddrs: np.ndarray   # int32 [n]
    status: np.ndarray   # int8 [n]

    def addresses(self, i: int) -> list[str]:
        return format_ipv4(self.addrs[i, : self.naddrs[i]])

    @property
    def resolved_mask(self) -> np.ndarray:
        return self.status == STATUS_OPEN


def _encode_name(s: str) -> bytes:
    """Hostname → DNS wire-ready bytes; b'' for unencodable names (the
    native layer reports those as SW_ERROR rather than failing the wave)."""
    if not s:
        return b""
    s = s.rstrip(".")
    try:
        return s.encode("ascii")
    except UnicodeEncodeError:
        pass
    try:
        return s.encode("idna")
    except UnicodeError:
        return b""


def dns_resolve(
    names: Sequence[str],
    resolvers: Sequence[str],
    *,
    resolver_port: int = 53,
    timeout_ms: int = 2000,
    retries: int = 2,
    max_addrs: int = 8,
    wave: int = 50_000,
) -> DnsResult:
    """Bulk A-record resolution against a resolver pool (dnsx analog).

    Waves of ≤50k keep inside the 16-bit DNS id namespace per socket.
    """
    lib = ensure_lib()
    n = len(names)
    addrs = np.zeros((max(n, 1), max_addrs), dtype=np.uint32)
    naddrs = np.zeros(max(n, 1), dtype=np.int32)
    status = np.zeros(max(n, 1), dtype=np.int8)
    res = parse_ipv4(list(resolvers))
    for start in range(0, n, wave):
        sub = names[start : start + wave]
        encoded = [_encode_name(s) for s in sub]
        blob = np.frombuffer(b"".join(encoded) or b"\0", dtype=np.uint8).copy()
        offs = np.zeros(len(sub), dtype=np.int32)
        lens = np.zeros(len(sub), dtype=np.int32)
        pos = 0
        for i, e in enumerate(encoded):
            offs[i] = pos
            lens[i] = len(e)
            pos += len(e)
        sub_addrs = np.zeros((len(sub), max_addrs), dtype=np.uint32)
        sub_naddrs = np.zeros(len(sub), dtype=np.int32)
        sub_status = np.zeros(len(sub), dtype=np.int8)
        rc = lib.swarm_dns_resolve(
            blob, offs, lens, len(sub),
            res, len(res), resolver_port,
            timeout_ms, retries, max_addrs,
            sub_addrs, sub_naddrs, sub_status,
        )
        if rc != 0:
            raise OSError(f"swarm_dns_resolve failed (rc={rc})")
        addrs[start : start + len(sub)] = sub_addrs
        naddrs[start : start + len(sub)] = sub_naddrs
        status[start : start + len(sub)] = sub_status
    return DnsResult(addrs=addrs[:n], naddrs=naddrs[:n], status=status[:n])
