from swarm_tpu.native.scanio import (  # noqa: F401
    STATUS_CLOSED,
    STATUS_ERROR,
    STATUS_OPEN,
    STATUS_TIMEOUT,
    STATUS_TLS_FAILED,
    DnsResult,
    ScanResult,
    dns_resolve,
    ensure_lib,
    tcp_scan,
    tls_available,
)
