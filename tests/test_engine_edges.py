"""Engine edge paths: host-always tail through dedup, empty inputs,
truncation-vs-memo interaction, and listener reply robustness."""

import textwrap

import yaml

from swarm_tpu.fingerprints.model import Response
from swarm_tpu.fingerprints.nuclei import parse_template
from swarm_tpu.ops.engine import MatchEngine


def T(doc: str, path="t/x.yaml"):
    return parse_template(yaml.safe_load(textwrap.dedent(doc)), source_path=path)


HOST_PART_TEMPLATE = """\
id: host-part-match
info: {name: h, severity: info}
requests:
  - method: GET
    path: ["{{BaseURL}}/"]
    matchers:
      - type: word
        part: host
        words: ["internal.corp"]
"""

BODY_TEMPLATE = """\
id: body-match
info: {name: b, severity: info}
requests:
  - method: GET
    path: ["{{BaseURL}}/"]
    matchers:
      - type: word
        words: ["hello-world"]
"""


def test_host_part_matcher_resolves_per_row_through_dedup():
    """A part-'host' word matcher reads beyond response content;
    content-identical rows on different hosts must diverge on it for
    every member of a deduped group (the row-dependent fixup path)."""
    templates = [T(HOST_PART_TEMPLATE), T(BODY_TEMPLATE)]
    eng = MatchEngine(templates, mesh=None)
    # the template is detected as row-dependent (not silently merged)
    ids = [t.id for t in eng.db.templates]
    assert "host-part-match" in ids
    assert ids.index("host-part-match") in eng._rowdep_t
    body = b"hello-world page"
    rows = [
        Response(host="a.internal.corp", port=80, status=200, body=body),
        Response(host="b.public.example", port=80, status=200, body=body),
        Response(host="c.internal.corp", port=80, status=200, body=body),
    ]
    got = eng.match(rows)
    for g in got:
        assert "body-match" in g.template_ids
    assert "host-part-match" in got[0].template_ids
    assert "host-part-match" not in got[1].template_ids
    assert "host-part-match" in got[2].template_ids
    # and again through the verdict memo (content now known)
    got2 = eng.match(rows)
    for a, b in zip(got, got2):
        assert sorted(a.template_ids) == sorted(b.template_ids)


def test_truncated_content_not_memoized():
    """Truncated rows are host-redone and must NOT enter the verdict
    memo — a later batch with the same content re-resolves fully."""
    t = T(BODY_TEMPLATE)
    eng = MatchEngine([t], mesh=None, max_body=512, max_header=256)
    big = Response(
        host="big", port=80, status=200,
        body=b"x" * 2000 + b"hello-world",  # beyond max_body -> truncated
    )
    small = Response(host="s", port=80, status=200, body=b"hello-world")
    for _ in range(2):
        got = eng.match([big, small])
        assert "body-match" in got[0].template_ids  # redo path found it
        assert "body-match" in got[1].template_ids
    # the truncated content never entered the memo; the small one did
    assert eng.memo_contains(small)
    assert not eng.memo_contains(big)


def test_empty_device_corpus_fused_planes():
    """num_templates == 0 still round-trips the fused device output:
    eval_verdicts pads the template planes to one packed byte each, and
    split_fused's widths must mirror that padding exactly — a mismatch
    silently shears every later plane (op bits read as template bits)."""
    eng = MatchEngine([], mesh=None)
    assert eng.db.num_templates == 0
    got = eng.match([Response(host="a", port=80, status=200, body=b"x")])
    assert got[0].template_ids == []


def test_empty_and_dead_batches():
    t = T(BODY_TEMPLATE)
    eng = MatchEngine([t], mesh=None)
    assert eng.match([]) == []
    dead = [Response(host=f"d{i}", alive=False) for i in range(5)]
    got = eng.match(dead)
    assert all(g.template_ids == [] for g in got)
    # mixed dead/alive via the packed path
    mixed = dead + [Response(host="a", port=80, status=200, body=b"hello-world")]
    got = eng.match(mixed)
    assert got[-1].template_ids == ["body-match"]
    assert all(g.template_ids == [] for g in got[:-1])


EXTRACT_TEMPLATE = """\
id: version-extract
info: {name: v, severity: info}
requests:
  - method: GET
    path: ["{{BaseURL}}/"]
    matchers:
      - type: word
        words: ["server-x"]
    extractors:
      - type: regex
        regex: ["server-x/([0-9.]+)"]
        group: 1
"""


def test_native_memo_matches_python_memo_path():
    """The C resident verdict cache and the Python dict memo must
    produce bit-identical engines: same verdicts, extractions, confirm
    attribution — across repeats (memo replay), truncation (never
    memoized), dead rows, and row-dependent host gates."""
    templates = [
        T(HOST_PART_TEMPLATE), T(BODY_TEMPLATE),
        T(EXTRACT_TEMPLATE, path="t/e.yaml"),
    ]
    nat = MatchEngine(templates, mesh=None, max_body=512, max_header=256)
    py = MatchEngine(templates, mesh=None, max_body=512, max_header=256)
    py._native_memo_ok = False  # force the dict-memo fallback
    if not nat._use_native_memo():
        import pytest

        pytest.skip("native lib unavailable")
    body = b"hello-world from server-x/2.71 build"
    rows = [
        Response(host="a.internal.corp", port=80, status=200, body=body),
        Response(host="b.public.example", port=80, status=200, body=body),
        Response(host="t", port=80, status=200,
                 body=b"x" * 900 + b"hello-world"),  # truncated
        Response(host="dead", alive=False),
        Response(host="c", port=80, status=200, body=body),
    ]
    for batch in (rows, rows, list(reversed(rows))):  # replay + reorder
        a = nat.match(batch)
        b = py.match(batch)
        for i, (x, y) in enumerate(zip(a, b)):
            assert sorted(x.template_ids) == sorted(y.template_ids), i
            assert x.extractions == y.extractions, i
    # both memos hold the small content, neither the truncated row
    assert nat.memo_contains(rows[0]) and py.memo_contains(rows[0])
    assert not nat.memo_contains(rows[2])
    assert not py.memo_contains(rows[2])


def test_property_rows_through_native_passes():
    """Rows whose byte attributes are PROPERTIES returning a fresh
    object per access exercise the C passes' PyObject_GetAttr fallback
    (no instance-__dict__ hit). The views built there keep interior
    byte pointers, so the pass must pin the fetched objects for its
    duration, and the memo's stored key must alias the objects the
    ENTRY owns — not the lookup view's short-lived buffers
    (ADVICE r2: fastpack.cpp row_view_dict / sw_memo_insert)."""
    body = b"hello-world from server-x/2.71 build"

    class FreshBytesRow(Response):
        # dataclass __init__ assigns through the setters; the getters
        # hand back a NEW bytes object every access
        @property
        def body(self):  # noqa: D102
            return bytes(memoryview(body))

        @body.setter
        def body(self, v):
            pass

        @property
        def header(self):  # noqa: D102
            return bytes(memoryview(b"HTTP/1.1 200 OK\r\nServer: x"))

        @header.setter
        def header(self, v):
            pass

    templates = [
        T(BODY_TEMPLATE), T(EXTRACT_TEMPLATE, path="t/e.yaml"),
    ]
    eng = MatchEngine(templates, mesh=None, max_body=512, max_header=256)
    if not eng._use_native_memo():
        import pytest

        pytest.skip("native lib unavailable")
    plain = Response(host="p", port=80, status=200, body=body,
                     header=b"HTTP/1.1 200 OK\r\nServer: x")
    expect = eng.match([plain])[0]
    for _ in range(3):  # miss, then memo-served replays
        rows = [FreshBytesRow(host="p", port=80, status=200) for _ in range(4)]
        got = eng.match(rows)
        for g in got:
            assert sorted(g.template_ids) == sorted(expect.template_ids)
            assert g.extractions == expect.extractions
    assert eng.memo_contains(FreshBytesRow(host="p", port=80, status=200))


NEG_HOST_ALWAYS = """\
id: ha-negative
info: {name: n, severity: info}
requests:
  - method: GET
    path: ["{{BaseURL}}/"]
    matchers:
      - type: word
        negative: true
        words: ["absent-token"]
"""


def test_host_always_tail_skips_dead_rows():
    """Dead rows match nothing by contract — including the host-always
    tail, whose negative matchers would otherwise fire on a dead row's
    empty body. The native path folds dead rows into the batch (state
    -2) instead of pre-filtering, so the tail must skip them itself."""
    eng = MatchEngine([T(BODY_TEMPLATE)], mesh=None)
    # fabricate a host-always tail (the reference corpus lowers fully,
    # so none exists naturally)
    eng.db.host_always.append(T(NEG_HOST_ALWAYS, path="t/n.yaml"))
    alive = Response(host="a", port=80, status=200, body=b"plain page")
    dead = Response(host="d", alive=False)
    got = eng.match_packed([alive, dead])
    assert (0, "ha-negative") in got.host_always_matches
    assert all(rb != 1 for rb, _tid in got.host_always_matches)


def test_dns_reply_builder_handles_garbage():
    from swarm_tpu.worker.oob import _build_a_reply, _parse_qname

    assert _parse_qname(b"") is None
    assert _parse_qname(b"\x00" * 11) is None
    # a query whose qname claims more bytes than exist
    bogus = b"\x12\x34" + b"\x01\x00" + b"\x00\x01\x00\x00\x00\x00\x00\x00" + b"\x3fshort"
    assert _parse_qname(bogus) is None
    # a degenerate query must not raise; a well-formed one must reply
    _build_a_reply(b"\x12", b"x", "127.0.0.1")
    good = (
        b"\x12\x34\x01\x00\x00\x01\x00\x00\x00\x00\x00\x00"
        + b"\x01x\x00\x00\x01\x00\x01"
    )
    assert _build_a_reply(good, b"x", "127.0.0.1") is not None
