"""DNS-protocol template execution: typed queries, rendering, matching.

Covers the corpus's 17 dns templates' op surface (SURVEY.md §2.3):
CNAME/MX/TXT/CAA/NS/PTR/A queries, dig-style rendering the matchers
run over, rcode words, and the active-scanner dns pass end-to-end
against a local UDP resolver.
"""

import socket
import socketserver
import struct
import textwrap
import threading

import pytest
import yaml

from swarm_tpu.fingerprints.nuclei import parse_template
from swarm_tpu.worker import active, dnsquery


# ---------------------------------------------------------------------------
# wire codec unit tests (loopback through our own builder/parser)


def _answer(name_ptr: int, rtype: int, rdata: bytes) -> bytes:
    return (
        struct.pack("!H", 0xC000 | name_ptr)
        + struct.pack("!HHIH", rtype, 1, 300, len(rdata))
        + rdata
    )


def _reply_packet(qid: int, qname: str, qtype: int, answers, rcode=0) -> bytes:
    q = dnsquery._encode_qname(qname)
    hdr = struct.pack("!HHHHHH", qid, 0x8180 | rcode, 1, len(answers), 0, 0)
    body = q + struct.pack("!HH", qtype, 1)
    return hdr + body + b"".join(answers)


def _name_bytes(name: str) -> bytes:
    return dnsquery._encode_qname(name)


def test_parse_cname_reply():
    pkt = _reply_packet(
        0, "docs.example.com", 5,
        [_answer(12, 5, _name_bytes("target.github.io"))],
    )
    reply = dnsquery.parse_reply(pkt, "docs.example.com", "CNAME")
    assert reply.rcode == "NOERROR"
    assert reply.answers[0].type_name == "CNAME"
    assert reply.answers[0].rdata == "target.github.io"
    assert b"github.io" in reply.render()


def test_parse_mx_txt_caa():
    pkt = _reply_packet(
        0, "example.com", 255,
        [
            _answer(12, 15, struct.pack("!H", 10) + _name_bytes("mail.example.com")),
            _answer(12, 16, b"\x0bv=spf1 -all"),
            _answer(12, 257, b"\x00\x05issue" + b"letsencrypt.org"),
        ],
    )
    reply = dnsquery.parse_reply(pkt, "example.com", "ANY")
    rendered = reply.render().decode()
    assert "10 mail.example.com" in rendered
    assert '"v=spf1 -all"' in rendered
    assert 'issue "letsencrypt.org"' in rendered


def test_parse_servfail_rcode():
    pkt = _reply_packet(0, "broken.example", 1, [], rcode=2)
    reply = dnsquery.parse_reply(pkt, "broken.example", "A")
    assert reply.rcode == "SERVFAIL"
    assert b"SERVFAIL" in reply.render()


def test_reverse_name():
    assert dnsquery.reverse_name("192.0.2.7") == "7.2.0.192.in-addr.arpa"


def test_compressed_name_decompression():
    # name at offset 12 (the question), answer CNAME pointing into it
    pkt = _reply_packet(
        0, "a.b.example.com", 5,
        [_answer(12, 5, struct.pack("!H", 0xC000 | 14))],  # ptr into qname
    )
    reply = dnsquery.parse_reply(pkt, "a.b.example.com", "CNAME")
    assert reply.answers[0].rdata.endswith("example.com")


# ---------------------------------------------------------------------------
# local UDP resolver fixture


class _UDPServer(socketserver.ThreadingUDPServer):
    allow_reuse_address = True
    daemon_threads = True


@pytest.fixture
def dns_server():
    """Answers CNAME queries for *.example.test with ghs.googlehosted.com;
    SERVFAIL for servfail.test; empty NOERROR otherwise."""

    class H(socketserver.BaseRequestHandler):
        def handle(self):
            data, sock = self.request
            if len(data) < 12:
                return
            qid = data[:2]
            qname, off = dnsquery._read_name(data, 12)
            qtype = struct.unpack("!H", data[off : off + 2])[0]
            question = data[12 : off + 4]
            if qname.endswith("servfail.test"):
                hdr = qid + struct.pack("!HHHHH", 0x8182, 1, 0, 0, 0)
                sock.sendto(hdr + question, self.client_address)
                return
            answers = b""
            an = 0
            if qtype == 5 and qname.endswith("example.test"):
                rdata = dnsquery._encode_qname("ghs.googlehosted.com")
                answers = (
                    struct.pack("!H", 0xC00C)
                    + struct.pack("!HHIH", 5, 1, 60, len(rdata))
                    + rdata
                )
                an = 1
            hdr = qid + struct.pack("!HHHHH", 0x8180, 1, an, 0, 0)
            sock.sendto(hdr + question + answers, self.client_address)

    srv = _UDPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_query_batch_chunks_above_id_namespace(dns_server, monkeypatch):
    """Batches larger than _MAX_BATCH split into waves transparently,
    preserving reply order across the chunk boundary."""
    monkeypatch.setattr(dnsquery, "_MAX_BATCH", 2)
    replies = dnsquery.query_batch(
        [("a.example.test", "CNAME"), ("b.example.test", "CNAME"),
         ("c.example.test", "CNAME"), ("app.servfail.test", "A"),
         ("other.test", "CNAME")],
        ["127.0.0.1"],
        timeout_ms=2000,
        port=dns_server,
    )
    assert len(replies) == 5
    for r in replies[:3]:
        assert r is not None and "ghs.googlehosted.com" in r.answers[0].rdata
    assert replies[3].rcode == "SERVFAIL"
    assert replies[4].rcode == "NOERROR" and not replies[4].answers


def test_query_ids_are_randomized(dns_server):
    """Transaction ids must not be the query index: an off-path forger
    should have to guess 16 random bits, not count upward."""
    seen: list[int] = []

    class H(socketserver.BaseRequestHandler):
        def handle(self):
            data, sock = self.request
            seen.append(struct.unpack("!H", data[:2])[0])
            qname, off = dnsquery._read_name(data, 12)
            question = data[12 : off + 4]
            hdr = data[:2] + struct.pack("!HHHHH", 0x8180, 1, 0, 0, 0)
            sock.sendto(hdr + question, self.client_address)

    srv = _UDPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        queries = [(f"q{i}.test", "A") for i in range(32)]
        replies = dnsquery.query_batch(
            queries, ["127.0.0.1"], timeout_ms=2000,
            port=srv.server_address[1],
        )
        assert all(r is not None for r in replies)
        ids = set(seen)
        assert len(ids) == 32  # all distinct
        assert ids != set(range(32))  # not the sequential index
    finally:
        srv.shutdown()


def test_query_batch_against_local_resolver(dns_server):
    replies = dnsquery.query_batch(
        [("app.example.test", "CNAME"), ("app.servfail.test", "A"),
         ("other.test", "CNAME")],
        ["127.0.0.1"],
        timeout_ms=2000,
        port=dns_server,
    )
    assert replies[0] is not None
    assert "ghs.googlehosted.com" in replies[0].answers[0].rdata
    assert replies[1].rcode == "SERVFAIL"
    assert replies[2].rcode == "NOERROR" and not replies[2].answers


# ---------------------------------------------------------------------------
# active-scanner dns pass end-to-end


DNS_TEMPLATE = """\
id: demo-cname-service
info:
  name: cname service detect
  severity: info
dns:
  - name: "{{FQDN}}"
    type: CNAME
    matchers:
      - type: word
        name: googlehosted
        words:
          - "googlehosted.com"
"""

SERVFAIL_TEMPLATE = """\
id: demo-servfail
info:
  name: servfail detect
  severity: info
dns:
  - name: "{{FQDN}}"
    type: A
    matchers:
      - type: word
        words:
          - "SERVFAIL"
          - "REFUSED"
"""


def T(doc, path="dns/x.yaml"):
    return parse_template(yaml.safe_load(textwrap.dedent(doc)), source_path=path)


def test_dns_plan_dedups_qtypes():
    t1 = T(DNS_TEMPLATE)
    t2 = T(DNS_TEMPLATE.replace("demo-cname-service", "other-cname"))
    t3 = T(SERVFAIL_TEMPLATE)
    plan = active.build_plan([t1, t2, t3])
    assert sorted(plan.dns_qtypes) == ["A", "CNAME"]
    cname_idx = plan.dns_qtypes.index("CNAME")
    assert plan.dns_owners[cname_idx] == {0, 1}


def test_dns_pass_end_to_end(dns_server, monkeypatch):
    from swarm_tpu.ops.engine import MatchEngine
    from swarm_tpu.worker import dnsquery as dq

    real_batch = dq.query_batch
    monkeypatch.setattr(
        dq, "query_batch",
        lambda queries, resolvers, timeout_ms=2000, retries=1, port=53:
            real_batch(queries, resolvers, timeout_ms, retries, port=dns_server),
    )
    templates = [T(DNS_TEMPLATE), T(SERVFAIL_TEMPLATE)]
    engine = MatchEngine(templates)
    scanner = active.ActiveScanner(
        engine, {"resolvers": ["127.0.0.1"], "read_timeout_ms": 2000}
    )
    # bypass A-record resolution: point both names at localhost
    monkeypatch.setattr(
        scanner.executor, "_resolve_names",
        lambda parsed, all_addrs=False: {
            t[0]: ["127.0.0.1"] for t in parsed
        },
    )
    hits, stats = scanner.run(["app.example.test:1", "app.servfail.test:1"])
    got = {(h.template_id, h.host) for h in hits}
    assert ("demo-cname-service", "app.example.test") in got
    assert ("demo-servfail", "app.servfail.test") in got
    # no cross-attribution: servfail template must not fire on the
    # healthy name, nor cname on the servfail name
    assert ("demo-servfail", "app.example.test") not in got
    assert ("demo-cname-service", "app.servfail.test") not in got
