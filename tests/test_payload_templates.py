"""Payload-attack template execution (default-logins / fuzzing class).

The reference delegates these to the nuclei binary
(worker/modules/nuclei.json runs the full corpus incl.
default-logins/minio/minio-default-login.yaml's ``payloads:`` block);
here the planner expands bounded attack combos into per-combo planned
requests and the responses batch-match on device.
"""

from __future__ import annotations

import socketserver
import textwrap
import threading

import pytest
import yaml

from swarm_tpu.fingerprints.nuclei import parse_template
from swarm_tpu.worker import active


def T(doc: str, path="t/x.yaml"):
    return parse_template(yaml.safe_load(textwrap.dedent(doc)), source_path=path)


LOGIN_TEMPLATE = """\
id: demo-default-login
info: {name: n, severity: high}
requests:
  - raw:
      - |
        POST /api/login HTTP/1.1
        Host: {{Hostname}}
        Content-Type: application/json

        {"username":"{{username}}","password":"{{password}}"}
    payloads:
      username:
        - admin
        - root
      password:
        - admin
        - toor
    attack: pitchfork
    matchers:
      - type: word
        part: body
        words:
          - "login-accepted"
"""


def test_pitchfork_expands_zip():
    plan = active.build_plan([T(LOGIN_TEMPLATE)])
    bodies = sorted(r.body for r in plan.requests)
    assert bodies == [
        b'{"username":"admin","password":"admin"}',
        b'{"username":"root","password":"toor"}',
    ]
    assert not plan.skipped


def test_clusterbomb_expands_product():
    t = T(LOGIN_TEMPLATE.replace("attack: pitchfork", "attack: clusterbomb"))
    plan = active.build_plan([t])
    assert len(plan.requests) == 4


def test_batteringram_single_stream():
    doc = """\
    id: demo-ram
    info: {name: n, severity: info}
    requests:
      - method: GET
        path:
          - "{{BaseURL}}/probe-{{word}}"
        payloads:
          word:
            - alpha
            - beta
        matchers:
          - type: status
            status:
              - 200
    """
    plan = active.build_plan([T(doc)])
    assert sorted(r.path for r in plan.requests) == [
        "/probe-alpha",
        "/probe-beta",
    ]


def test_exact_cap_product_is_not_flagged_truncated(monkeypatch):
    """A payload product of exactly the cap size dropped nothing —
    it must not be reported truncated (ADVICE r3), and truncation is
    its own stats channel, never a 'skipped' entry (the template runs)."""
    monkeypatch.setattr(active, "MAX_PAYLOAD_COMBOS", 4)
    t = T(LOGIN_TEMPLATE.replace("attack: pitchfork", "attack: clusterbomb"))
    plan = active.build_plan([t])  # 2x2 product == cap exactly
    assert len(plan.requests) == 4
    assert plan.payload_truncated == []
    assert "payload-truncated" not in plan.skipped

    monkeypatch.setattr(active, "MAX_PAYLOAD_COMBOS", 3)
    plan = active.build_plan([t])  # 2x2 product, one combo dropped
    assert len(plan.requests) == 3
    assert plan.payload_truncated == ["demo-default-login"]
    # truncated-but-ran: the template still planned its requests
    assert 0 in plan.planned_templates
    assert "payload-truncated" not in plan.skipped


def test_wordlist_file_payloads(tmp_path):
    words = tmp_path / "helpers" / "wordlists" / "paths.txt"
    words.parent.mkdir(parents=True)
    words.write_text("".join(f"w{i}\n" for i in range(500)))
    tdir = tmp_path / "fuzzing"
    tdir.mkdir()
    doc = {
        "id": "demo-fuzz",
        "info": {"name": "n", "severity": "info"},
        "requests": [
            {
                "method": "GET",
                "path": ["{{BaseURL}}/{{path}}"],
                "payloads": {"path": "helpers/wordlists/paths.txt"},
                "matchers": [{"type": "status", "status": [200]}],
            }
        ],
    }
    t = parse_template(doc, source_path=str(tdir / "demo-fuzz.yaml"))
    plan = active.build_plan([t])
    # bounded fan-out: at most MAX_PAYLOAD_VALUES lines — with the
    # default cap (100k, env-overridable) the whole 500-line file fans out
    assert len(plan.requests) == min(500, active.MAX_PAYLOAD_VALUES)
    assert plan.requests[0].path == "/w0"


def test_wordlist_file_payloads_env_clamp(tmp_path, monkeypatch):
    """SWARM_MAX_PAYLOAD_VALUES clamps the file fan-out."""
    words = tmp_path / "helpers" / "wordlists" / "paths.txt"
    words.parent.mkdir(parents=True)
    words.write_text("".join(f"w{i}\n" for i in range(500)))
    tdir = tmp_path / "fuzzing"
    tdir.mkdir()
    doc = {
        "id": "demo-fuzz-clamped",
        "info": {"name": "n", "severity": "info"},
        "requests": [
            {
                "method": "GET",
                "path": ["{{BaseURL}}/{{path}}"],
                "payloads": {"path": "helpers/wordlists/paths.txt"},
                "matchers": [{"type": "status", "status": [200]}],
            }
        ],
    }
    t = parse_template(doc, source_path=str(tdir / "demo.yaml"))
    monkeypatch.setattr(active, "MAX_PAYLOAD_VALUES", 37)
    plan = active.build_plan([t])
    assert len(plan.requests) == 37
    assert plan.requests[0].path == "/w0"
    # values dropped at the per-variable cap surface as truncation too
    # (the product cap never triggered here)
    assert plan.payload_truncated == ["demo-fuzz-clamped"]
    # exactly-cap-sized wordlist: nothing dropped, no flag
    monkeypatch.setattr(active, "MAX_PAYLOAD_VALUES", 500)
    plan = active.build_plan([t])
    assert len(plan.requests) == 500
    assert plan.payload_truncated == []


def test_expression_payload_placeholder():
    doc = """\
    id: demo-token
    info: {name: n, severity: info}
    requests:
      - method: GET
        path:
          - "{{BaseURL}}/check"
        headers:
          Authorization: "Basic {{base64('user:' + token)}}"
        payloads:
          token:
            - sekrit
        matchers:
          - type: status
            status:
              - 200
    """
    plan = active.build_plan([T(doc)])
    assert len(plan.requests) == 1
    import base64

    want = base64.b64encode(b"user:sekrit").decode()
    assert ("Authorization", f"Basic {want}") in plan.requests[0].headers


# --- end to end: an admin:admin endpoint caught by the login template ---


class _Srv(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


@pytest.fixture
def login_server():
    class H(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                data = self.request.recv(8192).decode("latin-1")
                body = data.split("\r\n\r\n", 1)[-1]
                if '"username":"admin","password":"admin"' in body:
                    out = "login-accepted token=xyz"
                else:
                    out = "denied"
                resp = (
                    "HTTP/1.1 200 OK\r\nContent-Type: text/html\r\n"
                    f"Content-Length: {len(out)}\r\nConnection: close\r\n\r\n{out}"
                )
                self.request.sendall(resp.encode())
            except OSError:
                pass

    srv = _Srv(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_default_login_caught_end_to_end(login_server):
    from swarm_tpu.ops.engine import MatchEngine

    t = T(LOGIN_TEMPLATE)
    engine = MatchEngine([t], mesh=None)
    scanner = active.ActiveScanner(engine, {"read_timeout_ms": 3000})
    hits, stats = scanner.run([f"127.0.0.1:{login_server}"])
    assert [h.template_id for h in hits] == ["demo-default-login"]


REFERENCE_MINIO = "/root/reference/worker/artifacts/templates/default-logins/minio/minio-default-login.yaml"


def test_reference_minio_default_login_caught():
    """VERDICT r1 #3's done-criterion, with the ACTUAL reference
    template: a fake minio whose webrpc accepts minioadmin:minioadmin
    is caught by default-logins/minio/minio-default-login.yaml."""
    import pathlib

    from swarm_tpu.fingerprints.nuclei import load_template_file
    from swarm_tpu.ops.engine import MatchEngine

    if not pathlib.Path(REFERENCE_MINIO).is_file():
        pytest.skip("reference corpus absent")

    class H(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                data = self.request.recv(8192).decode("latin-1")
                path = data.split(" ", 2)[1] if " " in data else ""
                body = data.split("\r\n\r\n", 1)[-1]
                if (
                    path == "/minio/webrpc"
                    and '"username":"minioadmin"' in body
                    and '"password":"minioadmin"' in body
                ):
                    out = ('{"jsonrpc":"2.0","id":1,"result":'
                           '{"token":"x","uiVersion":"2021"}}')
                    code = "200 OK"
                else:
                    out = '{"error":{"message":"denied"}}'
                    code = "401 Unauthorized"
                resp = (
                    f"HTTP/1.1 {code}\r\nContent-Type: application/json"
                    f"\r\nContent-Length: {len(out)}\r\n"
                    f"Connection: close\r\n\r\n{out}"
                )
                self.request.sendall(resp.encode())
            except OSError:
                pass

    srv = _Srv(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    try:
        t = load_template_file(REFERENCE_MINIO)
        eng = MatchEngine([t], mesh=None)
        scanner = active.ActiveScanner(
            eng, {"ports": [port], "connect_timeout_ms": 2000,
                  "read_timeout_ms": 2000},
        )
        hits, _stats = scanner.run([f"127.0.0.1:{port}"])
        assert "minio-default-login" in {h.template_id for h in hits}
    finally:
        srv.shutdown()
