import json

from swarm_tpu import datamodel as dm


def test_scan_id_format():
    sid = dm.generate_scan_id("nmap", timestamp=1700000000)
    assert sid == "nmap_1700000000"
    module, ts = dm.parse_scan_id(sid)
    assert module == "nmap" and ts == 1700000000


def test_job_id_roundtrip_with_underscored_module():
    sid = dm.generate_scan_id("http_probe", timestamp=123)
    jid = dm.job_id_for(sid, 7)
    scan_id, idx = dm.parse_job_id(jid)
    assert scan_id == sid and idx == 7


def test_chunk_generator_covers_all_rows():
    rows = [str(i) for i in range(103)]
    chunks = list(dm.chunk_generator(rows, 10))
    assert len(chunks) == 11
    assert sum(len(c) for c in chunks) == 103
    assert chunks[-1] == rows[100:]
    # reference treats batch_size 0 as one whole-file chunk (server.py:434-435)
    assert list(dm.chunk_generator(rows, 0)) == [rows]
    assert list(dm.chunk_generator([], 0)) == []


def test_chunk_keys_match_reference_layout():
    assert dm.chunk_input_key("nmap_1", 3) == "nmap_1/input/chunk_3.txt"
    assert dm.chunk_output_key("nmap_1", 3) == "nmap_1/output/chunk_3.txt"


def test_job_wire_roundtrip_ignores_unknown_keys():
    job = dm.Job.create("nmap_1700000000", 2, "nmap")
    wire = job.to_wire()
    wire["some_future_field"] = "ignored"
    back = dm.Job.from_json(json.dumps(wire))
    assert back == job


def test_status_taxonomy():
    assert dm.JobStatus.COMPLETE in dm.JobStatus.TERMINAL
    assert dm.JobStatus.CMD_FAILED in dm.JobStatus.FAILED
    assert dm.JobStatus.EXECUTING not in dm.JobStatus.TERMINAL
    assert "upload failed - credentials" in dm.JobStatus.ALL


def test_rollup_scans():
    jobs = {}
    for i in range(4):
        j = dm.Job.create("nmap_1700000000", i, "nmap")
        j.worker_id = f"w{i % 2}"
        if i < 3:
            j.status = dm.JobStatus.COMPLETE
            j.completed_at = 1700000100.0 + i
        jobs[j.job_id] = j.to_wire()
    [scan] = dm.rollup_scans(jobs)
    assert scan["total_chunks"] == 4
    assert scan["chunks_complete"] == 3
    assert scan["percent_complete"] == 75.0
    assert scan["scan_started"] == 1700000000
    assert scan["completed_at"] == 1700000102.0
    assert set(scan["workers"]) == {"w0", "w1"}

    jobs[dm.job_id_for("nmap_1700000000", 3)]["status"] = "complete"
    [scan] = dm.rollup_scans(jobs)
    assert scan["percent_complete"] == 100.0
    assert scan["scan_status"] == "complete"
