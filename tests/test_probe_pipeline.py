"""Probe front-end → match engine pipeline, end to end over localhost.

The reference's ``web`` module piped ``dnsx | httpx`` into files and ran
nuclei over them (``worker/modules/web.json``, ``nuclei.json``); this is
that composed path rebuilt: native resolve/connect/fetch feeding the
device matcher, driven through the full server/worker loop.
"""

from __future__ import annotations

import json
import socketserver
import threading

import pytest

from swarm_tpu.config import Config
from swarm_tpu.server.app import SwarmServer
from swarm_tpu.worker.executor import (
    ProbeExecutor,
    parse_http_response,
    parse_target,
)
from swarm_tpu.worker.runtime import JobProcessor
from swarm_tpu.client.cli import JobClient

TEMPLATES = "tests/data/templates"

PAGE = (
    b"<html><head><title>Demo Admin</title></head>"
    b"<body>site powered by AcmeCMS, demo-build 3.11</body></html>"
)


class _Server(socketserver.ThreadingTCPServer):
    request_queue_size = 256
    allow_reuse_address = True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        req = self.request.recv(4096)
        if not req.startswith(b"GET "):
            return
        self.request.sendall(
            b"HTTP/1.1 200 OK\r\nServer: demo\r\n"
            b"X-Widget-Version: 4.2\r\nContent-Length: %d\r\n\r\n%s"
            % (len(PAGE), PAGE)
        )


@pytest.fixture(scope="module")
def http_port():
    srv = _Server(("127.0.0.1", 0), _Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_parse_target_forms():
    assert parse_target("example.com") == ("example.com", None, "/", "")
    assert parse_target("example.com:8443") == ("example.com", 8443, "/", "")
    assert parse_target("10.0.0.1:80") == ("10.0.0.1", 80, "/", "")
    assert parse_target("http://example.com/admin") == (
        "example.com", None, "/admin", "http")
    assert parse_target("https://example.com") == ("example.com", 443, "/", "https")
    assert parse_target("# comment") is None
    assert parse_target("") is None


def test_parse_http_response():
    code, header, body = parse_http_response(
        b"HTTP/1.1 301 Moved\r\nLocation: /x\r\n\r\nhello"
    )
    assert code == 301 and b"Location" in header and body == b"hello"
    code, header, body = parse_http_response(b"garbage")
    assert code == 0


def test_probe_executor_http(http_port):
    ex = ProbeExecutor({"type": "http", "ports": [http_port]})
    rows = ex.run([f"127.0.0.1:{http_port}", "127.0.0.1"])
    assert len(rows) == 2
    for row in rows:
        assert row.status == 200
        assert b"X-Widget-Version: 4.2" in row.header
        assert b"AcmeCMS" in row.body


def test_probe_executor_unreachable_rows_kept(http_port):
    probe = __import__("socket").socket()
    probe.bind(("127.0.0.1", 0))
    closed = probe.getsockname()[1]
    probe.close()
    # low read timeout: the DNS attempt for the unresolvable name goes to
    # the (blackholed in CI) system resolver and must not stall the test
    ex = ProbeExecutor({"type": "http", "read_timeout_ms": 200})
    rows = ex.run([f"127.0.0.1:{closed}", "unresolvable-host.invalid:80"])
    assert len(rows) == 2
    assert all(r.status == 0 and not r.body and not r.alive for r in rows)


def test_malformed_targets_become_dead_rows(http_port):
    """One bad line must not sink the chunk — it yields a dead row."""
    ex = ProbeExecutor({"type": "http", "ports": [http_port]})
    rows = ex.run(
        ["http://host:70000/", "127.0.0.1:99999", f"127.0.0.1:{http_port}"]
    )
    assert len(rows) == 3
    ok = [r for r in rows if r.alive]
    assert len(ok) == 1 and ok[0].status == 200
    assert sum(not r.alive for r in rows) == 2


def test_dead_rows_never_match(http_port):
    """A dead target must not fire negative matchers on the phantom
    empty response (nuclei emits nothing for failed requests)."""
    from swarm_tpu.fingerprints import load_corpus
    from swarm_tpu.ops.engine import MatchEngine
    from swarm_tpu.fingerprints.model import Response

    templates, _ = load_corpus(TEMPLATES)
    engine = MatchEngine(templates)
    alive = Response(host="a", port=80, status=200, body=b"plain page")
    dead = Response(host="b", port=80, alive=False)
    res_alive, res_dead = engine.match([alive, dead])
    # demo-tech's negative matcher fires for the alive empty-ish body...
    assert "demo-tech" in res_alive.template_ids
    # ...but the dead row matches nothing at all
    assert res_dead.template_ids == []


def test_probe_to_match_end_to_end(http_port, tmp_path, monkeypatch):
    """targets chunk → native probe → device match → JSONL hits, through
    the full server/worker/client loop."""
    monkeypatch.setenv("SWARM_TEMPLATES_DIR", TEMPLATES)
    modules_dir = tmp_path / "modules"
    modules_dir.mkdir()
    (modules_dir / "web.json").write_text(
        json.dumps(
            {
                "backend": "tpu",
                "templates": "${SWARM_TEMPLATES_DIR}",
                "input_format": "targets",
                "probe": {"type": "http", "ports": [http_port]},
            }
        )
    )
    cfg = Config(
        host="127.0.0.1", port=0, api_key="probekey",
        blob_root=str(tmp_path / "blobs"), doc_root=str(tmp_path / "docs"),
        modules_dir=str(modules_dir),
        poll_interval_idle_s=0.05, poll_interval_busy_s=0.01,
    )
    srv = SwarmServer(cfg)
    srv.start_background()
    cfg.server_url = f"http://127.0.0.1:{srv.port}"
    try:
        client = JobClient(cfg.server_url, cfg.api_key)
        targets = tmp_path / "targets.txt"
        targets.write_text(f"127.0.0.1:{http_port}\n")
        code, resp = client.start_scan(
            str(targets), module="web", chunk_index=0, batch_size=0
        )
        assert code == 200

        wcfg = Config(**{**cfg.__dict__, "max_jobs": 1, "worker_id": "probe-w0"})
        JobProcessor(wcfg).process_jobs()

        [scan] = client.get_statuses()["scans"]
        assert scan["percent_complete"] == 100.0
        scan_id = scan["scan_id"]

        raw = client.fetch_raw(scan_id)
        lines = [json.loads(l) for l in raw.strip().splitlines()]
        # one match record + one workflow record (demo-workflow gates
        # demo-acme-vuln behind the acme-cms tech detection)
        assert len(lines) == 2
        wf = [l for l in lines if "workflow" in l]
        assert wf and wf[0]["workflow"] == "demo-workflow"
        assert wf[0]["matches"] == ["demo-acme-vuln"]
        hit = next(l for l in lines if "workflow" not in l)
        assert hit["port"] == http_port
        # demo-panel: title+build words AND status 200; demo-tech: header
        # regex + negative-word matcher
        assert "demo-panel" in hit["matches"]
        assert "demo-tech" in hit["matches"]
        assert hit["extractions"]["demo-panel"] == ["3.11"]
    finally:
        srv.shutdown()
