"""Continuous-batching scheduler (swarm_tpu/sched, docs/PIPELINE.md).

Three contracts pinned here:

1. **Bucket planning** — width-class choice, flush-at-target, partial
   final flush, fill-ratio accounting, occupancy reporting.
2. **Prefetch/backpressure bounds** — in-flight device batches never
   exceed the configured cap, queue depth bounds the encoded-batch
   buffer, and every row comes back exactly once (stub engine, so the
   bound is observed deterministically).
3. **End-to-end parity** — ``pipeline=on`` produces bit-identical
   verdicts AND extractions to ``pipeline=off`` on the test corpus:
   cold (fresh content), memo-warm, with dead rows interleaved, and
   through the decode-on-prefetch path.
"""

import threading

import numpy as np
import pytest

from swarm_tpu.fingerprints.model import Response
from swarm_tpu.sched import (
    BatchScheduler,
    BucketPlanner,
    SchedulerConfig,
    width_class,
)

# ----------------------------------------------------------------------
# bucket planner
# ----------------------------------------------------------------------


def test_width_class_mirrors_encoder_rounding():
    assert width_class(0) == 512
    assert width_class(1) == 512
    assert width_class(512) == 512
    assert width_class(513) == 1024
    assert width_class(1500) == 1536  # _width_for's 512-multiple ladder
    assert width_class(99999) == 4096  # capped
    assert width_class(700, cap=1024) == 1024
    assert width_class(3000, multiple=512, cap=2048) == 2048
    # lockstep with the encoder: a bucket's encoded width is its class,
    # so each bucket pins exactly one compiled shape
    from swarm_tpu.ops.encoding import _width_for

    for n in (0, 1, 511, 512, 513, 1100, 1536, 2047, 4000, 9999):
        assert width_class(n) == _width_for(
            np.asarray([n]), cap=4096, multiple=512
        ), n


def _row(body_len: int, header_len: int = 10, banner: bool = False):
    blob = b"x" * body_len
    return Response(
        body=b"" if banner else blob,
        banner=blob if banner else None,
        header=b"h" * header_len,
        status=200,
    )


def test_bucket_choice_body_and_banner():
    p = BucketPlanner(rows_target=8, max_body=4096, max_header=1024)
    assert p.bucket_of(_row(100)) == (512, 512)
    assert p.bucket_of(_row(600)) == (1024, 512)
    assert p.bucket_of(_row(600, header_len=800)) == (1024, 1024)
    assert p.bucket_of(_row(1500)) == (1536, 512)
    # "body" is the banner when one is set (encoding part semantics)
    assert p.bucket_of(_row(2000, banner=True)) == (2048, 512)


def test_planner_flushes_at_target_and_keys_by_shape():
    p = BucketPlanner(rows_target=3)
    out = []
    for i in range(5):
        pb = p.add_fresh(i, _row(100))
        if pb:
            out.append(pb)
    # rows 0-2 flushed as one full bucket; 3-4 still pending
    assert len(out) == 1
    assert out[0].ids == [0, 1, 2]
    assert out[0].bucket == "w512h512"
    assert out[0].kind == "fresh" and not out[0].final
    # a different shape accumulates independently
    assert p.add_fresh(5, _row(1500)) is None
    assert p.occupancy() == {"w512h512": 2, "w1536h512": 1}
    finals = list(p.flush_all())
    assert {f.bucket for f in finals} == {"w512h512", "w1536h512"}
    assert all(f.final for f in finals)
    assert p.pending_rows == 0


def test_planner_memo_lane_and_fill_ratio():
    p = BucketPlanner(rows_target=4)
    outs = [p.add_known(i, _row(10)) for i in range(5)]
    full = [o for o in outs if o]
    assert len(full) == 1 and full[0].bucket == "memo"
    assert full[0].ids == [0, 1, 2, 3]
    (tail,) = list(p.flush_all())
    assert tail.ids == [4] and tail.kind == "memo"
    # fill ratio is against the engine's 256-row padding
    assert full[0].fill_rows == pytest.approx(4 / 256)
    assert tail.fill_rows == pytest.approx(1 / 256)


# ----------------------------------------------------------------------
# prefetch / backpressure bounds (stub engine: deterministic)
# ----------------------------------------------------------------------


class _StubDB:
    num_templates = 1
    template_ids = ["t"]


class _StubPacked:
    template_ids = ["t"]
    extractions: dict = {}
    host_always_matches: list = []
    confirms_per_row: dict = {}

    def __init__(self, n):
        self.bits = np.zeros((n, 1), dtype=np.uint8)


class _StubEngine:
    """Just the scheduler-facing surface. Tracks concurrency bounds."""

    batch_rows = 8
    max_body = 4096
    max_header = 1024
    db = _StubDB()

    def __init__(self):
        self.inflight = 0
        self.max_inflight = 0
        self.outstanding_encodes = 0
        self.max_outstanding_encodes = 0
        self.lock = threading.Lock()

    def _use_native_memo(self):
        return False

    def memo_known_mask(self, rows):
        return np.zeros(len(rows), dtype=np.uint8)

    def encode_packed(self, rows, reuse_buffers=False):
        with self.lock:
            self.outstanding_encodes += 1
            self.max_outstanding_encodes = max(
                self.max_outstanding_encodes, self.outstanding_encodes
            )
        return ("stub", list(rows))

    def begin_packed(self, rows, pre=None):
        with self.lock:
            self.inflight += 1
            self.max_inflight = max(self.max_inflight, self.inflight)
        return ("h", list(rows), pre)

    def finish_packed(self, handle):
        _tag, rows, _pre = handle
        with self.lock:
            self.inflight -= 1
            if _pre is not None:
                self.outstanding_encodes -= 1
        return _StubPacked(len(rows))

    def rowmatches_from_packed(self, packed, n):
        from swarm_tpu.ops.engine import RowMatches

        return [
            RowMatches(template_ids=[], extractions={}) for _ in range(n)
        ]


@pytest.mark.parametrize("prefetch", ["inline", "thread"])
@pytest.mark.parametrize("inflight", [1, 2, 3])
def test_inflight_never_exceeds_cap(prefetch, inflight):
    eng = _StubEngine()
    sched = BatchScheduler(
        eng,
        SchedulerConfig(
            rows_target=8, inflight=inflight, prefetch=prefetch
        ),
    )
    sched._overlap_helps = True  # exercise the configured depth
    chunks = [[_row(50) for _ in range(5)] for _ in range(20)]
    total = 0
    for res in sched.run(chunks):
        total += len(res)
    assert total == 100
    assert eng.inflight == 0
    assert eng.max_inflight <= inflight
    # backpressure: encoded-but-unwalked batches stay bounded by
    # queue + in-flight + the one being produced
    assert (
        eng.max_outstanding_encodes
        <= sched.config.queue_depth + inflight + 1
    )


def test_results_in_order_across_bucket_shapes():
    eng = _StubEngine()
    sched = BatchScheduler(eng, SchedulerConfig(rows_target=4))
    # alternating shapes so consecutive rows land in different buckets
    chunks = [
        [_row(100 if (i + j) % 2 else 1500) for j in range(6)]
        for i in range(4)
    ]
    out = list(sched.run(chunks))
    assert [len(c) for c in out] == [6, 6, 6, 6]
    assert sched.stats.fresh_rows == 24
    # every device batch carries a fill ratio <= 1
    assert 0 < sched.stats.fill_ratio <= 1


def test_dead_rows_resolve_without_engine_traffic():
    eng = _StubEngine()
    sched = BatchScheduler(eng, SchedulerConfig(rows_target=4))
    dead = Response(host="d", alive=False)
    chunks = [[dead, _row(10), dead]]
    (res,) = list(sched.run(chunks))
    assert len(res) == 3
    assert res[0].template_ids == [] and res[2].template_ids == []
    assert sched.stats.dead_rows == 2 and sched.stats.fresh_rows == 1


def test_accelerator_drives_inflight_ge2_with_walk_offload():
    """On an accelerator backend the submit thread must keep ≥2 device
    batches genuinely in flight WHILE the offloaded walk runs (the
    ISSUE-6 overlap acceptance), and the recycled-plane accounting
    stays closed: begun-but-unwalked batches never exceed the offload
    cap (3) plus the single offloaded walk."""
    eng = _StubEngine()
    sched = BatchScheduler(
        eng,
        SchedulerConfig(
            rows_target=8, inflight=4, walk_offload="on",
            prefetch="inline",
        ),
    )
    sched._overlap_helps = True  # accelerator backend
    chunks = [[_row(50) for _ in range(5)] for _ in range(30)]
    total = sum(len(r) for r in sched.run(chunks))
    assert total == 150
    assert eng.inflight == 0
    assert eng.max_inflight >= 2, "overlap must actually happen"
    assert eng.max_inflight <= 4  # cap 3 + the one offloaded walk
    assert sched.stats.offloaded_walks > 0


def test_cpu_fallback_still_collapses_inflight_to_1():
    """The CPU backend's XLA threads ARE the walk's cores: in-flight
    must still collapse to 1 there, whatever the configured depth."""
    eng = _StubEngine()
    sched = BatchScheduler(
        eng, SchedulerConfig(rows_target=8, inflight=4, prefetch="inline")
    )
    sched._overlap_helps = False  # CPU fallback
    chunks = [[_row(50) for _ in range(5)] for _ in range(10)]
    total = sum(len(r) for r in sched.run(chunks))
    assert total == 50
    assert eng.max_inflight <= 1


def test_producer_error_propagates():
    eng = _StubEngine()
    sched = BatchScheduler(
        eng, SchedulerConfig(rows_target=4, prefetch="thread")
    )

    def chunks():
        yield [_row(10)]
        raise RuntimeError("decode blew up")

    with pytest.raises(RuntimeError, match="decode blew up"):
        list(sched.run(chunks()))


# ----------------------------------------------------------------------
# end-to-end parity on the test corpus
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def engines():
    from swarm_tpu.fingerprints import load_corpus
    from swarm_tpu.ops.engine import MatchEngine

    templates, _errors = load_corpus("tests/data/templates")
    e_off = MatchEngine(templates, mesh=None, batch_rows=128)
    e_on = MatchEngine(
        templates, mesh=None, batch_rows=128, pipeline="on"
    )
    return e_off, e_on


def _scan_rows(n: int, seed: int = 7, salt: bool = False):
    rng = np.random.default_rng(seed)
    bodies = [
        b"<html><head><title>Welcome to nginx!</title></head></html>",
        b"<html><head><title>Grafana</title></head><body>"
        b"grafana v9.1.0</body></html>",
        b"<html>404 Not Found</html>",
        b"",
        b"A" * 900,  # crosses into the 1024 width class
        b"B" * 1800,  # 2048 class
    ]
    rows = []
    for i in range(n):
        body = bodies[i % len(bodies)]
        if salt:
            body = (
                b"<!-- %s -->" % bytes(
                    rng.integers(97, 123, size=24, dtype=np.uint8)
                )
            ) + body
        rows.append(
            Response(
                host=f"198.51.100.{i % 254}",
                port=(80, 443)[i % 2],
                status=(200, 404, 301)[i % 3],
                body=body,
                header=b"Server: nginx\r\nContent-Type: text/html",
            )
        )
    # interleave dead rows (match nothing by contract)
    for k in (3, 11, n - 2):
        if 0 <= k < n:
            rows[k] = Response(host=f"dead{k}", alive=False)
    return rows


def _assert_same(a, b):
    # EXACT id order: both assembly paths emit ascending template
    # index, then the host-always tail (confirmed_on_host is excluded —
    # confirm attribution follows each batch's dedup representative)
    assert len(a) == len(b)
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra.template_ids == rb.template_ids, i
        assert ra.extractions == rb.extractions, i


def test_pipeline_parity_cold_and_memo_warm(engines):
    e_off, e_on = engines
    rows = _scan_rows(300, salt=True)
    r_off = e_off.match(rows)
    r_on = e_on.match(rows)
    _assert_same(r_off, r_on)
    # memo-warm second pass (content now resident in both engines):
    # the scheduler's memo split and steady-state speculation kick in
    clones = [
        Response(
            host=r.host, port=r.port, status=r.status,
            body=bytes(memoryview(r.body)),
            header=bytes(memoryview(r.header)),
            banner=None if r.banner is None else bytes(memoryview(r.banner)),
            alive=r.alive,
        )
        for r in rows
    ]
    _assert_same(e_off.match(clones), e_on.match(clones))
    assert e_on.scheduler().stats.memo_rows > 0


def test_pipeline_parity_through_run_with_decode(engines):
    e_off, e_on = engines
    rows = _scan_rows(120, seed=13, salt=True)
    chunks = [rows[i : i + 40] for i in range(0, len(rows), 40)]
    # decode runs on the prefetch stage: payloads are (index, rows)
    payloads = list(enumerate(chunks))
    seen_chunks = []

    def decode(payload):
        ci, chunk_rows = payload
        seen_chunks.append(ci)
        return chunk_rows

    out = []
    for res in e_on.scheduler().run(payloads, decode=decode):
        out.append(res)
    assert seen_chunks == [0, 1, 2]
    assert [len(c) for c in out] == [40, 40, 40]
    flat_on = [rm for c in out for rm in c]
    flat_off = e_off.match(rows)
    _assert_same(flat_off, flat_on)


def test_worker_runtime_tpu_pipeline_parity(tmp_path, monkeypatch):
    """The worker's response-lines tpu path (`_execute_tpu`) produces
    byte-identical output with `Config.pipeline="on"` (decode rides the
    scheduler's prefetch stage) vs the direct path."""
    import json

    # single-device engine: the virtual 8-device mesh is exercised by
    # test_sharding, not here (and this jax build lacks shard_map)
    import swarm_tpu.parallel.mesh as mesh_mod

    monkeypatch.setattr(mesh_mod, "make_mesh", lambda *a, **k: None)

    from swarm_tpu.config import Config
    from swarm_tpu.worker.modules import ModuleSpec
    from swarm_tpu.worker.runtime import JobProcessor

    module = ModuleSpec(
        "nuclei",
        {"backend": "tpu", "templates": "tests/data/templates"},
    )
    lines = []
    for i, r in enumerate(_scan_rows(90, seed=21, salt=True)):
        lines.append(
            json.dumps(
                {
                    "host": r.host,
                    "port": r.port,
                    "status": r.status,
                    "body": r.body.decode("latin-1"),
                    "header": r.header.decode("latin-1"),
                    "alive": r.alive,
                }
            )
        )
    data = ("\n".join(lines) + "\n").encode()
    outs = {}
    for mode in ("off", "on"):
        cfg = Config.load(
            server_url="http://127.0.0.1:1", api_key="k",
            worker_id="w", pipeline=mode,
        )
        proc = JobProcessor(
            cfg, client=object(), work_dir=str(tmp_path / mode)
        )
        outs[mode] = proc._execute_tpu(module, data)
        assert proc._engines["tests/data/templates"].pipeline == mode
    assert outs["on"] == outs["off"]


def test_scheduler_telemetry_families_present(engines):
    _e_off, e_on = engines
    from swarm_tpu.telemetry import REGISTRY

    e_on.match(_scan_rows(64, seed=99, salt=True))
    snap = REGISTRY.snapshot()
    for family in (
        "swarm_sched_batches_total",
        "swarm_sched_rows_total",
        "swarm_sched_fill_ratio",
        "swarm_sched_prefetch_stall_seconds_total",
        "swarm_sched_inflight_depth",
        "swarm_sched_bucket_rows",
    ):
        assert family in snap, family
