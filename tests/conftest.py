"""Test harness: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip behavior (dp/tp/sp shardings, halo exchange) is validated on
a virtual CPU mesh — the analog of the reference's multi-droplet setup
without a cluster (SURVEY.md §4f). Benchmarks run on real TPU separately.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the env presets a TPU platform
# hermetic corpus-compile cache: don't read/write ~/.cache during tests
# (lazy so a preset env var doesn't leak an orphan temp dir)
if "SWARM_DB_CACHE_DIR" not in os.environ:
    os.environ["SWARM_DB_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="swarm_test_dbc_"
    )
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize imports jax at interpreter start, so the env
# var alone may be too late — force the platform through the config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_stores(tmp_path):
    """Embedded stores rooted in a temp dir."""
    from swarm_tpu.config import Config
    from swarm_tpu.stores import build_stores

    cfg = Config(
        blob_root=str(tmp_path / "blobs"),
        doc_root=str(tmp_path / "docs"),
    )
    return build_stores(cfg)
