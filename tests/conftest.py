"""Test harness: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip behavior (dp/tp/sp shardings, halo exchange) is validated on
a virtual CPU mesh — the analog of the reference's multi-droplet setup
without a cluster (SURVEY.md §4f). Benchmarks run on real TPU separately.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the env presets a TPU platform
# hermetic corpus-compile cache: don't read/write ~/.cache during tests
# (lazy so a preset env var doesn't leak an orphan temp dir)
if "SWARM_DB_CACHE_DIR" not in os.environ:
    os.environ["SWARM_DB_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="swarm_test_dbc_"
    )
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize imports jax at interpreter start, so the env
# var alone may be too late — force the platform through the config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the suite (utils/xlacache.py —
# the same corpus kernels are re-jitted by many test modules from
# fresh DeviceDB/MatchEngine instances; deserializing an identical
# program beats recompiling it, and the tier-1 wall stays inside its
# timeout). Content-keyed, so staleness is impossible; a second run on
# the same machine starts warm. SWARM_TEST_XLA_CACHE_DIR= (empty)
# disables.
if "SWARM_XLA_CACHE_DIR" not in os.environ:
    from swarm_tpu.utils import xlacache  # noqa: E402

    # per-user default path: a fixed world-shared /tmp dir would be
    # unwritable (or poisonable) for the second user on a shared host
    xlacache.enable_compilation_cache(
        os.environ.get(
            "SWARM_TEST_XLA_CACHE_DIR",
            os.path.join(
                tempfile.gettempdir(),
                f"swarm_test_xla_cache_{os.getuid()}",
            ),
        )
    )
    # the suite compiles MANY sub-second kernels repeatedly across
    # modules (fresh jit closures per DeviceDB/engine instance) —
    # cache those too, not just the >1s production kernels
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)

import pytest  # noqa: E402


def pytest_configure(config):
    # jax warns at compile time when donated buffers can't alias into
    # outputs; EXPECTED on the split-phase dispatch (outputs are tiny
    # packed planes — donation buys early staged-buffer release, not
    # aliasing; docs/DEVICE_MATCH.md). ops/match.py filters it at
    # module scope for production processes; pytest re-applies its own
    # filters per test, so mirror the filter here.
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable",
    )


@pytest.fixture
def tmp_stores(tmp_path):
    """Embedded stores rooted in a temp dir."""
    from swarm_tpu.config import Config
    from swarm_tpu.stores import build_stores

    cfg = Config(
        blob_root=str(tmp_path / "blobs"),
        doc_root=str(tmp_path / "docs"),
    )
    return build_stores(cfg)
