"""Device read-back layout regressions.

XLA owns the on-device layout and may hand back Fortran-ordered host
buffers (observed on real TPU at corpus-scale plane shapes — the
BENCH_r03 crash: ``planes must be contiguous`` at the sw_ext_resolve
boundary, from a (304, 464) plane with strides (1, 304)). Layout is
the compiler's choice, not a contract, so every consumer below the
read-back boundary must accept any layout and produce identical bits.

These tests pin that: split_fused normalizes the fused buffer,
ext_resolve normalizes its plane inputs, and the full match_packed
native path produces bit-identical verdicts when every device plane is
forced Fortran-ordered (simulating the TPU layout on CPU, where XLA
happens to return C order for these shapes).
"""

import random
from pathlib import Path

import numpy as np
import pytest

from swarm_tpu.fingerprints import load_corpus
from swarm_tpu.fingerprints.model import Response
from swarm_tpu.ops import match as match_mod
from swarm_tpu.ops.engine import MatchEngine

DATA = Path(__file__).parent / "data" / "templates"
REFERENCE_CORPUS = Path("/root/reference/worker/artifacts/templates")


def _f_ordered(a):
    """Fortran-ordered copy of 2-D arrays; pass-through otherwise."""
    a = np.asarray(a)
    return np.asfortranarray(a) if a.ndim == 2 else a


def test_split_fused_accepts_fortran_buffer():
    """split_fused must yield identical planes for C- and F-ordered
    fused buffers, and its outputs must be safe to hand to the native
    pass (C-strided)."""
    templates, errors = load_corpus(DATA)
    assert templates and not errors
    eng = MatchEngine(templates, mesh=None)
    db = eng.db
    widths = match_mod.fused_plane_widths(db)
    rng = np.random.default_rng(7)
    buf_c = np.ascontiguousarray(
        rng.integers(0, 256, size=(304, sum(widths)), dtype=np.uint8)
    )
    buf_f = np.asfortranarray(buf_c)
    assert not buf_f.flags["C_CONTIGUOUS"]  # the TPU shape that crashed
    outs_c = match_mod.split_fused(db, buf_c)
    outs_f = match_mod.split_fused(db, buf_f)
    for pc, pf in zip(outs_c, outs_f):
        np.testing.assert_array_equal(np.asarray(pc), np.asarray(pf))


def test_ext_resolve_accepts_fortran_planes():
    """The native sw_ext_resolve boundary normalizes (not asserts)
    plane layout: F-ordered inputs are legal and bit-identical."""
    pytest.importorskip("swarm_tpu.native.scanio")
    from swarm_tpu.native.scanio import ensure_fastpack, ext_resolve

    try:
        ensure_fastpack()
    except Exception:
        pytest.skip("native fastpack unavailable")
    rng = np.random.default_rng(3)
    n_rows, nt = 304, 3700
    nb = (nt + 7) >> 3
    masked = rng.integers(0, 256, size=(n_rows, nb), dtype=np.uint8)
    # sparse: keep the hit count realistic
    masked &= rng.integers(0, 256, size=(n_rows, nb), dtype=np.uint8) < 8
    n_ops = 64
    nbo = (n_ops + 7) >> 3
    rowdep = np.zeros(nb, dtype=np.uint8)
    skip = np.zeros(n_rows, dtype=np.uint8)
    # each template owns one op, cycling over the op table
    indptr = np.arange(nt + 1, dtype=np.int64)
    opids = (np.arange(nt, dtype=np.int64)) % n_ops
    pop_value = rng.integers(0, 256, size=(n_rows, nbo), dtype=np.uint8)
    pop_unc = rng.integers(0, 256, size=(n_rows, nbo), dtype=np.uint8)
    got_c = ext_resolve(
        masked, nt, rowdep, skip, indptr, opids, pop_value, pop_unc
    )
    got_f = ext_resolve(
        np.asfortranarray(masked), nt, rowdep, skip, indptr, opids,
        np.asfortranarray(pop_value), np.asfortranarray(pop_unc),
    )
    for c, f in zip(got_c, got_f):
        np.testing.assert_array_equal(c, f)


@pytest.mark.skipif(
    not REFERENCE_CORPUS.is_dir(), reason="reference corpus absent"
)
def test_match_packed_native_path_fortran_planes_corpus_scale():
    """End-to-end: match_packed through the native path on a reference
    corpus DB at a ≥256-row batch, with EVERY device plane forced
    Fortran-ordered — must not crash and must be bit-identical to the
    C-ordered run. This is the exact failure mode of BENCH_r03
    (engine.py host walk → sw_ext_resolve contiguity assert)."""
    # network + a technologies slice: extractor templates (detect-rsyncd
    # etc.) route through the ext_resolve pass that crashed
    templates, _ = load_corpus(REFERENCE_CORPUS / "network")
    tech, _ = load_corpus(REFERENCE_CORPUS / "technologies", limit=120)
    templates = templates + tech
    assert len(templates) >= 100
    eng = MatchEngine(templates, mesh=None)
    if not eng._use_native_memo():
        pytest.skip("native memo path unavailable")

    rng = random.Random(11)
    words = []
    for t in templates:
        for _op, m in t.all_matchers():
            words.extend(w for w in getattr(m, "words", ()) or () if w)
    words = [w for w in words if 3 <= len(w) <= 40][:400]
    rows = []
    for i in range(256):
        body = bytearray()
        for _ in range(rng.randint(0, 4)):
            body += rng.choice(words).encode("utf-8", "ignore") + b" "
        body += bytes(rng.randrange(32, 127) for _ in range(rng.randint(0, 80)))
        rows.append(
            Response(
                host=f"h{i}.example",
                port=80,
                status=rng.choice([200, 200, 200, 301, 404, 503]),
                body=bytes(body),
                header=b"Server: "
                + rng.choice([b"nginx", b"Apache", b"rsyncd"])
                + b"\r\n",
            )
        )

    baseline = eng.match_packed(rows)
    # the batch must actually fire templates, else the walk is a no-op
    # and the regression proves nothing
    assert baseline.bits.any()

    # simulate the TPU layout: every 2-D plane the device hands back
    # becomes Fortran-ordered before the engine's host walk sees it
    orig = match_mod.split_fused

    def forder_split(db, buf):
        return tuple(_f_ordered(p) for p in orig(db, buf))

    # fresh content so the verdict memo can't serve cached bits
    if eng._vmemo is not None:
        eng._vmemo.clear()
    eng._verdict_memo.clear()
    eng._confirm_cache.clear()
    match_mod.split_fused, saved = forder_split, orig
    try:
        again = eng.match_packed(rows)
    finally:
        match_mod.split_fused = saved

    np.testing.assert_array_equal(baseline.bits, again.bits)
    assert baseline.extractions == again.extractions
    assert baseline.host_always_matches == again.host_always_matches


def _rsync_rows(n: int) -> list:
    """Rows firing detect-rsyncd, whose extractor is NOT internal —
    they exercise the extraction-output path (robots' is
    internal-only)."""
    return [
        Response(host=f"r{i}.x", port=873, status=0,
                 banner=b"@RSYNCD: 31.%d\nERROR: protocol startup error\n"
                 % i)
        for i in range(n)
    ]


def _assert_native_extraction_live(pattern=r"RSYNCD: \d\d.\d"):
    """Guard against vacuous equivalence tests: the compared fast path
    must actually be the native VM, not a silent Python fallback."""
    from swarm_tpu.native import crex as ncrex
    from swarm_tpu.ops import fastre

    assert ncrex.ensure_crex() is not None, "libcrex must be loadable"
    info = fastre.analyze(pattern)
    assert info.cprog is not None and ncrex.usable(info.cprog), pattern


def _run_with_env(monkeypatch, templates, rows, var: str, value: str):
    monkeypatch.setenv(var, value)
    eng = MatchEngine(templates, mesh=None)
    return eng.match_packed(list(rows))


@pytest.mark.skipif(
    not REFERENCE_CORPUS.is_dir(),
    reason="pre-existing env gap (ROADMAP housekeeping): /root/reference\n"
    "corpus absent — these templates (network/miscellaneous extractors)\n"
    "only exist there, so the batch path cannot fire",
)
def test_threaded_extraction_batches_bit_identical(monkeypatch):
    """SWARM_EXT_THREADS>1 runs the per-pattern native batches on a
    thread pool (GIL released in C) — results must be identical to the
    serial path."""
    _assert_native_extraction_live()
    templates, _ = load_corpus(REFERENCE_CORPUS / "network")
    misc, _ = load_corpus(REFERENCE_CORPUS / "miscellaneous")
    templates = templates + misc
    rows = [
        Response(
            host=f"h{i}.x", port=80, status=200,
            body=(b"User-agent: *\nDisallow: /admin%d/s\n"
                  b"Allow: /p%d v=9.%d" % (i, i, i)),
            header=b"Server: nginx\r\n",
        )
        for i in range(64)
    ] + _rsync_rows(8)

    serial = _run_with_env(monkeypatch, templates, rows,
                           "SWARM_EXT_THREADS", "1")
    threaded = _run_with_env(monkeypatch, templates, rows,
                             "SWARM_EXT_THREADS", "3")
    np.testing.assert_array_equal(serial.bits, threaded.bits)
    assert serial.extractions == threaded.extractions
    assert serial.extractions  # the batch path must actually fire


@pytest.mark.skipif(
    not REFERENCE_CORPUS.is_dir(),
    reason="pre-existing env gap (ROADMAP housekeeping): /root/reference\n"
    "corpus absent — these templates (network/miscellaneous extractors)\n"
    "only exist there, so the batch path cannot fire",
)
def test_percall_escape_hatch_bit_identical(monkeypatch):
    """SWARM_EXT_BATCH=0 (the per-call measurement hatch) must stay
    bit-identical to the batched default — it shares the oracle
    semantics through _extract_op."""
    _assert_native_extraction_live()
    templates, _ = load_corpus(REFERENCE_CORPUS / "network")
    rows = _rsync_rows(24)
    a = _run_with_env(monkeypatch, templates, rows, "SWARM_EXT_BATCH", "1")
    b = _run_with_env(monkeypatch, templates, rows, "SWARM_EXT_BATCH", "0")
    np.testing.assert_array_equal(a.bits, b.bits)
    assert a.extractions == b.extractions and a.extractions
