"""File-template engine tests (nuclei ``file`` protocol).

Reference behavior: the nuclei binary executes the 76 templates under
``worker/artifacts/templates/file/`` and the standalone
``worker/artifacts/s3-bucket.yaml`` over local files, gated by each
entry's ``extensions`` list. Golden case per VERDICT: extracting S3
URLs from a sample corpus via s3-bucket.yaml's regex extractors.
"""

from pathlib import Path

import pytest

from swarm_tpu.fingerprints import load_corpus
from swarm_tpu.fingerprints.nuclei import load_template_file
from swarm_tpu.worker.filescan import FileScanner, format_findings
from swarm_tpu.worker.modules import ModuleSpec

REFERENCE_TEMPLATES = Path("/root/reference/worker/artifacts/templates")
S3_TEMPLATE = Path("/root/reference/worker/artifacts/s3-bucket.yaml")


INLINE_PERLISH = """\
id: perlish-scanner
info:
  name: inline test scanner
  severity: info
file:
  - extensions:
      - pl
      - pm
    extractors:
      - type: regex
        regex:
          - 'eval'
          - 'syscall'
"""

INLINE_CONF_AUDIT = """\
id: conf-audit
info:
  name: inline conf audit
  severity: high
file:
  - extensions:
      - conf
    matchers-condition: and
    matchers:
      - type: word
        words:
          - "safety off"
        negative: true
      - type: word
        words:
          - "configure terminal"
"""


def _write(tmp_path: Path, name: str, content: str) -> Path:
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(content)
    return p


def test_extension_gating_extractor_only(tmp_path):
    t = load_template_file(_write(tmp_path, "t/perlish.yaml", INLINE_PERLISH))
    scanner = FileScanner([t])
    assert scanner.engine is None  # extractor-only: no device DB needed
    _write(tmp_path, "a.pl", "while(1) { eval $x; }\n")
    _write(tmp_path, "b.txt", "eval eval eval\n")  # right bytes, wrong ext
    _write(tmp_path, "c.pm", "nothing suspicious\n")
    findings, stats = scanner.scan_paths([str(tmp_path)])
    hits = {(f.template_id, Path(f.path).name) for f in findings}
    assert ("perlish-scanner", "a.pl") in hits
    assert all(name != "b.txt" for _, name in hits)
    assert all(name != "c.pm" for _, name in hits)
    [f] = [f for f in findings if Path(f.path).name == "a.pl"]
    assert "eval" in f.extractions


def test_matcher_template_negative_and_condition(tmp_path):
    t = load_template_file(_write(tmp_path, "t/conf.yaml", INLINE_CONF_AUDIT))
    scanner = FileScanner([t])
    assert scanner.engine is not None
    # fires: has the required word, lacks the negative word, right ext
    _write(tmp_path, "router.conf", "interface g0\nconfigure terminal\n")
    # suppressed by the negative matcher
    _write(tmp_path, "safe.conf", "configure terminal\nsafety off\n")
    # wrong extension: same bytes must not fire
    _write(tmp_path, "router.txt", "configure terminal\n")
    findings, _ = scanner.scan_paths([str(tmp_path)])
    names = {Path(f.path).name for f in findings}
    assert names == {"router.conf"}
    [f] = findings
    assert f.severity == "high"
    out = format_findings(findings).decode()
    assert "[conf-audit] [file] [high]" in out


@pytest.mark.skipif(not S3_TEMPLATE.is_file(), reason="reference corpus absent")
def test_s3_bucket_golden_extraction(tmp_path):
    """VERDICT #5's golden test: S3 URLs extracted from a sample corpus."""
    t = load_template_file(S3_TEMPLATE)
    assert t.protocol == "file"
    scanner = FileScanner([t])
    _write(
        tmp_path,
        "app.js",
        'fetch("https://prod-assets.s3.amazonaws.com/logo.png");\n'
        'const backup = "//s3.amazonaws.com/backup-bucket-2024";\n',
    )
    _write(tmp_path, "clean.js", "console.log('nothing to see');\n")
    findings, _ = scanner.scan_paths([str(tmp_path)])
    assert [Path(f.path).name for f in findings] == ["app.js"]
    [f] = findings
    assert f.template_id == "s3-bucket"
    assert "prod-assets.s3.amazonaws.com" in f.extractions
    assert "//s3.amazonaws.com/backup-bucket-2024" in f.extractions


@pytest.mark.skipif(
    not REFERENCE_TEMPLATES.is_dir(), reason="reference corpus absent"
)
def test_full_file_corpus_covered(tmp_path):
    """Every reference file template is executable: matcher-bearing ones
    compile into the device DB, the rest run as extractor-only."""
    templates, errors = load_corpus(REFERENCE_TEMPLATES / "file")
    assert not errors
    scanner = FileScanner(templates)
    assert len(scanner.templates) == len(templates)
    covered = {t.id for t in scanner.matcher_templates} | {
        t.id for t in scanner.extractor_only
    }
    assert covered == {t.id for t in templates}
    # cisco audit behavior against the real corpus: a config missing the
    # hardening line fires disable-ip-source-route; extension-gated.
    _write(
        tmp_path,
        "switch.conf",
        "configure terminal\nip source-route\nend\n",
    )
    findings, stats = scanner.scan_paths([str(tmp_path)])
    assert stats["files_scanned"] == 1
    assert "disable-ip-source-route" in {f.template_id for f in findings}


def test_runtime_file_backend(tmp_path):
    from swarm_tpu.config import Config
    from swarm_tpu.worker.runtime import JobProcessor

    tdir = tmp_path / "templates"
    _write(tmp_path, "templates/perlish.yaml", INLINE_PERLISH)
    _write(tmp_path, "scanme/x.pl", "open F; eval $y\n")
    cfg = Config.load(server_url="http://127.0.0.1:1", api_key="k", worker_id="w")
    proc = JobProcessor(cfg, client=object(), work_dir=str(tmp_path / "wd"))
    module = ModuleSpec(
        "file", {"backend": "file", "templates": str(tdir)}
    )
    data = f"{tmp_path / 'scanme'}\n".encode()
    out = proc._execute_file(module, data).decode()
    assert "[perlish-scanner] [file] [info]" in out
    assert "x.pl" in out


def test_scan_root_confinement(tmp_path):
    t = load_template_file(_write(tmp_path, "t/perlish.yaml", INLINE_PERLISH))
    inside = tmp_path / "allowed"
    _write(tmp_path, "allowed/a.pl", "eval $x\n")
    _write(tmp_path, "outside.pl", "eval $x\n")
    scanner = FileScanner([t], scan_root=str(inside))
    findings, _ = scanner.scan_paths(
        [str(inside), str(tmp_path / "outside.pl")]
    )
    names = {Path(f.path).name for f in findings}
    assert names == {"a.pl"}  # path outside the root is ignored


def test_scan_root_confinement_blocks_symlinks(tmp_path):
    t = load_template_file(_write(tmp_path, "t/perlish.yaml", INLINE_PERLISH))
    inside = tmp_path / "allowed"
    inside.mkdir()
    secret = _write(tmp_path, "secret/creds.pl", "eval $x\n")
    (inside / "link.pl").symlink_to(secret)
    (inside / "dirlink").symlink_to(tmp_path / "secret")
    scanner = FileScanner([t], scan_root=str(inside))
    findings, stats = scanner.scan_paths([str(inside)])
    assert findings == []  # symlinked escapes are refused
    assert stats["files_scanned"] == 0
