"""Real-service store adapters exercised with in-process fakes.

``RedisStateStore`` / ``S3BlobStore`` / ``MongoDocStore`` adapt the
production backends (redis / boto3 / pymongo — none installed in this
image). Fake client modules implementing the exact client subset each
adapter touches are injected into ``sys.modules``, then the adapters
are driven both directly and through a full queue→dispatch→complete→
rollup lifecycle via ``build_stores`` — the production wiring path
(``stores.py`` factory), not the embedded defaults.
"""

import sys
import types

import pytest

from swarm_tpu.config import Config


# ---------------------------------------------------------------------------
# fake redis: bytes-in/bytes-out semantics like redis-py
# ---------------------------------------------------------------------------


class _FakeRedisClient:
    def __init__(self):
        self.h: dict[str, dict[bytes, bytes]] = {}
        self.l: dict[str, list[bytes]] = {}

    @staticmethod
    def _b(v) -> bytes:
        return v if isinstance(v, bytes) else str(v).encode()

    def hset(self, name, key=None, value=None, mapping=None):
        h = self.h.setdefault(name, {})
        if mapping:
            for k, v in mapping.items():
                h[self._b(k)] = self._b(v)
        if key is not None:
            h[self._b(key)] = self._b(value)

    def hget(self, name, key):
        return self.h.get(name, {}).get(self._b(key))

    def hkeys(self, name):
        return list(self.h.get(name, {}).keys())

    def hgetall(self, name):
        return dict(self.h.get(name, {}))

    def hdel(self, name, key):
        self.h.get(name, {}).pop(self._b(key), None)

    def hmget(self, name, keys):
        h = self.h.get(name, {})
        return [h.get(self._b(k)) for k in keys]

    def hincrby(self, name, key, amount=1):
        h = self.h.setdefault(name, {})
        k = self._b(key)
        value = int(h.get(k, b"0")) + int(amount)
        h[k] = str(value).encode()
        return value

    def rpush(self, name, value):
        self.l.setdefault(name, []).append(self._b(value))

    def lpush(self, name, value):
        self.l.setdefault(name, []).insert(0, self._b(value))

    def lpop(self, name):
        q = self.l.get(name) or []
        return q.pop(0) if q else None

    def lrange(self, name, start, stop):
        q = self.l.get(name, [])
        stop = len(q) if stop == -1 else stop + 1
        return q[start:stop]

    def delete(self, name):
        self.l.pop(name, None)
        self.h.pop(name, None)

    def llen(self, name):
        return len(self.l.get(name, []))

    def flushall(self):
        self.h.clear()
        self.l.clear()


# ---------------------------------------------------------------------------
# fake boto3: the S3 client subset S3BlobStore calls
# ---------------------------------------------------------------------------


class _FakeBody:
    def __init__(self, data: bytes):
        self._data = data

    def read(self) -> bytes:
        return self._data


class _FakeS3Client:
    def __init__(self):
        self.objects: dict[tuple[str, str], bytes] = {}

    def put_object(self, Bucket, Key, Body):
        self.objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        if (Bucket, Key) not in self.objects:
            raise KeyError(Key)
        return {"Body": _FakeBody(self.objects[(Bucket, Key)])}

    def head_object(self, Bucket, Key):
        if (Bucket, Key) not in self.objects:
            raise KeyError(Key)
        return {}

    def get_paginator(self, op):
        assert op == "list_objects_v2"
        objects = self.objects

        class _P:
            def paginate(self, Bucket, Prefix):
                keys = sorted(
                    k for (b, k) in objects if b == Bucket and k.startswith(Prefix)
                )
                # two pages to exercise pagination handling
                mid = max(1, len(keys) // 2)
                for part in (keys[:mid], keys[mid:]):
                    yield {"Contents": [{"Key": k} for k in part]}

        return _P()


# ---------------------------------------------------------------------------
# fake pymongo: MongoClient[db][coll] with cursor-ish find + _id noise
# ---------------------------------------------------------------------------


class _FakeMongoColl:
    def __init__(self):
        self.docs: list[dict] = []
        self._next_id = 0

    def insert_one(self, doc):
        doc = dict(doc)
        doc["_id"] = self._next_id  # pymongo mutating-id behavior
        self._next_id += 1
        self.docs.append(doc)

    @staticmethod
    def _match(doc, query):
        return all(doc.get(k) == v for k, v in (query or {}).items())

    def find_one(self, query):
        for d in self.docs:
            if self._match(d, query):
                return dict(d)
        return None

    def find(self, query):
        return iter(dict(d) for d in self.docs if self._match(d, query))


class _FakeMongoDB(dict):
    def __getitem__(self, name):
        if name not in self:
            super().__setitem__(name, _FakeMongoColl())
        return super().__getitem__(name)


@pytest.fixture
def fake_backends(monkeypatch):
    """Install fake redis/boto3/pymongo modules; returns the live fake
    clients so tests can assert on backend state."""
    redis_client = _FakeRedisClient()
    s3_client = _FakeS3Client()
    mongo_dbs: dict[str, _FakeMongoDB] = {}

    redis_mod = types.ModuleType("redis")
    redis_mod.Redis = types.SimpleNamespace(
        from_url=lambda url: redis_client
    )
    boto3_mod = types.ModuleType("boto3")
    boto3_mod.client = lambda name, **kw: s3_client

    class _MongoClient:
        def __init__(self, url):
            pass

        def __getitem__(self, db):
            return mongo_dbs.setdefault(db, _FakeMongoDB())

    pymongo_mod = types.ModuleType("pymongo")
    pymongo_mod.MongoClient = _MongoClient

    monkeypatch.setitem(sys.modules, "redis", redis_mod)
    monkeypatch.setitem(sys.modules, "boto3", boto3_mod)
    monkeypatch.setitem(sys.modules, "pymongo", pymongo_mod)
    return redis_client, s3_client, mongo_dbs


def test_redis_adapter_contract(fake_backends):
    from swarm_tpu.stores import RedisStateStore

    store = RedisStateStore("redis://fake:6379/0")
    store.hset("jobs", "j1", '{"status": "queued"}')
    assert store.hget("jobs", "j1") == '{"status": "queued"}'
    assert store.hget("jobs", "nope") is None
    store.hset("jobs", "j2", "x")
    assert sorted(store.hkeys("jobs")) == ["j1", "j2"]
    assert store.hgetall("jobs")["j2"] == "x"
    store.hdel("jobs", "j2")
    assert "j2" not in store.hkeys("jobs")
    # result-cache tier surface (docs/CACHING.md): batched get +
    # atomic counter ride HMGET/HINCRBY on a real Redis
    assert store.hmget("jobs", ["j1", "nope"]) == [
        '{"status": "queued"}', None,
    ]
    store.hset_many("cache:v", {"d1": "a", "d2": "b"})
    assert store.hmget("cache:v", ["d1", "d2"]) == ["a", "b"]
    assert store.hincr("cache:meta", "fence_next") == 1
    assert store.hincr("cache:meta", "fence_next", 3) == 4
    store.rpush("job_queue", "a")
    store.rpush("job_queue", "b")
    store.lpush("job_queue", "front")
    assert store.llen("job_queue") == 3
    assert store.lrange("job_queue", 0, -1) == ["front", "a", "b"]
    assert store.lpop("job_queue") == "front"
    assert store.lpop("nothing") is None
    # journal recovery rebuilds dispatch lists wholesale (DEL on Redis)
    store.lclear("job_queue")
    assert store.llen("job_queue") == 0
    store.flushall()
    assert store.hkeys("jobs") == []


def test_s3_adapter_contract(fake_backends):
    from swarm_tpu.stores import S3BlobStore

    _, s3, _ = fake_backends
    store = S3BlobStore("bucket_name")
    store.put("scan_1/input/chunk_0.txt", b"hosts")
    assert store.get("scan_1/input/chunk_0.txt") == b"hosts"
    assert store.exists("scan_1/input/chunk_0.txt")
    assert not store.exists("scan_1/input/chunk_9.txt")
    for i in range(3):
        store.put(f"scan_1/output/chunk_{i}.txt", b"out%d" % i)
    assert store.list("scan_1/output/") == [
        f"scan_1/output/chunk_{i}.txt" for i in range(3)
    ]
    # reference bucket layout lands verbatim in the backend
    assert ("bucket_name", "scan_1/input/chunk_0.txt") in s3.objects
    with pytest.raises(NotImplementedError):
        store.delete_all()


def test_mongo_adapter_contract(fake_backends):
    from swarm_tpu.stores import MongoDocStore

    store = MongoDocStore("mongodb://fake:27017", "asm")
    scans = store.collection("scans")
    doc = {"scan_id": "s1", "progress": 100}
    scans.insert_one(doc)
    assert "_id" not in doc  # caller's dict not mutated
    got = scans.find_one({"scan_id": "s1"})
    assert got == {"scan_id": "s1", "progress": 100}  # _id stripped
    assert scans.find_one({"scan_id": "zz"}) is None
    scans.insert_one({"scan_id": "s2", "progress": 50})
    assert len(scans.find({})) == 2
    with pytest.raises(NotImplementedError):
        store.drop_all()


def test_full_lifecycle_on_real_adapters(fake_backends):
    """queue → dispatch → status flow → complete → rollup → raw, all on
    the redis/s3/mongo adapters via the production factory."""
    from swarm_tpu.server.queue import JobQueueService
    from swarm_tpu.stores import build_stores

    redis_client, s3_client, mongo_dbs = fake_backends
    cfg = Config(
        state_backend="redis",
        blob_backend="s3",
        doc_backend="mongo",
        api_key="k",
    )
    state, blobs, docs = build_stores(cfg)
    from swarm_tpu.stores import MongoDocStore, RedisStateStore, S3BlobStore

    assert isinstance(state, RedisStateStore)
    assert isinstance(blobs, S3BlobStore)
    assert isinstance(docs, MongoDocStore)

    q = JobQueueService(cfg, state, blobs, docs)
    q.queue_scan(
        {
            "module": "echo",
            "file_content": ["a.example\n", "b.example\n", "c.example\n"],
            "batch_size": 2,
            "scan_id": "echo_424242",
        }
    )
    # chunks land in the fake S3 under the reference key layout
    assert ("bucket_name", "echo_424242/input/chunk_0.txt") in s3_client.objects
    # the job queue lives in the fake redis
    assert redis_client.llen("job_queue") == 2

    for _ in range(2):
        job = q.next_job("w1")
        assert job["scan_id"] == "echo_424242"
        jid = job["job_id"]
        for status in ("starting", "downloading", "executing", "uploading"):
            assert q.update_job(jid, {"status": status, "worker_id": "w1"})
        q.put_output_chunk("echo_424242", int(job["chunk_index"]),
                           b"result-%d\n" % int(job["chunk_index"]))
        assert q.update_job(jid, {"status": "complete", "worker_id": "w1"})
    assert q.next_job("w1") is None

    st = q.statuses()
    scans = [s for s in st["scans"] if s["scan_id"] == "echo_424242"]
    assert scans and scans[0]["percent_complete"] == 100
    # completion summary persisted into the fake Mongo asm.scans
    summary = mongo_dbs["asm"]["scans"].find_one({"scan_id": "echo_424242"})
    assert summary is not None
    raw = q.raw_scan("echo_424242")
    assert "result-0" in raw and "result-1" in raw
