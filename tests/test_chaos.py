"""Chaos soak (docs/RESILIENCE.md capstone): a multi-chunk end-to-end
scan under a seeded fault plan — dropped polls, uploads failing through
the whole retry budget (spool + replay), dead heartbeats + an
over-lease chunk (expiry/re-lease/fencing), one poisoned job, one
device fault — must complete with verdicts BIT-IDENTICAL to the
fault-free run, and the poison job must land in dead-letter.

Two real worker threads drive the real HTTP server; the only
non-production piece is the deterministic fault plan.
"""

import base64
import json
import threading
import time

import pytest

from swarm_tpu.client.cli import JobClient
from swarm_tpu.config import Config
from swarm_tpu.resilience.faults import clear_plan, install_plan
from swarm_tpu.server.app import SwarmServer
from swarm_tpu.worker.runtime import JobProcessor

TEMPLATES = "tests/data/templates"

FAULT_PLAN = (
    "seed=7;"
    "transport.get_job:2,5;"          # dropped polls (retried)
    # chunk 0's upload fails past the whole retry budget (initial + 2
    # retries) → spooled, then replayed on the next successful poll
    "transport.put_chunk/victimscan_1_0:1-3;"
    # the slow chunk's heartbeats are dead → its lease CAN lapse
    # (scoped by job id so the spool's ownership-probe renewal for
    # chunk 0 still works)
    "transport.renew_lease/victimscan_1_2:*;"
    "executor.run/poisonscan*:*;"     # the poison job always fails
    "executor.run/victimscan_1_2:1:sleep=1.2;"  # chunk outlives its lease
    "device.dispatch:1"               # one device-path fault (degrade)
)


@pytest.fixture
def stack(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TEMPLATES_DIR", TEMPLATES)
    modules_dir = tmp_path / "modules"
    modules_dir.mkdir()
    (modules_dir / "fingerprint.json").write_text(
        json.dumps({"backend": "tpu", "templates": "${SWARM_TEMPLATES_DIR}"})
    )
    cfg = Config(
        host="127.0.0.1", port=0, api_key="chaoskey",
        blob_root=str(tmp_path / "blobs"), doc_root=str(tmp_path / "docs"),
        modules_dir=str(modules_dir),
        poll_interval_idle_s=0.03, poll_interval_busy_s=0.01,
        lease_seconds=0.5, max_attempts=3,
        transport_retries=2, transport_backoff_s=0.01,
        transport_backoff_max_s=0.05,
        transport_breaker_threshold=50, transport_breaker_cooldown_s=0.2,
        heartbeat_interval_s=0.1,
    )
    srv = SwarmServer(cfg)
    srv.start_background()
    cfg.server_url = f"http://127.0.0.1:{srv.port}"
    yield cfg, srv, tmp_path
    clear_plan()
    srv.shutdown()


def _victim_rows():
    rows = [
        {"host": f"10.0.0.{i}", "port": 443, "status": 200,
         "body": f"<title>Demo Admin</title> demo-build 7.{i} page {i}"}
        for i in range(6)
    ]
    rows.append(
        {"host": "10.0.9.1", "port": 7777,
         "banner_b64": base64.b64encode(b"DEMOD: 2 service ready").decode()}
    )
    rows.append({"host": "10.0.9.2", "port": 80, "status": 200,
                 "body": "hello world"})
    return rows


def _submit(client, tmp_path, scan_id, rows, batch):
    f = tmp_path / f"{scan_id}.jsonl"
    f.write_text("".join(json.dumps(r) + "\n" for r in rows))
    code, _ = client.start_scan(str(f), "fingerprint", 0, batch, scan_id=scan_id)
    assert code == 200


def _worker(cfg, worker_id):
    wcfg = Config(**{**cfg.__dict__, "worker_id": worker_id})
    return JobProcessor(wcfg)


def test_chaos_soak_bit_identical_and_quarantines(stack):
    cfg, srv, tmp_path = stack
    client = JobClient(cfg.resolve_url(), cfg.api_key)

    # --- fault-free baseline: same content, no plan ---
    _submit(client, tmp_path, "victimbase_1", _victim_rows(), batch=2)
    base_worker = _worker(cfg, "base-w")
    base_worker.cfg.max_jobs = 4
    base_worker.process_jobs()
    baseline_raw = client.fetch_raw("victimbase_1")
    assert baseline_raw  # 4 chunks of real output

    # --- arm the plan, submit victim + poison, unleash two workers ---
    plan = install_plan(FAULT_PLAN)
    _submit(client, tmp_path, "victimscan_1", _victim_rows(), batch=2)
    _submit(client, tmp_path, "poisonscan_1",
            [{"host": "10.1.0.1", "port": 80, "status": 200, "body": "x"}],
            batch=1)
    workers = [_worker(cfg, "w0"), _worker(cfg, "w1")]
    threads = [
        threading.Thread(target=w.process_jobs, daemon=True) for w in workers
    ]
    for t in threads:
        t.start()

    try:
        deadline = time.time() + 120
        victim_done = poison_dead = False
        while time.time() < deadline and not (victim_done and poison_dead):
            time.sleep(0.2)
            statuses = client.get_statuses()
            if statuses is None:
                continue
            for scan in statuses.get("scans", []):
                if scan["scan_id"] == "victimscan_1":
                    victim_done = scan["percent_complete"] == 100.0
            poison = statuses["jobs"].get("poisonscan_1_0")
            poison_dead = bool(poison) and poison["status"] == "dead letter"
        assert victim_done, "victim scan did not complete under chaos"
        assert poison_dead, "poison job did not reach dead-letter"
    finally:
        for w in workers:
            w.stop_requested = True
        for t in threads:
            t.join(timeout=30)

    # --- capstone: verdicts bit-identical to the fault-free run ---
    chaos_raw = client.fetch_raw("victimscan_1")
    assert chaos_raw == baseline_raw.replace("victimbase_1", "victimscan_1")

    # --- the poison job carries its provenance and is CLI-requeueable ---
    [dead] = client.dead_letter_jobs()
    assert dead["job_id"] == "poisonscan_1_0"
    assert len(dead["failure_history"]) == cfg.max_attempts
    assert all(f["status"] == "cmd failed" for f in dead["failure_history"])

    # --- every injected failure mode actually fired ---
    snap = plan.snapshot()
    assert snap["transport.get_job"]["fired"] == 2
    assert snap["transport.put_chunk/victimscan_1_0"]["fired"] == 3
    assert snap["transport.renew_lease/victimscan_1_2"]["fired"] >= 1
    assert snap["executor.run/poisonscan*"]["fired"] == cfg.max_attempts
    assert snap["executor.run/victimscan_1_2"]["fired"] == 1
    assert snap["device.dispatch"]["fired"] == 1

    # --- the spool caught the upload that failed past its retries ---
    # (already drained by replay at this point; assert via telemetry)
    from swarm_tpu.telemetry import REGISTRY

    metrics = {}
    for line in REGISTRY.render().splitlines():
        if line and not line.startswith("#"):
            name = line.split("{")[0].split(" ")[0]
            try:
                metrics[name] = metrics.get(name, 0.0) + float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    assert metrics.get("swarm_resilience_spooled_chunks_total", 0) >= 1
    assert metrics.get("swarm_resilience_spool_replayed_total", 0) >= 1

    # --- operator surface: /healthz shows quarantine + breakers ---
    health = client.get_healthz()
    assert health["dead_letter_jobs"] == 1
    assert isinstance(health["breakers"], dict)
    assert health["fault_plan"] == FAULT_PLAN

    # --- with the plan cleared, fault points return to no-ops ---
    clear_plan()
    from swarm_tpu.resilience.faults import fault_point

    fault_point("transport.get_job")  # must not raise


def test_dead_letter_requeue_completes_after_poison_lifts(stack):
    """Operator story: inspect the quarantined job, requeue it once the
    underlying cause is fixed (plan cleared), and watch it complete."""
    cfg, srv, tmp_path = stack
    client = JobClient(cfg.resolve_url(), cfg.api_key)
    install_plan("executor.run/poisonscan*:*")
    _submit(client, tmp_path, "poisonscan_9",
            [{"host": "10.1.0.2", "port": 80, "status": 200, "body": "y"}],
            batch=1)
    w = _worker(cfg, "wq")
    w.cfg.max_jobs = cfg.max_attempts
    w.process_jobs()  # burns all attempts → dead letter
    [dead] = client.dead_letter_jobs()
    assert dead["job_id"] == "poisonscan_9_0"
    clear_plan()  # "the bug is fixed"
    code, _ = client.requeue_job("poisonscan_9_0")
    assert code == 200
    w2 = _worker(cfg, "wq2")
    w2.cfg.max_jobs = 1
    w2.process_jobs()
    statuses = client.get_statuses()
    assert statuses["jobs"]["poisonscan_9_0"]["status"] == "complete"
    assert client.dead_letter_jobs() == []


def test_preempted_worker_hard_killed_mid_drain_recovers(stack):
    """Preemption soak (docs/RESILIENCE.md §Preemption): a worker with
    a finished chunk stranded in its spool gets a preemption notice,
    and the provider's kill lands before the graceful drain finishes —
    the armed worker.drain clause IS the kill -9 mid-drain-upload.
    Lease expiry hands the chunk to a rescue worker, the dead worker's
    surviving spool is fenced off on replay (no double-terminal), and
    the output stays bit-identical to a fault-free baseline."""
    cfg, srv, tmp_path = stack
    client = JobClient(cfg.resolve_url(), cfg.api_key)

    rows = _victim_rows()
    _submit(client, tmp_path, "prebase_1", rows, batch=len(rows))
    base = _worker(cfg, "base-w")
    base.cfg.max_jobs = 1
    base.process_jobs()
    baseline_raw = client.fetch_raw("prebase_1")
    assert baseline_raw

    # chunk 0's upload fails past the whole retry budget → spooled.
    # max_jobs=1 stops the doomed worker right after the spool write
    # (before any idle-loop replay could drain it): that frozen moment
    # is "the preemption notice arrived mid-upload"
    install_plan(
        "transport.put_chunk/preemptscan_1_0:1-3;"
        "worker.drain/doomed:*"
    )
    _submit(client, tmp_path, "preemptscan_1", rows, batch=len(rows))
    doomed_cfg = Config(**{
        **cfg.__dict__, "worker_id": "doomed", "max_jobs": 1,
        "spool_dir": str(tmp_path / "doomed_spool"),
    })
    doomed = JobProcessor(doomed_cfg)
    doomed.process_jobs()
    assert len(doomed.spool) == 1, "chunk never reached the spool"
    # the server-side notice journals the drain entry; the worker's
    # graceful drain then aborts mid-flight — the armed clause IS the
    # provider's kill landing before the upload finishes
    assert srv.queue.drain_worker("doomed", reason="preempted")
    doomed.request_drain("preempted")
    assert doomed.drain("preempted") == "aborted"  # the kill won
    assert len(doomed.spool) == 1                # nothing replayed or lost
    # no deregister ever arrived: the drain entry is still journaled
    assert srv.queue.draining_workers() == {"doomed": "preempted"}

    # recovery path 1: lease expiry requeues the chunk to a rescuer
    rescue = _worker(cfg, "rescue")
    rescue.cfg.max_jobs = 1
    rt = threading.Thread(target=rescue.process_jobs, daemon=True)
    rt.start()
    deadline = time.time() + 45
    while rt.is_alive() and time.time() < deadline:
        time.sleep(0.1)
    if rt.is_alive():
        rescue.stop_requested = True
        rt.join(timeout=10)
        raise AssertionError(
            "rescue never finished; job record="
            + repr(srv.queue.state.hget("jobs", "preemptscan_1_0"))
            + " leases=" + repr(srv.queue.state.hgetall("leases"))
            + " draining=" + repr(srv.queue.draining_workers())
        )
    chaos_raw = client.fetch_raw("preemptscan_1")
    assert chaos_raw == baseline_raw.replace("prebase_1", "preemptscan_1")
    rec = json.loads(srv.queue.state.hget("jobs", "preemptscan_1_0"))
    assert rec["status"] == "complete" and rec["worker_id"] == "rescue"

    # recovery path 2: the replacement node boots over the dead
    # worker's disk and replays the spool — fencing rejects the stale
    # completion (lease renewal bounces) instead of double-finalising
    clear_plan()
    doomed2 = JobProcessor(doomed_cfg)
    assert len(doomed2.spool) == 1               # survived on disk
    doomed2._replay_spool()
    assert len(doomed2.spool) == 0               # fenced → dropped
    assert client.fetch_raw("preemptscan_1") == chaos_raw  # untouched
    rec = json.loads(srv.queue.state.hget("jobs", "preemptscan_1_0"))
    assert rec["status"] == "complete" and rec["worker_id"] == "rescue"
