"""Native TLS-wrapped probing (scanio + executor https path).

The reference's https coverage came from its Go tools' TLS clients
(httpx/httprobe — SURVEY.md §2.2); here the native epoll engine wraps
connections in OpenSSL (dlopen'd libssl.so.3) with nonblocking
handshakes in the same event loop. Tests run against a real
ssl-module-served HTTPS endpoint.
"""

import http.server
import socketserver
import ssl
import subprocess
import threading

import pytest

from swarm_tpu.native import scanio

#: pre-existing environment gap (ROADMAP housekeeping): the native
#: engine dlopens libssl.so.3 (OpenSSL 3), but this image ships only
#: libssl.so.1.1 + NSS's libssl3.so — TLS-dependent tests skip with
#: this reason instead of failing. The probes stay in the suite so a
#: fixed image turns them back on automatically.
needs_libssl = pytest.mark.skipif(
    not scanio.tls_available(),
    reason="libssl.so.3 not loadable in this image (only libssl 1.1 / "
    "NSS present); native TLS handshakes cannot run",
)


@pytest.fixture(scope="module")
def https_server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tls")
    key, crt = tmp / "key.pem", tmp / "crt.pem"
    gen = subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(crt), "-days", "1",
            "-subj", "/CN=localhost",
        ],
        capture_output=True,
    )
    if gen.returncode != 0:
        pytest.skip("openssl unavailable")

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"<html><title>secure-widget</title>tls works</html>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Server", "https-test")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(str(crt), str(key))
    srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


@pytest.fixture(scope="module")
def plain_server():
    class H(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                self.request.recv(1024)
                self.request.sendall(
                    b"HTTP/1.1 200 OK\r\nContent-Length: 5\r\n\r\nplain"
                )
            except OSError:
                pass

    class S(socketserver.ThreadingTCPServer):
        daemon_threads = True
        allow_reuse_address = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


REQ = b"GET / HTTP/1.1\r\nHost: localhost\r\nConnection: close\r\n\r\n"


def test_tls_available():
    """The environment probe itself: if the image ships the OpenSSL 3
    soname the native layer dlopens, scanio MUST report TLS available —
    guarded by an INDEPENDENT ctypes load (not tls_available(), which
    would make this a tautology that can never fail)."""
    import ctypes

    try:
        ctypes.CDLL("libssl.so.3")
    except OSError:
        pytest.skip(
            "libssl.so.3 not loadable in this image (only libssl 1.1 / "
            "NSS present); native TLS handshakes cannot run"
        )
    assert scanio.tls_available(), (
        "image ships libssl.so.3 but the native TLS loader failed"
    )


@needs_libssl
def test_tls_scan_decrypts_response(https_server):
    r = scanio.tcp_scan(
        ["127.0.0.1"], [https_server], [REQ],
        tls=[True], sni=["localhost"], read_timeout_ms=4000,
    )
    assert int(r.status[0]) == scanio.STATUS_OPEN
    banner = r.banner(0)
    assert banner.startswith(b"HTTP/1.0 200") or banner.startswith(b"HTTP/1.1 200")
    assert b"secure-widget" in banner  # decrypted application data


def test_tls_to_plain_port_reports_tls_failed(plain_server):
    r = scanio.tcp_scan(
        ["127.0.0.1"], [plain_server], [REQ], tls=[True], read_timeout_ms=2000
    )
    assert int(r.status[0]) == scanio.STATUS_TLS_FAILED


@needs_libssl
def test_mixed_tls_and_plain_wave(https_server, plain_server):
    r = scanio.tcp_scan(
        ["127.0.0.1"] * 3,
        [https_server, plain_server, 1],
        [REQ, REQ, None],
        tls=[True, False, False],
        sni=["localhost", None, None],
        read_timeout_ms=4000,
    )
    assert int(r.status[0]) == scanio.STATUS_OPEN and b"200" in r.banner(0)
    assert int(r.status[1]) == scanio.STATUS_OPEN and r.banner(1).endswith(b"plain")
    assert int(r.status[2]) == scanio.STATUS_CLOSED


@needs_libssl
def test_executor_probes_https(https_server, monkeypatch):
    """The http probe path wraps 443/8443 in TLS; patch tls_port to
    treat the test port as TLS so the full parse path is exercised."""
    from swarm_tpu.worker import executor as ex

    monkeypatch.setattr(ex, "tls_port", lambda p: p == https_server)
    rows = ex.ProbeExecutor(
        {"ports": [https_server], "read_timeout_ms": 4000}
    ).run(["127.0.0.1"])
    assert len(rows) == 1
    row = rows[0]
    assert row.alive and row.status == 200
    assert b"secure-widget" in row.body
    assert b"https-test" in row.header


def test_executor_tls_failure_is_dead_row(plain_server, monkeypatch):
    from swarm_tpu.worker import executor as ex

    monkeypatch.setattr(ex, "tls_port", lambda p: p == plain_server)
    rows = ex.ProbeExecutor(
        {"ports": [plain_server], "read_timeout_ms": 2000}
    ).run(["127.0.0.1"])
    assert len(rows) == 1 and not rows[0].alive


def test_use_tls_scheme_overrides_port_heuristic():
    from swarm_tpu.worker.executor import use_tls

    assert use_tls("https", 9443) is True   # stated scheme wins
    assert use_tls("http", 8443) is False   # stated scheme wins
    assert use_tls("", 443) is True         # heuristic fallback
    assert use_tls("", 8443) is True
    assert use_tls("", 80) is False


def test_sni_unencodable_name_does_not_sink_batch(plain_server):
    # a hostname idna cannot encode must degrade to no-SNI, not raise
    r = scanio.tcp_scan(
        ["127.0.0.1"], [plain_server], None,
        tls=[False], sni=["ä" * 64 + ".example"], read_timeout_ms=500,
    )
    assert int(r.status[0]) == scanio.STATUS_OPEN
