"""Client ``stream`` action end-to-end (docs/GATEWAY.md): real server,
multi-chunk scan, mid-stream chunk arrival, and server restart
mid-stream resuming from the last acked chunk via the idempotent chunk
store."""

import json
import threading
import time

import pytest
import requests

from swarm_tpu.client.cli import JobClient
from swarm_tpu.config import Config
from swarm_tpu.server.app import SwarmServer


def _make_server(tmp_path, **kw) -> SwarmServer:
    cfg = Config(
        host="127.0.0.1", port=0, api_key="sk",
        blob_root=str(tmp_path / "blobs"), doc_root=str(tmp_path / "docs"),
        gateway_stream_poll_s=0.01, gateway_stream_idle_timeout_s=5.0,
        **kw,
    )
    srv = SwarmServer(cfg)
    srv.start_background()
    return srv


def _submit(srv, scan_id, chunks):
    resp = requests.post(
        f"http://127.0.0.1:{srv.port}/queue",
        json={
            "module": "echo",
            "file_content": [f"row{i}\n" for i in range(chunks)],
            "batch_size": 1, "scan_id": scan_id,
        },
        headers={"Authorization": "Bearer sk"},
        timeout=10,
    )
    assert resp.status_code == 200


def _complete_chunk(srv, scan_id, index, worker="w"):
    """Walk one chunk through the real HTTP worker surface."""
    base = f"http://127.0.0.1:{srv.port}"
    auth = {"Authorization": "Bearer sk"}
    requests.post(
        base + f"/put-output-chunk/{scan_id}/{index}",
        data=f"output-{index}\n".encode(), headers=auth, timeout=10,
    )
    requests.post(
        base + f"/update-job/{scan_id}_{index}",
        json={"status": "complete"}, headers=auth, timeout=10,
    )


def _lease_all(srv, scan_id, chunks):
    leased = []
    base = f"http://127.0.0.1:{srv.port}"
    for _ in range(chunks):
        r = requests.get(
            base + "/get-job", params={"worker_id": "w"},
            headers={"Authorization": "Bearer sk"}, timeout=10,
        )
        if r.status_code == 200:
            leased.append(r.json()["job_id"])
    return leased


def test_stream_orders_chunks_and_sees_mid_stream_arrival(tmp_path):
    """Chunks completing OUT of order, some landing after the stream
    is already attached, arrive at the client IN index order."""
    srv = _make_server(tmp_path)
    try:
        _submit(srv, "s_1", 4)
        _lease_all(srv, "s_1", 4)
        _complete_chunk(srv, "s_1", 1)  # out of order before attach

        client = JobClient(f"http://127.0.0.1:{srv.port}", "sk")
        got: list = []

        def consume():
            for chunk, text in client.stream_results("s_1"):
                got.append((chunk, text))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # stream attached, waiting on chunk 0
        assert got == []  # chunk 1 must NOT arrive before chunk 0
        _complete_chunk(srv, "s_1", 0)
        _complete_chunk(srv, "s_1", 3)
        time.sleep(0.3)
        _complete_chunk(srv, "s_1", 2)  # unblocks 2 then 3
        t.join(timeout=15)
        assert not t.is_alive(), "stream did not terminate on scan end"
        assert got == [(i, f"output-{i}\n") for i in range(4)]
    finally:
        srv.shutdown()


def test_stream_resumes_after_server_restart_from_last_acked(tmp_path):
    """Mid-stream disconnect + full server restart (fresh in-memory job
    table, SAME durable chunk store): the client reconnects with
    ?from=<last acked + 1> and the new server serves the remaining
    chunks from the idempotent blob store, then ends the stream."""
    srv = _make_server(tmp_path)
    port1 = srv.port
    _submit(srv, "s_2", 4)
    _lease_all(srv, "s_2", 4)
    for i in range(4):
        _complete_chunk(srv, "s_2", i)

    # consume exactly 2 records over the raw wire, then sever
    resp = requests.get(
        f"http://127.0.0.1:{port1}/stream/s_2",
        headers={"Authorization": "Bearer sk"}, stream=True, timeout=10,
    )
    acked = []
    for line in resp.iter_lines():
        if not line:
            continue
        rec = json.loads(line)
        acked.append(rec["chunk"])
        if len(acked) == 2:
            break
    resp.close()  # client-side disconnect mid-stream
    assert acked == [0, 1]
    srv.shutdown()  # the restart: job records die with the process

    srv2 = _make_server(tmp_path)  # same blob_root — the durable store
    try:
        client = JobClient(f"http://127.0.0.1:{srv2.port}", "sk")
        rest = list(client.stream_results("s_2", from_chunk=acked[-1] + 1))
        assert rest == [(2, "output-2\n"), (3, "output-3\n")]
    finally:
        srv2.shutdown()


def test_stream_skips_dead_letter_chunk(tmp_path):
    """A chunk that exhausts its attempts (dead letter) yields a skip —
    the stream moves past it instead of hanging forever."""
    srv = _make_server(tmp_path, max_attempts=1, retry_failed=True)
    try:
        _submit(srv, "s_3", 3)
        base = f"http://127.0.0.1:{srv.port}"
        auth = {"Authorization": "Bearer sk"}
        # lease all three; fail chunk 1 with a fenced terminal (one
        # attempt budget → straight to dead letter)
        jobs = _lease_all(srv, "s_3", 3)
        assert len(jobs) == 3
        _complete_chunk(srv, "s_3", 0)
        requests.post(
            base + "/update-job/s_3_1",
            json={"status": "cmd failed", "worker_id": "w"},
            headers=auth, timeout=10,
        )
        _complete_chunk(srv, "s_3", 2)
        client = JobClient(base, "sk")
        got = list(client.stream_results("s_3"))
        assert got == [(0, "output-0\n"), (2, "output-2\n")]
    finally:
        srv.shutdown()


def test_stream_idle_timeout_record_then_client_reconnects(tmp_path):
    """The server bounds stream handler lifetime with an idle-timeout
    record; the CLIENT treats it as a reconnect signal and continues
    from the cursor without data loss."""
    srv = _make_server(tmp_path)
    srv.cfg.gateway_stream_idle_timeout_s = 0.3
    try:
        _submit(srv, "s_4", 2)
        _lease_all(srv, "s_4", 2)
        _complete_chunk(srv, "s_4", 0)
        client = JobClient(f"http://127.0.0.1:{srv.port}", "sk")
        got: list = []

        def consume():
            for chunk, text in client.stream_results(
                "s_4", reconnect_delay_s=0.05
            ):
                got.append(chunk)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.8)  # at least one idle timeout + reconnect cycle
        assert got == [0]
        _complete_chunk(srv, "s_4", 1)
        t.join(timeout=15)
        assert not t.is_alive()
        assert got == [0, 1]
    finally:
        srv.shutdown()


def test_cli_stream_follow_action_prints_chunks(tmp_path, capsys):
    """`swarm stream --scan-id X` (no --module) = follow mode."""
    from swarm_tpu.client.cli import main as cli_main

    srv = _make_server(tmp_path)
    try:
        _submit(srv, "s_5", 2)
        _lease_all(srv, "s_5", 2)
        _complete_chunk(srv, "s_5", 0)
        _complete_chunk(srv, "s_5", 1)
        rc = cli_main(
            ["stream", "--scan-id", "s_5",
             "--server-url", f"http://127.0.0.1:{srv.port}",
             "--api-key", "sk"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert out == "output-0\noutput-1\n"
    finally:
        srv.shutdown()
