"""swarmlint self-tests (docs/ANALYSIS.md).

The analyzer polices invariants the runtime suites can only sample —
so the analyzer itself needs positive AND negative controls: fixture
modules with known violations must fire the expected rule at the
expected site, and the equivalent guarded/declared/waived form must
stay silent. Also pins the baseline workflow (new finding fails, a
baselined finding needs a written reason, stale entries are reported
not fatal) and the acceptance contract that ``python -m
tools.swarmlint`` exits 0 on HEAD.
"""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from tools.swarmlint import guards, jithygiene, native_audit
from tools.swarmlint.__main__ import main as swarmlint_main
from tools.swarmlint.common import (
    Baseline,
    Finding,
    diff_against_baseline,
)

REPO = Path(__file__).resolve().parents[1]


def _write(tmp_path: Path, name: str, body: str) -> Path:
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return p


def _rules(findings):
    return sorted(f.rule for f in findings)


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# guards pass
# ---------------------------------------------------------------------------

GUARD_FIXTURE = '''
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # guarded-by: _lock
        self.subs = []  # guarded-by: _lock
        self.mode = "idle"  # guarded-by: _lock (reads)

    def good(self):
        with self._lock:
            self.hits += 1
            self.subs.append(1)
            return self.mode

    def bad_write(self):
        self.hits += 1

    def bad_mutation(self):
        self.subs.append(2)

    def bad_subscript(self):
        self.subs[0] = 3

    def bad_read(self):
        return self.mode

    def waived(self):
        self.hits = 0  # unguarded-ok: fixture: single-threaded reset path

    def bad_waiver(self):
        self.hits = 0  # unguarded-ok:

    def closure_leaks_lock(self):
        with self._lock:
            def later():
                self.hits += 1
            return later
'''


def test_guards_positive_and_negative_controls(tmp_path):
    p = _write(tmp_path, "fix_guards.py", GUARD_FIXTURE)
    findings, _mg = guards.check_file(p)
    writes = _by_rule(findings, guards.RULE_WRITE)
    # the four bad sites + the closure (a with-block does NOT protect a
    # def'd closure that runs later) — and NOTHING in good()/__init__()
    bad_syms = sorted(f.symbol for f in writes)
    assert bad_syms == [
        "Counter.bad_mutation",
        "Counter.bad_subscript",
        "Counter.bad_waiver",  # empty reason does not waive the site...
        "Counter.bad_write",
        "Counter.closure_leaks_lock.later",
    ] or bad_syms == [
        # empty-reason waiver semantics: site waived but config finding
        "Counter.bad_mutation",
        "Counter.bad_subscript",
        "Counter.bad_write",
        "Counter.closure_leaks_lock.later",
    ]
    reads = _by_rule(findings, guards.RULE_READ)
    assert [f.symbol for f in reads] == ["Counter.bad_read"]
    # the empty '# unguarded-ok:' is itself a finding
    assert any(
        "needs a reason" in f.message
        for f in _by_rule(findings, guards.RULE_CONFIG)
    )
    # negative controls: no finding inside good() or __init__
    assert not any("good" in f.symbol for f in findings)
    assert not any("__init__" in f.symbol for f in findings)


INIT_CLOSURE_FIXTURE = '''
import threading


class Ticker:
    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0  # guarded-by: _lock
        def tick():
            self.ticks += 1
        self._thread = threading.Thread(target=tick)
'''


def test_guards_init_exemption_stops_at_nested_defs(tmp_path):
    """A closure defined in __init__ runs AFTER publication, on
    another thread (the Thread/Timer ticker pattern) — the
    construction exemption must not extend into it."""
    p = _write(tmp_path, "fix_init_closure.py", INIT_CLOSURE_FIXTURE)
    findings, _mg = guards.check_file(p)
    writes = _by_rule(findings, guards.RULE_WRITE)
    assert [f.symbol for f in writes] == ["Ticker.__init__.tick"]


REQUIRES_FIXTURE = '''
import threading

_GLOBAL_LOCK = threading.Lock()
_count = 0  # guarded-by: _GLOBAL_LOCK


def _bump_locked():  # requires-lock: _GLOBAL_LOCK
    global _count
    _count += 1


def good_caller():
    with _GLOBAL_LOCK:
        _bump_locked()


def bad_caller():
    _bump_locked()
'''


def test_guards_requires_lock_call_sites(tmp_path):
    p = _write(tmp_path, "fix_requires.py", REQUIRES_FIXTURE)
    findings, _mg = guards.check_file(p)
    calls = _by_rule(findings, guards.RULE_CALL)
    assert [f.symbol for f in calls] == ["bad_caller"]
    # the annotated body counts the lock as held: no write finding
    assert not _by_rule(findings, guards.RULE_WRITE)


GUARDS_LIST_FIXTURE = '''
import threading


class Stats:
    def __init__(self):
        self.inner = object()
        self._lock = threading.Lock()  # guards: inner.total, pending

    def good(self):
        with self._lock:
            self.inner.total = 5
            self.pending = 1

    def bad(self):
        self.inner.total = 5
'''


def test_guards_list_form_on_lock_line(tmp_path):
    p = _write(tmp_path, "fix_list.py", GUARDS_LIST_FIXTURE)
    findings, _mg = guards.check_file(p)
    writes = _by_rule(findings, guards.RULE_WRITE)
    assert [f.symbol for f in writes] == ["Stats.bad"]
    assert "inner.total" in writes[0].message


def test_guards_unknown_lock_is_a_config_finding(tmp_path):
    p = _write(tmp_path, "fix_unknown.py", '''
import threading

_x = 0  # guarded-by: _MISSING_LOCK
''')
    findings, _mg = guards.check_file(p)
    cfg = _by_rule(findings, guards.RULE_CONFIG)
    assert cfg and "unknown lock" in cfg[0].message


def test_guarded_paths_surface(tmp_path):
    p = _write(tmp_path, "fix_surface.py", GUARD_FIXTURE)
    paths = guards.guarded_paths(p)
    assert paths[("Counter", "hits")] == "_lock"
    assert paths[("Counter", "subs")] == "_lock"
    assert paths[("Counter", "mode")] == "_lock"


# ---------------------------------------------------------------------------
# jit-hygiene pass
# ---------------------------------------------------------------------------

JIT_FIXTURE = '''
import jax
import jax.numpy as jnp
import numpy as np


def build_undeclared(db):
    meta = db["meta"]

    @jax.jit
    def kernel(streams):
        return streams + meta

    return kernel


def build_declared(db):
    meta = db["meta"]

    @jax.jit
    def kernel(streams):  # jit-captures: meta (small layout tuple)
        return streams + meta

    return kernel


def build_array_capture(db):
    table = jnp.asarray(db["table"])

    @jax.jit
    def kernel(streams):  # jit-captures: table
        return streams + table

    return kernel
'''


def test_jit_capture_controls(tmp_path):
    p = _write(tmp_path, "fix_jit.py", JIT_FIXTURE)
    findings = jithygiene.check_file(p)
    caps = _by_rule(findings, jithygiene.RULE_CAPTURE)
    # undeclared capture fires; the declared twin is silent
    assert [(f.symbol, f.detail) for f in caps] == [
        ("kernel", "kernel:meta")
    ]
    # a declared capture bound from an array upload STILL fires — a
    # declaration asserts "small and static", an upload never is
    arrays = _by_rule(findings, jithygiene.RULE_CAPTURE_ARRAY)
    assert [f.detail for f in arrays] == ["kernel:table"]


WRAPPED_JIT_FIXTURE = '''
import jax
from jax.experimental.shard_map import shard_map


def build_wrapped_undeclared(db, mesh, specs):
    meta = db["meta"]

    def step(streams):
        return streams + meta

    fn = shard_map(step, mesh=mesh, in_specs=specs, out_specs=specs)
    return jax.jit(fn)


def build_wrapped_declared(db, mesh, specs):
    meta = db["meta"]

    def step(streams):  # jit-captures: meta (small layout tuple)
        return streams + meta

    fn = shard_map(step, mesh=mesh, in_specs=specs, out_specs=specs)
    return jax.jit(fn)


def build_wrapped_inline(db, mesh, specs):
    meta = db["meta"]

    def step(streams):
        return streams + meta

    return jax.jit(
        shard_map(step, mesh=mesh, in_specs=specs, out_specs=specs)
    )
'''


def test_wrapped_jit_subject_captures(tmp_path):
    """``jax.jit(shard_map(step, ...))`` — the sharded matcher's shape
    — still checks ``step``'s captures: the transform doesn't stop
    them becoming trace-time constants. One wrapper level resolves
    both through a bound name and inline."""
    p = _write(tmp_path, "fix_wrapped.py", WRAPPED_JIT_FIXTURE)
    findings = jithygiene.check_file(p)
    caps = _by_rule(findings, jithygiene.RULE_CAPTURE)
    # undeclared fires through the bound name AND inline; the declared
    # twin is silent
    assert [(f.symbol, f.detail) for f in caps] == [
        ("step", "step:meta"),  # build_wrapped_undeclared
        ("step", "step:meta"),  # build_wrapped_inline
    ], [f.render() for f in caps]


DONATE_FIXTURE = '''
import jax
import numpy as np


def run_kernel(db, streams, lengths):
    return streams


def dispatch_bad(db, streams, lengths):
    fb = jax.jit(run_kernel, donate_argnums=(1, 2))
    out = fb(db, streams, lengths)
    return out, streams


def dispatch_rebound(db, streams, lengths):
    fb = jax.jit(run_kernel, donate_argnums=(1, 2))
    out = fb(db, streams, lengths)
    streams = {}
    return out, streams


def dispatch_waived(db, streams, lengths):
    fb = jax.jit(run_kernel, donate_argnums=(1, 2))
    out = fb(db, streams, lengths)
    keep = streams  # donated-ok: fixture — caller hands over a copy
    return out, keep


def sync_paths(db, streams, lengths):
    fa = jax.jit(run_kernel)
    cnt = fa(db, streams, lengths)
    n = int(cnt)
    m = float(cnt)  # host-sync-ok: fixture — the one blessed scalar
    return n, m
'''


def test_donated_use_and_host_sync_controls(tmp_path):
    p = _write(tmp_path, "fix_donate.py", DONATE_FIXTURE)
    findings = jithygiene.check_file(p)
    donated = _by_rule(findings, jithygiene.RULE_DONATED)
    # only dispatch_bad reads a donated buffer after dispatch; the
    # rebound and waived twins are silent
    assert {f.symbol for f in donated} == {"dispatch_bad"}
    assert all("streams" in f.detail for f in donated)
    syncs = _by_rule(findings, jithygiene.RULE_SYNC)
    assert [f.detail for f in syncs] == ["sync_paths:int(cnt)"]


def test_jit_pass_clean_on_production_device_modules():
    """The legacy fused kernel and the split-phase path both declare
    their captures, route uploads through arguments, and annotate the
    single blessed 4-byte sync — the pass over the real device modules
    must be finding-free (this is the PR 3 HLO constant-scan test,
    generalized to every path instead of one traced batch shape)."""
    targets = [
        REPO / t
        for t in jithygiene.DEFAULT_TARGETS
        if (REPO / t).exists()
    ]
    assert targets, "device modules moved — update DEFAULT_TARGETS"
    findings = jithygiene.run(targets)
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# native audit pass
# ---------------------------------------------------------------------------

NATIVE_FIXTURE = r'''
#include <Python.h>

static PyObject* checked_alloc(PyObject* rows) {
  PyObject* out = PyList_New(0);
  if (out == NULL) return NULL;
  return out;
}

static PyObject* bad_alloc(PyObject* rows) {
  PyObject* out = PyList_New(0);
  PyObject* item = PyLong_FromLong(7);
  if (item == NULL) return NULL;
  PyList_Append(out, item);
  return out;
}

static PyObject* checked_append(PyObject* out, PyObject* item) {
  if (PyList_Append(out, item) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject* waived_append(PyObject* out, PyObject* item) {
  PyList_Append(out, item);  // retcheck-ok: fixture — best-effort log sink
  Py_RETURN_NONE;
}

static long bad_gil(PyObject* row, const char* buf, long n) {
  long total = 0;
  Py_BEGIN_ALLOW_THREADS
  for (long i = 0; i < n; ++i) total += buf[i];
  total += PyObject_IsTrue(row);
  total += row->ob_refcnt ? 1 : 0;
  Py_END_ALLOW_THREADS
  return total;
}

static long good_gil(PyObject* row, long n) {
  Py_ssize_t size = 0;
  char* data = NULL;
  if (PyBytes_AsStringAndSize(row, &data, &size) < 0) return -1;
  long total = 0;
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < size && i < n; ++i) total += data[i];
  Py_END_ALLOW_THREADS
  return total;
}

static long waived_gil(PyObject* row) {
  long total = 0;
  Py_BEGIN_ALLOW_THREADS
  total += (long)PyUnicode_GetLength(row);  // gil-ok: fixture — row is thread-private here
  Py_END_ALLOW_THREADS
  return total;
}

static long errquery_checked(PyObject* obj) {
  long v = PyLong_AsLong(obj);
  if (v == -1 && PyErr_Occurred()) return -2;
  return v;
}

static long errquery(PyObject* obj) {
  long v = PyLong_AsLong(obj);
  return v;
}
'''


def test_native_audit_controls(tmp_path):
    p = _write(tmp_path, "fix_native.cpp", NATIVE_FIXTURE)
    findings = native_audit.check_file(p)
    gil_api = _by_rule(findings, native_audit.RULE_GIL_API)
    # bad_gil's PyObject_IsTrue fires; good_gil (pointer extracted
    # BEFORE release) and waived_gil stay silent
    assert {f.symbol for f in gil_api} == {"bad_gil"}
    derefs = _by_rule(findings, native_audit.RULE_GIL_DEREF)
    assert {f.detail for f in derefs} == {"bad_gil:row"}
    unchecked = _by_rule(findings, native_audit.RULE_UNCHECKED)
    uc = {(f.symbol, f.detail.split(":")[1]) for f in unchecked}
    # bad_alloc's bare PyList_Append, bad_gil's GIL-span IsTrue (it is
    # ALSO unchecked), and the errquery without PyErr_Occurred
    assert ("bad_alloc", "PyList_Append") in uc
    assert ("errquery", "PyLong_AsLong") in uc
    for sym in ("checked_alloc", "checked_append", "waived_append",
                "errquery_checked", "good_gil", "waived_gil"):
        assert sym not in {s for s, _ in uc}, (sym, uc)


def test_native_audit_ignores_strings_and_comments(tmp_path):
    p = _write(tmp_path, "fix_strings.cpp", r'''
#include <Python.h>

// PyList_Append(out, item); commentary must not trip the checker
static const char* doc = "PyList_New(0) inside a string literal";

static long span_free(const char* buf, long n) {
  long total = 0;
  Py_BEGIN_ALLOW_THREADS
  /* PyObject_Str(row); in a block comment */
  for (long i = 0; i < n; ++i) total += buf[i];
  Py_END_ALLOW_THREADS
  return total;
}
''')
    assert native_audit.check_file(p) == []


# ---------------------------------------------------------------------------
# baseline workflow + CLI exit codes
# ---------------------------------------------------------------------------

def test_baseline_diff_semantics():
    f1 = Finding("guard-write", "m.py", 3, "C.f", "msg", detail="x:write")
    f2 = Finding("guard-write", "m.py", 9, "C.g", "msg", detail="y:write")
    bl = Baseline(entries={
        f1.fingerprint: {
            "fingerprint": f1.fingerprint, "reason": "known benign",
        },
        "deadbeefdeadbeef": {
            "fingerprint": "deadbeefdeadbeef", "reason": "old",
        },
    })
    res = diff_against_baseline([f1, f2], bl)
    assert [f.fingerprint for f in res.new] == [f2.fingerprint]
    assert [f.fingerprint for f in res.suppressed] == [f1.fingerprint]
    assert not res.unjustified
    assert [e["fingerprint"] for e in res.stale] == ["deadbeefdeadbeef"]
    assert not res.ok  # f2 is new
    # a baselined finding with an EMPTY reason is itself a failure
    bl.entries[f1.fingerprint]["reason"] = "  "
    res = diff_against_baseline([f1], bl)
    assert res.unjustified and not res.ok


def test_fingerprint_stable_across_line_moves():
    a = Finding("guard-write", "m.py", 3, "C.f", "msg", detail="x:write")
    b = Finding("guard-write", "m.py", 300, "C.f", "msg", detail="x:write")
    assert a.fingerprint == b.fingerprint


def test_cli_baseline_workflow_end_to_end(tmp_path, capsys):
    """The documented triage loop (docs/ANALYSIS.md): a violation fails
    → --update-baseline records it with an empty reason → the next run
    STILL fails until a human writes the reason → then passes → fixing
    the violation leaves a stale note but keeps passing."""
    fixture = _write(tmp_path, "fix_cli.py", '''
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0  # guarded-by: _lock

    def bad(self):
        self.n += 1
''')
    bl = tmp_path / "baseline.json"
    args = ["--pass", "guards", "--paths", str(fixture),
            "--baseline", str(bl)]
    # 1. new finding, no baseline -> fail
    assert swarmlint_main(args) == 1
    # 2. record it
    assert swarmlint_main(args + ["--update-baseline"]) == 0
    # 3. empty reason -> still fails
    assert swarmlint_main(args) == 1
    # 4. write the justification -> passes
    data = json.loads(bl.read_text())
    assert len(data["findings"]) == 1
    data["findings"][0]["reason"] = "fixture: exercised by the CLI test"
    bl.write_text(json.dumps(data))
    assert swarmlint_main(args) == 0
    # 5. fix the violation -> stale entry is a note, not a failure
    fixture.write_text(fixture.read_text().replace(
        "        self.n += 1",
        "        with self._lock:\n            self.n += 1",
    ))
    capsys.readouterr()
    assert swarmlint_main(args) == 0
    assert "stale" in capsys.readouterr().out


def test_cli_exits_zero_on_head():
    """Acceptance: the full three-pass run over the repo as committed
    is clean (every seed finding was fixed or carries a justified
    baseline entry)."""
    assert swarmlint_main([]) == 0


def test_cli_flags_fixture_violation_against_repo_baseline(tmp_path):
    """Acceptance: introducing a violation exits non-zero against the
    REAL baseline (its fingerprint cannot be present there)."""
    fixture = _write(tmp_path, "fix_new_violation.py", '''
import threading

_lk = threading.Lock()
_shared = []  # guarded-by: _lk


def racy():
    _shared.append(1)
''')
    assert swarmlint_main(
        ["--pass", "guards", "--paths", str(fixture)]
    ) == 1


# ---------------------------------------------------------------------------
# satellites riding the analyzer
# ---------------------------------------------------------------------------

def test_observability_doc_cross_check_clean_on_head():
    """tools/check_metrics.py's doc drift gate (both directions) holds
    on HEAD — the same check preflight runs."""
    import tools.check_metrics as cm

    problems, n_code = cm.check_doc_drift()
    assert problems == []
    assert n_code > 0


def test_crex_override_missing_lib_fails_loudly(monkeypatch, tmp_path):
    """tools/sanitize_natives.sh names a deliberate prebuilt set via
    SWARM_NATIVE_DIR; a missing libcrex.so there must raise, not fall
    back to the pure-Python engine — a silent fallback would let the
    sanitizer run report green with zero coverage of crex.cpp."""
    from swarm_tpu.native import crex as ncrex

    monkeypatch.setattr(ncrex, "_DIR_OVERRIDDEN", True)
    monkeypatch.setattr(ncrex, "_LIB_PATH", tmp_path / "libcrex.so")
    monkeypatch.setattr(ncrex, "_lib", None)
    monkeypatch.setattr(ncrex, "_lib_failed", False)
    with pytest.raises(FileNotFoundError):
        ncrex.ensure_crex()


def test_lock_using_modules_carry_guard_annotations():
    """The threading model the last three PRs debugged by hand is now
    DECLARED — and the module set is AUTO-DISCOVERED (grep for lock
    factories at analyzer startup, docs/ANALYSIS.md §inventory), so a
    new lock-using module can never silently skip annotation the way
    the old hand-maintained list here allowed. Every discovered lock
    declarer either carries guard annotations or a written
    '# swarmlint-exempt:' reason."""
    from tools.swarmlint import inventory

    discovered = {
        p for p, flags in inventory.discover().items() if flags["locks"]
    }
    # the discovery still covers the modules the hand list used to pin
    rels = {p.relative_to(REPO).as_posix() for p in discovered}
    for must in (
        "swarm_tpu/server/queue.py",
        "swarm_tpu/cache/tier.py",
        "swarm_tpu/aot/store.py",
        "swarm_tpu/ops/engine.py",
        "swarm_tpu/stores.py",
    ):
        assert must in rels, must
    bare = [
        f.path for f in inventory.run(sorted(discovered))
        if f.rule == inventory.RULE_BARE
    ]
    assert not bare, f"lock modules without annotations/exemption: {bare}"
