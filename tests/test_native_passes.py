"""Native fastpack passes: content dedup, alive mask, verdict cache.

These are the C primitives under the exact engine's steady-state path
(native/fastpack.cpp via swarm_tpu/native/scanio.py). The engine-level
equivalence suite (tests/test_engine_edges.py) pins end-to-end
bit-identity; this file pins the primitives directly — randomized
parity against pure-Python oracles, LRU/eviction behavior, and the
extras contract."""

import random

import numpy as np
import pytest

from swarm_tpu.fingerprints.model import Response
from swarm_tpu.ops.engine import _content_key

pytest.importorskip("swarm_tpu.native.scanio")
try:
    from swarm_tpu.native.scanio import (
        VerdictMemo, ensure_fastpack, rows_alive, rows_dedup,
    )

    ensure_fastpack()
except Exception:  # no toolchain and no prebuilt .so
    pytest.skip("native lib unavailable", allow_module_level=True)


def _content_pool():
    base = bytearray(b"x" * 500)
    # byte 50 is OUTSIDE every row_hash probe window for len 500
    # ([0,8), [125,133), [246,254), [367,375), [492,500)) — the two
    # rows hash identically and only the full memcmp separates them
    base[50] = ord("q")
    return [
        Response(body=b"page-A" * 100, header=b"Server: x\r\n", status=200),
        # status alone differs
        Response(body=b"page-A" * 100, header=b"Server: x\r\n", status=404),
        Response(body=b"page-B" * 100, header=b"Server: x\r\n", status=200),
        # banner-vs-body distinction (same bytes, different field)
        Response(banner=b"SSH-2.0", body=b"", header=b"", status=0),
        Response(banner=None, body=b"SSH-2.0", header=b"", status=0),
        Response(body=b"", header=b"", status=0),
        # OOB fields are key dimensions
        Response(body=b"oob", status=200, oob_protocols=("http",),
                 oob_requests=b"GET /x", oob_ips=("1.2.3.4",)),
        Response(body=b"oob", status=200, oob_protocols=("dns",),
                 oob_requests=b"GET /x", oob_ips=("1.2.3.4",)),
        # mid-body difference with identical length and boundary bytes
        # (forces the hash-collision → full-memcmp path)
        Response(body=b"x" * 500, header=b"", status=200),
        Response(body=bytes(base), header=b"", status=200),
    ]


def _clone(r: Response) -> Response:
    """Content-equal copy through fresh byte objects (defeats the
    same-object shortcut, the production allocation pattern)."""
    return Response(
        host=r.host, port=r.port, status=r.status,
        body=bytes(memoryview(r.body)), header=bytes(memoryview(r.header)),
        banner=None if r.banner is None else bytes(memoryview(r.banner)),
        oob_protocols=tuple(r.oob_protocols),
        oob_requests=bytes(memoryview(r.oob_requests)),
        oob_ips=tuple(r.oob_ips),
    )


def test_rows_dedup_randomized_parity():
    rng = random.Random(7)
    pool = _content_pool()
    for trial in range(100):
        rows = [rng.choice(pool) for _ in range(rng.randrange(0, 50))]
        rows += [_clone(r) for r in rows[:10]]
        uniq, back = rows_dedup(rows)
        key_of: dict = {}
        ouniq: list = []
        oback: list = []
        for i, r in enumerate(rows):
            k = _content_key(r)
            if k not in key_of:
                key_of[k] = len(ouniq)
                ouniq.append(i)
            oback.append(key_of[k])
        assert list(uniq) == ouniq, trial
        assert list(back) == oback, trial


def test_rows_alive_mask():
    rows = [Response(body=b"x", alive=(i % 3 != 0)) for i in range(10)]
    n, mask = rows_alive(rows)
    assert n == sum(r.alive for r in rows)
    assert list(mask) == [int(r.alive) for r in rows]


def test_memo_lookup_insert_dedupe_and_extras():
    m = VerdictMemo(8, 8)
    r1 = Response(body=b"aaa", header=b"h", status=200)
    r2 = Response(body=b"bbb", header=b"h", status=200)
    bits = np.zeros((3, 8), dtype=np.uint8)
    state, miss, extr, deferred = m.lookup([r1, r2, _clone(r1)], bits)
    assert list(state) == [0, 1, 0] and miss == [0, 1]
    assert extr == {} and deferred == []
    ment = (("t-x", ("v1", "v2")),)
    mdef = (3,)
    m.insert(r1, np.arange(8, dtype=np.uint8), (ment, mdef))
    assert m.contains(_clone(r1)) and not m.contains(r2)
    bits = np.zeros((3, 8), dtype=np.uint8)
    state, miss, extr, deferred = m.lookup([r2, _clone(r1), r1], bits)
    assert list(state) == [0, -1, -1] and miss == [0]
    assert (bits[1] == np.arange(8)).all() and (bits[2] == np.arange(8)).all()
    # extras applied per served row, values thawed to fresh lists
    assert extr == {(1, "t-x"): ["v1", "v2"], (2, "t-x"): ["v1", "v2"]}
    assert extr[(1, "t-x")] is not extr[(2, "t-x")]
    assert deferred == [(1, 3), (2, 3)]


def test_memo_dead_rows_served_as_zero():
    m = VerdictMemo(8, 4)
    live = Response(body=b"live", status=200)
    m.insert(live, np.full(4, 7, np.uint8), None)
    dead = Response(host="d", alive=False)
    bits = np.full((2, 4), 0xEE, dtype=np.uint8)
    state, miss, extr, deferred = m.lookup([dead, _clone(live)], bits)
    assert list(state) == [-2, -1] and miss == []
    assert (bits[0] == 0).all() and (bits[1] == 7).all()


def test_memo_lru_eviction_and_overwrite():
    m = VerdictMemo(4, 4)
    mk = lambda i: Response(body=b"x%d" % i, status=i)
    for i in range(6):
        m.insert(mk(i), np.full(4, i, np.uint8), None)
    assert len(m) == 4
    assert not m.contains(mk(0)) and not m.contains(mk(1))  # LRU evicted
    assert m.contains(mk(5))
    # touching an entry protects it from the next eviction
    bits = np.zeros((1, 4), dtype=np.uint8)
    m.lookup([mk(2)], bits)  # refresh 2 (oldest resident)
    m.insert(mk(9), np.full(4, 9, np.uint8), None)  # evicts 3, not 2
    assert m.contains(mk(2)) and not m.contains(mk(3))
    # overwrite keeps one entry and the new bits win
    m.insert(mk(5), np.full(4, 0x55, np.uint8), None)
    assert len(m) == 4
    m.lookup([_clone(mk(5))], bits)
    assert (bits[0] == 0x55).all()
    m.clear()
    assert len(m) == 0


def test_memo_contains_batch_mask_and_dead_rows():
    m = VerdictMemo(8, 4)
    live = Response(body=b"resident", status=200)
    m.insert(live, np.full(4, 3, np.uint8), None)
    # a dead row with content byte-equal to a resident ALIVE row must
    # probe as not-resident (dead rows match nothing by contract)
    dead_twin = _clone(live)
    dead_twin.alive = False
    miss = Response(body=b"novel", status=200)
    mask = m.contains_batch([_clone(live), miss, dead_twin])
    assert list(mask) == [1, 0, 0]
    assert m.contains_batch([]).shape == (0,)
    # no LRU side effects: capacity-4 memo, probe entry 0, then insert
    # 4 more — entry 0 must still be evicted as LRU tail
    m2 = VerdictMemo(2, 4)
    a, b = Response(body=b"a"), Response(body=b"b")
    m2.insert(a, np.zeros(4, np.uint8), None)
    m2.insert(b, np.zeros(4, np.uint8), None)
    m2.contains_batch([_clone(a)])  # probe must NOT refresh a
    m2.insert(Response(body=b"c"), np.zeros(4, np.uint8), None)
    assert not m2.contains(_clone(a)) and m2.contains(_clone(b))


def test_memo_insert_rejects_malformed_extras():
    m = VerdictMemo(4, 4)
    r = Response(body=b"x", status=200)
    with pytest.raises(ValueError):
        m.insert(r, np.zeros(4, np.uint8), [("tid", [1])])
    m.insert(r, np.zeros(4, np.uint8), None)  # None is fine
