"""AOT executable cache (docs/AOT.md).

Pins the ISSUE-13 acceptance contracts:

- a second-process warm-fetch bring-up compiles ZERO executables for an
  already-published shape class (``executable_count == 0``,
  ``fetched_executable_count > 0``) with verdict planes bit-identical
  to the compiled path, on both :class:`DeviceDB` and the 8-virtual-
  device :class:`ShardedMatcher` mesh;
- the compile-count spy and the ``_fn_cache`` LRU count a
  deserialized load DISTINCTLY from a compile (the width-bucket
  sharing property holds on the fetch path);
- any miss / deserialize failure / injected ``aot.fetch``/``aot.put``
  fault falls back to a live compile — breaker-wrapped, never blocks,
  verdicts identical;
- publishes ride the epoch + fencing-token discipline (a superseded
  writer is fenced; an epoch bump makes every artifact unreachable).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from swarm_tpu.aot import AotClient, AotStore, aval_signature
from swarm_tpu.fingerprints import load_corpus
from swarm_tpu.fingerprints.compile import compile_corpus
from swarm_tpu.ops.encoding import encode_batch
from swarm_tpu.ops.match import DeviceDB
from swarm_tpu.resilience.faults import clear_plan, install_plan
from swarm_tpu.stores import MemoryBlobStore, MemoryStateStore

from test_match_parity import fuzz_rows

DATA = "tests/data/templates"


@pytest.fixture(scope="module")
def world():
    templates, errors = load_corpus(DATA)
    assert templates and not errors
    db = compile_corpus(templates)
    rows = fuzz_rows(templates, random.Random(57), 16)
    batch = encode_batch(rows, max_body=512, max_header=512, pad_rows_to=16)
    return templates, db, rows, batch


def _store():
    return AotStore(MemoryStateStore(), MemoryBlobStore())


def _match(db, batch, client=None, prewarm=False):
    dev = DeviceDB(db)
    if client is not None:
        dev.attach_aot(client)
        if prewarm:
            dev.aot_prewarm()
    planes = dev.match(
        batch.streams, batch.lengths, batch.status, full=True
    )
    return dev, planes


def _assert_planes_equal(a, b):
    names = ("t_value", "t_unc", "op_value", "op_unc", "m_unc", "overflow")
    for name, x, y in zip(names, a, b):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=name
        )


# ----------------------------------------------------------------------
# warm fetch: the acceptance capstones
# ----------------------------------------------------------------------


def test_warm_fetch_devicedb_compiles_nothing(world):
    """Publisher process compiles + publishes; a fresh DeviceDB with a
    fresh client over the same store loads EVERYTHING — zero local
    compiles, planes bit-identical to both the compiled-and-published
    run and the no-AOT reference."""
    _t, db, _rows, batch = world
    store = _store()
    d1, p1 = _match(db, batch, AotClient(store, worker_id="pub"))
    assert d1.compile_count >= 1 and d1.executable_count() >= 1
    assert d1.fetched_executable_count() == 0

    c2 = AotClient(store, worker_id="join")
    d2, p2 = _match(db, batch, c2, prewarm=True)
    assert d2.executable_count() == 0
    assert d2.compile_count == 0
    assert d2.fetched_executable_count() > 0
    assert d2.fetch_count >= 1 and d2.fetch_seconds > 0
    assert c2.counters()["fetch_hits"] >= 2  # phase A + phase B
    _assert_planes_equal(p1, p2)

    d3, p3 = _match(db, batch)  # no AOT at all — the reference twin
    _assert_planes_equal(p2, p3)


def test_warm_fetch_lazy_without_prewarm(world):
    """The dispatch-time fetch alone (no bring-up prewarm) also
    compiles nothing for a published shape class."""
    _t, db, _rows, batch = world
    store = _store()
    _d1, p1 = _match(db, batch, AotClient(store, worker_id="pub"))
    d2, p2 = _match(
        db, batch, AotClient(store, worker_id="lazy"), prewarm=False
    )
    assert d2.executable_count() == 0 and d2.compile_count == 0
    assert d2.fetched_executable_count() > 0
    _assert_planes_equal(p1, p2)


def test_warm_fetch_sharded_mesh(world):
    """The mesh twin: a fresh ShardedMatcher over the 8-virtual-device
    mesh loads every published step — zero compiles, planes
    bit-identical."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the suite's forced multi-device host platform")
    from swarm_tpu.parallel.mesh import make_mesh
    from swarm_tpu.parallel.sharded import (
        ShardedMatcher,
        pad_streams_for_seq,
    )

    _t, db, _rows, batch = world
    mesh = make_mesh()
    store = _store()
    s1 = ShardedMatcher(db, mesh)
    s1.attach_aot(AotClient(store, worker_id="pub"))
    streams = dict(batch.streams)
    pad_streams_for_seq(streams, s1.ranks.get("seq", 1), s1.halo)
    p1 = s1.match(streams, batch.lengths, batch.status, full=True)
    assert s1.compile_count >= 1 and s1.executable_count() >= 1

    s2 = ShardedMatcher(db, mesh)
    c2 = AotClient(store, worker_id="join")
    s2.attach_aot(c2)
    assert s2.aot_prewarm() >= 2
    p2 = s2.match(streams, batch.lengths, batch.status, full=True)
    assert s2.executable_count() == 0 and s2.compile_count == 0
    assert s2.fetched_executable_count() > 0 and s2.fetch_count >= 1
    _assert_planes_equal(p1, p2)


def test_width_bucket_sharing_holds_on_fetch_path(world):
    """PR 3's width-bucket property, fetch edition: two batches of the
    SAME padded shape share one fetched executable — the second batch
    fetches nothing new and compiles nothing (the spy pair stays
    (0, constant))."""
    _t, db, rows, batch = world
    store = _store()
    _d1, _p1 = _match(db, batch, AotClient(store, worker_id="pub"))
    d2, _p2 = _match(db, batch, AotClient(store, worker_id="join"))
    n_fetched = d2.fetched_executable_count()
    assert n_fetched > 0
    # same padded shape AND same ladder rung (same content re-encoded
    # into fresh arrays — a different survivor count would honestly
    # select a different rung, which is a different executable):
    # the fetched executables serve, nothing new compiles or fetches
    batch2 = encode_batch(
        rows, max_body=512, max_header=512, pad_rows_to=16
    )
    d2.match(batch2.streams, batch2.lengths, batch2.status, full=True)
    assert d2.executable_count() == 0
    assert d2.fetched_executable_count() == n_fetched


# ----------------------------------------------------------------------
# fallback paths: miss / deserialize failure / chaos faults
# ----------------------------------------------------------------------


def test_deserialize_failure_falls_back_to_compile(world):
    """A corrupt artifact (or one from a foreign topology) is a MISS,
    never an exception: the worker compiles and verdicts are
    identical."""
    _t, db, _rows, batch = world
    store = _store()
    c1 = AotClient(store, worker_id="pub")
    _d1, p1 = _match(db, batch, c1)
    # corrupt every published payload in place
    epoch = f"g{store.epoch_generation()}"
    for digest in store.list_index(epoch):
        store._blobs.put(store._artifact_key(epoch, digest), b"garbage")
    c2 = AotClient(store, worker_id="victim")
    d2, p2 = _match(db, batch, c2)
    assert d2.compile_count >= 1 and d2.executable_count() >= 1
    assert d2.fetched_executable_count() == 0
    assert c2.counters()["deserialize_errors"] >= 1
    _assert_planes_equal(p1, p2)


def test_chaos_faults_degrade_to_compile(world):
    """``aot.fetch`` / ``aot.put`` fault points (docs/RESILIENCE.md):
    a faulted store trips the breaker, the dispatch compiles locally,
    and planes stay bit-identical."""
    _t, db, _rows, batch = world
    store = _store()
    _d1, p1 = _match(db, batch, AotClient(store, worker_id="pub"))
    plan = install_plan("seed=3;aot.fetch:1-4;aot.put:1-2")
    try:
        c2 = AotClient(store, worker_id="chaos", breaker_threshold=2)
        d2, p2 = _match(db, batch, c2)
        _assert_planes_equal(p1, p2)
        assert d2.compile_count >= 1  # fetch faulted → compiled
        snap = plan.snapshot()
        assert sum(c["fired"] for c in snap.values()) > 0
    finally:
        clear_plan()
    # store healthy again: the NEXT fresh client warm-fetches normally
    d3, p3 = _match(db, batch, AotClient(store, worker_id="after"))
    assert d3.compile_count == 0 and d3.fetched_executable_count() > 0
    _assert_planes_equal(p1, p3)


def test_epoch_bump_hides_artifacts(world):
    """The poisoned-artifact runbook lever: ``bump_epoch`` moves every
    reader/writer to a fresh namespace — the next worker compiles (and
    republished artifacts serve workers after it)."""
    _t, db, _rows, batch = world
    store = _store()
    _d1, p1 = _match(db, batch, AotClient(store, worker_id="pub"))
    store.bump_epoch()
    c2 = AotClient(store, worker_id="postbump")
    d2, p2 = _match(db, batch, c2)
    assert d2.compile_count >= 1 and d2.fetched_executable_count() == 0
    _assert_planes_equal(p1, p2)
    # the new epoch now holds the republished artifacts
    d3, _p3 = _match(db, batch, AotClient(store, worker_id="join2"))
    assert d3.compile_count == 0 and d3.fetched_executable_count() > 0


def test_superseded_writer_publishes_are_fenced(world):
    """The fencing-token discipline (docs/CACHING.md): re-acquiring a
    writer identity supersedes the old holder, whose publishes then
    report fenced instead of claiming success."""
    _t, db, _rows, batch = world
    store = _store()
    c1 = AotClient(store, worker_id="w")
    _d1, _p1 = _match(db, batch, c1)  # acquires the process token
    assert c1.counters()["published"] >= 1
    # a "restarted" instance of the same identity elsewhere supersedes
    store.acquire_writer("w:aot")
    out = c1.publish(
        c1.key_digest("test.k", "s", "()", "sig"), {}, _compiled_probe()
    )
    assert out == "fenced"
    assert c1.counters()["publish_fenced"] >= 1


def _compiled_probe():
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda x: x + 1).lower(jnp.ones((2,))).compile()


# ----------------------------------------------------------------------
# key schema
# ----------------------------------------------------------------------


def test_aval_signature_is_shape_and_dtype_sensitive():
    sig = aval_signature(
        {"a": np.zeros((2, 3), np.uint8), "b": np.zeros((4,), np.int32)}
    )
    assert sig == aval_signature(
        {"a": np.ones((2, 3), np.uint8), "b": np.ones((4,), np.int32)}
    )
    assert sig != aval_signature(
        {"a": np.zeros((2, 4), np.uint8), "b": np.zeros((4,), np.int32)}
    )
    assert sig != aval_signature(
        {"a": np.zeros((2, 3), np.uint16), "b": np.zeros((4,), np.int32)}
    )


def test_key_digest_separates_kernels_statics_and_shapes(world):
    store = _store()
    c = AotClient(store, worker_id="k")
    base = c.key_digest("dd.B", "salt", "(8,)", "sig")
    assert base != c.key_digest("dd.A", "salt", "(8,)", "sig")
    assert base != c.key_digest("dd.B", "salt", "(16,)", "sig")  # rung
    assert base != c.key_digest("dd.B", "salt2", "(8,)", "sig")
    assert base != c.key_digest("dd.B", "salt", "(8,)", "sig2")
    assert base == c.key_digest("dd.B", "salt", "(8,)", "sig")
